"""The in-process serving engine: admission → micro-batch → bucket → score.

Request lifecycle:

  1. ``submit_line`` / ``submit`` parses the request to the static
     ``max_nnz`` width and enqueues it on the BOUNDED admission queue.
     Overload policy (``serve_overload``): ``block`` applies
     backpressure to the caller; ``reject`` raises OverloadError
     immediately — the queue is the only elastic buffer, so memory under
     overload is capped at ``serve_queue_size`` requests either way.
  2. The collector thread gathers requests and flushes when
     ``serve_max_batch`` fills OR ``serve_flush_deadline_ms`` expires
     for the oldest pending request — whichever first.  The deadline is
     the latency/occupancy knob: 0 serves every request the moment it is
     seen (occupancy→1/bucket), large values fill buckets (throughput).
  3. A flush pads up to the nearest compile-ladder bucket
     (buckets.BucketLadder — no steady-state XLA compiles), scores,
     slices the padding off, and resolves per-request futures.
  4. A watcher thread polls ``model_file``; a changed checkpoint is
     restored OFF the hot path into a fresh state and staged; the
     collector swaps it in ATOMICALLY between flushes — no flush ever
     sees half-old half-new weights, and a torn/partial checkpoint write
     fails the stage (counted, retried next tick) without touching the
     serving state.

Single-device by design: one process, one chip (or CPU), the deployment
unit a load balancer replicates.  The mesh-sharded offline path
(dist_predict) stays the batch tool for backfills.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

import numpy as np

from fast_tffm_tpu.checkpoint import (
    checkpoint_save_id,
    checkpoint_signature,
    load_delta,
    read_delta_chain,
    read_publish_time,
)
from fast_tffm_tpu.config import Config
from fast_tffm_tpu.data.libsvm import parse_lines
from fast_tffm_tpu.serving.admission import AdmissionQueue
from fast_tffm_tpu.serving.buckets import BucketLadder
from fast_tffm_tpu.serving.metrics import ServingMetrics
from fast_tffm_tpu.serving.protocol import FRAME_STATUS_CODES, DeadlineExceeded
from fast_tffm_tpu.telemetry import log_quietly
from fast_tffm_tpu.telemetry import RunMonitor

__all__ = [
    "ServingEngine",
    "OverloadError",
    "DeadlineExceeded",
    "EngineClosed",
    "serve_lines",
]


class OverloadError(RuntimeError):
    """Admission queue full under serve_overload = reject, or a queued
    request evicted by a higher-class arrival (tiered admission)."""


class EngineClosed(RuntimeError):
    """Request submitted to (or unresolved inside) a closed engine."""


_CLOSE = object()  # collector shutdown sentinel

# Per-row status bytes for block (frame) responses — indices into
# protocol.FRAME_STATUS_CODES, so the wire and the engine agree by
# construction.
_ST_OK = 0
_ST_OVERLOADED = FRAME_STATUS_CODES.index("overloaded")
_ST_DEADLINE = FRAME_STATUS_CODES.index("deadline")
_ST_BAD_REQUEST = FRAME_STATUS_CODES.index("bad_request")
_ST_UNAVAILABLE = FRAME_STATUS_CODES.index("unavailable")


@dataclass
class _Request:
    row: tuple  # (ids [max_nnz] i32, vals [max_nnz] f32, fields [max_nnz] i32)
    future: Future = field(default_factory=Future)
    t_submit: float = field(default_factory=time.perf_counter)
    klass: str = ""  # client class name ("" = default tier)
    tier: int = 0  # admission tier (higher sheds later; from serve_classes)
    deadline_t: float | None = None  # perf_counter deadline; None = none

    n_rows = 1  # admission/flush row accounting (blocks carry many)


@dataclass
class _Block:
    """A whole decoded REQUEST frame admitted as ONE unit: one queue
    slot, one decode, one coalesced placement, one response.  ``future``
    resolves to ``(statuses u8[n], scores f32[n])`` — nonzero statuses
    index FRAME_STATUS_CODES, so per-row typed errors survive batching.
    Tier is the MINIMUM over its rows: under tiered overload a mixed
    frame sheds as its weakest member (a frame is one delivery unit; a
    caller who needs gold treatment must not staple gold rows to std
    ones)."""

    ids: np.ndarray  # (n, max_nnz) i32
    vals: np.ndarray  # (n, max_nnz) f32
    fields: np.ndarray | None  # (n, max_nnz) i32, or None
    deadline_t: np.ndarray  # (n,) f64 perf_counter deadlines; +inf = none
    statuses: np.ndarray  # (n,) u8; nonzero = decided before scoring
    klasses: list  # per-row class names (metrics attribution)
    future: Future = field(default_factory=Future)
    t_submit: float = field(default_factory=time.perf_counter)
    klass: str = ""  # representative class ("" when mixed)
    tier: int = 0

    @property
    def n_rows(self) -> int:
        return int(self.ids.shape[0])


class ServingEngine:
    """See module docstring.  Construct with a validated Config whose
    ``model_file`` holds a restorable checkpoint; scoring runs through
    the same ScoreFn as ``prediction.predict`` — bit-identical per batch
    shape (pinned by tests/test_serving.py); against predict's own
    differently-shaped batches, agreement is within a few float32 ULPs
    on backends where XLA programs of different shapes round apart."""

    def __init__(
        self, cfg: Config, log=print, state=None, model=None, replica: int | None = None
    ):
        from fast_tffm_tpu.prediction import load_scoring_state, make_score_fn
        from fast_tffm_tpu.training import scan_max_nnz

        self._cfg = cfg
        self._log = log
        if cfg.max_nnz <= 0 and not (
            cfg.train_files or cfg.validation_files or cfg.predict_files
        ):
            raise ValueError(
                "serving needs a static feature width: set max_nnz in [Train], "
                "or configure data files for the width scan"
            )
        max_nnz = scan_max_nnz(cfg)
        if state is None:
            # Baseline reload signature BEFORE the (possibly multi-second)
            # restore: a trainer save landing mid-restore must read as
            # "new" to the watcher, not as already-loaded — worst case it
            # redundantly reloads the checkpoint we started from.
            self._loaded_sig = checkpoint_signature(cfg.model_file)
            # Delta bookkeeping, also PRE-restore (under-counting is the
            # safe direction: re-applying an already-applied delta suffix
            # in order is idempotent; skipping one is not).
            self._loaded_save_id, self._applied_deltas = self._chain_baseline()
            model, state = load_scoring_state(cfg, log)
        else:
            # Injected state: the on-disk checkpoint was NEVER loaded, so
            # no signature is "already loaded" — whatever model_file holds
            # (even something older than this baseline) is news to us.
            self._loaded_sig = None
            self._loaded_save_id, self._applied_deltas = None, 0
        self._state = state
        self._score = make_score_fn(cfg, state, max_nnz, model=model)
        if (
            cfg.serve_reload_interval_s > 0
            and cfg.table_layout == "packed"
            and state.table_opt.accum.size == 0
        ):
            # An injected FUSED-packed state (empty-accum marker) compiled
            # a fused-gather ScoreFn, but the watcher's load_scoring_state
            # restores plain-packed — a swap would feed a D-stride table
            # to D+1-stride tile arithmetic: clamped gathers, confidently
            # wrong scores, no error.  Refuse the combination up front.
            raise ValueError(
                "hot reload (serve_reload_interval_s > 0) cannot re-pack "
                "checkpoints into an injected fused-packed state's layout — "
                "pass a plain-packed/rows state, or disable the watcher"
            )
        self._ladder = BucketLadder(
            self._score,
            cfg.serve_buckets,
            wire_format=cfg.wire_format,
            vocabulary_size=cfg.vocabulary_size,
        )
        self.max_batch = cfg.serve_max_batch or self._ladder.max_batch
        if self.max_batch > self._ladder.max_batch:
            raise ValueError(
                f"serve_max_batch {self.max_batch} exceeds the largest bucket "
                f"{self._ladder.max_batch} — a flush that size has no compiled shape"
            )
        self.deadline_s = cfg.serve_flush_deadline_ms / 1e3
        self._policy = cfg.serve_overload
        self._q = AdmissionQueue(cfg.serve_queue_size)
        # Tiered admission (serve_classes): class name -> tier; unknown /
        # absent classes land at tier 0 (shed first).  Per-request
        # deadlines default to serve_deadline_ms (0 = none) unless the
        # submit carries its own.
        self._tiers = dict(cfg.serve_classes)
        self._default_deadline_s = (
            cfg.serve_deadline_ms / 1e3 if cfg.serve_deadline_ms > 0 else None
        )
        # Chaos/latency injection (tools/chaos.py replica_slow@N:ms): the
        # next `_slow_flushes` flushes sleep `_slow_ms` before dispatch.
        self._slow_ms = 0.0
        self._slow_flushes = 0
        self._last_flush_t = time.perf_counter()
        self.metrics = ServingMetrics()
        # kind=serving records ride the same telemetry envelope as the
        # train/predict drivers (shared run_id per engine lifetime); the
        # compile sentinel turns any steady-state flush compile into a
        # kind=compile event — the bucket-ladder pin, now observable.
        # No stall watchdog here: an idle engine is healthy, not stalled.
        self._monitor = RunMonitor(
            cfg.metrics_path,
            run_id=cfg.telemetry_run_id,
            source="serving",
            mem_every_s=cfg.telemetry_mem_every_s,
            replica=replica,
            log=log,
        )
        self._flush_seq = 0  # telemetry step for serving = flush ordinal
        self._metrics_every = cfg.serve_metrics_every_s
        self._last_metrics_log = time.perf_counter()
        self._closed = False  # no new submits (set by close AND by a
        #   collector crash — see _collect's exception handler)
        self._close_done = False  # close() finalization ran (separate
        #   flag: a crash sets _closed, but close() must still write the
        #   final metrics record and join the watcher afterwards)
        self._stop = threading.Event()
        # Hot-reload handoff: the watcher STAGES a fully-restored state
        # here; the collector SWAPS it in between flushes.  One lock, two
        # one-line critical sections.
        self._reload_lock = threading.Lock()
        # Reload ticks SERIALIZE on this engine-level lock: under
        # continuous publish, a delta landing while a tick is mid-apply of
        # its PARENT can trigger a second reload_once from another thread
        # (a router reconnect's fresh control connection, a poll tick
        # racing a router command) — two concurrent ticks would both pass
        # the staged-state check and race _applied_deltas/_loaded_sig,
        # applying the chain out of order.  A blocking lock makes the
        # second caller QUEUE: it re-reads the (advanced) signature after
        # the first apply completes, so deltas apply strictly in chain
        # order (test-pinned under concurrent publish).
        self._tick_lock = threading.Lock()
        self._staged_state = None
        self._staged_step = None
        self._staged_is_delta = False
        # Freshness SLO bookkeeping: the staged checkpoint's publish
        # timestamp (stamped into the npz by the writer — wall clock, so
        # cross-host skew applies and negatives clamp to 0) travels with
        # the stage; the swap records publish→applied and the first
        # successful score after it completes publish→first-scored.
        self._staged_pub_t = None
        self._pending_fresh = None
        # Reload failure discipline for ONE observed signature (shared by
        # the polling watcher thread and router-driven reload_once calls):
        # retries back off exponentially, and after serve_reload_max_retries
        # consecutive failures the engine GIVES UP on that signature until
        # a NEW write lands.
        self._fail_sig = None
        self._fail_count = 0
        self._gave_up = False
        self._next_retry_t = 0.0

        n = self._ladder.warmup(self._state)
        if cfg.telemetry_profile_costs:
            # Measured cost ledger: one kind=profile record per bucket's
            # score program (bytes/FLOPs from XLA cost analysis).  Pure
            # re-lowering at the warmed shapes — no extra backend compile,
            # and it runs inside startup, never on the flush path.
            from fast_tffm_tpu.profiling import CostLedger

            ledger = CostLedger(self._monitor, source="serving")
            for bkt in self._ladder.buckets:
                ledger.stage(
                    f"serve_score_b{bkt}",
                    self._score.fn,
                    (self._state, self._ladder.example_batch(bkt)),
                    examples=bkt,
                )
            ledger.flush(0)
        # Attribute every startup compile (ladder rungs + unpackers) to
        # warmup; anything the sentinel sees after this is steady-state.
        self._monitor.on_dispatch(0, warmup=True)
        log(
            f"serving: warmed buckets {self._ladder.buckets} "
            f"(max_nnz {max_nnz}, {n if n >= 0 else '?'} compiled programs, "
            f"flush deadline {cfg.serve_flush_deadline_ms}ms, "
            f"queue {cfg.serve_queue_size} {self._policy})"
        )
        self._collector = threading.Thread(
            target=self._collect, name="serve-collector", daemon=True
        )
        self._collector.start()
        self._watcher = None
        if cfg.serve_reload_interval_s > 0:
            self._watcher = threading.Thread(
                target=self._watch, name="serve-reload", daemon=True
            )
            self._watcher.start()

    # -- submission ------------------------------------------------------

    @property
    def buckets(self) -> tuple[int, ...]:
        return self._ladder.buckets

    @property
    def step(self) -> int:
        """Step of the state CURRENTLY serving (advances at the first
        flush after a reload swap, not when the watcher stages)."""
        return int(self._state.step)

    @property
    def run_id(self) -> str:
        """Telemetry run id of this engine's monitor — the join key
        bench/probe artifacts stamp so they are joinable to the JSONL."""
        return self._monitor.run_id

    def compile_count(self) -> int | None:
        return self._ladder.compile_count()

    @property
    def max_nnz(self) -> int:
        """Static per-row feature width — what a binary-wire client must
        pack frames at (advertised in the hello ack)."""
        return self._score.max_nnz

    @property
    def uses_fields(self) -> bool:
        """Whether the model reads the fields section (ffm/fwfm)."""
        return bool(self._score.uses_fields)

    def submit_line(
        self,
        line: str,
        *,
        klass: str = "",
        deadline_ms: float | None = None,
        deadline_at: float | None = None,
    ) -> Future:
        """Submit one libsvm/libffm line (``label feat:val ...`` — the
        label is required by the grammar and ignored, the exact format of
        predict_files).  Returns a Future resolving to the float score.
        Malformed lines and rows wider than max_nnz raise ValueError in
        the caller (admission is never charged for parse errors).

        ``klass`` names the client class (tier from serve_classes;
        unknown = tier 0, shed first).  ``deadline_ms`` is THIS request's
        deadline from submit time (None = serve_deadline_ms; 0 disables):
        a request still unscored when it expires is shed pre-padding with
        DeadlineExceeded and counted as a deadline_drop.  ``deadline_at``
        (a ``time.monotonic()`` timestamp, same host) wins over both —
        it is how the socket front end anchors the budget at WIRE receipt
        so time spent in TCP buffers and reader backlog counts too; the
        engine converts it to a remaining budget at ingest, so the two
        clocks never need a shared epoch."""
        parsed = parse_lines(
            [line],
            vocabulary_size=self._cfg.vocabulary_size,
            hash_feature_id_flag=self._cfg.hash_feature_id,
            max_nnz=self._score.max_nnz,
        )
        return self._submit_row(
            (
                parsed.ids[0].astype(np.int32, copy=False),
                parsed.vals[0],
                parsed.fields[0],
            ),
            klass=klass,
            deadline_ms=deadline_ms,
            deadline_at=deadline_at,
        )

    def submit(
        self,
        ids,
        vals,
        fields=None,
        *,
        klass: str = "",
        deadline_ms: float | None = None,
        deadline_at: float | None = None,
    ) -> Future:
        """Submit one pre-parsed example (1-D ids/vals[/fields], up to
        max_nnz entries; zero-padded here).  The programmatic twin of
        submit_line for callers that skip text."""
        ids = np.asarray(ids, np.int32).reshape(-1)
        vals = np.asarray(vals, np.float32).reshape(-1)
        w = self._score.max_nnz
        if ids.shape != vals.shape or ids.size > w:
            raise ValueError(
                f"ids/vals must match and carry <= max_nnz={w} entries, "
                f"got {ids.shape} / {vals.shape}"
            )
        v = self._cfg.vocabulary_size
        if ids.size and (int(ids.min()) < 0 or int(ids.max()) >= v):
            # Same range invariant parse_lines enforces on the text path:
            # the jitted gather CLAMPS out-of-bounds ids, which would turn
            # a caller bug into a confidently wrong score from an
            # unrelated embedding row.
            raise ValueError(
                f"feature ids must lie in [0, {v}); got "
                f"[{int(ids.min())}, {int(ids.max())}]"
            )
        fields = (
            np.zeros(ids.shape, np.int32)
            if fields is None
            else np.asarray(fields, np.int32).reshape(-1)
        )
        if fields.shape != ids.shape:
            raise ValueError(f"fields shape {fields.shape} != ids shape {ids.shape}")
        pad = w - ids.size
        if pad:
            ids = np.pad(ids, (0, pad))
            vals = np.pad(vals, (0, pad))
            fields = np.pad(fields, (0, pad))
        return self._submit_row(
            (ids, vals, fields),
            klass=klass,
            deadline_ms=deadline_ms,
            deadline_at=deadline_at,
        )

    def _shed_evicted(self, evicted: "_Request | _Block | None") -> None:
        """Fail an evicted request's future with the typed overload error
        — the no-silent-drop half of tiered admission.  An evicted BLOCK
        resolves (never raises): its per-row statuses flip to overloaded
        so the frame's response stays row-typed."""
        if evicted is None:
            return
        if isinstance(evicted, _Block):
            if evicted.future.set_running_or_notify_cancel():
                st = evicted.statuses.copy()
                st[st == _ST_OK] = _ST_OVERLOADED
                evicted.future.set_result(
                    (st, np.zeros(evicted.n_rows, np.float32))
                )
            for k in evicted.klasses:
                self.metrics.on_evict(k)
            return
        if evicted.future.set_running_or_notify_cancel():
            evicted.future.set_exception(
                OverloadError(
                    f"shed: evicted by a higher-class arrival under overload "
                    f"(class {evicted.klass or 'default'!r}, tier {evicted.tier})"
                )
            )
        self.metrics.on_evict(evicted.klass)

    def submit_block(
        self,
        ids,
        vals,
        fields=None,
        *,
        deadlines_ms=None,
        classes=None,
    ) -> Future:
        """Submit a whole decoded REQUEST frame as ONE admission unit
        (ISSUE 16: one decode, one queue slot, one coalesced placement).

        ``ids``/``vals`` (and optional ``fields``) are (n, width) arrays
        with width <= max_nnz (column-padded here); ``deadlines_ms`` are
        per-row RELATIVE budgets anchored now (0 = serve_deadline_ms
        default).  Returns a Future resolving to ``(statuses, scores)``
        — u8 codes into FRAME_STATUS_CODES and float32 rows.  Frame-level
        shape bugs raise ValueError (a typed bad_request at the wire);
        rows with out-of-range ids fail per-row with bad_request status
        instead of poisoning their whole frame.
        """
        ids = np.asarray(ids, np.int32)
        vals = np.asarray(vals, np.float32)
        w = self._score.max_nnz
        if ids.ndim != 2 or vals.shape != ids.shape:
            raise ValueError(
                f"block ids/vals must be matching (n, width) arrays, got "
                f"{ids.shape} / {vals.shape}"
            )
        n, width = ids.shape
        if n < 1:
            raise ValueError("empty block")
        if n > self.max_batch:
            raise ValueError(
                f"block of {n} rows exceeds max_batch {self.max_batch} — "
                "honor the negotiated max_frame_rows"
            )
        if width > w:
            raise ValueError(f"block width {width} exceeds max_nnz {w}")
        if fields is not None:
            fields = np.asarray(fields, np.int32)
            if fields.shape != ids.shape:
                raise ValueError(
                    f"fields shape {fields.shape} != ids shape {ids.shape}"
                )
        if width < w:
            pad = ((0, 0), (0, w - width))
            ids = np.pad(ids, pad)
            vals = np.pad(vals, pad)
            if fields is not None:
                fields = np.pad(fields, pad)
        v = self._cfg.vocabulary_size
        bad = ((ids < 0) | (ids >= v)).any(axis=1)
        statuses = np.where(bad, np.uint8(_ST_BAD_REQUEST), np.uint8(_ST_OK))
        if classes is None:
            klasses = [""] * n
        else:
            klasses = [str(c or "") for c in classes]
            if len(klasses) != n:
                raise ValueError(f"classes carries {len(klasses)} entries for {n} rows")
        t_submit = time.perf_counter()
        base = self._default_deadline_s
        base_t = (t_submit + base) if (base is not None and base > 0) else np.inf
        if deadlines_ms is None:
            deadline_t = np.full(n, base_t)
        else:
            d = np.asarray(deadlines_ms, np.float64).reshape(-1)
            if d.shape != (n,):
                raise ValueError(f"deadlines_ms carries {d.shape} entries for {n} rows")
            deadline_t = np.where(d > 0, t_submit + d / 1e3, base_t)
        tiers = [self._tiers.get(k, 0) for k in klasses]
        block = _Block(
            ids=ids,
            vals=vals,
            fields=fields,
            deadline_t=deadline_t,
            statuses=statuses,
            klasses=klasses,
            t_submit=t_submit,
            klass=klasses[0] if len(set(klasses)) == 1 else "",
            tier=min(tiers),
        )
        if self._closed:
            raise EngineClosed("engine is closed")
        if self._policy == "reject":
            try:
                self._shed_evicted(self._q.put_nowait(block, tier=block.tier))
            except queue.Full:
                self.metrics.on_submit_many(n, accepted=False, klasses=klasses)
                raise OverloadError(
                    f"admission queue full ({self._q.maxsize} pending) — "
                    "overload; shed load or raise serve_queue_size / switch "
                    "serve_overload to block"
                ) from None
        else:
            while True:
                if self._closed:
                    raise EngineClosed("engine closed while blocked on admission")
                try:
                    self._shed_evicted(self._q.put(block, tier=block.tier, timeout=0.1))
                    break
                except queue.Full:
                    continue
        self.metrics.on_submit_many(n, accepted=True)
        # Same close-race epilogue as _submit_row: see the comment there.
        if self._closed and not self._collector.is_alive():
            self._drain_with_exception(EngineClosed("engine closed"))
        return block.future

    def _submit_row(
        self,
        row,
        *,
        klass: str = "",
        deadline_ms: float | None = None,
        deadline_at: float | None = None,
    ) -> Future:
        req = _Request(row, klass=klass, tier=self._tiers.get(klass, 0))
        if deadline_at is not None:
            # Wire-anchored absolute deadline: convert the REMAINING
            # monotonic budget into this engine's perf_counter terms (one
            # clock read; no shared epoch assumed).  May be <= 0 already —
            # the flush sheds it before padding, which is the point:
            # backlog time upstream of admission counts.
            req.deadline_t = req.t_submit + (deadline_at - time.monotonic())
        else:
            dl = self._default_deadline_s if deadline_ms is None else deadline_ms / 1e3
            if dl is not None and dl > 0:
                req.deadline_t = req.t_submit + dl
        if self._closed:
            raise EngineClosed("engine is closed")
        if self._policy == "reject":
            try:
                self._shed_evicted(self._q.put_nowait(req, tier=req.tier))
            except queue.Full:
                self.metrics.on_submit(accepted=False, klass=klass)
                raise OverloadError(
                    f"admission queue full ({self._q.maxsize} pending) — "
                    "overload; shed load or raise serve_queue_size / switch "
                    "serve_overload to block"
                ) from None
        else:  # block: backpressure, re-checking closure so a shutdown
            # mid-overload can't strand the caller forever.  (A strictly
            # lower-tier queued request is still evicted rather than
            # blocking the higher-class arrival behind shed-able traffic.)
            while True:
                if self._closed:
                    raise EngineClosed("engine closed while blocked on admission")
                try:
                    self._shed_evicted(self._q.put(req, tier=req.tier, timeout=0.1))
                    break
                except queue.Full:
                    continue
        self.metrics.on_submit(accepted=True, klass=klass)
        # Close-race epilogue: if close() finished its drain between our
        # closed-check and our enqueue, nobody will ever pop this request.
        # _closed is set BEFORE close joins/drains, so observing it here
        # (after the put) and draining ourselves closes the window — the
        # drain fails our own future with EngineClosed instead of
        # stranding the caller.
        if self._closed and not self._collector.is_alive():
            self._drain_with_exception(EngineClosed("engine closed"))
        return req.future

    # -- collector -------------------------------------------------------

    def _collect(self) -> None:
        pending: list[_Request | _Block] = []
        rows = 0  # real rows across `pending` (a block counts its n)
        deadline = 0.0
        draining = False
        try:
            while True:
                if pending and rows >= self.max_batch:
                    self._flush(pending, deadline_fired=False)
                    pending = []
                    rows = 0
                    continue
                timeout = None
                if pending:
                    timeout = deadline - time.perf_counter()
                    if timeout <= 0:
                        # Deadline expired: top up with already-QUEUED
                        # requests first.  Under backlog the oldest
                        # request's deadline is often already past when
                        # it is popped; flushing it alone would collapse
                        # micro-batching to singleton dispatches exactly
                        # when load is highest.
                        while rows < self.max_batch:
                            try:
                                extra = self._q.get_nowait()
                            except queue.Empty:
                                break
                            if extra is _CLOSE:
                                draining = True
                                break
                            pending.append(extra)
                            rows += extra.n_rows
                        self._flush(
                            pending,
                            deadline_fired=rows < self.max_batch,
                        )
                        pending = []
                        rows = 0
                        continue
                elif draining:
                    # Close requested and everything flushed: done.
                    return
                try:
                    item = self._q.get(timeout=timeout)
                except queue.Empty:
                    continue
                if item is _CLOSE:
                    # Flush what's pending plus anything still queued, in
                    # max_batch groups, then exit.
                    draining = True
                    deadline = time.perf_counter()  # expire immediately
                    continue
                if not pending:
                    # Deadline anchors at the oldest request's SUBMIT
                    # time (the documented contract), so time it spent in
                    # the admission queue behind a busy flush counts
                    # against the budget — not just time in `pending`.
                    deadline = item.t_submit + self.deadline_s
                pending.append(item)
                rows += item.n_rows
        except BaseException as e:  # never strand submitted futures
            # Mark the engine closed FIRST: with a dead collector, a
            # block-policy submit would otherwise spin on the full queue
            # forever (nothing consumes, nothing raises).  Stop the
            # watcher too — it would keep doing full restores every tick
            # on an engine that can no longer serve.
            self._closed = True
            self._stop.set()
            for r in pending:
                if not r.future.done():
                    r.future.set_exception(e)
            self._drain_with_exception(e)
            raise
        finally:
            self._drain_with_exception(EngineClosed("engine closed"))

    def _drain_with_exception(self, exc: BaseException) -> None:
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is not _CLOSE and not item.future.done():
                item.future.set_exception(exc)

    def _flush(self, pending: list[_Request], deadline_fired: bool) -> None:
        # Atomic reload swap: flushes are the only reader of _state, so
        # swapping here means every request in THIS flush (and all later
        # ones) scores against one consistent checkpoint.
        with self._reload_lock:
            staged, self._staged_state = self._staged_state, None
            staged_step = self._staged_step
            staged_is_delta = self._staged_is_delta
            staged_pub_t = self._staged_pub_t
        if staged is not None:
            self._state = staged
            if staged_pub_t is not None:
                # publish→applied is sealed HERE (the swap is the apply);
                # publish→first-scored completes when this (or, if this
                # flush is all-shed/fails, a later) flush resolves scores.
                self._pending_fresh = {
                    "published_at": staged_pub_t,
                    "applied_ms": max(0.0, (time.time() - staged_pub_t) * 1e3),
                    "step": staged_step,
                    "mode": "delta" if staged_is_delta else "full",
                }
            if not staged_is_delta:
                # Delta swaps are already counted (per FILE) by
                # on_delta_reload — keeping them out of `reloads` keeps
                # the two counters independent: reloads = full re-reads.
                self.metrics.on_reload(ok=True)
            log_quietly(self._log, f"serving: swapped in checkpoint step {staged_step}")
        # Blocks make `pending` row counts lumpy: a close-time drain (or
        # a block-heavy top-up) can exceed max_batch rows, which has no
        # compiled shape.  Partition into <=max_batch-row groups; a
        # single block never exceeds max_batch (submit_block enforces).
        chunk: list[_Request | _Block] = []
        chunk_rows = 0
        for item in pending:
            if chunk and chunk_rows + item.n_rows > self.max_batch:
                self._flush_units(chunk, deadline_fired)
                chunk = []
                chunk_rows = 0
            chunk.append(item)
            chunk_rows += item.n_rows
        if chunk:
            self._flush_units(chunk, deadline_fired)

    def _flush_units(
        self, pending: "list[_Request | _Block]", deadline_fired: bool
    ) -> None:
        # Claim the futures: a pending Future is always cancellable, and
        # resolving a cancelled one raises InvalidStateError — which,
        # unguarded, would kill the collector over ONE impatient caller.
        # set_running_or_notify_cancel() both blocks late cancels and
        # filters already-cancelled requests out of the batch.
        pending = [r for r in pending if r.future.set_running_or_notify_cancel()]
        # Deadline shed BEFORE padding: a request whose own deadline has
        # already expired cannot be answered in time — scoring it would
        # only inflate the bucket (and the batch's latency) for an answer
        # nobody is waiting for.  Shedding first can also shrink the
        # bucket the survivors pad to (the bucket is picked AFTER the
        # shed, over the whole coalesced flush).
        now = time.perf_counter()
        reqs: list[_Request] = []  # live per-row requests, in order
        blocks: list[tuple[_Block, np.ndarray]] = []  # (block, alive idx)
        n_alive = 0
        for r in pending:
            if isinstance(r, _Block):
                st = r.statuses
                expired = (now >= r.deadline_t) & (st == _ST_OK)
                if expired.any():
                    st[expired] = _ST_DEADLINE
                    for i in np.flatnonzero(expired):
                        self.metrics.on_deadline_drop(r.klasses[int(i)])
                alive = np.flatnonzero(st == _ST_OK)
                blocks.append((r, alive))
                n_alive += int(alive.size)
                continue
            if r.deadline_t is not None and now >= r.deadline_t:
                r.future.set_exception(
                    DeadlineExceeded(
                        f"deadline expired {1e3 * (now - r.deadline_t):.1f}ms "
                        f"before scoring (waited {1e3 * (now - r.t_submit):.1f}ms)"
                    )
                )
                self.metrics.on_deadline_drop(r.klass)
            else:
                reqs.append(r)
                n_alive += 1
        if n_alive == 0:
            # Every row shed — blocks still owe their ONE response (the
            # shed rows' typed codes travel in it).  Still PROGRESS: the
            # collector drained (and answered) work — an all-shed flush
            # must advance the liveness clock or a tight-deadline
            # overload reads as a wedged collector to the router's
            # health checks.
            for b, _ in blocks:
                b.future.set_result((b.statuses, np.zeros(b.n_rows, np.float32)))
            self._last_flush_t = time.perf_counter()
            return
        if self._slow_flushes > 0:  # injected latency (chaos replica_slow)
            self._slow_flushes -= 1
            time.sleep(self._slow_ms / 1e3)
        t_start = time.perf_counter()
        try:
            parts = [(r.row[0][None], r.row[1][None], r.row[2][None]) for r in reqs]
            parts += [
                (
                    b.ids[alive],
                    b.vals[alive],
                    b.fields[alive] if b.fields is not None else None,
                )
                for b, alive in blocks
            ]
            batch, bucket = self._ladder.assemble_parts(parts)
            t_dispatch = time.perf_counter()
            scores = np.asarray(self._ladder.score(self._state, batch))
            t_done = time.perf_counter()
        except BaseException as e:
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
            for b, alive in blocks:
                if not b.future.done():
                    # Blocks resolve, never raise: already-decided rows
                    # (deadline/bad_request) keep their codes; only the
                    # would-have-scored rows become unavailable.
                    st = b.statuses.copy()
                    st[alive] = _ST_UNAVAILABLE
                    b.future.set_result((st, np.zeros(b.n_rows, np.float32)))
            log_quietly(self._log, f"serving: flush failed: {e!r}")
            self._last_flush_t = time.perf_counter()  # answered = progress
            return
        pos = 0
        for r in reqs:
            r.future.set_result(float(scores[pos]))
            pos += 1
        for b, alive in blocks:
            out = np.zeros(b.n_rows, np.float32)
            out[alive] = scores[pos : pos + alive.size]
            pos += int(alive.size)
            b.future.set_result((b.statuses, out))
        t_resolved = time.perf_counter()
        self._flush_seq += 1
        if self._pending_fresh is not None:
            self._emit_freshness()
        try:
            self._monitor.on_dispatch(self._flush_seq)
        except (OSError, ValueError):
            # Same stance as the metrics writes below: a telemetry I/O
            # failure (ENOSPC mem record) degrades to a lost record —
            # it must NEVER kill the collector.
            pass
        self._last_flush_t = t_resolved
        # One metrics group per request plus one per BLOCK: a frame's
        # rows share submit/resolve instants, so its group carries a row
        # count instead of n duplicate histogram insertions.
        self.metrics.on_flush(
            bucket,
            n_alive,
            queue_waits=[t_start - r.t_submit for r in reqs]
            + [t_start - b.t_submit for b, _ in blocks],
            compute_s=t_done - t_dispatch,
            total_s=[t_resolved - r.t_submit for r in reqs]
            + [t_resolved - b.t_submit for b, _ in blocks],
            deadline_fired=deadline_fired,
            classes=[r.klass for r in reqs] + [b.klass for b, _ in blocks],
            counts=[1] * len(reqs) + [int(alive.size) for _, alive in blocks],
        )
        if (
            self._metrics_every > 0
            and t_resolved - self._last_metrics_log >= self._metrics_every
        ):
            self._last_metrics_log = t_resolved
            try:
                self.metrics.log_to(self._monitor)
            except (OSError, ValueError):
                # A full metrics disk (ENOSPC) must degrade to lost
                # metrics records, never to a dead collector: every
                # request behind a dead collector hangs or blocks.
                pass

    def _emit_freshness(self) -> None:
        """Seal one reload's freshness SLO: publish→applied was measured
        at the swap; publish→first-scored-with-new-rows completes now,
        at the first flush that RESOLVED scores against the new state.
        Collector thread only (it owns _pending_fresh after the swap)."""
        f, self._pending_fresh = self._pending_fresh, None
        scored_ms = max(0.0, (time.time() - f["published_at"]) * 1e3)
        self.metrics.on_freshness(f["applied_ms"] / 1e3, scored_ms / 1e3)
        try:
            self._monitor.emit(
                "freshness",
                step=self._flush_seq,
                publish_step=f["step"],
                publish_to_applied_ms=round(f["applied_ms"], 3),
                publish_to_first_scored_ms=round(scored_ms, 3),
                mode=f["mode"],
            )
        except (OSError, ValueError):
            pass  # a full metrics disk must not kill the collector

    # -- hot reload ------------------------------------------------------

    def _chain_baseline(self) -> tuple[str | None, int]:
        """(base save_id, delta-chain length) of the on-disk checkpoint,
        tolerant of anything unreadable (None/0 just means the in-place
        delta path stays off until the next full reload)."""
        import os as _os

        path = self._cfg.model_file
        if _os.path.isdir(path):
            return None, 0
        try:
            sid = checkpoint_save_id(path)
            _, chain = read_delta_chain(path)
            return sid, len(chain)
        except (ValueError, OSError):
            return checkpoint_save_id(path), 0

    def _apply_delta_state(self, state, delta):
        """Functional in-place apply of ONE delta to a serving state:
        scatter the logical rows into the (rows or plain-packed) table,
        swap the dense leaves, advance the step.  Never donates — the
        collector may be mid-flush on the current buffers.  (Optimizer
        accumulators are not updated: scoring never reads them, and the
        next full reload replaces them.)"""
        import jax
        import jax.numpy as jnp

        idx = delta["idx"]
        table = state.table
        if idx.size:
            i32 = jnp.asarray(idx.astype(np.int32))
            rows = jnp.asarray(delta["table_rows"])
            if self._cfg.table_layout == "packed":
                from fast_tffm_tpu.ops.packed_table import scatter_logical_rows

                table = scatter_logical_rows(
                    table, i32, rows, self._score.model.row_dim
                )
            else:
                table = table.at[i32].set(rows, mode="drop")
        dense = state.dense
        leaves, ddef = jax.tree.flatten(state.dense)
        if leaves:
            dense = jax.tree.unflatten(
                ddef, [jnp.asarray(x) for x in delta["dense"]]
            )
        return state._replace(
            table=table, dense=dense, step=jnp.asarray(delta["step"])
        )

    def _try_apply_deltas(self):
        """In-place incremental reload: when the on-disk base is STILL the
        one this engine loaded and only new delta files landed, apply the
        unapplied suffix to the current state and return (staged_state,
        n_applied) — no full-table re-read.  Returns None when the base
        changed (full reload required) and (None, 0) when nothing new."""
        import jax

        base_sig, chain = read_delta_chain(self._cfg.model_file)
        if (
            self._loaded_save_id is None
            or base_sig != self._loaded_save_id
        ):
            return None  # new (or unsigned) base: take the full-reload path
        new = chain[self._applied_deltas :]
        if not new:
            return (None, 0)
        state = self._state
        n_dense = len(jax.tree.leaves(state.dense))
        for meta in new:
            state = self._apply_delta_state(
                state, load_delta(meta["path"], n_dense)
            )
        return (state, len(new))

    def _note_reload_failure(self, sig, what, exc) -> None:
        """Failure discipline for ONE observed signature: retries back
        off exponentially from the poll interval, and after
        serve_reload_max_retries consecutive failures the engine GIVES
        UP on that signature (reload_giveups counter + kind=anomaly
        record) instead of hot-spinning reload_failures forever on a
        persistently corrupt file.  Any NEW write (signature change)
        resets the state and retries immediately."""
        self.metrics.on_reload(ok=False)
        if sig != self._fail_sig:
            self._fail_sig, self._fail_count, self._gave_up = sig, 0, False
        self._fail_count += 1
        backoff = min(
            max(self._cfg.serve_reload_interval_s, 0.01) * (2.0 ** self._fail_count),
            60.0,
        )
        self._next_retry_t = time.monotonic() + backoff
        self._log(
            f"serving: {what} of {self._cfg.model_file} failed "
            f"(attempt {self._fail_count}/{self._cfg.serve_reload_max_retries}, "
            f"next retry in {backoff:.2f}s): {exc!r}"
        )
        if self._fail_count >= self._cfg.serve_reload_max_retries:
            self._gave_up = True
            self.metrics.on_reload_giveup()
            try:
                self._monitor.emit_anomaly(
                    self.step, None, event="reload_giveup",
                    path=self._cfg.model_file, error=repr(exc),
                    attempts=self._fail_count,
                )
            except (OSError, ValueError):
                pass  # a full metrics disk must not kill the watcher
            self._log(
                f"serving: giving up on this checkpoint write after "
                f"{self._fail_count} failed reloads — persistently corrupt? "
                "serving continues on the loaded state; a NEW write "
                "will be retried"
            )

    def _reload_tick(self) -> str:
        """One reload attempt: check the signature, stage a new state if
        one landed.  Called by the polling watcher thread (its loop body)
        and by ``reload_once`` (a router fanning out ONE reload command
        to every replica).  Returns the outcome for the caller's ack:
        ``noop`` | ``staged`` | ``staged_delta`` | ``failed`` |
        ``backoff`` | ``busy``.

        Whole-tick serialization (``_tick_lock``): a second caller landing
        while a tick is mid-apply BLOCKS until that apply completes, then
        observes the advanced chain state — a delta published while the
        watcher is mid-apply of its parent QUEUES behind it instead of
        racing the bookkeeping (apply-in-order under continuous publish)."""
        with self._tick_lock:
            with self._reload_lock:
                if self._staged_state is not None:
                    # The collector hasn't swapped the previous stage yet;
                    # applying deltas onto _state now would drop that stage.
                    return "busy"
            sig = checkpoint_signature(self._cfg.model_file)
            if sig is None or sig == self._loaded_sig:
                return "noop"
            if sig == self._fail_sig:
                if self._gave_up or time.monotonic() < self._next_retry_t:
                    return "backoff"  # backing off / abandoned until a new write
            else:
                self._fail_sig, self._fail_count, self._gave_up = None, 0, False
            with self._monitor.warmup_window():
                return self._reload_attempt(sig)

    def _reload_attempt(self, sig) -> str:
        """The actual restore/apply work of one reload tick.  Runs inside
        a telemetry warmup_window: the chunked-restore and delta-apply
        programs it may compile execute OFF the hot path (the collector
        keeps flushing the old state), so they must not read as
        steady-state score recompiles."""
        import os as _os

        from fast_tffm_tpu.prediction import load_scoring_state

        # Freshness stamp captured BEFORE the (possibly multi-second)
        # restore: it names the chain head observed at attempt start.  A
        # publish landing mid-restore can only make the measured latency
        # OVERSTATE staleness (older stamp vs whatever got restored) —
        # the safe error direction for an SLO; reading after the restore
        # would attribute the staged (older) state to the newer publish.
        pub_t = read_publish_time(self._cfg.model_file)
        state = None
        applied = 0
        if not _os.path.isdir(self._cfg.model_file):
            try:
                got = self._try_apply_deltas()
            except Exception as e:
                # Torn/mid-write delta: count, keep serving, retry with
                # backoff (signature not advanced, so a complete write
                # still reloads).
                self._note_reload_failure(sig, "delta reload", e)
                return "failed"
            if got == (None, 0):
                # Signature moved without new chain content (e.g. a
                # same-base rewrite mid-observation) — nothing to do.
                self._loaded_sig = sig
                return "noop"
            if got is not None:
                state, applied = got
        if state is None:
            # Full restore OFF the hot path: the collector keeps serving
            # the old state while this loads.  Chain baseline is read
            # PRE-restore (under-count = safe, see above).
            new_sid, new_applied = self._chain_baseline()
            try:
                _, state = load_scoring_state(self._cfg, log=lambda *_: None)
            except Exception as e:
                # Torn write (non-atomic writer, or a checkpoint
                # mid-copy): count it, keep serving, back off.
                self._note_reload_failure(sig, "reload", e)
                return "failed"
            self._loaded_save_id = new_sid
            self._applied_deltas = new_applied
        else:
            self._applied_deltas += applied
            self.metrics.on_delta_reload(applied)
        self._fail_sig, self._fail_count, self._gave_up = None, 0, False
        self._loaded_sig = sig
        with self._reload_lock:
            self._staged_state = state
            self._staged_step = int(state.step)
            self._staged_is_delta = applied > 0
            self._staged_pub_t = pub_t
        return "staged_delta" if applied > 0 else "staged"

    def reload_once(self) -> dict:
        """Router-driven reload: one watcher tick on the CALLER's thread
        (the replica worker runs it off its reader loop).  The in-process
        polling watcher stays off (serve_reload_interval_s = 0) when a
        router owns reload fan-out — exactly one of the two drives
        reloads, so a delta is applied exactly once per replica."""
        status = self._reload_tick()
        return {"status": status, "step": self.step}

    def _watch(self) -> None:
        while not self._stop.wait(self._cfg.serve_reload_interval_s):
            self._reload_tick()

    # -- health / chaos ----------------------------------------------------

    def inject_slow(self, ms: float, flushes: int = 1) -> None:
        """Chaos hook (FaultPlan replica_slow@N:ms): make the next
        ``flushes`` flushes sleep ``ms`` before dispatch — a degraded or
        wedged replica, without touching real scoring."""
        self._slow_ms = float(ms)
        self._slow_flushes = int(flushes)

    def health(self) -> dict:
        """O(1) liveness probe for routers/load balancers: queue depth,
        age of the oldest QUEUED request (keeps growing when the
        collector wedges — the router's wedge signal), time since the
        last completed flush, and whether the engine still accepts."""
        now = time.perf_counter()
        oldest = self._q.oldest_wait_s(now)
        return {
            "ok": not self._closed,
            "closed": self._closed,
            "step": self.step,
            "queue_depth": self._q.qsize(),
            "oldest_wait_s": round(oldest, 4) if oldest is not None else None,
            "last_flush_age_s": round(now - self._last_flush_t, 4),
            "steady_compiles": self._monitor.compiles_steady,
        }

    # -- shutdown --------------------------------------------------------

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting, flush everything already admitted, stop the
        threads, write the final metrics record.  Idempotent."""
        if self._close_done:
            return
        self._close_done = True
        self._closed = True
        self._stop.set()
        # The sentinel bypasses the admission bound (put_sentinel), so a
        # full queue — or a dead collector behind one — can never block
        # close(); a dead collector's exit drain clears it regardless.
        self._q.put_sentinel(_CLOSE)
        self._collector.join(timeout=timeout)
        # A submit that passed the closed-check concurrently with this
        # close can enqueue AFTER the collector's exit drain — fail its
        # future rather than strand the caller (submit re-checks too).
        self._drain_with_exception(EngineClosed("engine closed"))
        if self._watcher is not None:
            self._watcher.join(timeout=timeout)
        try:
            # Same stance as the in-flush writes: a metrics I/O failure
            # (ENOSPC) degrades to a lost record, it must not turn an
            # otherwise-successful serve run into a nonzero exit.
            self.metrics.log_to(self._monitor)
        except (OSError, ValueError):
            pass
        finally:
            try:
                self._monitor.close()
            except (OSError, ValueError):
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def serve_lines(cfg: Config, lines=None, out=None, log=print) -> int:
    """The ``serve`` CLI verb: stream libsvm lines (default stdin) through
    a ServingEngine, writing one ``%.6f`` score per input line in input
    order — wire-compatible with predict's score file, but micro-batched
    through the online path.  A bounded future window keeps memory flat on
    arbitrarily long input; under serve_overload = reject the writer is
    its own load-shedder (drains a result, retries) so file-fed serving
    never drops a line."""
    import sys
    from collections import deque

    lines = sys.stdin if lines is None else lines
    out = sys.stdout if out is None else out
    window: deque = deque()
    n = 0

    def write_next(block: bool = True) -> bool:
        """Pop-and-write the oldest future; False when it isn't done yet
        (non-blocking mode) or nothing is in flight."""
        nonlocal n
        if not window or (not block and not window[0].done()):
            return False
        out.write(f"{window.popleft().result():.6f}\n")
        n += 1
        return True

    with ServingEngine(cfg, log=log) as engine:
        cap = max(4 * engine.max_batch, 1024)
        for line in lines:
            line = line.strip()
            if not line:
                continue
            while True:
                try:
                    window.append(engine.submit_line(line))
                    break
                except OverloadError:
                    if not write_next():  # nothing of ours in flight:
                        time.sleep(engine.deadline_s or 0.001)
            # Opportunistic in-order drain: a LIVE stream (slow stdin
            # producer) must see each score as soon as it resolves, not
            # in cap-sized bursts at EOF.
            wrote = False
            while write_next(block=False):
                wrote = True
            while len(window) >= cap:  # bound memory on a fast producer
                wrote = write_next() or wrote
            if wrote:
                out.flush()
        while write_next():
            pass
        out.flush()
        snap = engine.metrics_snapshot()
    log(
        f"served {n} scores: occupancy {snap['batch_occupancy']}, "
        f"p50/p99 total {snap['total_ms'].get('p50')}/"
        f"{snap['total_ms'].get('p99')}ms, reloads {snap['reloads']}"
    )
    return 0
