"""Online serving subsystem: micro-batched, bucket-compiled inference,
replicated behind a socket front end.

The offline drivers (prediction.py) stream whole files; this package is
the low-latency ONLINE path the ROADMAP north star ("serves heavy traffic
from millions of users") asks for.

Pieces (DESIGN.md "Serving" + "Serving resilience"):

  * ``BucketLadder`` (buckets.py) — predict functions pre-compiled at a
    ladder of batch sizes; requests pad up to the nearest bucket so no
    request ever triggers a fresh XLA compile in steady state;
  * ``ServingEngine`` (engine.py) — micro-batching collector (flush on
    ``serve_max_batch`` or the ``serve_flush_deadline_ms`` timer),
    tiered admission (admission.py: shed-by-class eviction under
    overload), per-request deadlines shed before bucket padding, hot
    checkpoint reload with atomic swap between flushes;
  * ``ServingMetrics`` (metrics.py) — queue/compute latency histograms
    (p50/p95/p99, per client class), occupancy, shed/drop/reload
    counters, exported through the telemetry JSONL path;
  * the replicated tier (protocol.py, replica.py, router.py,
    frontend.py) — a TCP front end (`serve --port`) multiplexing onto N
    engine worker processes behind a health-checked router: failover
    with one bit-identical retry, bounded-backoff replica restart with
    MTTR telemetry, one checkpoint watcher fanning reloads to all
    replicas, typed wire errors (overloaded | deadline | bad_request |
    unavailable) — never a silently dropped connection.

``tools/loadgen.py`` drives either transport (in-process, or the socket
tier via --connect/--spawn) and emits a BENCH_SERVE JSON; ``tools/
chaos.py --serve`` kills/slows/corrupts replicas under live traffic and
pins the no-hung-client + bit-identical-scores acceptance.
"""

from fast_tffm_tpu.serving.admission import AdmissionQueue
from fast_tffm_tpu.serving.buckets import BucketLadder, validate_buckets
from fast_tffm_tpu.serving.engine import (
    DeadlineExceeded,
    EngineClosed,
    OverloadError,
    ServingEngine,
    serve_lines,
)
from fast_tffm_tpu.serving.metrics import LatencyHistogram, ServingMetrics
from fast_tffm_tpu.serving.protocol import (
    BadRequest,
    Overloaded,
    Unavailable,
    WireError,
)

__all__ = [
    "AdmissionQueue",
    "BadRequest",
    "BucketLadder",
    "DeadlineExceeded",
    "EngineClosed",
    "LatencyHistogram",
    "Overloaded",
    "OverloadError",
    "ServingEngine",
    "ServingMetrics",
    "Unavailable",
    "WireError",
    "serve_lines",
    "validate_buckets",
]
