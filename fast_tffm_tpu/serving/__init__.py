"""Online serving subsystem: micro-batched, bucket-compiled inference.

The offline drivers (prediction.py) stream whole files; this package is
the low-latency ONLINE path the ROADMAP north star ("serves heavy traffic
from millions of users") asks for.  In-process, no network layer — a
transport (gRPC/HTTP) would wrap ``ServingEngine.submit_line`` without
touching anything here.

Pieces (DESIGN.md "Serving"):

  * ``BucketLadder`` (buckets.py) — predict functions pre-compiled at a
    ladder of batch sizes; requests pad up to the nearest bucket so no
    request ever triggers a fresh XLA compile in steady state;
  * ``ServingEngine`` (engine.py) — micro-batching collector (flush on
    ``serve_max_batch`` or the ``serve_flush_deadline_ms`` timer),
    bounded admission queue (block | reject), hot checkpoint reload with
    atomic swap between flushes;
  * ``ServingMetrics`` (metrics.py) — queue/compute latency histograms
    (p50/p95/p99), batch occupancy, reload counters, exported through the
    existing utils.tracing.MetricsLogger JSONL path.

``tools/loadgen.py`` drives the engine open-loop (Poisson) or closed-loop
and emits a BENCH_SERVE JSON, the serving analog of bench.py's train
BENCH files.
"""

from fast_tffm_tpu.serving.buckets import BucketLadder, validate_buckets
from fast_tffm_tpu.serving.engine import (
    EngineClosed,
    OverloadError,
    ServingEngine,
    serve_lines,
)
from fast_tffm_tpu.serving.metrics import LatencyHistogram, ServingMetrics

__all__ = [
    "BucketLadder",
    "EngineClosed",
    "LatencyHistogram",
    "OverloadError",
    "ServingEngine",
    "ServingMetrics",
    "serve_lines",
    "validate_buckets",
]
