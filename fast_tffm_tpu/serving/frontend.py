"""Socket front end: the network door to the replicated serving tier.

A stdlib ``socketserver.ThreadingTCPServer`` speaking the JSONL wire
protocol (protocol.py): clients connect, pipeline any number of
requests, and read responses keyed by their own ``id`` (out-of-order —
micro-batching and failover reorder).  Every request gets exactly one
response line: a score or a typed error code; admission control
(deadlines, tiered shed) runs in the replica engines, so the front end
stays a thin multiplexer that never holds state a failover would lose.

    python fast_tffm.py serve run.cfg --port 0     # ephemeral, announced
    # [Serving] port/replicas in the config for a fixed deployment

On startup it spawns the router (which spawns and warms the replicas)
BEFORE binding, then announces::

    SERVE_READY port=<port> pid=<pid> replicas=<n>

on stdout — the line tools/loadgen.py --spawn and tools/chaos.py --serve
block on.  Ops: ``ping`` (cheap router snapshot), ``stats`` (router +
per-replica engine metrics), ``slow`` (chaos latency injection,
forwarded to one replica).
"""

from __future__ import annotations

import os
import socketserver
import sys
import threading
import time

from fast_tffm_tpu.serving.protocol import (
    SERVE_READY_PREFIX,
    BadRequest,
    decode,
    encode,
    error_response,
)
from fast_tffm_tpu.serving.router import Router

__all__ = ["Frontend", "run_frontend"]


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        router: Router = self.server.router  # type: ignore[attr-defined]
        wlock = threading.Lock()
        inflight = threading.Semaphore(self.server.max_pipeline)  # type: ignore[attr-defined]

        def send(obj: dict) -> None:
            try:
                with wlock:
                    self.wfile.write(encode(obj))
                    self.wfile.flush()
            except (OSError, ValueError):
                # Client went away; late future callbacks land on a
                # CLOSED wfile, which raises ValueError (not OSError) —
                # both just mean nobody is listening anymore.
                pass

        for raw in self.rfile:
            raw = raw.strip()
            if not raw:
                continue
            try:
                msg = decode(raw)
            except BadRequest as e:
                send(error_response(None, e))
                continue
            req_id = msg.get("id")
            if "line" in msg:
                # Anchor the deadline budget HERE, at wire receipt: an
                # absolute monotonic deadline travels with the request,
                # so seconds spent in TCP buffers or a backlogged replica
                # reader count against it — under overload the request is
                # shed typed instead of scored uselessly late.
                dl_ms = msg.get("deadline_ms")
                if dl_ms is None:
                    dl_ms = self.server.default_deadline_ms  # type: ignore[attr-defined]
                deadline_at = (
                    time.monotonic() + float(dl_ms) / 1e3 if dl_ms else None
                )
                # Per-connection pipeline bound: a client blasting faster
                # than the tier sheds would otherwise grow the router's
                # pending maps without limit.  Waiting here is plain TCP
                # backpressure on that one client.
                inflight.acquire()
                try:
                    fut = router.submit(
                        str(msg["line"]),
                        klass=str(msg.get("class", "") or ""),
                        deadline_at=deadline_at,
                    )
                except Exception as e:
                    inflight.release()
                    send(error_response(req_id, e))
                    continue

                def done(f, req_id=req_id):
                    inflight.release()
                    exc = f.exception()
                    if exc is None:
                        send({"id": req_id, "score": f.result()})
                    else:
                        send(error_response(req_id, exc))

                fut.add_done_callback(done)
                continue
            op = msg.get("op")
            try:
                if op == "hello":
                    # Wire negotiation + placement (ISSUE 16).  The ack
                    # names the wire the tier allows; with affinity on it
                    # also hands the client a healthy replica's port to
                    # pin its DATA connection to — the replica answers
                    # directly and the front end / router drop out of the
                    # score path.  On that replica's death the CLIENT
                    # re-hellos here for a peer (retry-once-on-peer).
                    want = str(msg.get("wire", "jsonl") or "jsonl").lower()
                    wire = self.server.wire  # type: ignore[attr-defined]
                    ack = {
                        "id": req_id,
                        "ok": True,
                        "op": "hello",
                        "wire": "binary" if (want == "binary" and wire == "binary") else "jsonl",
                        "affinity": self.server.affinity,  # type: ignore[attr-defined]
                    }
                    if self.server.affinity:  # type: ignore[attr-defined]
                        idx, rport = router.assign()
                        ack["replica"] = idx
                        ack["port"] = rport
                    send(ack)
                elif op == "ping":
                    send({"id": req_id, "ok": True, "op": "ping", **router.snapshot()})
                elif op == "stats":
                    send({"id": req_id, "ok": True, "op": "stats", **router.stats()})
                elif op == "slow":
                    ack = router.admin(
                        int(msg.get("replica", 0)),
                        "slow",
                        ms=float(msg.get("ms", 0.0)),
                        flushes=int(msg.get("flushes", 1)),
                    )
                    send({"id": req_id, "ok": True, "op": "slow", "ack": ack})
                else:
                    send(error_response(req_id, BadRequest(f"unknown op {op!r}")))
            except Exception as e:
                send(error_response(req_id, e))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class Frontend:
    """Bind, serve on a background thread, introspect the real port
    (``port = 0`` = ephemeral — the collision-proof default for tests)."""

    def __init__(
        self,
        router: Router,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        max_pipeline: int = 1024,
        default_deadline_ms: float = 0.0,
        wire: str = "binary",
        affinity: bool = True,
    ):
        self._srv = _Server((host, port), _Handler)
        self._srv.router = router  # type: ignore[attr-defined]
        self._srv.max_pipeline = max_pipeline  # type: ignore[attr-defined]
        self._srv.default_deadline_ms = float(default_deadline_ms)  # type: ignore[attr-defined]
        self._srv.wire = wire  # type: ignore[attr-defined]
        self._srv.affinity = bool(affinity)  # type: ignore[attr-defined]
        self.host, self.port = self._srv.server_address[:2]
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="serve-frontend",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5.0)


def run_frontend(cfg, config_path: str, *, port: int | None = None, log=None) -> int:
    """The ``serve`` CLI verb's socket mode: router + replicas + front
    end, running until SIGTERM/SIGINT.  ``port`` overrides [Serving]
    port (0 = ephemeral)."""
    log = log or (lambda *a: print(*a, file=sys.stderr))
    stop = threading.Event()
    router = Router(
        cfg, config_path=config_path, run_id=cfg.telemetry_run_id, log=log
    )
    try:
        fe = Frontend(
            router,
            port=cfg.serve_port if port is None else port,
            default_deadline_ms=cfg.serve_deadline_ms,
            wire=cfg.serve_wire,
            affinity=cfg.serve_affinity,
        )
    except Exception:
        router.close()
        raise
    try:
        import signal as _signal

        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                _signal.signal(sig, lambda *_: stop.set())
            except (ValueError, OSError):
                pass  # not the main thread (tests drive run_frontend directly)
        n = len(router.slots)
        log(
            f"serving: front end listening on {fe.host}:{fe.port} "
            f"({n} replica(s), run_id {router.run_id})"
        )
        print(
            f"{SERVE_READY_PREFIX}port={fe.port} pid={os.getpid()} replicas={n}",
            flush=True,
        )
        stop.wait()
        log("serving: front end shutting down")
        return 0
    finally:
        fe.close()
        router.close()
