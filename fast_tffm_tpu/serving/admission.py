"""Tiered admission queue: bounded FIFO with shed-by-class eviction.

The engine's admission queue is the ONLY elastic buffer between the
socket and the device, so overload policy lives here.  A plain bounded
queue degrades uniformly — the 100th free-tier request and the first
gold-tier request are rejected alike.  This queue degrades by PRIORITY:
when full, an arriving request may EVICT a queued request of a strictly
lower tier (the oldest of the lowest tier present), so overload sheds
the cheapest traffic first and gold requests only start failing once
nothing below them is left to shed.

FIFO within the bound (tier never reorders service — a queued gold
request behind ten std requests still waits its turn; tiers only decide
who gets SHED, not who gets served first, which keeps latency fair and
the shed policy orthogonal).  queue.Full / queue.Empty are reused so
callers keep stdlib-queue idioms.  Thread-safe; jax-free.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque

__all__ = ["AdmissionQueue"]


class AdmissionQueue:
    """Bounded FIFO of (tier, item) with lowest-tier-first eviction.

    ``put_nowait``/``put`` return the EVICTED item (or None) instead of
    silently dropping it — the caller owns failing its future with a
    typed Overloaded error and counting the shed.
    """

    def __init__(self, maxsize: int):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = int(maxsize)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._d: deque = deque()  # entries: (tier, item); sentinel tier None

    def qsize(self) -> int:
        with self._lock:
            return len(self._d)

    def oldest_wait_s(self, now: float | None = None) -> float | None:
        """Age of the oldest queued item carrying a ``t_submit`` attr —
        the health probe a router uses to spot a wedged collector (the
        queue keeps aging when nothing downstream drains it)."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            for _, item in self._d:
                t = getattr(item, "t_submit", None)
                if t is not None:
                    return now - t
        return None

    # -- producers ---------------------------------------------------------

    def _try_admit(self, item, tier: int):
        """Lock held.  Returns (admitted, evicted)."""
        if len(self._d) < self.maxsize:
            self._d.append((tier, item))
            self._not_empty.notify()
            return True, None
        # Full: shed the OLDEST entry of the LOWEST tier strictly below
        # the arrival's.  Oldest-of-lowest is deterministic and sheds the
        # entry most likely to be stale by the time it would flush.
        victim_i = victim_tier = None
        for i, (t, entry) in enumerate(self._d):
            if t is None or t >= tier:  # sentinel / not strictly lower
                continue
            if victim_tier is None or t < victim_tier:
                victim_i, victim_tier = i, t
        if victim_i is None:
            return False, None
        victim = self._d[victim_i][1]
        del self._d[victim_i]
        self._d.append((tier, item))
        self._not_empty.notify()
        return True, victim

    def put_nowait(self, item, tier: int = 0):
        """Admit or raise queue.Full; returns the evicted item or None."""
        with self._lock:
            admitted, evicted = self._try_admit(item, tier)
            if not admitted:
                raise queue.Full
            return evicted

    def put(self, item, tier: int = 0, timeout: float | None = None):
        """Blocking admit (backpressure policy); still evicts a strictly
        lower tier rather than waiting — a gold request must not block
        behind shed-able free traffic.  Raises queue.Full on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                admitted, evicted = self._try_admit(item, tier)
                if admitted:
                    return evicted
                wait = None if deadline is None else deadline - time.monotonic()
                if wait is not None and wait <= 0:
                    raise queue.Full
                self._not_full.wait(wait)

    def put_sentinel(self, obj) -> None:
        """Enqueue a control object (e.g. a close sentinel) UNCONDITIONALLY
        — it bypasses the bound (by at most one entry) and can never be
        evicted, so shutdown cannot be starved by a full queue."""
        with self._lock:
            self._d.append((None, obj))
            self._not_empty.notify()

    # -- the consumer (collector thread) -----------------------------------

    def get(self, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while not self._d:
                wait = None if deadline is None else deadline - time.monotonic()
                if wait is not None and wait <= 0:
                    raise queue.Empty
                self._not_empty.wait(wait)
            _, item = self._d.popleft()
            self._not_full.notify()
            return item

    def get_nowait(self):
        with self._lock:
            if not self._d:
                raise queue.Empty
            _, item = self._d.popleft()
            self._not_full.notify()
            return item
