"""Client-side serving helpers: pipelined wire connection + spawner.

tools/loadgen.py (bench) and tools/chaos.py (chaos probe) both speak to
a live front end; this module is their ONE implementation of the
pipelined JSONL connection and the `SERVE_READY` spawn-and-wait, so a
wire or readiness change cannot silently split the tools.  jax-free.

``ServeConnection`` multiplexes by caller-assigned ``id``: attach a
``meta`` to each send and route responses through ``on_response(msg,
meta)`` (return falsy to ALSO keep the message in ``responses``), or
use the default accumulation in ``responses`` and the synchronous
``request()`` for ops.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from fast_tffm_tpu.telemetry import log_quietly
from fast_tffm_tpu.serving.protocol import (
    SERVE_READY_PREFIX,
    BadRequest,
    decode,
    encode,
)

__all__ = ["ServeConnection", "spawn_serve"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _SyncBox:
    """Meta marker that turns a response into a synchronous result."""

    def __init__(self):
        self.event = threading.Event()
        self.msg = None


class ServeConnection:
    """One pipelined TCP connection to a front end (or replica — same
    wire).  Thread-safe sends; one reader thread resolves responses."""

    def __init__(self, port: int, host: str = "127.0.0.1", on_response=None,
                 timeout: float = 60.0):
        import socket as _socket

        self.sock = _socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._f = self.sock.makefile("rb")
        self._on_response = on_response
        self.lock = threading.Lock()
        self._pending: dict = {}  # id -> meta
        self.responses: dict = {}  # id -> msg (unconsumed responses)
        self._next = 0
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def next_id(self) -> int:
        with self.lock:
            self._next += 1
            return self._next

    def send(self, msg: dict, meta=None) -> None:
        """Send one message; ``msg['id']`` is the response key (assigned
        from the connection counter when absent)."""
        if "id" not in msg:
            msg["id"] = self.next_id()
        with self.lock:
            self._pending[msg["id"]] = meta
        self.sock.sendall(encode(msg))

    def request(self, msg: dict, timeout: float = 30.0) -> dict:
        """Synchronous op (ping/stats/slow): send and wait for its ack."""
        box = _SyncBox()
        self.send(msg, meta=box)
        if not box.event.wait(timeout):
            raise TimeoutError(f"op {msg.get('op')!r} not answered in {timeout}s")
        return box.msg

    def _read(self) -> None:
        try:
            for raw in self._f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    msg = decode(raw)
                except BadRequest:
                    continue  # a garbled line never kills the reader
                with self.lock:
                    meta = self._pending.pop(msg.get("id"), None)
                if isinstance(meta, _SyncBox):
                    meta.msg = msg
                    meta.event.set()
                    continue
                if self._on_response is not None and self._on_response(msg, meta):
                    continue
                with self.lock:
                    self.responses[msg.get("id")] = msg
        except (OSError, ValueError):
            pass

    def inflight(self) -> int:
        with self.lock:
            return len(self._pending)

    def wait_answered(self, ids, timeout: float) -> set:
        """Block until every id in ``ids`` has a stored response (default
        routing); returns the ids still missing at the deadline."""
        deadline = time.monotonic() + timeout
        missing = set(ids)
        while missing and time.monotonic() < deadline:
            with self.lock:
                missing = {i for i in missing if i not in self.responses}
            if missing:
                time.sleep(0.05)
        return missing

    def drain_inflight(self, timeout: float) -> int:
        """Wait for the pending map to empty; returns what's left."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and self.inflight():
            time.sleep(0.01)
        return self.inflight()

    def close(self) -> None:
        import socket as _socket

        # shutdown() before close(): the makefile in _f holds an io ref,
        # so close() alone defers the real fd close and the reader's
        # readline never sees EOF — shutdown delivers it immediately.
        try:
            self.sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass  # peer already hung up
        try:
            self.sock.close()
        except OSError:
            pass
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2.0)
        try:
            self._f.close()
        except OSError:
            pass


def spawn_serve(
    cfg_path: str,
    *,
    port: int = 0,
    timeout_s: float = 300.0,
    log=None,
) -> tuple[subprocess.Popen, int]:
    """Launch ``fast_tffm.py serve <cfg> --port N`` and block until its
    SERVE_READY line (deadline bounds SILENCE — a child wedged before
    its first output fails at the deadline, not never); returns (proc,
    announced port).  Caller owns terminate()."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "fast_tffm.py"), "serve",
         cfg_path, "--port", str(port)],
        stdout=subprocess.PIPE,
        stderr=None,
        text=True,
        env=env,
        cwd=_REPO,
    )
    ready = threading.Event()
    box: list[int | None] = [None]

    def wait_ready():
        try:
            for line in proc.stdout:
                line = line.strip()
                if line.startswith(SERVE_READY_PREFIX):
                    fields = dict(
                        kv.split("=", 1)
                        for kv in line[len(SERVE_READY_PREFIX):].split()
                    )
                    box[0] = int(fields["port"])
                    ready.set()
                    break
                if line and log is not None:
                    log(line)
            # After readiness (or EOF), keep draining so the pipe never
            # fills and blocks the server.
            for line in proc.stdout:
                if line.strip() and log is not None:
                    log(line.strip())
        except Exception as e:
            # ANY failure (torn SERVE_READY line, raising log callback)
            # must still reach ready.set() — a dead waiter would turn a
            # fast loud failure into a full spawn-timeout hang, and a
            # dead drain would let the child block on a full pipe.
            log_quietly(log, f"serve ready-waiter error: {e!r}")
        ready.set()

    threading.Thread(target=wait_ready, name="serve-ready", daemon=True).start()
    ready.wait(timeout_s)
    if box[0] is None:
        proc.kill()
        raise RuntimeError(
            f"spawned front end never announced SERVE_READY within {timeout_s:.0f}s"
        )
    return proc, box[0]
