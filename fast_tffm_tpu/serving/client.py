"""Client-side serving helpers: pipelined wire connection + spawner.

tools/loadgen.py (bench) and tools/chaos.py (chaos probe) both speak to
a live front end; this module is their ONE implementation of the
pipelined JSONL connection and the `SERVE_READY` spawn-and-wait, so a
wire or readiness change cannot silently split the tools.  jax-free.

``ServeConnection`` multiplexes by caller-assigned ``id``: attach a
``meta`` to each send and route responses through ``on_response(msg,
meta)`` (return falsy to ALSO keep the message in ``responses``), or
use the default accumulation in ``responses`` and the synchronous
``request()`` for ops.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from fast_tffm_tpu.telemetry import log_quietly
from fast_tffm_tpu.serving.protocol import (
    FRAME_KIND_ERROR,
    FRAME_KIND_SCORES,
    FRAME_STATUS_CODES,
    SERVE_READY_PREFIX,
    BadRequest,
    decode,
    encode,
    pack_request_frame,
    read_frame,
    unpack_error_frame,
    unpack_scores_frame,
)

__all__ = ["FrameConnection", "ServeConnection", "WireRefused", "spawn_serve"]

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _SyncBox:
    """Meta marker that turns a response into a synchronous result."""

    def __init__(self):
        self.event = threading.Event()
        self.msg = None


class ServeConnection:
    """One pipelined TCP connection to a front end (or replica — same
    wire).  Thread-safe sends; one reader thread resolves responses."""

    def __init__(self, port: int, host: str = "127.0.0.1", on_response=None,
                 timeout: float = 60.0):
        import socket as _socket

        self.sock = _socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._f = self.sock.makefile("rb")
        self._on_response = on_response
        self.lock = threading.Lock()
        self._pending: dict = {}  # id -> meta
        self.responses: dict = {}  # id -> msg (unconsumed responses)
        self._next = 0
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def next_id(self) -> int:
        with self.lock:
            self._next += 1
            return self._next

    def send(self, msg: dict, meta=None) -> None:
        """Send one message; ``msg['id']`` is the response key (assigned
        from the connection counter when absent)."""
        if "id" not in msg:
            msg["id"] = self.next_id()
        with self.lock:
            self._pending[msg["id"]] = meta
        self.sock.sendall(encode(msg))

    def request(self, msg: dict, timeout: float = 30.0) -> dict:
        """Synchronous op (ping/stats/slow): send and wait for its ack."""
        box = _SyncBox()
        self.send(msg, meta=box)
        if not box.event.wait(timeout):
            raise TimeoutError(f"op {msg.get('op')!r} not answered in {timeout}s")
        return box.msg

    def _read(self) -> None:
        try:
            for raw in self._f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    msg = decode(raw)
                except BadRequest:
                    continue  # a garbled line never kills the reader
                with self.lock:
                    meta = self._pending.pop(msg.get("id"), None)
                if isinstance(meta, _SyncBox):
                    meta.msg = msg
                    meta.event.set()
                    continue
                if self._on_response is not None and self._on_response(msg, meta):
                    continue
                with self.lock:
                    self.responses[msg.get("id")] = msg
        except (OSError, ValueError):
            pass

    def inflight(self) -> int:
        with self.lock:
            return len(self._pending)

    def wait_answered(self, ids, timeout: float) -> set:
        """Block until every id in ``ids`` has a stored response (default
        routing); returns the ids still missing at the deadline."""
        deadline = time.monotonic() + timeout
        missing = set(ids)
        while missing and time.monotonic() < deadline:
            with self.lock:
                missing = {i for i in missing if i not in self.responses}
            if missing:
                time.sleep(0.05)
        return missing

    def drain_inflight(self, timeout: float) -> int:
        """Wait for the pending map to empty; returns what's left."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and self.inflight():
            time.sleep(0.01)
        return self.inflight()

    def close(self) -> None:
        import socket as _socket

        # shutdown() before close(): the makefile in _f holds an io ref,
        # so close() alone defers the real fd close and the reader's
        # readline never sees EOF — shutdown delivers it immediately.
        try:
            self.sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass  # peer already hung up
        try:
            self.sock.close()
        except OSError:
            pass
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2.0)
        try:
            self._f.close()
        except OSError:
            pass


class WireRefused(RuntimeError):
    """The front end would not grant the binary DATA wire (server pinned
    to jsonl, or affinity off).  Carries the hello ack so a caller can
    fall back to JSONL without a second round trip."""

    def __init__(self, ack: dict):
        super().__init__(
            f"binary wire refused: wire={ack.get('wire')!r} "
            f"affinity={ack.get('affinity')!r}"
        )
        self.ack = ack


def _hello(host: str, port: int, timeout: float) -> dict:
    """One-shot JSONL hello to the front end: wire negotiation +
    replica placement.  Its own short-lived socket so the data path
    never shares a connection with ops."""
    import socket as _socket

    sock = _socket.create_connection((host, port), timeout=timeout)
    try:
        sock.sendall(encode({"id": 1, "op": "hello", "wire": "binary"}))
        line = sock.makefile("rb").readline()
    finally:
        try:
            sock.close()
        except OSError:
            pass
    if not line:
        raise OSError("front end closed the connection during hello")
    return decode(line.strip())


class _Frame:
    """One in-flight REQUEST frame: the packed bytes (kept so failover
    can resend it verbatim), its row ids, and the retry latch."""

    __slots__ = ("data", "req_ids", "unanswered", "retried")

    def __init__(self, data: bytes, req_ids):
        self.data = data
        self.req_ids = [int(r) for r in req_ids]
        self.unanswered = set(self.req_ids)
        self.retried = False


class FrameConnection:
    """Binary DATA connection pinned to one replica (affinity).

    Hellos the FRONT END for placement, then connects straight to the
    assigned replica's port and hellos IT (the JSONL ack carries
    ``max_frame_rows``/``max_nnz``/``fields``); everything after that
    ack is frames.  The replica answers directly — the router is out of
    the score path.

    Failover is client-driven, retry-once-on-peer: when the pinned
    replica dies mid-flight (reader EOF/error with frames pending), the
    client re-hellos the front end for a peer and resends each pending
    frame EXACTLY once; a frame whose retry also dies resolves its
    unanswered rows ``unavailable`` locally — never a hang, never a
    third replica.  Answers dedup first-wins, so a frame whose response
    was torn mid-write re-scores harmlessly (same checkpoint + same
    per-bucket programs on every replica ⇒ bit-identical scores).

    Raises ``WireRefused`` when the tier won't grant binary+affinity —
    callers fall back to ``ServeConnection`` JSONL."""

    def __init__(
        self,
        port: int,
        host: str = "127.0.0.1",
        timeout: float = 60.0,
        on_result=None,
    ):
        self.host = host
        self.frontend_port = int(port)
        self.timeout = float(timeout)
        # on_result(req_id, status, score) fires once per row on its FIRST
        # resolution (reader thread, lock held — must be fast and must not
        # call back into this connection; loadgen appends to a sink).
        self._on_result = on_result
        self.lock = threading.Lock()
        self.results: dict[int, tuple[str, float]] = {}  # req_id -> (status, score)
        self._frames: dict[int, _Frame] = {}  # frame seq -> frame
        self._req2seq: dict[int, int] = {}
        self._seq = 0
        self._closing = False
        self._dead = False
        self.last_error: str | None = None
        self.failovers = 0
        ack = _hello(host, port, timeout)
        if not ack.get("ok") or ack.get("wire") != "binary" or "port" not in ack:
            raise WireRefused(ack)
        self._attach(int(ack["port"]), int(ack.get("replica", -1)))
        self._reader = threading.Thread(
            target=self._read, name="frame-reader", daemon=True
        )
        self._reader.start()

    def _attach(self, rport: int, replica: int) -> None:
        """Connect + hello the assigned replica; frames after the ack."""
        import socket as _socket

        sock = _socket.create_connection((self.host, rport), timeout=self.timeout)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        rf = sock.makefile("rb")
        sock.sendall(encode({"id": 0, "op": "hello", "wire": "binary"}))
        ack = decode(rf.readline().strip())
        if ack.get("wire") != "binary":
            sock.close()
            raise WireRefused(ack)
        # Publish the new connection under the lock: send_packed reads
        # self.sock there, and a failover re-attach must never hand a
        # sender the half-swapped state.
        with self.lock:
            self.replica = replica
            self.replica_port = rport
            self.max_frame_rows = int(ack.get("max_frame_rows", 1))
            self.max_nnz = int(ack.get("max_nnz", 0))
            self.uses_fields = bool(ack.get("fields", False))
            self.sock = sock
            self._rf = rf

    def send_packed(self, data: bytes, req_ids) -> None:
        """Send one pre-packed REQUEST frame (loadgen packs outside the
        timed loop); rows resolve into ``results``."""
        with self.lock:
            if self._closing:
                raise OSError("connection closed")
            self._seq += 1
            seq = self._seq
            fr = _Frame(data, req_ids)
            self._frames[seq] = fr
            for r in fr.req_ids:
                self._req2seq[r] = seq
            if self._dead:
                # Failover already gave up; resolve locally, typed.
                self._resolve_unavailable_locked([fr])
                return
            sock = self.sock
        try:
            sock.sendall(data)
        except OSError:
            pass  # reader sees the dead socket; failover resends the frame

    def send_batch(
        self, req_ids, ids, vals, fields=None, deadlines_ms=None, klass: str = ""
    ) -> None:
        """Pack + send one frame.  One class per frame on purpose: the
        engine attributes a block's server-side latency to a single
        class, so mixing classes in a frame would blur the per-class p99
        the SLO gate reads."""
        n = len(req_ids)
        data = pack_request_frame(
            req_ids,
            ids,
            vals,
            fields=fields,
            deadlines_ms=deadlines_ms,
            classes=[klass] * n if klass else None,
        )
        self.send_packed(data, req_ids)

    def _resolve_unavailable_locked(self, frames) -> None:
        for fr in frames:
            for r in list(fr.unanswered):
                if r not in self.results:
                    self.results[r] = ("unavailable", 0.0)
                    if self._on_result is not None:
                        self._on_result(r, "unavailable", 0.0)
            self._retire_locked(fr)

    def _retire_locked(self, fr: _Frame) -> None:
        fr.unanswered.clear()
        for r in fr.req_ids:
            if self._req2seq.get(r) is not None:
                self._req2seq.pop(r, None)
        for seq, f in list(self._frames.items()):
            if f is fr:
                self._frames.pop(seq, None)

    def _on_scores(self, count: int, payload: bytes) -> None:
        req_ids, statuses, scores = unpack_scores_frame(count, payload)
        with self.lock:
            for i in range(count):
                rid = int(req_ids[i])
                if rid not in self.results:  # first answer wins (dedup)
                    st = FRAME_STATUS_CODES[int(statuses[i])]
                    sc = float(scores[i])
                    self.results[rid] = (st, sc)
                    if self._on_result is not None:
                        self._on_result(rid, st, sc)
                seq = self._req2seq.pop(rid, None)
                if seq is not None:
                    fr = self._frames.get(seq)
                    if fr is not None:
                        fr.unanswered.discard(rid)
                        if not fr.unanswered:
                            self._frames.pop(seq, None)

    def _read(self) -> None:
        """Reader loop with inline failover: inner loop reads frames off
        the current replica; when it dies the OUTER loop re-pins."""
        while True:
            fatal = None
            try:
                while True:
                    fr = read_frame(self._rf)
                    if fr is None:
                        break  # replica gone (EOF)
                    kind, _flags, count, _width, payload = fr
                    if kind == FRAME_KIND_SCORES:
                        self._on_scores(count, payload)
                    elif kind == FRAME_KIND_ERROR:
                        # The replica lost framing on OUR bytes — the
                        # connection is untrustworthy; fail over.
                        code, detail = unpack_error_frame(payload)
                        fatal = f"{code}: {detail}"
                        break
            except (BadRequest, OSError, ValueError) as e:
                fatal = repr(e)  # torn read — treat as a dead connection
            if fatal:
                self.last_error = fatal
            try:
                self.sock.close()
            except OSError:
                pass
            with self.lock:
                if self._closing:
                    return
                pending = list(self._frames.values())
                retry = [f for f in pending if not f.retried]
                spent = [f for f in pending if f.retried]
                # Second death for these frames: unavailable, locally.
                self._resolve_unavailable_locked(spent)
            if not self._failover(retry):
                return

    def _failover(self, retry) -> bool:
        """Re-hello the front end, pin a peer, resend ``retry`` frames
        once.  False = no peer (or handshake died): resolve + stop."""
        try:
            ack = _hello(self.host, self.frontend_port, self.timeout)
            if not ack.get("ok") or ack.get("wire") != "binary" or "port" not in ack:
                raise OSError(f"re-hello refused: {ack}")
            self._attach(int(ack["port"]), int(ack.get("replica", -1)))
        except (OSError, ValueError, BadRequest, WireRefused) as e:
            self.last_error = repr(e)
            with self.lock:
                self._dead = True
                self._resolve_unavailable_locked(list(self._frames.values()))
            return False
        self.failovers += 1
        with self.lock:
            for fr in retry:
                fr.retried = True
            sock = self.sock
        for fr in retry:
            try:
                sock.sendall(fr.data)
            except OSError:
                break  # the NEW replica died too; next loop pass handles it
        return True

    def answered(self) -> int:
        with self.lock:
            return len(self.results)

    def inflight(self) -> int:
        with self.lock:
            return sum(len(f.unanswered) for f in self._frames.values())

    def wait_answered(self, ids, timeout: float) -> set:
        """Block until every req_id in ``ids`` has a result; returns the
        ids still missing at the deadline (never raises — a missing id
        is the caller's `unanswered` accounting)."""
        deadline = time.monotonic() + timeout
        missing = set(int(i) for i in ids)
        while missing and time.monotonic() < deadline:
            with self.lock:
                missing = {i for i in missing if i not in self.results}
            if missing:
                time.sleep(0.02)
        return missing

    def close(self) -> None:
        import socket as _socket

        with self.lock:
            self._closing = True
        try:
            self.sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2.0)


def spawn_serve(
    cfg_path: str,
    *,
    port: int = 0,
    timeout_s: float = 300.0,
    log=None,
) -> tuple[subprocess.Popen, int]:
    """Launch ``fast_tffm.py serve <cfg> --port N`` and block until its
    SERVE_READY line (deadline bounds SILENCE — a child wedged before
    its first output fails at the deadline, not never); returns (proc,
    announced port).  Caller owns terminate()."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(_REPO, "fast_tffm.py"), "serve",
         cfg_path, "--port", str(port)],
        stdout=subprocess.PIPE,
        stderr=None,
        text=True,
        env=env,
        cwd=_REPO,
    )
    ready = threading.Event()
    box: list[int | None] = [None]

    def wait_ready():
        try:
            for line in proc.stdout:
                line = line.strip()
                if line.startswith(SERVE_READY_PREFIX):
                    fields = dict(
                        kv.split("=", 1)
                        for kv in line[len(SERVE_READY_PREFIX):].split()
                    )
                    box[0] = int(fields["port"])
                    ready.set()
                    break
                if line and log is not None:
                    log(line)
            # After readiness (or EOF), keep draining so the pipe never
            # fills and blocks the server.
            for line in proc.stdout:
                if line.strip() and log is not None:
                    log(line.strip())
        except Exception as e:
            # ANY failure (torn SERVE_READY line, raising log callback)
            # must still reach ready.set() — a dead waiter would turn a
            # fast loud failure into a full spawn-timeout hang, and a
            # dead drain would let the child block on a full pipe.
            log_quietly(log, f"serve ready-waiter error: {e!r}")
        ready.set()

    threading.Thread(target=wait_ready, name="serve-ready", daemon=True).start()
    ready.wait(timeout_s)
    if box[0] is None:
        proc.kill()
        raise RuntimeError(
            f"spawned front end never announced SERVE_READY within {timeout_s:.0f}s"
        )
    return proc, box[0]
