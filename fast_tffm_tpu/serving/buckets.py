"""Bucketed compile ladder: pre-jitted predict shapes, pad-to-bucket.

XLA compiles one program per input shape.  An online engine that
dispatched every micro-batch at its natural size would compile on the
hot path whenever a new size showed up — tens of ms to seconds of
latency cliff, at p99, exactly where it hurts.  The ladder fixes the
shape vocabulary up front: a small ascending set of batch sizes
(default 1/8/64/512), every flush padded up to the nearest bucket, every
bucket compiled ONCE at startup by an explicit warmup pass.  Steady
state then never sees a compile — pinned by ``compile_count()`` staying
flat (tests/test_serving.py, tools/loadgen.py).

Padding rows are all-zero with weight 0: the score function evaluates
them (sigmoid(0) rows that cost a few flops) and the engine slices them
off before resolving futures — the same neutral-padding contract the
offline drivers use for short tail batches.
"""

from __future__ import annotations

import bisect

import jax
import jax.numpy as jnp
import numpy as np

from fast_tffm_tpu.config import validate_buckets
from fast_tffm_tpu.models.base import Batch

__all__ = ["BucketLadder", "validate_buckets"]


class BucketLadder:
    """Routes n-row flushes to the smallest compiled bucket >= n.

    ``score`` is a prediction.ScoreFn; the ladder owns no state — the
    engine passes the CURRENT serving state at every call, which is what
    lets hot reload swap states without touching compiled programs (the
    programs are shape-keyed, not weight-keyed).
    """

    def __init__(self, score, buckets, *, wire_format="arrays", vocabulary_size=0):
        self._score = score
        self.buckets = validate_buckets(buckets)
        self.max_nnz = score.max_nnz
        self.uses_fields = score.uses_fields
        self.warmed = False
        self._wire = None
        if wire_format == "packed" and vocabulary_size > 0:
            # Packed wire staging (the training/predict streamed format,
            # data/wire.py): each flush ships ONE coalesced byte buffer —
            # narrow ids, 1-byte labels, weights rebuilt on device from
            # the real-row count — instead of five device_puts.  Request
            # vals are arbitrary floats, so they always ship explicit
            # (elision is a convert-time per-file fact; serving has no
            # files).  One unpack program per bucket shape, compiled by
            # the same warmup pass that pins the score programs, so the
            # zero-steady-state-recompiles invariant is unchanged.
            from fast_tffm_tpu.data.wire import WireConverter, make_spec

            self._wire = WireConverter(
                make_spec(
                    vocabulary_size,
                    self.max_nnz,
                    with_vals=True,
                    with_fields=self.uses_fields,
                    with_weights=False,
                ),
                # Rows were range-validated at admission (submit_line's
                # parse / submit's explicit bounds check) — skip the
                # packer's per-flush id scan on the latency path.
                verify_ids=False,
            )

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n.  Callers cap flushes at ``max_batch``, so
        an overflow here is an engine bug, not an input condition."""
        if n < 1 or n > self.buckets[-1]:
            raise ValueError(f"flush of {n} rows outside buckets {self.buckets}")
        return self.buckets[bisect.bisect_left(self.buckets, n)]

    def _empty(self, bucket: int) -> tuple[np.ndarray, ...]:
        w = self.max_nnz
        fw = w if self.uses_fields else 0
        return (
            np.zeros((bucket,), np.float32),  # labels (unused by scoring)
            np.zeros((bucket, w), np.int32),  # ids
            np.zeros((bucket, w), np.float32),  # vals
            np.zeros((bucket, fw), np.int32),  # fields
            np.zeros((bucket,), np.float32),  # weights (0 = padding row)
        )

    def _finalize(self, labels, ids, vals, fields, weights) -> Batch:
        """Stage one fully-placed bucket batch.  EVERY dispatched batch —
        warmup, per-row assemble, coalesced-frame assemble — funnels
        through here, so a warmed shape can never diverge from a flushed
        shape (which would defeat the compile ladder) and the wire
        staging decision rides the same single path."""
        if self._wire is not None:
            from fast_tffm_tpu.data.libsvm import ParsedBatch

            # Explicit-vals specs ship no nnz section at all (the packer
            # never reads this placeholder); the real-row prefix count
            # drives the weight rebuild.
            parsed = ParsedBatch(
                labels=labels,
                ids=ids,
                vals=vals,
                fields=fields,
                nnz=np.zeros((labels.shape[0],), np.int32),
            )
            return self._wire(parsed, weights)
        return Batch(
            labels=jnp.asarray(labels),
            ids=jnp.asarray(ids),
            vals=jnp.asarray(vals),
            fields=jnp.asarray(fields),
            weights=jnp.asarray(weights),
        )

    def _batch(self, bucket: int, rows=()) -> Batch:
        """``rows`` placed over an all-padding base, one row at a time."""
        labels, ids, vals, fields, weights = self._empty(bucket)
        for i, (rid, rval, rfld) in enumerate(rows):
            ids[i] = rid
            vals[i] = rval
            if self.uses_fields:
                fields[i] = rfld
        weights[: len(rows)] = 1.0
        return self._finalize(labels, ids, vals, fields, weights)

    def assemble(self, rows) -> tuple[Batch, int]:
        """Stack parsed request rows [(ids, vals, fields), ...] into one
        device Batch padded up to the nearest bucket.  Each row is already
        width-``max_nnz`` (submit-time parsing fixed it), so assembly is
        pure row placement — no per-flush width decisions that could
        produce an unladdered shape."""
        bucket = self.bucket_for(len(rows))
        return self._batch(bucket, rows), bucket

    def assemble_parts(self, parts) -> tuple[Batch, int]:
        """Coalesced assembly for whole-frame ingest: ``parts`` is a list
        of ``(ids, vals, fields_or_None)`` 2-D chunks, each already width
        ``max_nnz``; rows land contiguously in part order.  Slice
        placement instead of assemble()'s per-row Python loop, and the
        bucket is chosen AFTER coalescing the flush — the occupancy fix:
        one frame of n rows pads to the bucket for n, not to whatever the
        per-request trickle happened to accumulate."""
        n = sum(int(p[0].shape[0]) for p in parts)
        bucket = self.bucket_for(n)
        labels, ids, vals, fields, weights = self._empty(bucket)
        pos = 0
        for pid, pval, pfld in parts:
            k = int(pid.shape[0])
            ids[pos : pos + k] = pid
            vals[pos : pos + k] = pval
            if self.uses_fields and pfld is not None:
                fields[pos : pos + k] = pfld
            pos += k
        weights[:n] = 1.0
        return self._finalize(labels, ids, vals, fields, weights), bucket

    def warmup(self, state) -> int:
        """Compile every bucket ONCE, before traffic: score an all-padding
        batch per rung and block until the results (hence the programs)
        are ready.  Returns the compiled-program count afterwards (None
        becomes -1 when the runtime hides the jit cache)."""
        for bucket in self.buckets:
            jax.block_until_ready(self._score(state, self._batch(bucket)))
        self.warmed = True
        n = self.compile_count()
        return -1 if n is None else n

    def compile_count(self) -> int | None:
        """Programs compiled so far for the scoring function — flat after
        warmup is the no-steady-state-recompiles invariant."""
        return self._score.cache_size()

    def example_batch(self, bucket: int) -> Batch:
        """An all-padding batch of ``bucket``'s exact dispatched shape —
        what the measured cost ledger lowers the score program at (the
        same single _batch path warmup and assemble use, so the profiled
        shape can never diverge from the served one)."""
        return self._batch(bucket)

    def score(self, state, batch: Batch):
        return self._score(state, batch)
