"""Shared-nothing router: N replica workers, health-checked failover.

The middle of the production serving shape (ISSUE 8)::

    clients ─ frontend.py ─► Router ─┬─► replica 0 (worker process)
                                     ├─► replica 1
                                     └─► replica N-1

Each replica is a separate PROCESS (serving/replica.py) with its own jit
cache, admission queue, and telemetry monitor — shared-nothing, so one
replica's death, wedge, or compile storm cannot touch its peers.  The
router owns everything cross-replica:

  * **Routing** — round-robin over healthy replicas, one TCP connection
    per replica, requests multiplexed by id.
  * **Health** — a checker pings every replica on a cadence; a missed
    pong (dead socket) or a reported wedge (the engine's oldest queued
    request aging past ``wedge_timeout_s`` — collector stuck, socket
    alive) declares the replica down and SIGKILLs a wedged one.
  * **Failover** — the no-hung-client invariant: when a replica dies,
    every request in flight on it is retried ONCE on a healthy peer
    (scores are bit-identical across replicas — same checkpoint, same
    per-bucket programs) or failed with a typed ``unavailable`` error.
    Nothing ever waits on a corpse.
  * **Restart** — the resilience.Supervisor semantics in serving form
    (one shared RestartPolicy): bounded relaunches with exponential
    backoff while the router drains around the hole; every death emits
    ``kind=fault`` and every recovery ``kind=restart`` with the measured
    replica MTTR (death detected → replica answering pings again).
  * **Reload fan-out** — ONE checkpoint watcher for the whole tier: the
    router polls ``model_file``'s signature and fans a single ``reload``
    command to every replica per observed write, so each published delta
    is applied exactly once per replica (N independent watchers would
    race the filesystem N times per write).

The router itself is device-free — it relays bytes and stats; jax lives
only in the replica workers.
"""

from __future__ import annotations

import itertools
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from fast_tffm_tpu.resilience import RestartPolicy
from fast_tffm_tpu.telemetry import log_quietly
from fast_tffm_tpu.serving.protocol import (
    REPLICA_READY_PREFIX as _READY_PREFIX,
    BadRequest,
    Unavailable,
    WireError,
    decode,
    encode,
)

__all__ = ["Router", "ReplicaProcess", "spawn_replica"]


class ReplicaProcess:
    """Handle for one spawned replica worker: the Popen, its announced
    port, and liveness/kill.  Tests substitute a duck-typed fake (a
    thread-backed socket server) via Router(launcher=...)."""

    def __init__(self, proc: subprocess.Popen, port: int, pid: int):
        self.proc = proc
        self.port = port
        self.pid = pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    @property
    def returncode(self):
        return self.proc.poll()

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass

    def wait(self, timeout: float | None = None) -> None:
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            pass


def spawn_replica(
    config_path: str,
    index: int,
    *,
    run_id: str = "",
    metrics_path: str | None = None,
    env: dict | None = None,
    log=print,
    ready_timeout_s: float = 180.0,
) -> ReplicaProcess:
    """Default launcher: start ``python -m fast_tffm_tpu.serving.replica``
    and block until its REPLICA_READY line (the ladder is warm — a
    replica is never routed to cold).  stderr passes through; stdout is
    drained to ``log`` after the readiness line."""
    cmd = [
        sys.executable, "-m", "fast_tffm_tpu.serving.replica",
        config_path, "--replica", str(index), "--port", "0",
    ]
    if run_id:
        cmd += ["--run-id", run_id]
    if metrics_path is not None:
        cmd += ["--metrics-path", metrics_path]
    child_env = dict(os.environ if env is None else env)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    child_env["PYTHONPATH"] = (
        pkg_root + os.pathsep + child_env["PYTHONPATH"]
        if child_env.get("PYTHONPATH")
        else pkg_root
    )
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=None, text=True, env=child_env
    )
    # Readiness wait on a SIDE thread: a child wedged before its first
    # stdout line would park a plain readline forever — the deadline must
    # bound silence, not just the gaps between lines.
    ready = threading.Event()
    port_box: list[int | None] = [None]

    def wait_ready():
        try:
            for line in proc.stdout:
                line = line.strip()
                if line.startswith(_READY_PREFIX):
                    fields = dict(
                        kv.split("=", 1)
                        for kv in line[len(_READY_PREFIX):].split()
                    )
                    port_box[0] = int(fields["port"])
                    ready.set()
                    return
                if line:
                    log(f"replica {index}: {line}")
        except Exception as e:
            # ANY failure (torn READY line, raising log callback) must
            # still reach ready.set() below — a dead waiter otherwise
            # turns a fast loud failure into a full ready-timeout hang.
            log_quietly(log, f"replica {index}: ready-waiter error: {e!r}")
        ready.set()  # EOF / error: unblock the waiter to fail loudly

    waiter = threading.Thread(
        target=wait_ready, name=f"replica-{index}-ready", daemon=True
    )
    waiter.start()
    ready.wait(ready_timeout_s)
    port = port_box[0]
    if port is None:
        proc.kill()
        raise Unavailable(
            f"replica {index} never announced readiness within "
            f"{ready_timeout_s:.0f}s (rc={proc.poll()}) — see its stderr above"
        )

    def drain():  # keep the pipe from filling after READY
        try:
            for line in proc.stdout:
                line = line.rstrip()
                if line:
                    log(f"replica {index}: {line}")
        except Exception as e:
            # the drain exists so the child's stdout pipe can never fill
            # and block it — it must survive even a raising log callback
            log_quietly(log, f"replica {index}: drain error: {e!r}")

    threading.Thread(target=drain, name=f"replica-{index}-drain", daemon=True).start()
    return ReplicaProcess(proc, port, proc.pid)


class _Pending:
    __slots__ = ("msg", "future", "retried", "t0", "kind", "gen")

    def __init__(self, msg, future, kind="score", retried=False, gen=0):
        self.msg = msg
        self.future = future
        self.kind = kind
        self.retried = retried
        self.gen = gen  # reload fan-out ordinal (freshness attribution)
        self.t0 = time.perf_counter()


class _Slot:
    """Per-replica mutable state.  ``state`` ∈ starting | healthy | dead
    | restarting | failed (restart budget spent)."""

    def __init__(self, index: int):
        self.index = index
        self.lock = threading.Lock()  # pending map + writer
        self.handle: ReplicaProcess | None = None
        self.sock: socket.socket | None = None  # data (scores)
        self.ctrl: socket.socket | None = None  # control (ping/reload/...)
        self.state = "starting"
        self.pending: dict[int, _Pending] = {}
        self.requests = 0
        self.restarts = 0
        self.death_t: float | None = None
        self.last_pong_t: float | None = None
        self.ping_outstanding_t: float | None = None
        self.reload_acks = 0
        self.last_reload: dict | None = None

    def inflight(self) -> int:
        with self.lock:
            return len(self.pending)


class Router:
    """See module docstring.  ``launcher(index) -> ReplicaProcess`` (or a
    duck-type) overrides subprocess spawning for tests; ``config_path``
    is required only with the default launcher."""

    def __init__(
        self,
        cfg,
        *,
        config_path: str | None = None,
        launcher=None,
        run_id: str = "",
        log=print,
        health_interval_s: float = 0.5,
        ping_timeout_s: float = 2.0,
        wedge_timeout_s: float = 5.0,
        monitor=None,
    ):
        if launcher is None and config_path is None:
            raise ValueError("Router needs config_path (or a custom launcher)")
        self._cfg = cfg
        self._log = log
        self._health_interval = float(health_interval_s)
        self._ping_timeout = float(ping_timeout_s)
        self._wedge_timeout = float(wedge_timeout_s)
        self._policy = RestartPolicy(
            cfg.restart_max, cfg.restart_backoff_s, cfg.restart_backoff_max_s
        )
        if monitor is None:
            from fast_tffm_tpu.telemetry import RunMonitor

            monitor = RunMonitor(
                cfg.metrics_path, run_id=run_id, source="router", log=log
            )
        self._monitor = monitor
        self.run_id = self._monitor.run_id
        self._launcher = launcher or (
            lambda i: spawn_replica(
                config_path,
                i,
                run_id=self.run_id,
                metrics_path=cfg.metrics_path or None,
                log=self._log,
            )
        )
        self._closed = False
        self._stop = threading.Event()
        # Maintenance threads (assigned after bring-up; close() may run
        # on a bring-up failure before either exists).
        self._health_thread: threading.Thread | None = None
        self._watch_thread: threading.Thread | None = None
        self._next_id = itertools.count(1)
        self._rr = itertools.count()
        # Cross-replica counters (the router's own story for report.py).
        # Reader/health/watch threads and callers all bump these; every
        # += is a read-modify-write, so they share one leaf lock (never
        # held across a call — no ordering edges).
        self._stats_lock = threading.Lock()
        self.failovers = 0  # requests re-sent to a peer after a death
        self.failed_unanswerable = 0  # typed `unavailable` failures
        self.reload_fanouts = 0  # signature changes fanned out
        self.reload_retries = 0  # re-fans after a failed/deferred ack
        self._reload_retry = False  # guarded by _retry_lock: the reader
        #   threads set it, the watch tick swap-reads it — an unlocked
        #   read-then-clear pair could drop the LAST failed ack forever
        self._retry_lock = threading.Lock()
        self.mttr_s: list[float] = []
        # Fleet freshness (ISSUE 9): per fan-out, the router measures
        # checkpoint-publish → each replica's staged ack (wall clocks on
        # both ends; the engine-side kind=freshness records carry the
        # precise applied/first-scored pair — this is the tier-level
        # roll-up).  One kind=freshness record per COMPLETED fan-out,
        # stamped with the slowest replica's latency; stats() reports the
        # running percentiles operators poll over the wire.
        from fast_tffm_tpu.serving.metrics import LatencyHistogram

        self._fresh_lock = threading.Lock()
        # Per-replica staged latencies, bounded: the same fixed-bin
        # histogram the engine's freshness pair uses (a raw list would
        # grow one float per ack forever and re-sort under the lock on
        # every stats poll).
        self.freshness_hist = LatencyHistogram()
        self._fanout_gen = 0  # fan-out ordinal: a slow replica's ack from
        #   fan-out N must not be measured against (or close) fan-out N+1
        self._fanout_pub_t: float | None = None
        self._fanout_pending: set[int] = set()
        self._fanout_ms: list[float] = []  # current fan-out only (<= replicas)
        # Reload-watch baseline, captured BEFORE the replicas spawn so a
        # publish landing during their multi-second bring-up still fans
        # out (replicas already on it ack noop — idempotent).
        self._watch_baseline = None
        if cfg.serve_reload_interval_s > 0:
            from fast_tffm_tpu.checkpoint import checkpoint_signature

            self._watch_baseline = checkpoint_signature(cfg.model_file)
        self.slots = [_Slot(i) for i in range(max(1, cfg.serve_replicas))]
        # Parallel bring-up: replica warmup is seconds of jax import +
        # ladder compiles; serial would multiply it by N.
        errs: list[BaseException] = []

        def up(slot):
            try:
                self._launch_into(slot)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [
            threading.Thread(
                target=up, args=(s,), name=f"router-up-{s.index}", daemon=True
            )
            for s in self.slots
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs or not self.healthy_replicas():
            self.close()
            raise Unavailable(
                f"router bring-up failed: {errs or 'no replica became healthy'}"
            )
        self._health_thread = threading.Thread(
            target=self._health_loop, name="router-health", daemon=True
        )
        self._health_thread.start()
        self._watch_thread = None
        if cfg.serve_reload_interval_s > 0:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="router-reload", daemon=True
            )
            self._watch_thread.start()

    # -- bring-up / connections -------------------------------------------

    def _launch_into(self, slot: _Slot) -> None:
        handle = self._launcher(slot.index)
        # Two connections: DATA carries scores; CONTROL carries
        # ping/reload/slow/stats so health checking never queues behind a
        # score backlog (an overloaded replica must read as overloaded,
        # not dead).
        sock = socket.create_connection(("127.0.0.1", handle.port), timeout=30.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        ctrl = socket.create_connection(("127.0.0.1", handle.port), timeout=30.0)
        ctrl.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with slot.lock:
            # Ghost entries registered into the slot between _on_down's
            # drain and this relaunch (lost races) must not carry over:
            # nothing on the NEW connection will ever answer their ids.
            leftovers = list(slot.pending.values())
            slot.pending.clear()
            slot.handle = handle
            slot.sock = sock
            slot.ctrl = ctrl
            slot.state = "healthy"
            slot.last_pong_t = time.monotonic()
            slot.ping_outstanding_t = None
        for p in leftovers:
            if p.kind == "score":
                self._fail_unanswerable(p)
            elif not p.future.done():
                p.future.set_exception(Unavailable("replica restarted"))
        for s, name in ((sock, "read"), (ctrl, "ctrl")):
            threading.Thread(
                target=self._read_loop,
                args=(slot, s),
                name=f"router-{name}-{slot.index}",
                daemon=True,
            ).start()

    def healthy_replicas(self) -> list[_Slot]:
        return [s for s in self.slots if s.state == "healthy"]

    def assign(self) -> tuple[int, int]:
        """Placement for an affinity-pinned DATA connection (ISSUE 16):
        pick a healthy replica round-robin and return ``(index, port)``
        — the client connects to the replica DIRECTLY and it answers
        without a router hop.  The router keeps health/reload/placement/
        failover: when the pinned replica dies the client comes back
        here for a peer (its retry-once).  Raises Unavailable when no
        replica is healthy, so the hello gets a typed answer instead of
        a dangling connection."""
        healthy = self.healthy_replicas()
        if not healthy:
            raise Unavailable(
                "no healthy replica to pin (all starting/dead/failed)"
            )
        slot = healthy[next(self._rr) % len(healthy)]
        port = getattr(slot.handle, "port", None)
        if port is None:
            raise Unavailable(f"replica {slot.index} has no port yet")
        return slot.index, int(port)

    # -- submission / routing ---------------------------------------------

    def _send(self, slot: _Slot, obj: dict, ctrl: bool = False) -> None:
        """Whole-line send under the slot lock; raises OSError on a dead
        socket (callers route that into _on_down)."""
        data = encode(obj)
        with slot.lock:
            sock = slot.ctrl if ctrl else slot.sock
            if sock is None:
                raise OSError("replica connection closed")
            sock.sendall(data)

    def _register(self, slot: _Slot, pending: _Pending) -> int:
        req_id = next(self._next_id)
        msg = dict(pending.msg)
        msg["id"] = req_id
        with slot.lock:
            # The msg swap rides the slot lock too: a failover retry
            # re-registers a pending another thread may still observe.
            pending.msg = msg
            slot.pending[req_id] = pending
            slot.requests += 1
        return req_id

    def _dispatch(self, pending: _Pending) -> bool:
        """Send to the next healthy replica; False when none exists (the
        caller fails the future typed)."""
        healthy = self.healthy_replicas()
        if not healthy:
            return False
        slot = healthy[next(self._rr) % len(healthy)]
        req_id = self._register(slot, pending)
        try:
            self._send(slot, pending.msg)
        except OSError as e:
            # The write found the corpse.  _on_down drains slot.pending —
            # but if the slot was ALREADY transitioned (we registered
            # into a dead slot after losing the race with a concurrent
            # _on_down), that drain has run and OUR entry would be
            # stranded forever.  Pull it back out ourselves and give it
            # the same one-retry-or-typed-failure treatment — the
            # no-hung-client invariant must hold against this race too.
            self._on_down(slot, f"send failed: {e}")
            with slot.lock:
                stranded = slot.pending.pop(req_id, None)
            if stranded is not None and not stranded.future.done():
                if stranded.kind != "score" or stranded.retried:
                    self._fail_unanswerable(stranded)
                else:
                    stranded.retried = True
                    with self._stats_lock:
                        self.failovers += 1
                    if not self._dispatch(stranded):
                        self._fail_unanswerable(stranded)
        return True

    def submit(
        self,
        line: str,
        *,
        klass: str = "",
        deadline_ms: float | None = None,
        deadline_at: float | None = None,
    ):
        """Route one request; returns a Future resolving to the float
        score or raising a typed WireError (never hanging on a dead
        replica — failover or a typed failure is guaranteed).
        ``deadline_at`` is an absolute time.monotonic() deadline (same
        host) anchoring the budget at wire receipt; ``deadline_ms`` is
        relative to engine admission."""
        from concurrent.futures import Future

        fut = Future()
        if self._closed:
            fut.set_exception(Unavailable("router is closed"))
            return fut
        msg: dict = {"line": line}
        if klass:
            msg["class"] = klass
        if deadline_ms is not None:
            msg["deadline_ms"] = deadline_ms
        if deadline_at is not None:
            msg["deadline_at"] = deadline_at
        if not self._dispatch(_Pending(msg, fut)):
            with self._stats_lock:
                self.failed_unanswerable += 1
            fut.set_exception(Unavailable("no healthy replica"))
        return fut

    def admin(self, replica: int, op: str, timeout: float = 10.0, **fields) -> dict:
        """Send one op (ping/stats/slow/reload) to replica ``replica``
        and wait for its ack — the chaos/introspection side door."""
        from concurrent.futures import Future

        slot = self.slots[replica]
        if slot.state != "healthy":
            raise Unavailable(f"replica {replica} is {slot.state}")
        pending = _Pending({"op": op, **fields}, Future(), kind=op)
        req_id = self._register(slot, pending)
        try:
            self._send(slot, pending.msg, ctrl=True)
        except OSError as e:
            # Same register-into-a-just-died-slot race _dispatch handles:
            # _on_down's drain may have run BEFORE our register, so pull
            # our own entry back out and fail typed instead of letting
            # the caller block out its timeout on a ghost.
            self._on_down(slot, f"send failed: {e}")
            with slot.lock:
                slot.pending.pop(req_id, None)
            if not pending.future.done():
                pending.future.set_exception(
                    Unavailable(f"replica {replica} died during {op}")
                )
        return pending.future.result(timeout=timeout)

    # -- responses ---------------------------------------------------------

    def _read_loop(self, slot: _Slot, sock: socket.socket) -> None:
        try:
            buf = sock.makefile("rb")
            for line in buf:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = decode(line)
                except BadRequest:
                    continue  # a garbled line never kills the link
                self._on_response(slot, msg)
        except (OSError, ValueError):
            pass
        # EOF/error: if this socket is still one of the slot's current
        # pair, the replica died under us (a restart swaps both first).
        with slot.lock:
            current = sock in (slot.sock, slot.ctrl)
        if current and not self._stop.is_set():
            self._on_down(slot, "connection lost")

    def _on_response(self, slot: _Slot, msg: dict) -> None:
        req_id = msg.get("id")
        with slot.lock:
            pending = slot.pending.pop(req_id, None)
        if pending is None:
            return
        if pending.kind == "ping":
            now = time.monotonic()
            with slot.lock:
                slot.last_pong_t = now
                slot.ping_outstanding_t = None
            pending.future.set_result(msg)
            # A wedged collector is a failure the socket-level checks can
            # never see.  The signal is a CONJUNCTION: the router holds
            # an unanswered score request older than the wedge budget
            # (covers work the collector already popped off the queue —
            # the engine's own oldest_wait_s goes blind there) AND the
            # replica reports no flush completing for that long.  Either
            # alone false-fires: pending age exceeds the budget under
            # deep-backlog overload (flushes still completing), flush age
            # exceeds it on any idle→burst transition (the new request
            # just arrived).  Together they only name a stuck engine.
            age = msg.get("last_flush_age_s")
            if (
                slot.state == "healthy"
                and isinstance(age, (int, float))
                and age > self._wedge_timeout
            ):
                now_pc = time.perf_counter()
                with slot.lock:
                    oldest = min(
                        (
                            p.t0
                            for p in slot.pending.values()
                            if p.kind == "score"
                        ),
                        default=None,
                    )
                if oldest is not None and now_pc - oldest > self._wedge_timeout:
                    self._declare_wedged(
                        slot,
                        min(age, now_pc - oldest),
                        what="no flush while scores wait",
                    )
            return
        if pending.kind == "reload":
            with slot.lock:
                slot.reload_acks += 1
                slot.last_reload = msg
            pending.future.set_result(msg)
            if msg.get("status") in ("staged", "staged_delta"):
                self._note_reload_staged(slot, msg, pending.gen)
            if msg.get("status") in ("failed", "busy"):
                # The replica could not complete this reload (torn write
                # mid-read, or a previous stage unswapped).  Its own
                # polling watcher is OFF in router mode, so the ROUTER
                # must re-drive it: flag a retry fan-out for the next
                # watcher tick (engine-side failure backoff still governs
                # the actual reload attempt rate).
                with self._retry_lock:
                    self._reload_retry = True
            return
        if "score" in msg:
            pending.future.set_result(float(msg["score"]))
        elif msg.get("ok"):
            pending.future.set_result(msg)
        else:
            code = msg.get("code", "unavailable")
            err = WireError(msg.get("error", code))
            err.code = code if code in ("overloaded", "deadline", "bad_request") else "unavailable"
            pending.future.set_exception(err)

    def _note_reload_staged(self, slot: _Slot, msg: dict, pending_gen: int = 0) -> None:
        """One replica staged the fanned-out checkpoint: record its
        publish→staged latency; when the whole fleet has, emit ONE
        aggregate kind=freshness record (the slowest replica's latency is
        the tier's — a client can land anywhere).  Reader threads call
        this concurrently; the lock owns all fan-out state."""
        ms = None
        fleet_done = False
        publish_step = msg.get("step")
        with self._fresh_lock:
            if self._fanout_pub_t is None or pending_gen != self._fanout_gen:
                # No stamp, pre-baseline reload, or a STALE ack: a slow
                # replica still staging fan-out N while fan-out N+1 opened
                # must not be measured against N+1's publish time (nor
                # shrink N+1's pending set).
                return
            ms = max(0.0, (time.time() - self._fanout_pub_t) * 1e3)
            self.freshness_hist.add(ms / 1e3)  # histogram takes seconds
            self._fanout_ms.append(ms)
            self._fanout_pending.discard(slot.index)
            if not self._fanout_pending:
                fleet_done = True
                worst = max(self._fanout_ms)
                n = len(self._fanout_ms)
                self._fanout_pub_t = None
                self._fanout_ms = []
        if fleet_done:
            try:
                self._monitor.emit(
                    "freshness",
                    publish_step=publish_step,
                    publish_to_applied_ms=round(worst, 3),
                    publish_to_first_scored_ms=None,
                    replicas=n,
                    scope="fleet_staged",
                )
            except (OSError, ValueError):
                pass  # lost freshness record, never a dead watcher

    def freshness_percentiles(self) -> dict:
        """Running publish→staged percentiles across every ack observed —
        the fleet freshness number the `stats` wire op reports (the same
        {count, mean, p50, p95, p99, max}-in-ms snapshot vocabulary every
        serving histogram speaks)."""
        with self._fresh_lock:
            return self.freshness_hist.snapshot()

    # -- failure handling --------------------------------------------------

    def _declare_wedged(
        self, slot: _Slot, age: float, what: str = "unanswered ping"
    ) -> None:
        self._log(
            f"router: replica {slot.index} wedged ({what} "
            f"{age:.2f}s > budget) — killing it"
        )
        try:
            self._monitor.emit(
                "fault", event="replica_wedged", replica=slot.index,
                age_s=round(float(age), 3), wedge_signal=what,
            )
        except (OSError, ValueError):
            pass  # lost fault record, never a skipped kill
        # SIGKILL, then the down path (triggered by the socket dropping
        # or directly here) drains and restarts.
        if slot.handle is not None:
            slot.handle.kill()
        self._on_down(slot, "wedged (killed by health check)")

    def _on_down(self, slot: _Slot, why: str) -> None:
        """Replica died: fail over its in-flight requests and start the
        bounded-backoff restart.  Idempotent per incident."""
        with slot.lock:
            if slot.state in ("dead", "restarting", "failed"):
                return
            slot.state = "dead"
            slot.death_t = time.monotonic()
            sock, slot.sock = slot.sock, None
            ctrl, slot.ctrl = slot.ctrl, None
            orphans = list(slot.pending.values())
            slot.pending.clear()
        for s in (sock, ctrl):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        rc = slot.handle.returncode if slot.handle is not None else None
        self._log(f"router: replica {slot.index} down ({why}, rc={rc})")
        try:
            self._monitor.emit(
                "fault", event="replica_crash", replica=slot.index,
                exit_code=rc, detail=why,
            )
        except (OSError, ValueError):
            pass  # lost fault record, never a skipped drain
        # Drain around the corpse: one retry on a healthy peer, else a
        # typed failure — nothing hangs, nothing silently drops.
        for pending in orphans:
            if pending.future.done():
                continue
            if pending.kind != "score" or pending.retried:
                self._fail_unanswerable(pending)
                continue
            pending.retried = True
            with self._stats_lock:
                self.failovers += 1
            if not self._dispatch(pending):
                self._fail_unanswerable(pending)
        if not self._stop.is_set():
            threading.Thread(
                target=self._restart_loop,
                args=(slot,),
                name=f"router-restart-{slot.index}",
                daemon=True,
            ).start()

    def _fail_unanswerable(self, pending: _Pending) -> None:
        with self._stats_lock:
            self.failed_unanswerable += 1
        if not pending.future.done():
            pending.future.set_exception(
                Unavailable("replica died mid-flight and no healthy peer could retry")
            )

    def _restart_loop(self, slot: _Slot) -> None:
        with slot.lock:
            slot.state = "restarting"
        rc = slot.handle.returncode if slot.handle is not None else None
        while not self._stop.is_set():
            with slot.lock:
                slot.restarts += 1
                attempt = slot.restarts
            backoff = self._policy.backoff(attempt)
            if backoff is None:
                with slot.lock:
                    slot.state = "failed"
                self._log(
                    f"router: giving up on replica {slot.index} after "
                    f"{attempt - 1} restart(s) (restart_max "
                    f"= {self._policy.max_restarts})"
                )
                try:
                    self._monitor.emit(
                        "fault", event="replica_giveup", replica=slot.index,
                        attempts=attempt - 1,
                    )
                except (OSError, ValueError):
                    pass  # lost fault record; the giveup state is already set
                return
            if backoff > 0:
                self._log(
                    f"router: replica {slot.index} restart #{attempt} in {backoff:.1f}s"
                )
                if self._stop.wait(backoff):
                    return
            try:
                self._launch_into(slot)
            except Exception as e:
                self._log(f"router: replica {slot.index} relaunch failed: {e!r}")
                continue
            mttr = None
            if slot.death_t is not None:
                mttr = round(time.monotonic() - slot.death_t, 3)
                with self._stats_lock:
                    self.mttr_s.append(mttr)
            self._log(
                f"router: replica {slot.index} back (restart #{attempt}, "
                f"MTTR {mttr}s)"
            )
            try:
                self._monitor.emit(
                    "restart", attempt=attempt, exit_code=rc,
                    backoff_s=round(backoff, 3), mttr_s=mttr, replica=slot.index,
                )
            except (OSError, ValueError):
                pass  # lost restart record; the replica is back either way
            return

    # -- health ------------------------------------------------------------

    def _health_loop(self) -> None:
        from concurrent.futures import Future

        while not self._stop.wait(self._health_interval):
            now = time.monotonic()
            for slot in self.slots:
                if slot.state != "healthy":
                    continue
                # A process that exited is down no matter what the socket
                # says (SIGKILL often leaves the FIN to the kernel).
                if slot.handle is not None and not slot.handle.alive():
                    self._on_down(slot, "process exited")
                    continue
                with slot.lock:
                    outstanding = slot.ping_outstanding_t
                if outstanding is not None and now - outstanding > self._ping_timeout:
                    self._declare_wedged(slot, now - outstanding)
                    continue
                if outstanding is None:
                    pending = _Pending({"op": "ping"}, Future(), kind="ping")
                    with slot.lock:
                        slot.ping_outstanding_t = now
                    self._register(slot, pending)
                    try:
                        self._send(slot, pending.msg, ctrl=True)
                    except OSError as e:
                        self._on_down(slot, f"ping send failed: {e}")

    # -- reload fan-out ----------------------------------------------------

    def _watch_loop(self) -> None:
        from concurrent.futures import Future
        from fast_tffm_tpu.checkpoint import checkpoint_signature, read_publish_time

        # The baseline was captured in __init__ BEFORE the replicas were
        # spawned: a checkpoint published during the multi-second
        # bring-up window must read as NEW here (replicas that loaded it
        # at spawn just ack noop), not become an invisible baseline.
        last_sig = self._watch_baseline
        while not self._stop.wait(self._cfg.serve_reload_interval_s):
            sig = checkpoint_signature(self._cfg.model_file)
            with self._retry_lock:
                retry, self._reload_retry = self._reload_retry, False
            if sig is None or (sig == last_sig and not retry):
                continue
            if sig != last_sig:
                last_sig = sig
                with self._stats_lock:
                    self.reload_fanouts += 1
                why = "checkpoint changed"
            else:
                with self._stats_lock:
                    self.reload_retries += 1
                why = "re-driving a failed/deferred reload"
            targets = self.healthy_replicas()
            self._log(
                f"router: {why} — fanning reload to {len(targets)} replica(s)"
            )
            # Fleet freshness window: measure publish → each replica's
            # staged ack against the chain head's publish stamp (None on
            # pre-stamp checkpoints — measurement degrades to absent).
            with self._fresh_lock:
                self._fanout_gen += 1
                gen = self._fanout_gen
                self._fanout_pub_t = read_publish_time(self._cfg.model_file)
                self._fanout_pending = {s.index for s in targets}
                self._fanout_ms = []
            for slot in targets:
                pending = _Pending({"op": "reload"}, Future(), kind="reload", gen=gen)
                self._register(slot, pending)
                try:
                    self._send(slot, pending.msg, ctrl=True)
                except OSError as e:
                    self._on_down(slot, f"reload send failed: {e}")

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        reps = []
        for s in self.slots:
            reps.append(
                {
                    "replica": s.index,
                    "state": s.state,
                    "pid": getattr(s.handle, "pid", None),
                    "port": getattr(s.handle, "port", None),
                    "requests": s.requests,
                    "inflight": s.inflight(),
                    "restarts": s.restarts,
                    "reload_acks": s.reload_acks,
                }
            )
        with self._stats_lock:
            counters = {
                "failovers": self.failovers,
                "failed_unanswerable": self.failed_unanswerable,
                "reload_fanouts": self.reload_fanouts,
                "reload_retries": self.reload_retries,
                "mttr_s": list(self.mttr_s),
            }
        return {
            "run_id": self.run_id,
            "replicas": reps,
            **counters,
            "freshness_staged_ms": self.freshness_percentiles(),
        }

    def stats(self, timeout: float = 10.0) -> dict:
        """Router snapshot + each healthy replica's engine stats (the
        ``stats`` wire op's payload) + the fleet freshness roll-up: the
        router's publish→staged percentiles and, from the engines' own
        histograms, the worst replica's publish→first-scored p99 — the
        end-to-end freshness SLO an operator polls without tailing JSONL."""
        out = self.snapshot()
        engines = {}
        for slot in list(self.healthy_replicas()):
            try:
                engines[str(slot.index)] = self.admin(slot.index, "stats", timeout=timeout)
            except Exception as e:
                engines[str(slot.index)] = {"error": repr(e)}
        out["engines"] = engines
        scored_p99 = [
            h.get("p99")
            for e in engines.values()
            for h in ((e.get("engine") or {}).get("freshness_scored_ms"),)
            if isinstance(h, dict) and isinstance(h.get("p99"), (int, float))
        ]
        out["freshness"] = {
            "staged_ms": out.pop("freshness_staged_ms"),
            "scored_p99_ms_worst_replica": max(scored_p99) if scored_p99 else None,
        }
        return out

    # -- shutdown ----------------------------------------------------------

    def close(self, timeout: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for slot in self.slots:
            orphans = []
            with slot.lock:
                orphans = list(slot.pending.values())
                slot.pending.clear()
                sock, slot.sock = slot.sock, None
                ctrl, slot.ctrl = slot.ctrl, None
                slot.state = "dead"
            for p in orphans:
                if not p.future.done():
                    p.future.set_exception(Unavailable("router closed"))
            if sock is not None:
                try:
                    sock.sendall(encode({"op": "close"}))
                except OSError:
                    pass
            for s in (sock, ctrl):
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
        deadline = time.monotonic() + timeout
        for slot in self.slots:
            h = slot.handle
            if h is None:
                continue
            h.wait(timeout=max(0.1, deadline - time.monotonic()))
            if h.alive():
                h.kill()
                h.wait(timeout=2.0)
        # Bound the maintenance threads' lifetime: _stop is set, so both
        # exit at their next wait() tick — a bounded join keeps close()
        # from returning while they still touch slots/monitor state.
        me = threading.current_thread()
        if self._health_thread is not None and self._health_thread is not me:
            self._health_thread.join(timeout=2.0)
        if self._watch_thread is not None and self._watch_thread is not me:
            self._watch_thread.join(timeout=2.0)
        try:
            fresh = self.freshness_percentiles()
            with self._stats_lock:
                failovers = self.failovers
                unanswerable = self.failed_unanswerable
            self._monitor.close(
                router_failovers=failovers,
                router_unanswerable=unanswerable,
                router_restarts=sum(s.restarts for s in self.slots),
                **(
                    {"router_freshness_staged_p99_ms": fresh["p99"]}
                    if fresh.get("count")
                    else {}
                ),
            )
        except (OSError, ValueError):
            pass  # lost summary record on close
