"""Serving-side metrics: latency histograms, occupancy, reload counters.

Online latency is a distribution, not a mean — an overloaded collector
shows up at p99 long before it moves the average.  ``LatencyHistogram``
keeps fixed log-spaced bins (O(bins) memory for any request count, the
same bounded-memory stance as metrics.StreamingAUC) and interpolates
quantiles inside the hit bin; ``ServingMetrics`` aggregates the per-stage
histograms plus the engine's counters and renders one flat JSONL record
for utils.tracing.MetricsLogger.
"""

from __future__ import annotations

import threading

import numpy as np

__all__ = ["LatencyHistogram", "ServingMetrics"]


class LatencyHistogram:
    """Fixed log-spaced latency histogram with interpolated quantiles.

    Bins span [lo, hi) seconds geometrically (default 10µs..100s, 120
    bins → ~13% resolution per bin, tighter than any SLO anyone sets);
    samples outside clamp to the edge bins, and exact min/max/sum ride
    along so the snapshot never lies about the tails' extremes.
    """

    def __init__(self, lo: float = 1e-5, hi: float = 100.0, bins: int = 120):
        if not (0 < lo < hi) or bins < 2:
            raise ValueError(f"bad histogram spec lo={lo} hi={hi} bins={bins}")
        self._edges = np.geomspace(lo, hi, bins + 1)
        self._counts = np.zeros(bins, np.int64)
        self._n = 0
        self._sum = 0.0
        self._min = float("inf")
        self._max = 0.0

    def add(self, seconds: float) -> None:
        self.add_many(seconds, 1)

    def add_many(self, seconds: float, k: int) -> None:
        """``k`` samples of the same value in one bin update — how a
        whole-frame flush records its rows without k searchsorted calls."""
        if k <= 0:
            return
        i = int(np.searchsorted(self._edges, seconds, side="right")) - 1
        self._counts[min(max(i, 0), self._counts.size - 1)] += k
        self._n += k
        self._sum += seconds * k
        self._min = min(self._min, seconds)
        self._max = max(self._max, seconds)

    @property
    def count(self) -> int:
        return self._n

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` (log-interpolated inside the hit bin);
        nan when empty.  Clamped by the exact min/max so a one-sample
        histogram reports the sample, not its bin edge."""
        if self._n == 0:
            return float("nan")
        target = q * self._n
        cum = np.cumsum(self._counts)
        i = int(np.searchsorted(cum, target, side="left"))
        i = min(i, self._counts.size - 1)
        prev = float(cum[i - 1]) if i > 0 else 0.0
        inbin = float(self._counts[i])
        frac = (target - prev) / inbin if inbin > 0 else 0.0
        lo, hi = self._edges[i], self._edges[i + 1]
        v = float(lo * (hi / lo) ** min(max(frac, 0.0), 1.0))
        return min(max(v, self._min), self._max)

    def snapshot(self) -> dict:
        """{count, mean, p50, p95, p99, max} in MILLISECONDS (the unit
        every serving dashboard speaks; raw seconds would misread 1000x)."""
        if self._n == 0:
            return {"count": 0}
        ms = 1e3
        return {
            "count": self._n,
            "mean": round(self._sum / self._n * ms, 3),
            "p50": round(self.quantile(0.50) * ms, 3),
            "p95": round(self.quantile(0.95) * ms, 3),
            "p99": round(self.quantile(0.99) * ms, 3),
            "max": round(self._max * ms, 3),
        }


class ServingMetrics:
    """Aggregate serving counters + per-stage latency histograms.

    Writers: ``submit`` callers (requests/rejected) and the collector
    thread (everything else) — one lock covers both; every op is O(1) so
    contention is noise next to a flush's device dispatch.

    Stages: ``queue`` (submit → flush start: micro-batching wait +
    deadline), ``compute`` (device dispatch → scores on host, whole
    flush), ``total`` (submit → future resolved, what a caller feels).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.queue = LatencyHistogram()
        self.compute = LatencyHistogram()
        self.total = LatencyHistogram()
        self.requests = 0
        self.rejected = 0
        self.flushes = 0
        self.deadline_drops = 0  # requests shed at flush because their OWN
        #   deadline expired before scoring (pre-padding; typed `deadline`)
        self.drops_by_class: dict[str, int] = {}  # deadline drops per class
        self.sheds_by_class: dict[str, int] = {}  # overload sheds per class:
        #   submit-side rejects AND tiered evictions (typed `overloaded`)
        self.evicted = 0  # queued requests evicted by a higher-class arrival
        #   (a subset of the sheds — says tiering, not just pressure, fired)
        self.class_total: dict[str, LatencyHistogram] = {}  # per-class
        #   submit→resolved latency (the per-class p50/p99 the SLO gate reads)
        self.flushes_deadline = 0  # timer fired before max_batch filled
        self.flushes_full = 0  # max_batch filled before the timer
        self.rows = 0  # real rows scored (excl. bucket padding)
        self.padded_rows = 0  # bucket-padding rows scored and discarded
        self.reloads = 0  # FULL checkpoint re-reads swapped in
        self.reload_failures = 0  # watcher restore attempts that raised
        self.reload_giveups = 0  # checkpoint signatures abandoned after
        #   reload_max_retries consecutive failures (a persistently corrupt
        #   file; the watcher stops retrying it until a NEW write lands)
        self.delta_reloads = 0  # delta FILES applied in place (a delta
        #   swap does NOT also bump `reloads` — the counters are disjoint)
        self.bucket_rows: dict[int, int] = {}  # bucket size -> real rows
        self.bucket_padded: dict[int, int] = {}  # bucket size -> padding
        #   rows (per-bucket occupancy = rows / (rows + padded): WHERE the
        #   padding waste lives, not just that it exists)
        # Freshness SLO distributions (ISSUE 9): one sample per reload
        # swap — checkpoint publish → state applied (collector swap) and
        # publish → first score resolved against the new state.  Wall
        # clocks on both ends (the publisher stamps, this process reads),
        # so cross-host skew is the documented error bar.
        self.fresh_applied = LatencyHistogram()
        self.fresh_scored = LatencyHistogram()

    @staticmethod
    def _class_key(klass: str) -> str:
        return klass or "default"

    def on_submit(self, accepted: bool, klass: str = "") -> None:
        with self._lock:
            self.requests += 1
            if not accepted:
                self.rejected += 1
                k = self._class_key(klass)
                self.sheds_by_class[k] = self.sheds_by_class.get(k, 0) + 1

    def on_submit_many(self, n: int, accepted: bool, klasses=None) -> None:
        """A whole frame admitted (or rejected) as one unit still counts
        as its n requests — QPS math must not depend on the wire."""
        with self._lock:
            self.requests += n
            if not accepted:
                self.rejected += n
                for klass in klasses if klasses is not None else [""] * n:
                    k = self._class_key(klass)
                    self.sheds_by_class[k] = self.sheds_by_class.get(k, 0) + 1

    def on_evict(self, klass: str = "") -> None:
        """A QUEUED request was shed to admit a higher-class arrival."""
        with self._lock:
            self.evicted += 1
            k = self._class_key(klass)
            self.sheds_by_class[k] = self.sheds_by_class.get(k, 0) + 1

    def on_deadline_drop(self, klass: str = "") -> None:
        """A request's own deadline expired before scoring — shed at the
        flush, BEFORE it could pad a bucket."""
        with self._lock:
            self.deadline_drops += 1
            k = self._class_key(klass)
            self.drops_by_class[k] = self.drops_by_class.get(k, 0) + 1

    def on_flush(
        self,
        bucket: int,
        n_rows: int,
        queue_waits: list[float],
        compute_s: float,
        total_s: list[float],
        deadline_fired: bool,
        classes: list[str] | None = None,
        counts: list[int] | None = None,
    ) -> None:
        """``queue_waits``/``total_s``/``classes`` are parallel per-GROUP
        lists; ``counts[i]`` is how many rows share entry i (a whole
        frame's rows enter as one group — None = every group is 1 row,
        the per-request path)."""
        with self._lock:
            self.flushes += 1
            if deadline_fired:
                self.flushes_deadline += 1
            else:
                self.flushes_full += 1
            self.rows += n_rows
            self.padded_rows += bucket - n_rows
            self.bucket_rows[bucket] = self.bucket_rows.get(bucket, 0) + n_rows
            self.bucket_padded[bucket] = self.bucket_padded.get(bucket, 0) + (
                bucket - n_rows
            )
            self.compute.add(compute_s)
            if counts is None:
                counts = [1] * len(total_s)
            for w, c in zip(queue_waits, counts):
                self.queue.add_many(w, c)
            for i, t in enumerate(total_s):
                c = counts[i]
                self.total.add_many(t, c)
                if classes is not None:
                    k = self._class_key(classes[i])
                    h = self.class_total.get(k)
                    if h is None:
                        h = self.class_total[k] = LatencyHistogram()
                    h.add_many(t, c)

    def on_reload(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.reloads += 1
            else:
                self.reload_failures += 1

    def on_reload_giveup(self) -> None:
        with self._lock:
            self.reload_giveups += 1

    def on_freshness(self, applied_s: float, scored_s: float) -> None:
        """One reload swap's freshness pair (seconds since publish)."""
        with self._lock:
            self.fresh_applied.add(max(0.0, applied_s))
            self.fresh_scored.add(max(0.0, scored_s))

    def on_delta_reload(self, n_deltas: int) -> None:
        """The watcher applied ``n_deltas`` incremental checkpoint files in
        place (no full-table re-read) — counted separately from full
        reloads so a dashboard can see the cheap path is the one firing."""
        with self._lock:
            self.delta_reloads += n_deltas

    def snapshot(self) -> dict:
        """One flat dict (JSONL-ready).  Latencies in ms, keyed per stage;
        occupancy in [0, 1]; bucket_rows keyed by stringified bucket size
        (JSON objects take string keys)."""
        with self._lock:
            scored = self.rows + self.padded_rows
            return {
                "requests": self.requests,
                "rejected": self.rejected,
                "deadline_drops": self.deadline_drops,
                "deadline_drops_by_class": dict(sorted(self.drops_by_class.items())),
                "sheds_by_class": dict(sorted(self.sheds_by_class.items())),
                "evicted": self.evicted,
                "class_total_ms": {
                    k: h.snapshot() for k, h in sorted(self.class_total.items())
                },
                "flushes": self.flushes,
                "flushes_deadline": self.flushes_deadline,
                "flushes_full": self.flushes_full,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "batch_occupancy": round(self.rows / scored, 4) if scored else None,
                "reloads": self.reloads,
                "reload_failures": self.reload_failures,
                "reload_giveups": self.reload_giveups,
                "delta_reloads": self.delta_reloads,
                "bucket_rows": {str(k): v for k, v in sorted(self.bucket_rows.items())},
                "bucket_padded_rows": {
                    str(k): v for k, v in sorted(self.bucket_padded.items())
                },
                "bucket_occupancy": {
                    str(k): round(
                        self.bucket_rows.get(k, 0)
                        / (self.bucket_rows.get(k, 0) + v),
                        4,
                    )
                    for k, v in sorted(self.bucket_padded.items())
                    if self.bucket_rows.get(k, 0) + v
                },
                "queue_ms": self.queue.snapshot(),
                "compute_ms": self.compute.snapshot(),
                "total_ms": self.total.snapshot(),
                "freshness_applied_ms": self.fresh_applied.snapshot(),
                "freshness_scored_ms": self.fresh_scored.snapshot(),
            }

    def log_to(self, sink) -> None:
        """Append the snapshot as a ``kind=serving`` record.  ``sink`` is
        a telemetry.RunMonitor (the engine's — records get the shared
        envelope) or, for bare callers, a utils.tracing.MetricsLogger
        (no-op logger ⇒ no-op here)."""
        snap = self.snapshot()
        emit = getattr(sink, "emit", None)
        if emit is not None:
            emit("serving", **snap)
        else:
            sink.log(kind="serving", **snap)
