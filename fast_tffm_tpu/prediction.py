"""Prediction drivers: restore a model and write scores for input files.

Capability parity with the reference's predict/dist_predict entrypoints
(`renyi533/fast_tffm` :: py/ predictor: Saver.restore → stream the predict
file through parser+scorer → write sigmoid scores, one per line, to the
score path; dist variant shards input across workers).
"""

from __future__ import annotations

import time
from typing import Any, Callable, NamedTuple

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import restore_checkpoint
from fast_tffm_tpu.config import Config, build_model
from fast_tffm_tpu.models.base import Batch
from fast_tffm_tpu.telemetry import RunMonitor
from fast_tffm_tpu.training import _batch_converter, _stream, scan_max_nnz
from fast_tffm_tpu.trainer import init_state, make_predict_step

__all__ = [
    "ScoreFn",
    "load_scoring_state",
    "make_score_fn",
    "predict",
    "dist_predict",
]


class ScoreFn(NamedTuple):
    """A jitted scoring function plus the static facts its callers need.

    ``fn(state, batch) -> sigmoid scores [B]`` is the ONE single-host
    scoring definition: the offline predict driver streams files through
    it and the serving engine (serving/engine.py) dispatches micro-batches
    to it — score parity between the two paths is structural, not tested
    into existence (though tests/test_serving.py pins it anyway).
    """

    fn: Callable  # jitted (state, Batch) -> [B] sigmoid scores
    model: Any  # built model (uses_fields, row_dim)
    max_nnz: int  # static feature width every batch must carry

    def __call__(self, state, batch: Batch):
        return self.fn(state, batch)

    @property
    def uses_fields(self) -> bool:
        return self.model.uses_fields

    def cache_size(self) -> int | None:
        """Compiled-program count (one per distinct batch shape) — how the
        serving bucket ladder pins "zero steady-state recompiles"; None
        when the JAX runtime doesn't expose the jit cache."""
        f = getattr(self.fn, "_cache_size", None)
        try:
            return int(f()) if f is not None else None
        except Exception:
            return None


def load_scoring_state(cfg: Config, log=print):
    """Build the model and restore ``cfg.model_file`` into the configured
    single-host inference layout: checkpoints hold LOGICAL arrays, so a
    packed config lane-packs after the restore (plain packed, never the
    fused RMW layout — scoring only gathers, and the plain gather serves
    any checkpoint regardless of the accumulator it was trained with).

    The one definition of "load a model for inference", shared by
    ``predict()`` and the serving engine's startup AND hot reload — a
    reload can never restore into a different layout than startup did.
    """
    model = build_model(cfg)
    state = init_state(
        model, jax.random.key(0), cfg.init_accumulator_value, cfg.adagrad_accumulator
    )
    state = restore_checkpoint(
        cfg.model_file, state, chunk_bytes=cfg.checkpoint_chunk_mb << 20
    )
    log(f"restored {cfg.model_file} at step {int(state.step)}")
    if cfg.table_layout == "packed":
        from fast_tffm_tpu.trainer import pack_state

        state = pack_state(state, cfg.init_accumulator_value)
    return model, state


def make_score_fn(cfg: Config, state, max_nnz: int, model=None) -> ScoreFn:
    """The single-host scoring step for ``state``'s layout.

    ``cfg.table_layout`` picks rows vs packed; ``state`` itself supplies
    the fused evidence (pack_state's empty-accum marker), so a live
    fused-packed trainer state scores through the fused gather without
    any extra flag.  ``model`` avoids a rebuild when the caller already
    has one; a rebuilt model is identical (pure function of cfg).
    """
    if model is None:
        model = build_model(cfg)
    if cfg.table_layout == "packed":
        from fast_tffm_tpu.trainer import make_packed_predict_step

        fused = state.table_opt.accum.size == 0
        fn = make_packed_predict_step(model, fused=fused)
    else:
        fn = make_predict_step(model)
    return ScoreFn(fn=fn, model=model, max_nnz=int(max_nnz))


def _run_predict(
    cfg: Config, state, predict_step, max_nnz, log=print, mesh=None, with_fields=True
) -> str:
    if not cfg.predict_files:
        raise ValueError("no predict_files configured")
    # Multi-host: the sharded predict step is ONE SPMD program over the
    # global mesh; replicated scores come back on every process and process
    # 0 writes them.  When the batch size divides evenly, the INPUT is also
    # sharded — process p parses only rows [p·B/P, (p+1)·B/P) of each
    # global batch (the reference's dist_predict spread input files across
    # workers; here parse throughput scales with the host count the same
    # way).  Otherwise every process parses identical full batches and the
    # mesh still shards the compute at chip granularity.
    nproc = jax.process_count()
    is_lead = jax.process_index() == 0
    shard_input = mesh is not None and nproc > 1 and cfg.batch_size % nproc == 0
    stream_kw = {}
    # The local converter (uses_fields-marked) — scoring rides the same
    # packed-wire staging as training when wire_format = packed and the
    # input is FMB-backed (one coalesced H2D buffer per batch).
    to_batch = _batch_converter(with_fields)
    remaining = None
    bs = cfg.batch_size  # per-process stream batch size
    if shard_input:
        from fast_tffm_tpu.data.native import count_lines
        from fast_tffm_tpu.parallel import make_global_batch

        total = count_lines(cfg.predict_files)
        bs = cfg.batch_size // nproc
        # The stream's batch size MUST equal shard_block: block-cyclic line
        # selection is aligned to global batch slots only at that size.
        stream_kw = dict(
            shard_index=jax.process_index(),
            shard_count=nproc,
            shard_block=bs,
            pad_to_batches=-(-total // cfg.batch_size),  # ceil
        )
        to_batch = lambda parsed, w: make_global_batch(mesh, parsed, w, with_fields=with_fields)
        # uses_fields without wire_capable: honest kind=input byte
        # estimates, packed wire off (the global stitch ships arrays).
        to_batch.uses_fields = with_fields
        # Padding (short final batch + all-empty tail batches) sits strictly
        # after the data rows, so the real scores are exactly the first
        # `total` of the concatenated stream — no global weight mask needed.
        remaining = total
        if is_lead:
            log(f"predict input sharding: {total} rows over {nproc} processes")
    n = 0
    batches = 0
    # Same envelope/sentinels as training, tagged source=predict: a
    # steady-state recompile or a parse stall in a backfill surfaces in
    # the same JSONL stream tools/report.py reads.
    monitor = RunMonitor(
        cfg.metrics_path if is_lead else None,
        run_id=cfg.telemetry_run_id,
        source="predict",
        stall_timeout_s=cfg.telemetry_stall_timeout_s,
        mem_every_s=cfg.telemetry_mem_every_s,
        log=log,
    )
    # Measured cost ledger (profiling.py): ONE kind=profile record for
    # the predict program — bytes accessed / FLOPs from XLA cost
    # analysis, emitted after the first dispatch compiled it.
    ledger = None
    if cfg.telemetry_profile_costs:
        from fast_tffm_tpu.profiling import CostLedger

        ledger = CostLedger(monitor, source="predict")
    t_start = time.perf_counter()
    out = None
    try:
        # Inside the try: an unwritable score_path must still close the
        # monitor (summary record, watchdog thread) on the way out.
        out = open(cfg.score_path, "w") if is_lead else None
        # _stream owns the prefetch wiring AND the conversion-placement
        # policy (H2D in the prefetch thread iff the input is FMB-backed);
        # a None batch means convert here in the consumer (text input).
        stream = _stream(
            cfg,
            cfg.predict_files,
            max_nnz,
            epochs=1,
            batch_size=bs,
            weights=None,
            to_batch=to_batch,
            **stream_kw,
        )
        monitor.set_queue_depth_fn(getattr(stream, "queue_depth", None))
        for b, parsed, w in stream:
            if b is None:
                b = to_batch(parsed, w)
            if ledger is not None and ledger.want("predict_step"):
                ledger.stage(
                    "predict_step", predict_step, (state, b),
                    examples=int(getattr(b.labels, "shape", (0,))[0] or 0) or None,
                )
            scores = np.asarray(predict_step(state, b))
            batches += 1
            monitor.on_dispatch(batches, warmup=(batches == 1))
            if ledger is not None:
                ledger.flush(batches)
            if not np.isfinite(scores).all():
                # Under lookup_overflow=fallback an overflow cannot
                # poison scores (the lookup reran via allgather).
                cause = (
                    "an alltoall-lookup capacity overflow (raise "
                    "lookup_capacity_factor, set lookup_overflow = "
                    "fallback, or use lookup=allgather) or a diverged model"
                    if cfg.lookup == "alltoall" and cfg.lookup_overflow == "abort"
                    else "a diverged model (non-finite weights)"
                )
                monitor.emit_anomaly(
                    batches, None, event="nonfinite_scores", state=state
                )
                raise RuntimeError(
                    f"non-finite scores — {cause}; refusing to write a "
                    f"poisoned score file to {cfg.score_path}"
                )
            if remaining is not None:
                take = min(remaining, len(scores))
                remaining -= take
                real = np.arange(len(scores)) < take
            else:
                real = w > 0  # drop batch-size padding rows
            if out is not None:
                for s in scores[real]:
                    out.write(f"{s:.6f}\n")
            n += int(real.sum())
        dt = time.perf_counter() - t_start
        stats = getattr(stream, "stats", None)
        if stats is not None:
            rec = stats.drain()
            if rec:
                monitor.emit("input", step=batches, **rec)
        monitor.emit(
            "predict",
            step=batches,
            examples=n,
            examples_per_sec=round(n / dt, 1) if dt > 0 else None,
        )
    finally:
        if out is not None:
            out.close()
        monitor.close()
    if is_lead:
        log(f"wrote {n} scores -> {cfg.score_path}")
    return cfg.score_path


def predict(cfg: Config, log=print) -> str:
    """Single-device prediction — the reference's `predict` mode."""
    model, state = load_scoring_state(cfg, log)
    score = make_score_fn(cfg, state, scan_max_nnz(cfg), model=model)
    return _run_predict(
        cfg, state, score.fn, score.max_nnz, log, with_fields=score.uses_fields
    )


def dist_predict(cfg: Config, log=print, mesh=None) -> str:
    """Mesh-sharded prediction — the reference's `dist_predict` mode."""
    from fast_tffm_tpu.parallel import (
        check_batch_divides,
        init_sharded_state,
        make_mesh,
        make_sharded_predict_step,
    )
    from fast_tffm_tpu.distributed import initialize_runtime

    initialize_runtime(cfg, log=log)
    model = build_model(cfg)
    max_nnz = scan_max_nnz(cfg)
    if mesh is None:
        row = cfg.row_parallel or cfg.vocabulary_block_num
        data = cfg.data_parallel or None
        mesh = make_mesh(data, row)
    check_batch_divides(cfg.batch_size, mesh)
    if cfg.table_layout == "packed":
        # Checkpoints hold logical arrays; restore into a rows-layout
        # template on the PACKED padding and convert per shard on device
        # (multi-host safe — no host gather; same scheme as dist_train's
        # packed resume).
        from fast_tffm_tpu.parallel import pack_sharded_on_device
        from fast_tffm_tpu.parallel.train_step import packed_shard_meta

        # A fused-trained checkpoint is padded with the FUSED pack factor
        # (stride D+1), which differs from the plain packed padding —
        # the template must match or the multi-host restore (which cannot
        # re-pad) raises on the shape.  The predict step then reads the
        # same layout the state was packed into.
        fused_acc = cfg.adagrad_accumulator == "fused"
        padded_model, _, _ = packed_shard_meta(model, mesh, fused=fused_acc)
        logical = restore_checkpoint(
            cfg.model_file,
            init_sharded_state(
                padded_model, mesh, jax.random.key(0),
                cfg.init_accumulator_value, cfg.adagrad_accumulator,
            ),
            chunk_bytes=cfg.checkpoint_chunk_mb << 20,
        )
        state = pack_sharded_on_device(
            logical, model, mesh, cfg.init_accumulator_value, fused=fused_acc
        )
    else:
        state = init_sharded_state(
            model, mesh, jax.random.key(0), cfg.init_accumulator_value,
            cfg.adagrad_accumulator,
        )
        state = restore_checkpoint(
            cfg.model_file, state, chunk_bytes=cfg.checkpoint_chunk_mb << 20
        )
    return _run_predict(
        cfg,
        state,
        make_sharded_predict_step(
            model, mesh, lookup=cfg.lookup,
            capacity_factor=cfg.lookup_capacity_factor,
            overflow_mode=cfg.lookup_overflow, table_layout=cfg.table_layout,
            accumulator=cfg.adagrad_accumulator,
        ),
        max_nnz,
        log,
        mesh=mesh,
        with_fields=model.uses_fields,
    )
