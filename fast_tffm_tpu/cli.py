"""CLI dispatcher — the reference's fast_tffm.py entry surface.

`renyi533/fast_tffm` :: fast_tffm.py: positional mode + cfg path
(`python fast_tffm.py {train,predict,dist_train,dist_predict} <cfg>
[job_name task_index]`).  The job_name/task_index pair is accepted for CLI
compatibility but ignored with a notice: under single-program SPMD there is
no per-task launch — one process drives the whole mesh.
"""

from __future__ import annotations

import argparse
import sys

from fast_tffm_tpu.config import load_config

MODES = ("train", "predict", "dist_train", "dist_predict", "convert", "serve")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fast_tffm",
        description="TPU-native factorization machine trainer (fast_tffm capabilities)",
    )
    ap.add_argument("mode", choices=MODES)
    ap.add_argument("config", help="INI config file (see sample.cfg)")
    ap.add_argument("legacy", nargs="*", help="ignored job_name/task_index (TF-1.x compat)")
    ap.add_argument("--resume", action="store_true", help="resume training from model_file")
    ap.add_argument(
        "--metrics-path",
        default=None,
        metavar="PATH",
        help="telemetry JSONL sink; overrides [Train] metrics_path so a run "
        "can be instrumented (tools/report.py) without editing the config",
    )
    ap.add_argument(
        "--run-id",
        default=None,
        metavar="ID",
        help="telemetry run id stamped on every record; overrides "
        "[Telemetry] run_id (default: auto-generated per run)",
    )
    args = ap.parse_args(argv)

    from fast_tffm_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    cfg = load_config(args.config)
    if args.metrics_path is not None:
        cfg.metrics_path = args.metrics_path
    if args.run_id is not None:
        cfg.telemetry_run_id = args.run_id
    if cfg.telemetry_compilation_cache_dir:
        # Before any driver import compiles a program: repeated runs (and
        # serving cold starts) then read their XLA programs back from the
        # on-disk cache instead of recompiling — the compile sentinel
        # reports the hits distinctly (kind=compile cache_hits).
        from fast_tffm_tpu.telemetry import enable_compilation_cache

        enable_compilation_cache(cfg.telemetry_compilation_cache_dir)
    if args.legacy:
        print(
            f"note: ignoring legacy cluster args {args.legacy!r} — the SPMD mesh "
            "replaces ps/worker tasks (one launch drives all devices)",
            file=sys.stderr,
        )

    if args.mode == "train":
        from fast_tffm_tpu.training import train

        train(cfg, resume=args.resume)
    elif args.mode == "dist_train":
        from fast_tffm_tpu.training import dist_train

        dist_train(cfg, resume=args.resume)
    elif args.mode == "predict":
        from fast_tffm_tpu.prediction import predict

        predict(cfg)
    elif args.mode == "serve":
        # Online path: libsvm lines on stdin -> one score per line on
        # stdout, micro-batched through the bucket-compiled engine
        # ([Serving] config).  Logs/metrics go to stderr/metrics_path so
        # the score stream stays clean for piping.
        from fast_tffm_tpu.serving import serve_lines

        return serve_lines(cfg, log=lambda *a: print(*a, file=sys.stderr))
    elif args.mode == "convert":
        # Pre-pack every configured data file into its FMB binary cache
        # (what `binary_cache = true` would do lazily at first stream) —
        # handy before a pod run so training starts at memmap speed.
        # Per FILE, not one ensure_fmb_cache call: the all-or-nothing text
        # fallback is a per-STREAM rule, but these files feed independent
        # streams — an unwritable predict mount must not abort packing the
        # train files.  No upfront width scan either: a fresh-cache rerun
        # stays nearly free, and write_fmb defaults to each file's widest
        # row (compatible with any training-time max_nnz >= it).
        from fast_tffm_tpu.data.binary import ensure_fmb_cache, is_fmb

        files = tuple(
            dict.fromkeys((*cfg.train_files, *cfg.validation_files, *cfg.predict_files))
        )
        if not files:
            print("no data files configured", file=sys.stderr)
            return 1
        if not cfg.binary_cache:
            print(
                "note: this config has binary_cache = false — set it to true "
                "(or put the .fmb paths in the file lists) so train/predict "
                "actually stream the packed caches",
                file=sys.stderr,
            )
        failures = 0
        for src in files:
            try:
                (dst,) = ensure_fmb_cache(
                    [src],
                    vocabulary_size=cfg.vocabulary_size,
                    hash_feature_id=cfg.hash_feature_id,
                    max_nnz=cfg.max_nnz or None,
                    log=print,
                )
            except (OSError, ValueError, RuntimeError) as e:
                # ValueError: malformed libsvm / id out of range;
                # RuntimeError: file changed mid-convert.  One bad FILE must
                # not abort packing the rest any more than a bad mount does.
                print(f"{src}: FAILED ({e})", file=sys.stderr)
                failures += 1
                continue
            if dst == src and not is_fmb(src):
                # The unwritable-location fallback hands back the text path.
                print(f"{src}: FAILED (cache location unwritable)", file=sys.stderr)
                failures += 1
            elif src == dst:
                print(f"{src} (already FMB)")
            else:
                print(f"{src} -> {dst}")
        if failures:
            print(f"{failures} of {len(files)} files not converted", file=sys.stderr)
            return 1
    else:
        from fast_tffm_tpu.prediction import dist_predict

        dist_predict(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
