"""CLI dispatcher — the reference's fast_tffm.py entry surface.

`renyi533/fast_tffm` :: fast_tffm.py: positional mode + cfg path
(`python fast_tffm.py {train,predict,dist_train,dist_predict} <cfg>
[job_name task_index]`).  The job_name/task_index pair is accepted for CLI
compatibility but ignored with a notice: under single-program SPMD there is
no per-task launch — one process drives the whole mesh.
"""

from __future__ import annotations

import argparse
import sys

from fast_tffm_tpu.config import load_config

MODES = ("train", "predict", "dist_train", "dist_predict", "convert", "serve")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fast_tffm",
        description="TPU-native factorization machine trainer (fast_tffm capabilities)",
    )
    ap.add_argument("mode", choices=MODES)
    ap.add_argument("config", help="INI config file (see sample.cfg)")
    ap.add_argument("legacy", nargs="*", help="ignored job_name/task_index (TF-1.x compat)")
    ap.add_argument("--resume", action="store_true", help="resume training from model_file")
    ap.add_argument(
        "--metrics-path",
        default=None,
        metavar="PATH",
        help="telemetry JSONL sink; overrides [Train] metrics_path so a run "
        "can be instrumented (tools/report.py) without editing the config",
    )
    ap.add_argument(
        "--run-id",
        default=None,
        metavar="ID",
        help="telemetry run id stamped on every record; overrides "
        "[Telemetry] run_id (default: auto-generated per run)",
    )
    ap.add_argument(
        "--profile-steps",
        default=None,
        metavar="A:B",
        help="capture a jax.profiler trace over steps [A, B) (rounded to "
        "dispatch boundaries under step fusion) into <model_file>.profile "
        "(trace_dir overrides); overrides [Telemetry] profile_steps",
    )
    ap.add_argument(
        "--supervised",
        action="store_true",
        help="train/dist_train only: run the trainer as a SUPERVISED child "
        "process — a crash relaunches it with bounded retries and "
        "exponential backoff ([Resilience] restart_* keys), resuming from "
        "the latest full+delta checkpoint chain; kind=fault/restart "
        "telemetry (incl. MTTR) goes to metrics_path",
    )
    ap.add_argument(
        "--max-restarts",
        type=int,
        default=None,
        metavar="N",
        help="override [Resilience] restart_max for --supervised",
    )
    ap.add_argument(
        "--fault-plan",
        default=None,
        metavar="SPEC",
        help="arm a deterministic fault plan (chaos testing): "
        "'kill@120,io_error@45,nan@200:210,torn_delta@1' or "
        "'random:kill=2,io_error=3' drawn from --fault-seed; under "
        "--supervised the plan applies to the FIRST launch only (restarts "
        "run clean)",
    )
    ap.add_argument(
        "--fault-seed", type=int, default=0, metavar="N",
        help="seed for random: fault plans (same seed = same schedule)",
    )
    ap.add_argument(
        "--fault-horizon", type=int, default=1000, metavar="STEPS",
        help="step horizon random: fault plans draw positions from",
    )
    ap.add_argument(
        "--port", type=int, default=None, metavar="P",
        help="serve only: run the SOCKET front end (serving/frontend.py) on "
        "this TCP port instead of the stdin/stdout pipe — replicated "
        "engines, health-checked failover, typed wire errors ([Serving] "
        "replicas/classes/deadline_ms).  0 = ephemeral (announced as "
        "SERVE_READY on stdout).  [Serving] port > 0 implies this mode",
    )
    ap.add_argument(
        "--fault-process", type=int, default=0, metavar="P",
        help="pod-supervised dist_train only: arm --fault-plan on host P "
        "(default 0, the checkpoint writer; -1 = every host — e.g. nan "
        "faults, which each host must observe) — the writer-kill vs "
        "survivor-kill axis of the pod chaos matrix",
    )
    args = ap.parse_args(argv)

    from fast_tffm_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    cfg = load_config(args.config)
    if args.metrics_path is not None:
        cfg.metrics_path = args.metrics_path
    if args.run_id is not None:
        cfg.telemetry_run_id = args.run_id
    if args.profile_steps is not None:
        from fast_tffm_tpu.profiling import parse_profile_steps

        parse_profile_steps(args.profile_steps)  # fail fast on a bad spec
        cfg.telemetry_profile_steps = args.profile_steps
    if cfg.telemetry_compilation_cache_dir:
        # Before any driver import compiles a program: repeated runs (and
        # serving cold starts) then read their XLA programs back from the
        # on-disk cache instead of recompiling — the compile sentinel
        # reports the hits distinctly (kind=compile cache_hits).
        from fast_tffm_tpu.telemetry import enable_compilation_cache

        enable_compilation_cache(cfg.telemetry_compilation_cache_dir)
    if args.legacy:
        print(
            f"note: ignoring legacy cluster args {args.legacy!r} — the SPMD mesh "
            "replaces ps/worker tasks (one launch drives all devices)",
            file=sys.stderr,
        )

    if args.supervised:
        if args.mode not in ("train", "dist_train"):
            ap.error("--supervised applies to train / dist_train only")
        # The supervisor process stays device-free: it re-execs THIS CLI
        # as a child (without --supervised), watches it, and relaunches
        # on crash with --resume so the child restores the latest
        # full+delta chain at the exact saved input position.
        import os

        from fast_tffm_tpu.resilience import Supervisor

        # ONE run id for the whole supervised run: the supervisor's
        # fault/restart records and every child's train/ckpt/input
        # records must share it, or tools/report.py (which summarizes
        # one run_id per file) would drop the crash history and the
        # Resilience section from a supervised run's report.
        if not cfg.telemetry_run_id:
            from fast_tffm_tpu.telemetry import new_run_id

            cfg.telemetry_run_id = new_run_id()
        base = [sys.executable, "-m", "fast_tffm_tpu.cli", args.mode, args.config]
        if args.metrics_path is not None:
            base += ["--metrics-path", args.metrics_path]
        base += ["--run-id", cfg.telemetry_run_id]
        # The child resolves the package the same way THIS process did —
        # works for pip installs and straight-from-checkout runs alike.
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        child_env = dict(os.environ)
        child_env["PYTHONPATH"] = (
            pkg_root + os.pathsep + child_env["PYTHONPATH"]
            if child_env.get("PYTHONPATH")
            else pkg_root
        )

        if args.mode == "dist_train" and cfg.num_processes > 1:
            # POD supervision: one supervisor process owns N local trainer
            # children (one per pod host), the shared generation file, and
            # the single-host-relaunch recovery protocol (distributed.py).
            # A chaos plan arms on --fault-process's first launch only.
            runtime_dir = cfg.runtime_dir or (cfg.model_file + ".dist")

            def build_pod_cmd(attempt: int, resume_flag: bool, proc: int) -> list[str]:
                cmd = list(base)
                if resume_flag:
                    cmd += ["--resume"]
                if args.fault_plan and attempt == 0 and (
                    proc == args.fault_process or args.fault_process < 0
                ):
                    cmd += [
                        "--fault-plan", args.fault_plan,
                        "--fault-seed", str(args.fault_seed),
                        "--fault-horizon", str(args.fault_horizon),
                    ]
                return cmd

            sup = Supervisor(
                build_pod_cmd,
                model_file=cfg.model_file,
                max_restarts=(
                    args.max_restarts if args.max_restarts is not None else cfg.restart_max
                ),
                backoff_s=cfg.restart_backoff_s,
                backoff_max_s=cfg.restart_backoff_max_s,
                metrics_path=cfg.metrics_path or None,
                run_id=cfg.telemetry_run_id,
                log=lambda *a: print(*a, file=sys.stderr),
                child_log=print,
                env=child_env,
                processes=cfg.num_processes,
                runtime_dir=runtime_dir,
                straggler_timeout_s=cfg.host_stall_timeout_s,
            )
            return sup.run(resume=args.resume)

        def build_cmd(attempt: int, resume_flag: bool) -> list[str]:
            cmd = list(base)
            if resume_flag:
                cmd += ["--resume"]
            if args.fault_plan and attempt == 0:
                # Chaos plans arm the FIRST launch only: a kill fault that
                # re-armed on every relaunch would crash-loop forever.
                cmd += [
                    "--fault-plan", args.fault_plan,
                    "--fault-seed", str(args.fault_seed),
                    "--fault-horizon", str(args.fault_horizon),
                ]
            return cmd

        sup = Supervisor(
            build_cmd,
            model_file=cfg.model_file,
            max_restarts=(
                args.max_restarts if args.max_restarts is not None else cfg.restart_max
            ),
            backoff_s=cfg.restart_backoff_s,
            backoff_max_s=cfg.restart_backoff_max_s,
            metrics_path=cfg.metrics_path or None,
            run_id=cfg.telemetry_run_id,
            log=lambda *a: print(*a, file=sys.stderr),
            child_log=print,
            env=child_env,
        )
        return sup.run(resume=args.resume)

    step_hook = None
    if args.fault_plan:
        from fast_tffm_tpu.resilience import FaultPlan, install_faults

        inj = install_faults(
            FaultPlan.parse(
                args.fault_plan, seed=args.fault_seed, horizon=args.fault_horizon
            )
        )
        print(f"fault plan armed: {inj.plan.to_json()}", file=sys.stderr)
        step_hook = inj.step_hook

    if args.mode == "train":
        from fast_tffm_tpu.training import train

        train(cfg, resume=args.resume, step_hook=step_hook)
    elif args.mode == "dist_train":
        from fast_tffm_tpu.training import dist_train

        try:
            dist_train(cfg, resume=args.resume, step_hook=step_hook)
        except Exception as e:
            from fast_tffm_tpu.resilience import NonFiniteLossError

            if isinstance(e, NonFiniteLossError):
                raise  # a shared, deterministic decision — never peer loss
            import os
            import time as _time

            from fast_tffm_tpu.distributed import (
                ENV_GENERATION,
                ENV_RUNTIME_DIR,
                PEER_LOST_EXIT,
                read_generation,
            )

            gen_env = os.environ.get(ENV_GENERATION)
            rdir = os.environ.get(ENV_RUNTIME_DIR)
            if gen_env is None or not rdir:
                raise
            # Pod child: an escaping error here is USUALLY collateral of a
            # peer dying (gloo/coordination errors surface as generic
            # runtime errors).  Dying now would turn one host's crash into
            # N relaunches, so PARK: the supervisor's generation bump
            # re-execs this process via the watcher thread mid-sleep.  If
            # no bump arrives, the failure was ours alone — re-raise it.
            print(
                f"dist_train failed ({e!r}); parking for a pod generation "
                "bump (peer crash?) before giving up",
                file=sys.stderr,
            )
            deadline = _time.monotonic() + min(30.0, cfg.barrier_timeout_s)
            while _time.monotonic() < deadline:
                _time.sleep(0.25)
            info = read_generation(rdir)
            if info is not None and int(info.get("generation", -1)) > int(gen_env):
                # Bump landed but the watcher lost the exec race — die
                # with the collateral code; the supervisor relaunches us
                # into the current generation.
                return PEER_LOST_EXIT
            raise
    elif args.mode == "predict":
        from fast_tffm_tpu.prediction import predict

        predict(cfg)
    elif args.mode == "serve":
        if args.port is not None or cfg.serve_port > 0:
            # Socket mode: TCP front end -> router -> serve_replicas
            # engine worker processes (per-replica jit caches), with
            # health-checked failover, deadline/class admission, and the
            # router-owned checkpoint-reload fan-out.
            from fast_tffm_tpu.serving.frontend import run_frontend

            return run_frontend(
                cfg,
                args.config,
                port=args.port,
                log=lambda *a: print(*a, file=sys.stderr),
            )
        # Pipe mode: libsvm lines on stdin -> one score per line on
        # stdout, micro-batched through the bucket-compiled engine
        # ([Serving] config).  Logs/metrics go to stderr/metrics_path so
        # the score stream stays clean for piping.
        from fast_tffm_tpu.serving import serve_lines

        return serve_lines(cfg, log=lambda *a: print(*a, file=sys.stderr))
    elif args.mode == "convert":
        # Pre-pack every configured data file into its FMB binary cache
        # (what `binary_cache = true` would do lazily at first stream) —
        # handy before a pod run so training starts at memmap speed.
        # Per FILE, not one ensure_fmb_cache call: the all-or-nothing text
        # fallback is a per-STREAM rule, but these files feed independent
        # streams — an unwritable predict mount must not abort packing the
        # train files.  No upfront width scan either: a fresh-cache rerun
        # stays nearly free, and write_fmb defaults to each file's widest
        # row (compatible with any training-time max_nnz >= it).
        from fast_tffm_tpu.data.binary import ensure_fmb_cache, is_fmb

        files = tuple(
            dict.fromkeys((*cfg.train_files, *cfg.validation_files, *cfg.predict_files))
        )
        if not files:
            print("no data files configured", file=sys.stderr)
            return 1
        if not cfg.binary_cache:
            print(
                "note: this config has binary_cache = false — set it to true "
                "(or put the .fmb paths in the file lists) so train/predict "
                "actually stream the packed caches",
                file=sys.stderr,
            )
        failures = 0
        for src in files:
            try:
                (dst,) = ensure_fmb_cache(
                    [src],
                    vocabulary_size=cfg.vocabulary_size,
                    hash_feature_id=cfg.hash_feature_id,
                    max_nnz=cfg.max_nnz or None,
                    log=print,
                )
            except (OSError, ValueError, RuntimeError) as e:
                # ValueError: malformed libsvm / id out of range;
                # RuntimeError: file changed mid-convert.  One bad FILE must
                # not abort packing the rest any more than a bad mount does.
                print(f"{src}: FAILED ({e})", file=sys.stderr)
                failures += 1
                continue
            if dst == src and not is_fmb(src):
                # The unwritable-location fallback hands back the text path.
                print(f"{src}: FAILED (cache location unwritable)", file=sys.stderr)
                failures += 1
            elif src == dst:
                print(f"{src} (already FMB)")
            else:
                print(f"{src} -> {dst}")
        if failures:
            print(f"{failures} of {len(files)} files not converted", file=sys.stderr)
            return 1
    else:
        from fast_tffm_tpu.prediction import dist_predict

        dist_predict(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
