"""CLI dispatcher — the reference's fast_tffm.py entry surface.

`renyi533/fast_tffm` :: fast_tffm.py: positional mode + cfg path
(`python fast_tffm.py {train,predict,dist_train,dist_predict} <cfg>
[job_name task_index]`).  The job_name/task_index pair is accepted for CLI
compatibility but ignored with a notice: under single-program SPMD there is
no per-task launch — one process drives the whole mesh.
"""

from __future__ import annotations

import argparse
import sys

from fast_tffm_tpu.config import load_config

MODES = ("train", "predict", "dist_train", "dist_predict")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fast_tffm",
        description="TPU-native factorization machine trainer (fast_tffm capabilities)",
    )
    ap.add_argument("mode", choices=MODES)
    ap.add_argument("config", help="INI config file (see sample.cfg)")
    ap.add_argument("legacy", nargs="*", help="ignored job_name/task_index (TF-1.x compat)")
    ap.add_argument("--resume", action="store_true", help="resume training from model_file")
    args = ap.parse_args(argv)

    from fast_tffm_tpu.utils.platform import apply_platform_env

    apply_platform_env()

    cfg = load_config(args.config)
    if args.legacy:
        print(
            f"note: ignoring legacy cluster args {args.legacy!r} — the SPMD mesh "
            "replaces ps/worker tasks (one launch drives all devices)",
            file=sys.stderr,
        )

    if args.mode == "train":
        from fast_tffm_tpu.training import train

        train(cfg, resume=args.resume)
    elif args.mode == "dist_train":
        from fast_tffm_tpu.training import dist_train

        dist_train(cfg, resume=args.resume)
    elif args.mode == "predict":
        from fast_tffm_tpu.prediction import predict

        predict(cfg)
    else:
        from fast_tffm_tpu.prediction import dist_predict

        dist_predict(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
