"""INI config schema honoring the reference's key vocabulary.

Capability parity with `renyi533/fast_tffm` :: sample.cfg + the
ConfigParser reads inside its train/predict modules: General (factor_num,
vocabulary_size, vocabulary_block_num, hash_feature_id, model_file), Train
(files, epoch_num, batch_size, learning_rate, init_value_range,
factor_lambda, bias_lambda, ...), Predict (input + score path).  New,
TPU-specific keys are additive: [General] model/order/num_fields for the
model zoo, [Distributed] data_parallel/row_parallel for the mesh (the
reference's ps_hosts/worker_hosts cluster section has no meaning under
single-program SPMD — vocabulary_block_num maps to row_parallel).
"""

from __future__ import annotations

import configparser
import dataclasses


@dataclasses.dataclass
class Config:
    # [General]
    model: str = "fm"  # fm | ffm | deepfm
    factor_num: int = 8
    order: int = 2
    num_fields: int = 0  # required for ffm/deepfm
    hidden_dims: tuple[int, ...] = (400, 400, 400)  # deepfm MLP head
    compute_dtype: str = "float32"  # MXU input precision: deepfm MLP matmuls
    #   and ffm interaction einsums (float32 | bfloat16; accumulation stays f32)
    vocabulary_size: int = 1 << 20
    vocabulary_block_num: int = 1  # reference key; default row_parallel
    hash_feature_id: bool = False
    table_layout: str = "rows"  # rows ([V,D]) | packed (lane-packed [V/P,128]
    #   tile rows — fixes the partial-lane scatter cliff, DESIGN §6; composes
    #   with both accumulator granularities and both lookup collectives;
    #   dist shards it, incl. multi-host)
    model_file: str = "model.ckpt"
    checkpoint_format: str = "npz"  # npz | orbax (orbax = sharded, pod-scale)
    # [Checkpoint] — async/incremental saves (checkpoint_async.py; npz only)
    async_save: bool = False  # take full saves off the train loop: on-device
    #   snapshot at the boundary, a writer thread does convert/D2H/write;
    #   at most one in flight (next boundary blocks if the writer lags);
    #   SIGTERM/final saves stay synchronous (last-good-state unchanged)
    delta_every_steps: int = 0  # >0: between full saves, write a delta-NNNN
    #   file every N steps carrying ONLY the rows the window touched (the
    #   on-device touched-row bitmap) + dense leaves, content-signature
    #   chained to the base; restore replays base+chain; 0 = off
    delta_chain_max: int = 16  # deltas per chain before the next boundary
    #   promotes itself to a full save (bounds restore replay length)
    delta_full_every_s: float = 0.0  # [Checkpoint] full_every_s: AGE-based
    #   chain compaction — a delta boundary promotes itself to a full save
    #   once this many seconds passed since the last full publish, so an
    #   hours-long online run compacts (full saves unlink old deltas) even
    #   when the chain count stays under delta_chain_max (0 = off)
    delta_chain_max_bytes: int = 0  # [Checkpoint] chain_max_bytes: SIZE-based
    #   chain compaction — promote to full once the current chain's delta
    #   files total this many bytes (0 = off); together with full_every_s
    #   this bounds the delta chain's disk footprint for unbounded runs
    checkpoint_chunk_mb: int = 64  # save/restore host-staging bound: arrays
    #   stream D2H/disk in this many MB per slice (never 2x table on host)
    # [Train]
    train_files: tuple[str, ...] = ()
    weight_files: tuple[float, ...] = ()  # per-file example weights
    validation_files: tuple[str, ...] = ()
    epoch_num: int = 1
    batch_size: int = 1024
    max_nnz: int = 0  # 0 = infer from first batch file scan
    learning_rate: float = 0.01
    init_value_range: float = 0.01
    factor_lambda: float = 0.0
    bias_lambda: float = 0.0
    init_accumulator_value: float = 0.1
    adagrad_accumulator: str = "element"  # element (TF parity) | row (D×-smaller
    #   state) | fused (row semantics, accumulator stored inside the packed
    #   table's tile rows — 2-random-op RMW; requires table_layout=packed)
    packed_compact_cap: int = 0  # fused compact tail: cap the compacted-row
    #   buffer (0 = exact min(VP, M)); overflowing batches take an exact
    #   lax.cond fallback, so skewed (Zipf/CTR) ids get a ~3x smaller RMW
    #   with no correctness risk (ops/packed_table.py round-5 entry)
    packed_update: str = "auto"  # packed sparse tail: auto | dense | compact | sorted
    #   (dense = wide scatter-add into a [VP,128] grad buffer + dense Adagrad
    #   sweep, measured 3.5× the sorted pipeline; compact = sort-free
    #   touched-row compaction, O(M) buffers — the giant-vocab path; sorted =
    #   the bit-parity reference pipeline; auto picks dense/compact by size)
    tail: str = "auto"  # sparse Adagrad tail: xla (the gather/scatter program
    #   chain) | pallas (ops/pallas_tail.py one-pass gather→update→scatter
    #   kernel, double-buffered row DMA) | auto (pallas on TPU, xla
    #   elsewhere — off-TPU the kernel would run interpreted).  pallas with
    #   table_layout=packed requires adagrad_accumulator=fused (the kernel's
    #   merged layout); incompatible with dedup_gather_rows (the kernel
    #   dedups internally)
    thread_num: int = 0  # host-side parse workers; 0 = all cores (reference: queue threads)
    binary_cache: bool = False  # parse text once into <file>.fmb, stream that
    binary_cache_wait: float = 600.0  # multi-host: non-lead wait for lead's build (s)
    shuffle: bool = False  # per-epoch global shuffle of train rows (FMB input only)
    shuffle_seed: int = 0
    device_cache: bool = False  # load the (FMB) train set to device HBM once,
    #   slice batches on-chip — zero per-step host→device bytes; dist_train
    #   shards the resident arrays over the mesh, per-process assembly
    #   multi-host (no shuffle on dist)
    steps_per_call: int = 1  # fuse K train steps into ONE jitted dispatch
    #   (lax.scan over K micro-batches).  1 = one dispatch per batch (the
    #   classic loop); K>1 amortizes per-step dispatch/H2D overhead on every
    #   path: streamed input ships [K, B, ...] superbatches (one transfer
    #   per K steps), device_cache scans K resident batch slices with zero
    #   host involvement in between, dist_train scans around the SPMD body.
    #   Per-step losses keep full granularity; stop/checkpoint boundaries
    #   become K-step-aligned (DESIGN.md "Step fusion").
    dedup_gather_rows: int = 0  # device-side dedup-before-gather on the
    #   streamed path (ROADMAP item 2(a)): >0 caps the per-batch unique-id
    #   set at N — the forward gather reads at most N table rows (one HBM
    #   read per unique row; per-slot re-reads hit the compact buffer),
    #   cashing in the measured 0.291 dedup ratio.  Values are identical
    #   to the direct gather, so losses stay BIT-IDENTICAL (test-pinned).
    #   The stream VERIFIES each batch fits N before it ships (loud error,
    #   never silent truncation).  0 = off; rows layout, streamed local
    #   train only
    wire_format: str = "packed"  # streamed H2D staging: packed (ONE coalesced
    #   byte buffer per superbatch, with device-side reconstruction of
    #   elidable tensors — all-ones vals, unused fields, uniform weights,
    #   narrow ids; bit-identical batches, ~2-3x fewer wire bytes on CTR
    #   libsvm) | arrays (classic one-device_put-per-tensor staging).
    #   Engages on FMB-backed streams; text input always ships arrays.
    queue_size: int = 8  # prefetch depth
    log_every: int = 100
    save_every_epochs: int = 1
    trace_dir: str = ""  # jax.profiler trace output (TensorBoard/XProf)
    trace_steps: int = 20  # bounded trace window length (after warmup)
    metrics_path: str = ""  # JSONL telemetry sink (enveloped records; see
    #   telemetry.py SCHEMAS and tools/report.py)
    # [Telemetry] — the RunMonitor knobs (records go to metrics_path)
    telemetry_run_id: str = ""  # envelope run id; empty = auto-generated
    telemetry_mem_every_s: float = 30.0  # kind=mem watermark cadence
    #   (0 = only the guaranteed final record at close)
    telemetry_stall_timeout_s: float = 0.0  # liveness watchdog: dump thread
    #   stacks + prefetch depth as kind=stall when no step completes for
    #   this many seconds (0 = watchdog off)
    telemetry_compilation_cache_dir: str = ""  # persistent XLA compilation
    #   cache directory (jax_compilation_cache_dir): serving cold-start
    #   warmup and repeated bench runs skip recompiles across processes;
    #   the compile sentinel marks cache hits distinctly ("" = off)
    telemetry_profile_steps: str = ""  # "A:B" captures a jax.profiler trace
    #   over steps [A, B) (rounded to dispatch boundaries under step
    #   fusion) into <model_file>.profile (trace_dir overrides); start/
    #   stop land as kind=profile event records ("" = no trace)
    telemetry_profile_costs: bool = True  # per-compiled-program MEASURED
    #   cost ledger (XLA cost analysis: bytes accessed, FLOPs) emitted as
    #   ONE kind=profile record per program on train/predict/serving —
    #   one re-lowering each, no second backend compile, no hot-path work
    telemetry_datastats_every_steps: int = 0  # sample device-side id-traffic
    #   statistics (unique/dedup ratio, heavy-hitter sketch, rows-seen)
    #   every N steps as kind=datastats records (0 = off; the sampled
    #   batch pays one O(M log M) device sort per window)
    telemetry_heavy_hitter_k: int = 16  # top-K buckets of the datastats
    #   heavy-hitter sketch reported per record (sizes ROADMAP item 3's
    #   hot-id cache; bucket collisions overstate mass — an upper bound)
    # [Predict]
    predict_files: tuple[str, ...] = ()
    score_path: str = "scores.txt"
    # [Serving] — the online engine (serving/; `serve` CLI verb)
    serve_buckets: tuple[int, ...] = (1, 8, 64, 512)  # compile-ladder batch
    #   sizes; every flush pads to the nearest rung so steady state never
    #   recompiles (warmed once at startup)
    serve_max_batch: int = 0  # collector flush size; 0 = largest bucket
    serve_flush_deadline_ms: float = 5.0  # max micro-batching wait for the
    #   oldest pending request (latency/occupancy knob; 0 = flush instantly)
    serve_queue_size: int = 4096  # bounded admission queue — the ONLY
    #   elastic buffer, so overload memory is capped here
    serve_overload: str = "block"  # queue-full policy: block (backpressure)
    #   | reject (raise OverloadError to the submitter — shed load)
    serve_reload_interval_s: float = 0.0  # hot checkpoint reload poll; the
    #   watcher restores changed model_file checkpoints off the hot path
    #   and the collector swaps them in between flushes (0 = no watcher)
    serve_metrics_every_s: float = 10.0  # serving-metrics JSONL cadence
    #   (written to metrics_path, tagged kind=serving; 0 = final record only)
    serve_reload_max_retries: int = 8  # consecutive reload failures on ONE
    #   checkpoint signature before the watcher gives up on it (counted as
    #   reload_giveups + a kind=anomaly record; retries back off
    #   exponentially from reload_interval_s; a NEW write resets)
    serve_port: int = 0  # socket front end (serving/frontend.py): TCP port
    #   the `serve` verb listens on; 0 = stdin/stdout mode (the historical
    #   pipe path) unless the CLI passes --port (0 there = ephemeral,
    #   introspected and printed — what tests use)
    serve_replicas: int = 1  # engine replica WORKER PROCESSES behind the
    #   router (shared-nothing: per-replica jit caches and admission
    #   queues); 1 still runs the full router path when the front end is up
    serve_deadline_ms: float = 0.0  # default per-request deadline budget
    #   (submit -> scored); an expired request is shed BEFORE padding a
    #   bucket (typed `deadline`, counted as deadline_drops).  0 = none;
    #   a request's own deadline_ms field overrides
    serve_classes: tuple[tuple[str, int], ...] = ()  # tiered admission:
    #   client class -> tier ("gold:2,std:1"); under overload the queue
    #   sheds strictly-lower tiers first (oldest of the lowest present),
    #   so degradation follows priority.  Unknown/absent class = tier 0
    serve_wire: str = "binary"  # DATA-plane wire a client may negotiate
    #   via {"op":"hello"}: "binary" allows the batched frame protocol
    #   (protocol.py DATA frames; JSONL stays the fallback), "jsonl"
    #   refuses the upgrade so every data connection stays line-oriented
    serve_affinity: bool = True  # hello hands the client a healthy
    #   replica's port to pin its DATA connection to (replica answers
    #   directly; router keeps health/reload/placement/failover only).
    #   False: hello returns no placement and data stays on the front end
    # [Online] — online learning from an append-only event stream
    online_follow: bool = False  # tail-follow the FMS train stream: at EOF
    #   the reader polls for growth instead of ending the epoch
    #   (data/stream.py; train only, one FMS train file, epoch_num = 1)
    online_poll_s: float = 0.2  # bounded EOF poll interval (seconds)
    online_idle_timeout_s: float = 0.0  # >0: end the stream after this much
    #   continuous writer silence (bounded tools/tests); 0 = follow until
    #   the process is stopped (SIGTERM checkpoints + exits as usual)
    online_max_batches: int = 0  # >0: end the stream once the TOTAL emitted
    #   batch index reaches N (resume-skipped batches count — the
    #   pad_to_batches convention, so --resume composes); 0 = unbounded
    online_adagrad_decay: float = 1.0  # γ: touched-row accumulator decay
    #   (accum = γ·accum + g²) so old gradient history can't freeze the
    #   step size on a moving distribution; 1.0 = classic Adagrad,
    #   bit-identical program; γ < 1 requires table_layout = rows
    online_accum_restart_steps: int = 0  # window-restart alternative to
    #   decay: every N steps (K-aligned) reset EVERY accumulator to
    #   init_accumulator_value; 0 = off; exclusive with adagrad_decay < 1
    # [ParamStore] — tiered host/device parameter store (paramstore/):
    # beyond-HBM tables — a device-resident hot tier (top-K rows) + the
    # full logical table in a memmap-backed host cold store; the prefetch
    # thread resolves each superbatch's ids ahead of dispatch and miss
    # rows ride the packed wire alongside the batch
    paramstore: bool = False  # enable the tiered store (local train only;
    #   table_layout = rows, npz checkpoints)
    paramstore_hot_rows: int = 4096  # device-resident hot rows (the PR-9
    #   coverage curve: top-4096 absorb 59% of gathers at the Zipf(1.1)
    #   scale shape)
    paramstore_miss_rows: int = 0  # staging capacity for one superbatch's
    #   unique non-resident rows; 0 = auto (batch_size * max_nnz *
    #   steps_per_call — the can't-overflow bound); a tighter cap shrinks
    #   device memory and fails LOUDLY if a batch exceeds it
    paramstore_dir: str = ""  # cold-store directory; "" = <model_file>.store
    paramstore_residency: str = "sample"  # hot-set policy: sample (exact
    #   frequency count over the first sample_batches of the train stream,
    #   top-K — the heavy-hitter telemetry's exact twin) | first (ids
    #   [0, K)) | file:PATH (id list exported from telemetry)
    paramstore_sample_batches: int = 8  # batches the sample policy counts
    paramstore_materialize: str = "auto"  # cold-store init: auto
    #   (materialize the exact jax init draw at small vocab — the
    #   bit-identity-with-resident path — lazy hashed init beyond) |
    #   always | never
    # [Resilience] — crash recovery + fault handling (resilience.py)
    on_nan: str = "abort"  # non-finite loss policy: abort (raise before the
    #   next save overwrites good state — the historical behavior) |
    #   rollback (restore the last checkpoint, SKIP the diverged window's
    #   input via the saved cursor, continue; local train only)
    max_rollbacks: int = 2  # rollback budget per run; exhausted -> abort
    io_retries: int = 3  # FMB reader: transient-OSError retries per read op
    io_retry_backoff_s: float = 0.05  # first retry backoff (doubles per try)
    restart_max: int = 5  # supervisor (train --supervised): bounded restarts
    restart_backoff_s: float = 1.0  # supervisor backoff base (doubles)
    restart_backoff_max_s: float = 30.0  # supervisor backoff cap
    # [Distributed]
    data_parallel: int = 0  # 0 = all devices / row_parallel
    row_parallel: int = 0  # 0 = vocabulary_block_num
    lookup: str = "allgather"  # embedding lookup collective (| alltoall)
    lookup_capacity_factor: float = 2.0  # alltoall per-destination slack
    lookup_overflow: str = "fallback"  # fallback (retry step via allgather) | abort
    coordinator_address: str = ""  # multi-host: host:port of process 0
    num_processes: int = 0  # multi-host: total process count
    process_id: int = -1  # multi-host: this process's index
    input_assignment: str = "rows"  # multi-host streamed input split: rows
    #   (block-cyclic line sharding of every file — the historical mode) |
    #   files (shard-disjoint file assignment: host p streams files
    #   [p::P] whole, so each host touches only its own files; short
    #   hosts pad the epoch tail with weight-0 batches)
    runtime_dir: str = ""  # shared coordination dir for the pod runtime
    #   (heartbeats, generation file, file-KV fallback); "" = off for
    #   plain runs, defaults to <model_file>.dist under the pod
    #   supervisor (dist_train --supervised with num_processes > 1)
    heartbeat_s: float = 2.0  # per-host heartbeat cadence into runtime_dir
    host_stall_timeout_s: float = 0.0  # peer-heartbeat staleness that
    #   classifies a host-level kind=stall (host-heartbeat-lost); the pod
    #   supervisor also uses it for straggler kills (0 = monitor off)
    barrier_timeout_s: float = 120.0  # cross-process barrier / signature
    #   / cursor-gather wait budget; a timeout means a peer is gone
    #   (PeerLostError -> exit PEER_LOST_EXIT under the supervisor)

    def validate(self) -> "Config":
        if self.model not in ("fm", "ffm", "deepfm"):
            raise ValueError(f"unknown model {self.model!r}")
        if self.model in ("ffm", "deepfm") and self.num_fields <= 0:
            raise ValueError(f"{self.model} requires num_fields > 0")
        if self.model == "fm" and self.order < 2:
            raise ValueError("order must be >= 2")
        if self.vocabulary_size <= 0 or self.batch_size <= 0:
            raise ValueError("vocabulary_size and batch_size must be positive")
        if self.vocabulary_size > 2**31 - 1:
            # Device feature ids are int32 (TPU gathers index with int32);
            # a larger vocabulary would silently wrap when batches narrow
            # to the device dtype.  Hash mode folds any id space into range.
            raise ValueError(
                f"vocabulary_size {self.vocabulary_size} exceeds int32 "
                "(2**31 - 1), the device feature-id dtype"
            )
        if self.checkpoint_format not in ("npz", "orbax"):
            raise ValueError(f"unknown checkpoint_format {self.checkpoint_format!r}")
        if self.delta_every_steps < 0:
            raise ValueError(
                f"delta_every_steps must be >= 0 (0 = off), got {self.delta_every_steps}"
            )
        if self.delta_every_steps > 0 and self.checkpoint_format == "orbax":
            # The delta container is an npz sibling file chained by content
            # signature; orbax directories have no such sidecar format (and
            # orbax's own async machinery is the pod-scale answer there).
            raise ValueError(
                "delta_every_steps > 0 requires checkpoint_format = npz "
                "(the delta chain is an npz sidecar format)"
            )
        if self.delta_chain_max < 1:
            raise ValueError(
                f"delta_chain_max must be >= 1, got {self.delta_chain_max}"
            )
        if self.checkpoint_chunk_mb < 1:
            raise ValueError(
                f"checkpoint_chunk_mb must be >= 1, got {self.checkpoint_chunk_mb}"
            )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unknown compute_dtype {self.compute_dtype!r}")
        if self.lookup not in ("allgather", "alltoall"):
            raise ValueError(f"unknown lookup {self.lookup!r} (allgather | alltoall)")
        if self.lookup_overflow not in ("fallback", "abort"):
            raise ValueError(
                f"unknown lookup_overflow {self.lookup_overflow!r} (fallback | abort)"
            )
        if self.steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {self.steps_per_call}"
            )
        if self.wire_format not in ("packed", "arrays"):
            raise ValueError(
                f"unknown wire_format {self.wire_format!r} (packed | arrays)"
            )
        if self.thread_num < 0:
            raise ValueError(
                f"thread_num must be >= 0 (0 = all cores), got {self.thread_num}"
            )
        if self.shuffle_seed < 0:
            # numpy SeedSequence rejects negatives — fail at the config,
            # not deep inside the prefetch thread.
            raise ValueError(f"shuffle_seed must be >= 0, got {self.shuffle_seed}")
        if self.adagrad_accumulator not in ("element", "row", "fused"):
            raise ValueError(
                f"unknown adagrad_accumulator {self.adagrad_accumulator!r} "
                "(element | row | fused)"
            )
        if self.packed_compact_cap < 0:
            raise ValueError(
                f"packed_compact_cap must be >= 0, got {self.packed_compact_cap}"
            )
        if self.packed_compact_cap > 0 and self.adagrad_accumulator != "fused":
            # The cap only exists on the fused compact tail; silently inert
            # knobs corrupt A/B comparisons (packed_update rationale above).
            raise ValueError(
                "packed_compact_cap > 0 requires adagrad_accumulator = fused "
                "(it sizes the fused compact tail's row buffer)"
            )
        if self.adagrad_accumulator == "fused" and self.table_layout != "packed":
            # Fused is a PHYSICAL layout choice (row accumulator stored in
            # the table's own tile rows); it only exists packed.
            raise ValueError(
                "adagrad_accumulator = fused requires table_layout = packed"
            )
        if self.table_layout not in ("rows", "packed"):
            raise ValueError(
                f"unknown table_layout {self.table_layout!r} (rows | packed)"
            )
        if self.init_accumulator_value <= 0:
            # TF AdagradOptimizer requires a positive initial accumulator
            # for the same reason: a zero accumulator makes the first
            # update of any element with zero summed gradient compute
            # 0/sqrt(0) = NaN (rows layout: zero-grad elements of touched
            # rows; packed layout: untouched logical rows sharing a tile
            # row), silently corrupting the table.
            raise ValueError(
                f"init_accumulator_value must be > 0, got {self.init_accumulator_value}"
            )
        self.serve_buckets = validate_buckets(self.serve_buckets)
        if self.serve_max_batch < 0:
            raise ValueError(
                f"serve_max_batch must be >= 0 (0 = largest bucket), "
                f"got {self.serve_max_batch}"
            )
        if self.serve_max_batch > self.serve_buckets[-1]:
            raise ValueError(
                f"serve_max_batch {self.serve_max_batch} exceeds the largest "
                f"bucket {self.serve_buckets[-1]} — a flush that size would "
                "have no compiled shape (raise serve_buckets or lower it)"
            )
        if self.serve_flush_deadline_ms < 0:
            raise ValueError(
                f"serve_flush_deadline_ms must be >= 0, got {self.serve_flush_deadline_ms}"
            )
        if self.serve_queue_size < 1:
            raise ValueError(
                f"serve_queue_size must be >= 1, got {self.serve_queue_size}"
            )
        if self.serve_overload not in ("block", "reject"):
            raise ValueError(
                f"unknown serve_overload {self.serve_overload!r} (block | reject)"
            )
        if self.serve_reload_interval_s < 0 or self.serve_metrics_every_s < 0:
            raise ValueError(
                "serve_reload_interval_s and serve_metrics_every_s must be >= 0"
            )
        if self.serve_reload_max_retries < 1:
            raise ValueError(
                f"serve_reload_max_retries must be >= 1, got "
                f"{self.serve_reload_max_retries}"
            )
        if not (0 <= self.serve_port <= 65535):
            raise ValueError(f"serve_port must be in [0, 65535], got {self.serve_port}")
        if self.serve_replicas < 1:
            raise ValueError(
                f"serve_replicas must be >= 1, got {self.serve_replicas}"
            )
        if self.serve_deadline_ms < 0:
            raise ValueError(
                f"serve_deadline_ms must be >= 0 (0 = none), got "
                f"{self.serve_deadline_ms}"
            )
        self.serve_classes = validate_classes(self.serve_classes)
        if self.serve_wire not in ("binary", "jsonl"):
            raise ValueError(
                f"unknown serve_wire {self.serve_wire!r} (binary | jsonl)"
            )
        if self.online_poll_s <= 0:
            raise ValueError(f"[Online] poll_s must be > 0, got {self.online_poll_s}")
        if self.online_idle_timeout_s < 0 or self.online_max_batches < 0:
            raise ValueError(
                "[Online] idle_timeout_s and max_batches must be >= 0 (0 = off)"
            )
        if not (0.0 < self.online_adagrad_decay <= 1.0):
            raise ValueError(
                f"[Online] adagrad_decay must be in (0, 1], got "
                f"{self.online_adagrad_decay}"
            )
        if self.online_adagrad_decay != 1.0 and self.table_layout != "rows":
            # The packed tile-row RMWs rely on the zero-grad accumulator
            # identity (untouched logical rows sharing a tile row must not
            # change); a lane-blind decay would break it silently.
            raise ValueError(
                "[Online] adagrad_decay < 1 requires table_layout = rows"
            )
        if self.online_accum_restart_steps < 0:
            raise ValueError(
                f"[Online] accum_restart_steps must be >= 0, got "
                f"{self.online_accum_restart_steps}"
            )
        if self.online_accum_restart_steps > 0 and self.adagrad_accumulator == "fused":
            # The fused layout stores the accumulator inside the table's
            # own tile rows — there is no separate array to reset.
            raise ValueError(
                "[Online] accum_restart_steps requires adagrad_accumulator "
                "= element or row (the fused layout has no separate "
                "accumulator array to reset)"
            )
        if self.online_accum_restart_steps > 0 and self.delta_every_steps > 0:
            # The reset rewrites EVERY accumulator row, but delta saves
            # ship only the touched-row window — a crash-resume would
            # replay PRE-reset accumulators for every untouched row,
            # silently breaking the exact-position-resume invariant.
            raise ValueError(
                "[Online] accum_restart_steps cannot combine with "
                "delta_every_steps: a global accumulator reset is not "
                "representable in a touched-row delta (resume would "
                "restore stale accumulators) — use full saves, or "
                "adagrad_decay"
            )
        if self.online_accum_restart_steps > 0 and self.online_adagrad_decay != 1.0:
            # Two competing forgetting mechanisms make every A/B reading
            # ambiguous — pick one per run.
            raise ValueError(
                "[Online] adagrad_decay < 1 and accum_restart_steps > 0 are "
                "exclusive — choose one forgetting mechanism"
            )
        if self.online_follow:
            if self.shuffle:
                raise ValueError(
                    "[Online] follow = true cannot shuffle: an append-only "
                    "stream has no fixed row count to permute"
                )
            if self.device_cache:
                raise ValueError(
                    "[Online] follow = true is a streamed input mode — "
                    "device_cache loads a FIXED dataset to HBM once"
                )
            if self.epoch_num != 1:
                raise ValueError(
                    "[Online] follow = true runs ONE endless epoch — set "
                    f"epoch_num = 1 (got {self.epoch_num})"
                )
        if self.dedup_gather_rows < 0:
            raise ValueError(
                f"dedup_gather_rows must be >= 0 (0 = off), got "
                f"{self.dedup_gather_rows}"
            )
        if self.dedup_gather_rows > 0:
            if self.table_layout != "rows":
                # The dedup body gathers/indexes the plain [V, D] table;
                # the packed layouts have their own compaction story
                # (packed_update = compact).
                raise ValueError(
                    "dedup_gather_rows > 0 requires table_layout = rows"
                )
            if self.device_cache:
                raise ValueError(
                    "dedup_gather_rows applies to the STREAMED path; "
                    "device_cache slices resident batches (drop one)"
                )
            if self.paramstore:
                raise ValueError(
                    "dedup_gather_rows is redundant under [ParamStore] "
                    "(tiered resolution already dedups before the gather) "
                    "— drop one"
                )
            if self.online_follow:
                # The follow stream (_follow_stream) does not run the
                # per-batch cap guard; without it an over-cap appended
                # batch would truncate silently inside the jitted dedup.
                raise ValueError(
                    "dedup_gather_rows with [Online] follow is not "
                    "supported: the tail-following stream has no "
                    "per-batch cap verification yet"
                )
        if self.paramstore:
            if self.table_layout != "rows":
                raise ValueError(
                    "[ParamStore] requires table_layout = rows (the "
                    "compact device tier is a plain [C, D] table)"
                )
            if self.checkpoint_format != "npz":
                raise ValueError(
                    "[ParamStore] requires checkpoint_format = npz (both "
                    "tiers publish through the npz chain)"
                )
            if self.device_cache:
                raise ValueError(
                    "[ParamStore] and device_cache are exclusive: the "
                    "tiered store IS the residency decision"
                )
            if self.online_follow:
                raise ValueError(
                    "[ParamStore] with [Online] follow is not supported "
                    "yet (ROADMAP item 4 composes them)"
                )
            if self.async_save:
                raise ValueError(
                    "[ParamStore] saves are synchronous (the post-publish "
                    "store apply must order after the npz publish) — drop "
                    "async_save"
                )
            if self.adagrad_accumulator == "fused":
                raise ValueError(
                    "[ParamStore] supports adagrad_accumulator = element "
                    "or row (fused is a packed-layout storage choice)"
                )
            if self.on_nan == "rollback":
                raise ValueError(
                    "[ParamStore] with on_nan = rollback is not supported "
                    "yet — use abort (the tiered restore path does not "
                    "plug into the in-process rollback loop)"
                )
            if self.online_accum_restart_steps > 0:
                raise ValueError(
                    "[ParamStore] cannot combine with accum_restart_steps: "
                    "a global accumulator reset cannot reach the cold "
                    "tier's rows — use adagrad_decay"
                )
            if self.paramstore_hot_rows < 1:
                raise ValueError(
                    f"[ParamStore] hot_rows must be >= 1, got "
                    f"{self.paramstore_hot_rows}"
                )
            if self.paramstore_miss_rows < 0:
                raise ValueError(
                    "[ParamStore] miss_rows must be >= 0 (0 = auto), got "
                    f"{self.paramstore_miss_rows}"
                )
            if self.paramstore_sample_batches < 1:
                raise ValueError(
                    "[ParamStore] sample_batches must be >= 1, got "
                    f"{self.paramstore_sample_batches}"
                )
            if self.paramstore_residency not in ("sample", "first") and not (
                self.paramstore_residency.startswith("file:")
                and len(self.paramstore_residency) > 5
            ):
                raise ValueError(
                    f"unknown [ParamStore] residency "
                    f"{self.paramstore_residency!r} (sample | first | "
                    "file:PATH)"
                )
            if self.paramstore_materialize not in ("auto", "always", "never"):
                raise ValueError(
                    f"unknown [ParamStore] materialize "
                    f"{self.paramstore_materialize!r} (auto | always | never)"
                )
        if self.delta_full_every_s < 0 or self.delta_chain_max_bytes < 0:
            raise ValueError(
                "[Checkpoint] full_every_s and chain_max_bytes must be >= 0 "
                "(0 = off)"
            )
        if self.on_nan not in ("abort", "rollback"):
            raise ValueError(f"unknown on_nan {self.on_nan!r} (abort | rollback)")
        if self.max_rollbacks < 0:
            raise ValueError(f"max_rollbacks must be >= 0, got {self.max_rollbacks}")
        if self.io_retries < 0:
            raise ValueError(f"io_retries must be >= 0, got {self.io_retries}")
        if self.io_retry_backoff_s < 0:
            raise ValueError(
                f"io_retry_backoff_s must be >= 0, got {self.io_retry_backoff_s}"
            )
        if self.restart_max < 0:
            raise ValueError(f"restart_max must be >= 0, got {self.restart_max}")
        if self.restart_backoff_s < 0 or self.restart_backoff_max_s < 0:
            raise ValueError(
                "restart_backoff_s and restart_backoff_max_s must be >= 0"
            )
        if self.input_assignment not in ("rows", "files"):
            raise ValueError(
                f"unknown input_assignment {self.input_assignment!r} (rows | files)"
            )
        if self.heartbeat_s <= 0:
            raise ValueError(f"heartbeat_s must be > 0, got {self.heartbeat_s}")
        if self.host_stall_timeout_s < 0:
            raise ValueError(
                f"host_stall_timeout_s must be >= 0 (0 = off), got "
                f"{self.host_stall_timeout_s}"
            )
        if self.barrier_timeout_s <= 0:
            raise ValueError(
                f"barrier_timeout_s must be > 0, got {self.barrier_timeout_s}"
            )
        if self.telemetry_mem_every_s < 0 or self.telemetry_stall_timeout_s < 0:
            raise ValueError(
                "telemetry_mem_every_s and telemetry_stall_timeout_s must be "
                ">= 0 (0 disables)"
            )
        if self.telemetry_profile_steps:
            # Parse-validate at config time, not at step N of a long run.
            from fast_tffm_tpu.profiling import parse_profile_steps

            parse_profile_steps(self.telemetry_profile_steps)
        if self.telemetry_datastats_every_steps < 0:
            raise ValueError(
                "telemetry_datastats_every_steps must be >= 0 (0 = off), got "
                f"{self.telemetry_datastats_every_steps}"
            )
        if self.telemetry_heavy_hitter_k < 1:
            raise ValueError(
                f"telemetry_heavy_hitter_k must be >= 1, got "
                f"{self.telemetry_heavy_hitter_k}"
            )
        if self.packed_update not in ("auto", "dense", "compact", "sorted"):
            raise ValueError(
                f"unknown packed_update {self.packed_update!r} "
                "(auto | dense | compact | sorted)"
            )
        if self.packed_update != "auto" and self.table_layout != "packed":
            # Silently inert knobs corrupt A/B comparisons: a run that
            # pins the update strategy but forgets the layout would
            # measure the rows layout and call it dense/sorted.
            raise ValueError(
                f"packed_update = {self.packed_update} requires "
                "table_layout = packed (it selects the packed layout's "
                "sparse-tail strategy)"
            )
        if (
            self.table_layout == "packed"
            and self.adagrad_accumulator in ("row", "fused")
            and self.packed_update == "sorted"
        ):
            # The sorted packed update's whole-tile-row RMW is exact only
            # with the element accumulator (zero-grad identity per LANE);
            # the row accumulator's [VP, P] scalar slots need a scatter-add
            # tail (dense or compact — both handle both granularities).
            raise ValueError(
                "table_layout = packed with adagrad_accumulator = row "
                "requires packed_update = auto, dense or compact (the "
                "sorted whole-tile-row RMW needs the element accumulator)"
            )
        if self.tail not in ("auto", "xla", "pallas"):
            raise ValueError(
                f"unknown tail {self.tail!r} (auto | xla | pallas)"
            )
        if (
            self.tail == "pallas"
            and self.table_layout == "packed"
            and self.adagrad_accumulator != "fused"
        ):
            # The packed Pallas tail addresses rows through the merged
            # D+1-lane slots; the split packed accumulator layouts keep
            # their XLA update strategies (packed_update).
            raise ValueError(
                "tail = pallas with table_layout = packed requires "
                "adagrad_accumulator = fused (the kernel updates the "
                "merged fused layout's D+1-lane slots in one pass)"
            )
        if self.tail == "pallas" and self.dedup_gather_rows > 0:
            # Both features dedup the batch's ids; stacking them would
            # dedup twice and measure neither cleanly.
            raise ValueError(
                "tail = pallas is incompatible with dedup_gather_rows > 0 "
                "(the kernel dedups internally — pick one)"
            )
        return self


def validate_buckets(buckets) -> tuple[int, ...]:
    """Normalize a serve_buckets spec: positive ints, sorted, deduped,
    non-empty.  Lives here (not serving/) so config validation stays
    jax-free — serving/buckets.py imports it back."""
    try:
        out = tuple(sorted({int(b) for b in buckets}))
    except (TypeError, ValueError) as e:
        raise ValueError(f"serve_buckets must be integers, got {buckets!r}") from e
    if not out or out[0] < 1:
        raise ValueError(f"serve_buckets must be positive and non-empty, got {buckets!r}")
    return out


def validate_classes(classes) -> tuple[tuple[str, int], ...]:
    """Normalize a serve_classes spec: a ``"gold:2,std:1"`` string or an
    iterable of (name, tier) pairs → sorted tuple of (name, tier).  Tiers
    are non-negative ints; names non-empty and unique.  Lives here (like
    validate_buckets) so config validation stays jax-free."""
    if isinstance(classes, str):
        pairs = []
        for tok in _split(classes):
            name, sep, tier = tok.partition(":")
            if not sep or not name:
                raise ValueError(
                    f"serve_classes entries are name:tier, got {tok!r}"
                )
            pairs.append((name, tier))
        classes = pairs
    out = []
    try:
        for name, tier in classes:
            name, tier = str(name), int(tier)
            if not name or tier < 0:
                raise ValueError
            out.append((name, tier))
    except (TypeError, ValueError):
        raise ValueError(
            f"serve_classes must be name:tier pairs with tier >= 0, got {classes!r}"
        ) from None
    # Outside the try: the generic format message must not swallow the
    # far more actionable duplicate-name diagnosis.
    seen = set()
    for name, _ in out:
        if name in seen:
            raise ValueError(f"duplicate serve_classes name {name!r}")
        seen.add(name)
    return tuple(sorted(out))


def _split(s: str) -> tuple[str, ...]:
    return tuple(x for x in (t.strip() for t in s.replace(",", " ").split()) if x)


def _split_files(s: str) -> tuple[str, ...]:
    """File list with glob expansion: `train_files = data/part-*.libsvm`.

    Matches expand sorted (stable shard order across workers); a pattern
    with no match is kept literally so the missing-file error names the
    user's path, not a silently empty list.
    """
    import glob as _glob

    out: list[str] = []
    for tok in _split(s):
        if any(c in tok for c in "*?["):
            out.extend(sorted(_glob.glob(tok)) or [tok])
        else:
            out.append(tok)
    return tuple(out)


def load_config(path: str) -> Config:
    """Parse an INI file into a validated Config."""
    # The reference's sample.cfg style annotates values in place
    # ("key = value  ; comment"); ConfigParser keeps inline comments unless
    # told otherwise, which would corrupt every annotated value.
    ini = configparser.ConfigParser(inline_comment_prefixes=(";", "#"))
    with open(path) as f:
        ini.read_file(f)
    cfg = Config()

    def get(section, key, conv, default):
        if ini.has_option(section, key):
            raw = ini.get(section, key)
            return conv(raw)
        return default

    g = "General"
    cfg.model = get(g, "model", str, cfg.model).lower()
    cfg.factor_num = get(g, "factor_num", int, cfg.factor_num)
    cfg.order = get(g, "order", int, cfg.order)
    cfg.num_fields = get(g, "num_fields", int, cfg.num_fields)
    cfg.hidden_dims = get(
        g, "hidden_dims", lambda s: tuple(int(x) for x in _split(s)), cfg.hidden_dims
    )
    cfg.compute_dtype = get(g, "compute_dtype", str, cfg.compute_dtype).lower()
    cfg.vocabulary_size = get(g, "vocabulary_size", int, cfg.vocabulary_size)
    cfg.vocabulary_block_num = get(g, "vocabulary_block_num", int, cfg.vocabulary_block_num)
    cfg.hash_feature_id = get(g, "hash_feature_id", ini._convert_to_boolean, cfg.hash_feature_id)
    cfg.table_layout = get(g, "table_layout", str, cfg.table_layout).lower()
    cfg.model_file = get(g, "model_file", str, cfg.model_file)
    cfg.checkpoint_format = get(g, "checkpoint_format", str, cfg.checkpoint_format).lower()

    t = "Train"
    cfg.train_files = get(t, "train_files", _split_files, cfg.train_files)
    cfg.weight_files = get(
        t, "weight_files", lambda s: tuple(float(x) for x in _split(s)), cfg.weight_files
    )
    cfg.validation_files = get(t, "validation_files", _split_files, cfg.validation_files)
    cfg.epoch_num = get(t, "epoch_num", int, cfg.epoch_num)
    cfg.batch_size = get(t, "batch_size", int, cfg.batch_size)
    cfg.max_nnz = get(t, "max_nnz", int, cfg.max_nnz)
    cfg.learning_rate = get(t, "learning_rate", float, cfg.learning_rate)
    cfg.init_value_range = get(t, "init_value_range", float, cfg.init_value_range)
    cfg.factor_lambda = get(t, "factor_lambda", float, cfg.factor_lambda)
    cfg.bias_lambda = get(t, "bias_lambda", float, cfg.bias_lambda)
    cfg.init_accumulator_value = get(
        t, "init_accumulator_value", float, cfg.init_accumulator_value
    )
    cfg.adagrad_accumulator = get(
        t, "adagrad_accumulator", str, cfg.adagrad_accumulator
    ).lower()
    cfg.packed_update = get(t, "packed_update", str, cfg.packed_update).lower()
    cfg.tail = get(t, "tail", str, cfg.tail).lower()
    cfg.packed_compact_cap = get(
        t, "packed_compact_cap", int, cfg.packed_compact_cap
    )
    cfg.thread_num = get(t, "thread_num", int, cfg.thread_num)
    cfg.binary_cache = get(t, "binary_cache", ini._convert_to_boolean, cfg.binary_cache)
    cfg.binary_cache_wait = get(t, "binary_cache_wait", float, cfg.binary_cache_wait)
    cfg.shuffle = get(t, "shuffle", ini._convert_to_boolean, cfg.shuffle)
    cfg.shuffle_seed = get(t, "shuffle_seed", int, cfg.shuffle_seed)
    cfg.device_cache = get(t, "device_cache", ini._convert_to_boolean, cfg.device_cache)
    cfg.dedup_gather_rows = get(
        t, "dedup_gather_rows", int, cfg.dedup_gather_rows
    )
    cfg.steps_per_call = get(t, "steps_per_call", int, cfg.steps_per_call)
    cfg.wire_format = get(t, "wire_format", str, cfg.wire_format).lower()
    cfg.queue_size = get(t, "queue_size", int, cfg.queue_size)
    cfg.log_every = get(t, "log_every", int, cfg.log_every)
    cfg.save_every_epochs = get(t, "save_every_epochs", int, cfg.save_every_epochs)
    cfg.trace_dir = get(t, "trace_dir", str, cfg.trace_dir)
    cfg.trace_steps = get(t, "trace_steps", int, cfg.trace_steps)
    cfg.metrics_path = get(t, "metrics_path", str, cfg.metrics_path)

    te = "Telemetry"
    cfg.telemetry_run_id = get(te, "run_id", str, cfg.telemetry_run_id)
    cfg.telemetry_mem_every_s = get(te, "mem_every_s", float, cfg.telemetry_mem_every_s)
    cfg.telemetry_stall_timeout_s = get(
        te, "stall_timeout_s", float, cfg.telemetry_stall_timeout_s
    )
    cfg.telemetry_compilation_cache_dir = get(
        te, "compilation_cache_dir", str, cfg.telemetry_compilation_cache_dir
    )
    cfg.telemetry_profile_steps = get(
        te, "profile_steps", str, cfg.telemetry_profile_steps
    )
    cfg.telemetry_profile_costs = get(
        te, "profile_costs", ini._convert_to_boolean, cfg.telemetry_profile_costs
    )
    cfg.telemetry_datastats_every_steps = get(
        te, "datastats_every_steps", int, cfg.telemetry_datastats_every_steps
    )
    cfg.telemetry_heavy_hitter_k = get(
        te, "heavy_hitter_k", int, cfg.telemetry_heavy_hitter_k
    )

    c = "Checkpoint"
    cfg.async_save = get(c, "async_save", ini._convert_to_boolean, cfg.async_save)
    cfg.delta_every_steps = get(c, "delta_every_steps", int, cfg.delta_every_steps)
    cfg.delta_chain_max = get(c, "delta_chain_max", int, cfg.delta_chain_max)
    cfg.delta_full_every_s = get(c, "full_every_s", float, cfg.delta_full_every_s)
    cfg.delta_chain_max_bytes = get(
        c, "chain_max_bytes", int, cfg.delta_chain_max_bytes
    )
    cfg.checkpoint_chunk_mb = get(c, "chunk_mb", int, cfg.checkpoint_chunk_mb)

    p = "Predict"
    cfg.predict_files = get(p, "predict_files", _split_files, cfg.predict_files)
    cfg.score_path = get(p, "score_path", str, cfg.score_path)

    s = "Serving"
    cfg.serve_buckets = get(
        s, "buckets", lambda v: tuple(int(x) for x in _split(v)), cfg.serve_buckets
    )
    cfg.serve_max_batch = get(s, "max_batch", int, cfg.serve_max_batch)
    cfg.serve_flush_deadline_ms = get(
        s, "flush_deadline_ms", float, cfg.serve_flush_deadline_ms
    )
    cfg.serve_queue_size = get(s, "queue_size", int, cfg.serve_queue_size)
    cfg.serve_overload = get(s, "overload", str, cfg.serve_overload).lower()
    cfg.serve_reload_interval_s = get(
        s, "reload_interval_s", float, cfg.serve_reload_interval_s
    )
    cfg.serve_metrics_every_s = get(
        s, "metrics_every_s", float, cfg.serve_metrics_every_s
    )
    cfg.serve_reload_max_retries = get(
        s, "reload_max_retries", int, cfg.serve_reload_max_retries
    )
    cfg.serve_port = get(s, "port", int, cfg.serve_port)
    cfg.serve_replicas = get(s, "replicas", int, cfg.serve_replicas)
    cfg.serve_deadline_ms = get(s, "deadline_ms", float, cfg.serve_deadline_ms)
    cfg.serve_classes = get(s, "classes", str, cfg.serve_classes)
    cfg.serve_wire = get(s, "wire", str, cfg.serve_wire).lower()
    cfg.serve_affinity = get(
        s, "affinity", ini._convert_to_boolean, cfg.serve_affinity
    )

    o = "Online"
    cfg.online_follow = get(o, "follow", ini._convert_to_boolean, cfg.online_follow)
    cfg.online_poll_s = get(o, "poll_s", float, cfg.online_poll_s)
    cfg.online_idle_timeout_s = get(
        o, "idle_timeout_s", float, cfg.online_idle_timeout_s
    )
    cfg.online_max_batches = get(o, "max_batches", int, cfg.online_max_batches)
    cfg.online_adagrad_decay = get(
        o, "adagrad_decay", float, cfg.online_adagrad_decay
    )
    cfg.online_accum_restart_steps = get(
        o, "accum_restart_steps", int, cfg.online_accum_restart_steps
    )

    ps = "ParamStore"
    cfg.paramstore = get(ps, "enabled", ini._convert_to_boolean, cfg.paramstore)
    cfg.paramstore_hot_rows = get(ps, "hot_rows", int, cfg.paramstore_hot_rows)
    cfg.paramstore_miss_rows = get(ps, "miss_rows", int, cfg.paramstore_miss_rows)
    cfg.paramstore_dir = get(ps, "store_dir", str, cfg.paramstore_dir)
    cfg.paramstore_residency = get(ps, "residency", str, cfg.paramstore_residency)
    cfg.paramstore_sample_batches = get(
        ps, "sample_batches", int, cfg.paramstore_sample_batches
    )
    cfg.paramstore_materialize = get(
        ps, "materialize", str, cfg.paramstore_materialize
    ).lower()

    r = "Resilience"
    cfg.on_nan = get(r, "on_nan", str, cfg.on_nan).lower()
    cfg.max_rollbacks = get(r, "max_rollbacks", int, cfg.max_rollbacks)
    cfg.io_retries = get(r, "io_retries", int, cfg.io_retries)
    cfg.io_retry_backoff_s = get(
        r, "io_retry_backoff_s", float, cfg.io_retry_backoff_s
    )
    cfg.restart_max = get(r, "restart_max", int, cfg.restart_max)
    cfg.restart_backoff_s = get(r, "restart_backoff_s", float, cfg.restart_backoff_s)
    cfg.restart_backoff_max_s = get(
        r, "restart_backoff_max_s", float, cfg.restart_backoff_max_s
    )

    d = "Distributed"
    cfg.data_parallel = get(d, "data_parallel", int, cfg.data_parallel)
    cfg.row_parallel = get(d, "row_parallel", int, cfg.row_parallel)
    cfg.lookup = get(d, "lookup", str, cfg.lookup).lower()
    cfg.lookup_overflow = get(d, "lookup_overflow", str, cfg.lookup_overflow).lower()
    cfg.lookup_capacity_factor = get(
        d, "lookup_capacity_factor", float, cfg.lookup_capacity_factor
    )
    cfg.coordinator_address = get(d, "coordinator_address", str, cfg.coordinator_address)
    cfg.num_processes = get(d, "num_processes", int, cfg.num_processes)
    cfg.process_id = get(d, "process_id", int, cfg.process_id)
    cfg.input_assignment = get(d, "input_assignment", str, cfg.input_assignment).lower()
    cfg.runtime_dir = get(d, "runtime_dir", str, cfg.runtime_dir)
    cfg.heartbeat_s = get(d, "heartbeat_s", float, cfg.heartbeat_s)
    cfg.host_stall_timeout_s = get(
        d, "host_stall_timeout_s", float, cfg.host_stall_timeout_s
    )
    cfg.barrier_timeout_s = get(d, "barrier_timeout_s", float, cfg.barrier_timeout_s)

    return cfg.validate()


def build_model(cfg: Config):
    """Instantiate the configured model (the reference's graph-builder role)."""
    from fast_tffm_tpu.models import DeepFMModel, FFMModel, FMModel

    if cfg.model == "fm":
        return FMModel(
            vocabulary_size=cfg.vocabulary_size,
            factor_num=cfg.factor_num,
            order=cfg.order,
            init_value_range=cfg.init_value_range,
            factor_lambda=cfg.factor_lambda,
            bias_lambda=cfg.bias_lambda,
        )
    if cfg.model == "ffm":
        return FFMModel(
            vocabulary_size=cfg.vocabulary_size,
            num_fields=cfg.num_fields,
            factor_num=cfg.factor_num,
            init_value_range=cfg.init_value_range,
            factor_lambda=cfg.factor_lambda,
            bias_lambda=cfg.bias_lambda,
            compute_dtype=cfg.compute_dtype,
        )
    return DeepFMModel(
        vocabulary_size=cfg.vocabulary_size,
        num_fields=cfg.num_fields,
        factor_num=cfg.factor_num,
        hidden_dims=cfg.hidden_dims,
        init_value_range=cfg.init_value_range,
        factor_lambda=cfg.factor_lambda,
        bias_lambda=cfg.bias_lambda,
        compute_dtype=cfg.compute_dtype,
    )
