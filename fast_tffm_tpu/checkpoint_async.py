"""Async + incremental checkpointing: take multi-GB saves off the train loop.

The reference trainer's Saver blocked workers at every save, and the
reproduction kept that shape: the loop suspended, converted packed→logical
on device, pulled the full table D2H, and wrote it — tens of GB of dead
chip time per save at the roadmap scale.  Two levers fix it
(Check-N-Run-style differential checkpointing for recommendation tables):

  * **Async full saves** — at a save boundary the loop takes a cheap
    on-device snapshot (the saveable conversion plus a device copy of any
    leaf still aliased to the live state, so the next donated step cannot
    invalidate it), resumes training immediately, and a dedicated writer
    thread performs the packed→logical compute wait, chunked D2H (bounded
    host staging — never 2x table bytes on the host), and the atomic
    tmp + ``os.replace`` publish.  At most ONE save is in flight; if the
    writer falls behind, the next boundary blocks on it (counted as
    back-pressure stall).  The SIGTERM/final/abort paths stay synchronous,
    so the last-good-state guarantee is unchanged.
  * **Delta saves** — between full saves, a device-resident touched-row
    bitmap (OR-reduced across steps; the same bitmap
    ``packed_compact_adagrad_update`` builds per step) names the rows a
    window actually updated, and a ``delta-NNNN`` file ships only those
    logical rows + the dense leaves, chained to its base by content
    signature (checkpoint.save_delta).  ``restore_checkpoint`` replays
    base + chain; the serving watcher applies deltas in place.  Save cost
    drops from O(table) blocking to O(touched rows) overlapped.

Every save emits a ``kind=ckpt`` telemetry record (snapshot/convert/D2H/
write timings, bytes, rows, train-loop stall) through the RunMonitor, so
``tools/report.py`` can render checkpoint stall share next to the
input-vs-compute split.

On a multi-process pod (a ``distributed.DistributedRuntime`` supplied by
the driver) the npz format runs the SINGLE-WRITER protocol: process 0
alone publishes full+delta files and posts each publish's content
signature to the pod KV store; every other host synchronizes on those
signatures and mirrors the chain bookkeeping from the published
outcomes (DESIGN.md "Distributed runtime", crash-consistency invariant
6).  Orbax saves stay collective — every host writes its own shards.
"""

from __future__ import annotations

import threading
import time
import uuid

import numpy as np

from fast_tffm_tpu.checkpoint import (
    DEFAULT_CHUNK_BYTES,
    read_delta_chain,
    save_checkpoint,
    save_delta,
)
from fast_tffm_tpu.telemetry import log_quietly

__all__ = ["AsyncCheckpointer", "device_snapshot", "make_row_gather", "make_touched_marker"]


def _tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def _device_copy(x):
    """Fresh device buffer with x's exact bits, dispatch-only.  A full
    ``lax.slice`` is a real primitive (never a jax-level passthrough, and
    XLA outputs never alias inputs without donation), unlike ``x.copy()``
    which routes through host numpy — measured ~100 ms for a 36 MB state
    on CPU vs sub-ms here."""
    import jax

    shape = tuple(getattr(x, "shape", ()))
    return jax.lax.slice(x, (0,) * len(shape), shape)


def device_snapshot(state):
    """On-device copy of the RAW live state, safe against the next step's
    buffer donation.  The copy — not the packed→logical conversion — is
    the only work that must happen on the loop side (it has to be
    dispatched before the next donated step consumes the buffers); the
    ``saveable`` conversion runs in the WRITER thread against the
    snapshot, so a packed run's O(table) unpack never stalls the loop at
    all."""
    import jax

    return jax.tree.map(_device_copy, state)


def make_row_gather(table_layout: str, row_dim: int):
    """Jitted ``(state, idx) -> (table_rows, accum_rows)`` returning the
    LOGICAL rows for logical ids, straight from the live layout — no
    O(table) unpack per delta.  Packed states dispatch on the fused
    marker (empty accumulator) at trace time; rows states index directly."""
    import jax

    packed = table_layout == "packed"
    d = row_dim

    def gather(state, idx):
        if not packed:
            return state.table[idx], state.table_opt.accum[idx]
        from fast_tffm_tpu.ops.packed_table import (
            fused_accum_gather,
            fused_gather,
            packed_accum_gather_any,
            packed_gather,
        )

        if state.table_opt.accum.size == 0:  # pack_state's fused marker
            return (
                fused_gather(state.table, idx, d),
                fused_accum_gather(state.table, idx, d),
            )
        return (
            packed_gather(state.table, idx, d),
            packed_accum_gather_any(state.table_opt.accum, idx, d),
        )

    return jax.jit(gather)


def make_touched_marker():
    """Jitted ``(bitmap, ids) -> bitmap`` OR-ing a batch's logical ids into
    the device-resident touched-row bitmap (donated — zero copies).  The
    default marker for drivers whose per-step batch carries ``ids`` on the
    host side of the dispatch (streamed local + dist); the device-cache
    driver supplies its own (it marks from the resident id arrays)."""
    import jax
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def mark(bitmap, ids):
        return bitmap.at[ids.reshape(-1)].set(True, mode="drop")

    return mark


class AsyncCheckpointer:
    """Owns the save boundaries of one training run (see module docstring).

    Drivers call, in loop order: ``note_batch`` after every dispatch (delta
    mode only), ``delta_due``/``delta_boundary`` at step boundaries,
    ``save_boundary`` at epoch saves, and ``finalize`` + a synchronous
    ``save_boundary(sync=True)`` on the way out.  Telemetry lands on the
    supplied RunMonitor as ``kind=ckpt`` records (thread-safe; writer
    failures are counted and logged, never raised into the loop — the
    previous checkpoint stays the last good state).
    """

    def __init__(
        self,
        path: str,
        fmt: str,
        *,
        monitor=None,
        log=print,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        async_save: bool = False,
        delta_every_steps: int = 0,
        delta_chain_max: int = 16,
        full_every_s: float = 0.0,
        chain_max_bytes: int = 0,
        vocab: int = 0,
        table_layout: str = "rows",
        row_dim: int = 0,
        mark_fn=None,
        start_step: int = 0,
        cursor_fn=None,
        runtime=None,
        mesh=None,
        paramstore=None,
    ):
        self._path = path
        self._fmt = fmt
        self._monitor = monitor
        self._log = log
        self._chunk = int(chunk_bytes)
        # Tiered runs (paramstore.TieredParamServer): every boundary is
        # synchronous and spans BOTH tiers — publish the npz (hot rows +
        # pending cold rows through the same chain), THEN apply pending
        # to the cold store (invariant 7: store writes are always
        # chain-replayable redo).  config.validate rejects async_save.
        self._ps = paramstore
        self._async = bool(async_save) and fmt == "npz" and paramstore is None
        self._delta_every = int(delta_every_steps) if fmt == "npz" else 0
        self._chain_max = max(1, int(delta_chain_max))
        # Age/size-based chain compaction ([Checkpoint] full_every_s /
        # chain_max_bytes): an hours-long online run (delta_every_steps
        # publishing continuously) must not grow unbounded disk — a full
        # save unlinks the whole chain, so promoting a delta boundary once
        # the chain is OLD or FAT bounds both restore-replay length and
        # on-disk footprint.  Single-writer-pod runs ignore the knobs: the
        # promote decision selects which COLLECTIVE every host dispatches,
        # and a wall-clock threshold read on each host independently could
        # disagree near the boundary (step-count promotion stays exact).
        self._full_every_s = float(full_every_s)
        self._chain_bytes_max = int(chain_max_bytes)
        if runtime is not None and getattr(runtime, "active", False):
            self._full_every_s = 0.0
            self._chain_bytes_max = 0
        self._last_full_t = time.monotonic()
        self._chain_bytes = 0
        self._vocab = int(vocab)
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._last_boundary_step = int(start_step)
        # Multi-host single-writer protocol (distributed.DistributedRuntime,
        # npz format only — orbax saves stay collective, every host writes
        # its own shards): process 0 is the SOLE publisher; after every
        # publish it posts the content signature to the pod KV store, and
        # every other host synchronizes on that signature — immediately
        # for synchronous saves, at the NEXT boundary for async/delta ones
        # (exactly mirroring the lead's own one-in-flight back-pressure).
        # No host passes a save barrier before the signature it observed
        # is durable (DESIGN.md crash-consistency invariant 6).  Boundary
        # ordinals (_seq) advance identically on every host — boundaries
        # are step-deterministic — so the KV keys line up by construction.
        self._rt = runtime if (runtime is not None and runtime.active) else None
        self._lead_writer = self._rt is not None and fmt == "npz"
        self._is_writer = self._rt is None or self._rt.is_lead
        self._seq = 0
        self._pending_await: int | None = None
        self._mesh = mesh
        self._replicate = None
        if self._lead_writer and mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())
            self._replicate = jax.jit(lambda x: x, out_shardings=rep)
        # Exact-position resume: ``cursor_fn()`` (supplied by the driver)
        # names the input position matching the state at a boundary; the
        # dict is captured ON THE LOOP SIDE at each boundary — the writer
        # thread must never read the (moving) live cursor.
        self._cursor_fn = cursor_fn
        self._bitmap = None
        self._mark = None
        self._gather = None
        if self._delta_every > 0:
            self._mark = mark_fn if mark_fn is not None else make_touched_marker()
            self._gather = make_row_gather(table_layout, row_dim)
        # Chain bookkeeping: a RESUMED run extends the chain it restored
        # from (the on-disk head step must equal our start step — anything
        # else is a different model, and chaining deltas onto it would
        # splice two histories).  A fresh run starts with no parent, so
        # the first delta boundary promotes itself to a full save.
        self._parent_sig = None
        self._next_seq = 1
        self._chain_len = 0
        if self._delta_every > 0 and int(start_step) > 0:
            from fast_tffm_tpu.checkpoint import latest_step

            try:
                on_disk = latest_step(path)
                base_sig, chain = read_delta_chain(path)
            except (ValueError, OSError):
                on_disk, base_sig, chain = None, None, []
            if on_disk == int(start_step):
                if chain:
                    self._parent_sig = chain[-1]["save_id"]
                    self._next_seq = len(chain) + 1
                    self._chain_len = len(chain)
                    # Size-based compaction must count the RESUMED chain's
                    # existing files, not start from zero.
                    import os as _os

                    self._chain_bytes = sum(
                        _os.path.getsize(m["path"])
                        for m in chain
                        if _os.path.isfile(m.get("path", ""))
                    )
                else:
                    self._parent_sig = base_sig
        # Counters (ride the kind=summary record via summary()).
        self.full_saves = 0
        self.delta_saves = 0
        self.sync_saves = 0
        self.write_failures = 0
        self.cursor_failures = 0
        self.blocked_boundaries = 0
        self.blocked_ms = 0.0

    # -- loop-side hooks --------------------------------------------------

    @property
    def delta_enabled(self) -> bool:
        return self._delta_every > 0

    def note_batch(self, b) -> None:
        """OR the batch's touched rows into the device bitmap (delta mode
        only; one tiny fused dispatch, overlapped like any other).  ``b``
        is whatever the driver's step consumed: a Batch (its ``ids``
        mark), or an opaque handle a custom ``mark_fn`` understands
        (device-cache batch indices)."""
        if self._mark is None:
            return
        if self._bitmap is None:
            self._bitmap = self._fresh_bitmap()
        ids = getattr(b, "ids", b)
        self._bitmap = self._mark(self._bitmap, ids)

    def delta_due(self, step: int) -> bool:
        return (
            self._delta_every > 0
            and step - self._last_boundary_step >= self._delta_every
        )

    def _fresh_bitmap(self):
        import jax.numpy as jnp

        if self._replicate is not None:
            # Multi-host: the bitmap must be a GLOBAL replicated array so
            # the mark dispatch (global sharded ids in) and the boundary
            # fetch (host read) are well-defined on every pod host.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            return jax.device_put(
                np.zeros((self._vocab,), bool),
                NamedSharding(self._mesh, PartitionSpec()),
            )
        return jnp.zeros((self._vocab,), bool)

    def _cursor(self) -> dict | None:
        if self._cursor_fn is None:
            return None
        try:
            return self._cursor_fn()
        except Exception as e:
            # a cursor bug must never cost the checkpoint — but it must
            # leave a trace: counted into summary(), logged best-effort
            # (the checkpoint then saves WITHOUT a cursor, which resume
            # reports as a legacy start-of-data fallback)
            with self._lock:
                self.cursor_failures += 1
            log_quietly(self._log, f"cursor capture failed (saving without one): {e!r}")
            return None

    # -- multi-host protocol ----------------------------------------------

    def _bump_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _merged_cursor(self, bseq: int) -> dict | None:
        """The cursor this boundary embeds.  Multi-host: every host posts
        its own cursor to the pod KV store; the LEAD gathers the vector
        and embeds it — ``hosts[p]`` names host p's exact input position,
        travelling inside the same atomic publish as the state (the PR-6
        invariant, now per host)."""
        cursor = self._cursor()
        if self._rt is None or self._cursor_fn is None:
            return cursor
        vec = self._rt.share_cursor(bseq, cursor)
        if vec is None:  # non-lead: posted ours, nothing to embed
            return cursor
        merged = dict(cursor or {})
        merged["process_count"] = self._rt.process_count
        merged["hosts"] = [
            {
                "process": p,
                "epoch": (c or {}).get("epoch"),
                "batch_in_epoch": (c or {}).get("batch_in_epoch"),
            }
            for p, c in enumerate(vec)
        ]
        return merged

    def _publish_outcome(self, bseq: int, sig: str | None, meta: str) -> None:
        """Lead: post boundary ``bseq``'s publish outcome (sig durable on
        disk, or meta="failed") so peers can synchronize + mirror the
        chain state.  Runs in whatever thread published (writer thread
        for async/delta)."""
        if not self._lead_writer or not self._is_writer:
            return
        try:
            self._rt.publish_signature(bseq, sig, meta)
        except Exception as e:
            # A dead KV store means the pod is coming apart; peers will
            # surface it as PeerLostError — log, never kill the writer.
            log_quietly(self._log, f"checkpoint signature publish failed: {e!r}")

    def _apply_outcome(self, out: dict | None) -> None:
        """Non-lead chain-state mirror: fold one awaited publish outcome
        into (_parent_sig, _chain_len) so the promote-to-full decision —
        which every host must take identically — tracks the lead's."""
        if out is None:
            return
        sig, meta = out.get("sig"), out.get("meta")
        with self._lock:
            if meta == "full" and sig:
                self._parent_sig = sig
                self._next_seq = 1
                self._chain_len = 0
            elif meta == "delta" and sig:
                self._parent_sig = sig
                self._next_seq += 1
                self._chain_len += 1
            else:  # failed write: mirror the lead's promote-to-full reset
                self._parent_sig = None

    def _await_pending(self, count: bool = False) -> None:
        """Non-lead back-pressure point: block until the previous
        outstanding publish's signature is durable (the save barrier —
        mirrors the lead's own one-writer-in-flight drain)."""
        if self._pending_await is None:
            return
        t0 = time.perf_counter()
        bseq, self._pending_await = self._pending_await, None
        self._apply_outcome(self._rt.await_signature(bseq))
        blocked = (time.perf_counter() - t0) * 1e3
        if count and blocked > 1.0:
            self.blocked_boundaries += 1
            self.blocked_ms += blocked

    # -- boundaries -------------------------------------------------------

    def save_boundary(self, state, saveable, step: int, *, sync: bool = False, emit: bool = True):
        """Full save.  Async (snapshot + writer thread) unless ``sync`` or
        the format/flags demand the blocking path.  Multi-host npz: the
        packed→logical conversion (a cross-host collective on sharded
        states) is dispatched by EVERY host; only process 0 writes, then
        posts the content signature every other host synchronizes on."""
        t0 = time.perf_counter()
        self._drain(count=True)
        self._await_pending(count=True)
        if self._delta_every > 0:
            # A full save supersedes the accumulated window either way.
            self._bitmap = self._fresh_bitmap() if self._bitmap is not None else None
            self._last_boundary_step = int(step)
        bseq = self._bump_seq()
        cursor = self._merged_cursor(bseq)
        if self._ps is not None:
            return self._tiered_full(state, step, cursor, t0, emit)
        if sync or not self._async:
            sid = uuid.uuid4().hex
            timings: dict = {}
            # Every host dispatches the conversion (collective on
            # multi-host sharded states; the lead's write below consumes
            # the replicated result).
            logical = saveable(state)
            t1 = time.perf_counter()
            if self._lead_writer and not self._is_writer:
                # Save barrier: do not proceed until the signature the
                # lead published is durable on the shared filesystem.
                self._apply_outcome(self._rt.await_signature(bseq))
                return
            try:
                nbytes = save_checkpoint(
                    self._path, logical, self._fmt,
                    chunk_bytes=self._chunk, save_id=sid, timings=timings,
                    cursor=cursor,
                )
            except Exception:
                with self._lock:
                    self.write_failures += 1
                self._publish_outcome(bseq, None, "failed")
                raise  # a SYNC save failing must surface — it is the last line
            self._on_full_published(sid)
            self._publish_outcome(bseq, sid, "full")
            self.sync_saves += 1
            stall = (time.perf_counter() - t0) * 1e3
            if emit:
                self._emit(
                    "sync", step, timings,
                    nbytes=nbytes or 0,
                    rows=int(logical.table.shape[0]),
                    snapshot_ms=0.0,
                    convert_ms=(t1 - t0) * 1e3,
                    train_stall_ms=stall,
                )
            return
        if self._lead_writer:
            # Multi-host async: snapshot + conversion dispatched loop-side
            # by every host together (collectives cannot be issued from
            # one host's writer thread alone); the writer thread only
            # waits, fetches, and writes.
            snap = saveable(device_snapshot(state))
            convert = None
        else:
            snap = device_snapshot(state)
            convert = saveable
        if self._lead_writer and not self._is_writer:
            del snap  # the collective still runs; the result is the lead's
            self._pending_await = bseq
            return
        sid = uuid.uuid4().hex
        stall_ms = (time.perf_counter() - t0) * 1e3
        self._spawn(
            self._write_full,
            (snap, convert, int(step), sid, stall_ms, emit, cursor, bseq),
        )

    def delta_boundary(self, state, saveable, step: int):
        """Delta save of the touched window; promotes itself to a full
        save when there is no signed base yet or the chain hit its cap.
        Multi-host npz: the bitmap fetch and the row gather are global
        computations every host dispatches; only the lead writes."""
        t0 = time.perf_counter()
        self._drain(count=True)
        self._await_pending(count=True)
        # Snapshot the chain state under the lock: the (drained) writer
        # thread updates it there, and the promote decision must not read
        # a torn parent/len/bytes triple.
        with self._lock:
            parent_sig = self._parent_sig
            chain_len = self._chain_len
            chain_bytes = self._chain_bytes
            last_full_t = self._last_full_t
        if (
            parent_sig is None
            or chain_len >= self._chain_max
            or (
                self._full_every_s > 0
                and time.monotonic() - last_full_t >= self._full_every_s
            )
            or (
                self._chain_bytes_max > 0
                and chain_bytes >= self._chain_bytes_max
            )
        ):
            return self.save_boundary(state, saveable, step)
        if self._ps is not None:
            return self._tiered_delta(state, step, t0)
        import jax.numpy as jnp

        bseq = self._bump_seq()
        if self._bitmap is not None:
            # Pack to bits ON DEVICE before the fetch: the loop-side D2H
            # is V/8 bytes instead of one bool byte per vocab row (~25 MB
            # vs ~200 MB at the 201M rung — this transfer is train stall).
            bm = self._bitmap
            if self._replicate is not None:
                # Normalize to a replicated (fully addressable) layout so
                # the host fetch below works on every pod host.
                bm = self._replicate(bm)
            host_bm = np.unpackbits(
                np.asarray(jnp.packbits(bm)), count=self._vocab
            ).astype(bool)
        else:
            host_bm = np.zeros((self._vocab,), bool)
        self._bitmap = self._fresh_bitmap()
        self._last_boundary_step = int(step)
        idx = np.flatnonzero(host_bm).astype(np.int64)
        n = int(idx.size)
        # Pad the gather to a power-of-two bucket: one compiled program per
        # bucket instead of one per distinct touched count.
        k = 1 << max(6, (max(n, 1) - 1).bit_length())
        pad_idx = np.zeros((k,), np.int32)
        pad_idx[:n] = idx
        trows, arows = self._gather(state, jnp.asarray(pad_idx))
        if self._replicate is not None:
            trows, arows = self._replicate(trows), self._replicate(arows)
        import jax

        dense = [_device_copy(x) for x in jax.tree.leaves(state.dense)]
        dacc = [_device_copy(x) for x in jax.tree.leaves(state.dense_opt.accum)]
        step_arr = _device_copy(state.step)
        with self._lock:
            seq, parent = self._next_seq, self._parent_sig
        cursor = self._merged_cursor(bseq)
        if self._lead_writer and not self._is_writer:
            # The gather/copies above were this host's share of the
            # collective dispatch; the write itself is the lead's.
            self._pending_await = bseq
            return
        stall_ms = (time.perf_counter() - t0) * 1e3
        self._spawn(
            self._write_delta,
            (seq, parent, idx, n, trows, arows, dense, dacc, step_arr, int(step),
             stall_ms, cursor, bseq),
        )

    # -- tiered boundaries (paramstore; single-host, synchronous) ----------

    def _tiered_full(self, state, step, cursor, t0, emit):
        """Full save spanning both tiers: flush the in-flight writeback,
        publish ONE npz carrying dense + the whole hot tier + residency +
        every pending cold row (paramstore.ckpt.write_tiered_full — same
        atomic chain-reset publish as _save_npz), then apply pending to
        the cold store.  Publish-before-apply is invariant 7: the store
        write is redo the chain can replay."""
        from fast_tffm_tpu.paramstore.ckpt import write_tiered_full

        sid = uuid.uuid4().hex
        self._ps.flush_writeback(state)
        pending_rows = self._ps.pending_rows
        t1 = time.perf_counter()
        timings: dict = {}
        try:
            nbytes = write_tiered_full(
                self._path, self._ps, state, int(step),
                save_id=sid, cursor=cursor, chunk_bytes=self._chunk,
            )
        except Exception:
            with self._lock:
                self.write_failures += 1
            raise  # tiered saves are sync — a failure must surface
        self._on_full_published(sid)
        self._apply_tiered(sid)
        self.sync_saves += 1
        stall = (time.perf_counter() - t0) * 1e3
        if emit:
            self._emit(
                "sync", step, timings, nbytes=nbytes or 0,
                rows=self._ps.hot_rows + pending_rows,
                snapshot_ms=0.0, convert_ms=(t1 - t0) * 1e3,
                train_stall_ms=stall,
            )

    def _tiered_delta(self, state, step, t0):
        """Delta save spanning both tiers: the window's touched rows as
        LOGICAL rows through the unchanged save_delta format — touched
        hot slots gather off the compact device state (and translate to
        logical ids via the residency map), pending cold rows come off
        the overlay (flush first, so the LAST dispatch's staging rows are
        in it).  Hot and pending are disjoint by construction (a
        resident row never misses)."""
        import jax
        import jax.numpy as jnp

        bseq = self._bump_seq()
        cursor = self._merged_cursor(bseq)
        self._ps.flush_writeback(state)
        if self._bitmap is not None:
            host_bm = np.unpackbits(
                np.asarray(jnp.packbits(self._bitmap)), count=self._vocab
            ).astype(bool)
        else:
            host_bm = np.zeros((self._vocab,), bool)
        self._bitmap = self._fresh_bitmap()
        self._last_boundary_step = int(step)
        slots = np.flatnonzero(host_bm)
        hot_slots = slots[slots < self._ps.hot_rows].astype(np.int64)
        n_hot = int(hot_slots.size)
        # Pow2-bucketed gather like the resident delta path: one compiled
        # program per bucket.
        k = 1 << max(6, (max(n_hot, 1) - 1).bit_length())
        pad_idx = np.zeros((k,), np.int32)
        pad_idx[:n_hot] = hot_slots
        trows, arows = self._gather(state, jnp.asarray(pad_idx))
        jax.block_until_ready((trows, arows))
        hot_ids = self._ps.hot_logical_ids(hot_slots)
        pend_ids, pend_t, pend_a = self._ps.pending_snapshot()
        idx = np.concatenate([hot_ids, pend_ids])
        t_all = np.concatenate([np.asarray(trows)[:n_hot], pend_t])
        a_all = np.concatenate([np.asarray(arows)[:n_hot], pend_a])
        with self._lock:
            seq, parent = self._next_seq, self._parent_sig
        stall_ms = (time.perf_counter() - t0) * 1e3
        timings: dict = {}
        try:
            out_path, sid, nbytes = save_delta(
                self._path, seq,
                idx=idx.astype(np.int64), table_rows=t_all, accum_rows=a_all,
                dense_leaves=[np.asarray(x) for x in _tree_leaves(state.dense)],
                dense_accum_leaves=[
                    np.asarray(x) for x in _tree_leaves(state.dense_opt.accum)
                ],
                step=np.asarray(state.step), parent_sig=parent,
                chunk_bytes=self._chunk, timings=timings, cursor=cursor,
            )
            from fast_tffm_tpu.resilience import maybe_torn_delta

            maybe_torn_delta(out_path)
        except Exception as e:
            # Mirror the async writer's contract: the chain on disk stays
            # complete; the next boundary promotes itself to a full save.
            with self._lock:
                self.write_failures += 1
            self._on_write_failed()
            log_quietly(self._log, f"tiered delta write failed (chain intact): {e!r}")
            return
        with self._lock:
            self._parent_sig = sid
            self._next_seq = seq + 1
            self._chain_len += 1
            self._chain_bytes += int(nbytes)
            self.delta_saves += 1
        self._apply_tiered(sid)
        self._emit(
            "delta", step, timings, nbytes=nbytes, rows=int(idx.size),
            snapshot_ms=stall_ms, convert_ms=0.0, train_stall_ms=stall_ms,
        )

    def _apply_tiered(self, sid: str) -> None:
        """Post-publish store apply; a failure here never un-publishes —
        pending stays intact and simply rides (and re-applies after) the
        next boundary."""
        try:
            self._ps.apply_pending(sid)
        except Exception as e:
            with self._lock:
                self.write_failures += 1
            log_quietly(
                self._log,
                f"paramstore apply failed after publish (pending rows "
                f"retained; chain intact): {e!r}",
            )

    # -- writer thread ----------------------------------------------------

    def _spawn(self, fn, args) -> None:
        self._thread = threading.Thread(
            target=fn, args=args, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    def _drain(self, count: bool = False) -> float:
        """Back-pressure point: wait out the (at most one) in-flight
        writer.  Returns the blocked milliseconds."""
        t = self._thread
        if t is None or not t.is_alive():
            if t is not None:
                t.join()
                self._thread = None
            return 0.0
        t0 = time.perf_counter()
        t.join()
        self._thread = None
        blocked = (time.perf_counter() - t0) * 1e3
        if count:
            self.blocked_boundaries += 1
            self.blocked_ms += blocked
        return blocked

    def finalize(self) -> None:
        """Join any in-flight write — called before the final synchronous
        save so an older async publish can never clobber a newer one.
        Non-lead pod hosts drain their outstanding signature wait the
        same way."""
        self._drain()
        self._await_pending()

    def _write_full(self, snap, saveable, step, sid, stall_ms, emit, cursor=None, bseq=0) -> None:
        import jax

        try:
            t0 = time.perf_counter()
            # Packed->logical conversion runs HERE, against the snapshot,
            # entirely off the train loop.  (Multi-host: the conversion is
            # a collective, already dispatched loop-side by every host —
            # saveable arrives as None and this thread only waits.)
            if saveable is not None:
                snap = saveable(snap)
            jax.block_until_ready(snap)
            convert_ms = (time.perf_counter() - t0) * 1e3
            timings: dict = {}
            nbytes = save_checkpoint(
                self._path, snap, "npz",
                chunk_bytes=self._chunk, save_id=sid, timings=timings,
                cursor=cursor,
            )
            self._on_full_published(sid)
            self._publish_outcome(bseq, sid, "full")
            with self._lock:
                self.full_saves += 1
            if emit:
                self._emit(
                    "full", step, timings, nbytes=nbytes or 0,
                    rows=int(snap.table.shape[0]),
                    snapshot_ms=stall_ms, convert_ms=convert_ms,
                    train_stall_ms=stall_ms,
                )
        except Exception as e:
            with self._lock:
                self.write_failures += 1
            self._on_write_failed()
            self._publish_outcome(bseq, None, "failed")
            log_quietly(self._log, f"async checkpoint write failed (previous checkpoint intact): {e!r}")

    def _write_delta(
        self, seq, parent, idx, n, trows, arows, dense, dacc, step_arr, step,
        stall_ms, cursor=None, bseq=0,
    ) -> None:
        import jax

        try:
            t0 = time.perf_counter()
            jax.block_until_ready((trows, arows))
            convert_ms = (time.perf_counter() - t0) * 1e3
            t1 = time.perf_counter()
            trows_h = np.asarray(trows)[:n]
            arows_h = np.asarray(arows)[:n]
            dense_h = [np.asarray(x) for x in dense]
            dacc_h = [np.asarray(x) for x in dacc]
            step_h = np.asarray(step_arr)
            d2h_ms = (time.perf_counter() - t1) * 1e3
            timings: dict = {}
            out_path, sid, nbytes = save_delta(
                self._path, seq,
                idx=idx, table_rows=trows_h, accum_rows=arows_h,
                dense_leaves=dense_h, dense_accum_leaves=dacc_h,
                step=step_h, parent_sig=parent,
                chunk_bytes=self._chunk, timings=timings, cursor=cursor,
            )
            # Chaos injection point: a planned torn_delta fault truncates
            # the file just published — simulating the torn write a crash
            # (or dying disk) leaves on a non-atomic filesystem, so the
            # repair/restart path is testable deterministically.
            from fast_tffm_tpu.resilience import maybe_torn_delta

            maybe_torn_delta(out_path)
            with self._lock:
                self._parent_sig = sid
                self._next_seq = seq + 1
                self._chain_len += 1
                self._chain_bytes += int(nbytes)
                self.delta_saves += 1
            self._publish_outcome(bseq, sid, "delta")
            timings["d2h_ms"] = timings.get("d2h_ms", 0.0) + d2h_ms
            self._emit(
                "delta", step, timings, nbytes=nbytes, rows=n,
                snapshot_ms=stall_ms, convert_ms=convert_ms,
                train_stall_ms=stall_ms,
            )
        except Exception as e:
            with self._lock:
                self.write_failures += 1
            self._on_write_failed()
            self._publish_outcome(bseq, None, "failed")
            log_quietly(self._log, f"delta checkpoint write failed (chain intact): {e!r}")

    def _on_full_published(self, sid: str) -> None:
        with self._lock:
            self._parent_sig = sid
            self._next_seq = 1
            self._chain_len = 0
            self._chain_bytes = 0
            self._last_full_t = time.monotonic()

    def _on_write_failed(self) -> None:
        """A failed write DROPPED its window's rows (the boundary already
        reset the bitmap / advanced past them), so later deltas alone can
        no longer reconstruct the state: clear the chain parent, forcing
        the next delta boundary to promote itself to a full save.  The
        on-disk base+chain stays exactly as it was — complete and
        loadable — it just stops growing until a full save lands."""
        with self._lock:
            self._parent_sig = None

    # -- telemetry --------------------------------------------------------

    def _emit(
        self, mode, step, timings, *, nbytes, rows, snapshot_ms, convert_ms,
        train_stall_ms,
    ) -> None:
        if self._monitor is None:
            return
        try:
            self._monitor.emit(
                "ckpt",
                step=int(step),
                mode=mode,
                snapshot_ms=round(float(snapshot_ms), 3),
                convert_ms=round(float(convert_ms), 3),
                d2h_ms=round(float(timings.get("d2h_ms", 0.0)), 3),
                write_ms=round(float(timings.get("write_ms", 0.0)), 3),
                bytes=int(nbytes),
                rows_written=int(rows),
                train_stall_ms=round(float(train_stall_ms), 3),
            )
        except (OSError, ValueError):
            pass  # a full metrics disk must not cost the checkpoint

    def summary(self) -> dict:
        """End-of-run counters, merged into the kind=summary record."""
        with self._lock:
            out = {
                "ckpt_full_saves": self.full_saves,
                "ckpt_delta_saves": self.delta_saves,
                "ckpt_sync_saves": self.sync_saves,
                "ckpt_write_failures": self.write_failures,
                "ckpt_cursor_failures": self.cursor_failures,
                "ckpt_blocked_boundaries": self.blocked_boundaries,
            }
        if self.blocked_ms:
            out["ckpt_blocked_ms"] = round(self.blocked_ms, 3)
        return {k: v for k, v in out.items() if v}
