"""Host-side cold tier: the full logical table as memmap-backed files.

The beyond-HBM half of the tiered parameter store (DESIGN "Tiered
parameter store").  One ``ColdStore`` owns a directory holding the full
``[V, D]`` table and ``[V, A]`` accumulator as row-addressable memmaps
plus a one-bit-per-row "written" bitmap.  Two properties make vocabs far
past device HBM (and even past host RAM) workable:

  * **sparse files** — the data files are created by ``truncate`` alone,
    so a 2^30-row store costs disk only for the rows actually written
    (the OS hands out zero pages for the rest); host RAM is only the
    page cache's working set, not the table;
  * **lazy row init** — rows never written read as their deterministic
    init value, computed on demand: a counter-based hash expands
    ``(seed, id, col)`` to the same uniform ``[-r, r)`` factor draw every
    time (bias column 0 stays 0.0, matching every model's
    ``init_table``), so the init never has to materialize.  Small vocabs
    can instead ``materialize=True`` the exact ``model.init_table`` draw
    into the store — that is what makes a tiered run bit-identical to
    the resident path at overlapping vocab (jax's bulk RNG draw is not
    reproducible per-row, so exact parity requires materializing it).

Durability contract (crash-consistency invariant 7, DESIGN): rows reach
the store ONLY through the post-publish apply of a checkpoint boundary
whose npz already carries the same rows — every store write is a redo
the chain can replay, so a crash at ANY point leaves a row's latest
value recoverable from exactly one tier plus the chain.  ``meta.json``
records the last applied boundary's save_id (atomic tmp+replace); a
store whose ``applied_sig`` names a save the on-disk chain no longer
contains (the narrow unlink-to-rename crash window of a full save) is
detected at restore and refused loudly — never silently mixed.
"""

from __future__ import annotations

import json
import os
import uuid

import numpy as np

__all__ = ["ColdStore", "hashed_uniform_rows"]

_META = "meta.json"
_TABLE = "table.dat"
_ACCUM = "accum.dat"
_WRITTEN = "written.dat"

STORE_VERSION = 1


def hashed_uniform_rows(
    ids: np.ndarray, row_dim: int, seed: int, init_range: float
) -> np.ndarray:
    """Deterministic per-row init: uniform [-r, r) factors from a
    counter-based integer hash of (seed, id, col); column 0 (the bias
    slot every model's init_table zeroes) stays 0.0.  Vectorized — a
    2^30-row store never materializes anything; rows are conjured as
    they are first touched."""
    ids = np.asarray(ids, np.uint64).reshape(-1, 1)
    cols = np.arange(row_dim, dtype=np.uint64).reshape(1, -1)
    # splitmix64 over a (seed, id, col) counter — full-width avalanche,
    # so adjacent ids/cols decorrelate.
    seed_mix = np.uint64((int(seed) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF)
    x = (
        ids * np.uint64(0x9E3779B97F4A7C15)
        + cols * np.uint64(0xBF58476D1CE4E5B9)
        + seed_mix
    )
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    u = (x >> np.uint64(40)).astype(np.float32) / np.float32(1 << 24)  # [0, 1)
    rows = ((u * 2.0 - 1.0) * np.float32(init_range)).astype(np.float32)
    rows[:, 0] = 0.0  # bias column
    return rows


class ColdStore:
    """Row-addressable host store for one logical table (+ accumulator).

    All reads/writes take LOGICAL row ids.  Reads overlay nothing — the
    caller (paramstore.tiered) owns the pending-writeback overlay; this
    class is purely the durable bottom tier."""

    def __init__(self, path: str, meta: dict):
        self.path = path
        self.meta = meta
        self.vocab = int(meta["vocab"])
        self.row_dim = int(meta["row_dim"])
        self.accum_width = int(meta["accum_width"])
        self._table = np.memmap(
            os.path.join(path, _TABLE), np.float32, mode="r+",
            shape=(self.vocab, self.row_dim),
        )
        self._accum = np.memmap(
            os.path.join(path, _ACCUM), np.float32, mode="r+",
            shape=(self.vocab, self.accum_width),
        )
        self._written = np.memmap(
            os.path.join(path, _WRITTEN), np.uint8, mode="r+",
            shape=((self.vocab + 7) // 8,),
        )

    # -- creation / opening ----------------------------------------------

    @classmethod
    def create(
        cls,
        path: str,
        *,
        vocab: int,
        row_dim: int,
        accum_width: int,
        seed: int,
        init_range: float,
        init_accum: float,
        init_table=None,
        init_accum_arr=None,
    ) -> "ColdStore":
        """Fresh store.  ``init_table``/``init_accum_arr`` (host arrays)
        materialize the exact init into the files (small vocabs — the
        bit-identity path); without them, rows stay lazy (sparse files,
        hashed_uniform on first read)."""
        os.makedirs(path, exist_ok=True)
        for name, width in ((_TABLE, row_dim), (_ACCUM, accum_width)):
            with open(os.path.join(path, name), "wb") as f:
                f.truncate(vocab * width * 4)
        with open(os.path.join(path, _WRITTEN), "wb") as f:
            f.truncate((vocab + 7) // 8)
        meta = {
            "version": STORE_VERSION,
            "vocab": int(vocab),
            "row_dim": int(row_dim),
            "accum_width": int(accum_width),
            "seed": int(seed),
            "init_range": float(init_range),
            "init_accum": float(init_accum),
            "materialized": init_table is not None,
            "fingerprint": uuid.uuid4().hex,
            "applied_sig": None,
        }
        cls._write_meta(path, meta)
        store = cls(path, meta)
        if init_table is not None:
            t = np.asarray(init_table, np.float32)
            a = np.asarray(init_accum_arr, np.float32)
            if t.shape != (vocab, row_dim) or a.shape != (vocab, accum_width):
                raise ValueError(
                    f"materialized init shapes {t.shape}/{a.shape} do not "
                    f"match store [{vocab}, {row_dim}]/[{vocab}, {accum_width}]"
                )
            # Chunked copy: bounded dirty pages, no 2x table on heap.
            chunk = max(1, (64 << 20) // max(1, row_dim * 4))
            for lo in range(0, vocab, chunk):
                hi = min(vocab, lo + chunk)
                store._table[lo:hi] = t[lo:hi]
                store._accum[lo:hi] = a[lo:hi]
            store._written[:] = 0xFF
            store.flush()
        return store

    @classmethod
    def open(cls, path: str) -> "ColdStore":
        meta_path = os.path.join(path, _META)
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(
                f"parameter store at {path!r} is missing or corrupt "
                f"({e}) — delete the directory to start fresh"
            ) from e
        if int(meta.get("version", 0)) != STORE_VERSION:
            raise ValueError(
                f"parameter store {path!r} has version "
                f"{meta.get('version')}, this build writes {STORE_VERSION}"
            )
        return cls(path, meta)

    @staticmethod
    def _write_meta(path: str, meta: dict) -> None:
        tmp = os.path.join(path, _META + ".tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, _META))

    @property
    def fingerprint(self) -> str:
        return self.meta["fingerprint"]

    @property
    def applied_sig(self) -> str | None:
        return self.meta.get("applied_sig")

    def set_applied(self, sig: str | None) -> None:
        """Record the last checkpoint boundary whose rows were applied
        (atomic publish — the restore-time orphan check reads this)."""
        self.meta["applied_sig"] = sig
        self._write_meta(self.path, self.meta)

    # -- row IO ------------------------------------------------------------

    def _written_mask(self, ids: np.ndarray) -> np.ndarray:
        b = self._written[ids >> 3]
        return (b >> (ids & 7).astype(np.uint8)) & 1 > 0

    def read_rows(self, ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(table_rows [n, D], accum_rows [n, A]) for logical ``ids`` —
        written rows from the memmaps, unwritten rows from the lazy init."""
        ids = np.asarray(ids, np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.vocab):
            raise ValueError(
                f"store read out of range: ids span "
                f"[{ids.min()}, {ids.max()}] for vocab {self.vocab}"
            )
        written = self._written_mask(ids)
        t = np.empty((ids.size, self.row_dim), np.float32)
        a = np.empty((ids.size, self.accum_width), np.float32)
        if written.any():
            w_ids = ids[written]
            t[written] = self._table[w_ids]
            a[written] = self._accum[w_ids]
        if not written.all():
            cold = ids[~written]
            t[~written] = hashed_uniform_rows(
                cold, self.row_dim, self.meta["seed"], self.meta["init_range"]
            )
            a[~written] = np.float32(self.meta["init_accum"])
        return t, a

    def write_rows(self, ids: np.ndarray, table_rows, accum_rows) -> None:
        ids = np.asarray(ids, np.int64)
        if ids.size and (ids.min() < 0 or ids.max() >= self.vocab):
            raise ValueError(
                f"store write out of range: ids span "
                f"[{ids.min()}, {ids.max()}] for vocab {self.vocab}"
            )
        self._table[ids] = np.asarray(table_rows, np.float32)
        self._accum[ids] = np.asarray(accum_rows, np.float32)
        # OR the written bits in (np fancy-index |= would lose duplicate
        # byte updates; ids within one write are unique by contract).
        np.bitwise_or.at(
            self._written, ids >> 3, (1 << (ids & 7)).astype(np.uint8)
        )

    def flush(self) -> None:
        self._table.flush()
        self._accum.flush()
        self._written.flush()

    def close(self) -> None:
        self.flush()
        # memmaps release with the object; explicit del keeps Windows-ish
        # semantics obvious and makes close() idempotent-safe.
        del self._table, self._accum, self._written
