"""Tiered checkpointing glue: both tiers through ONE atomic-publish chain.

A tiered run's save boundaries go through the same npz chain machinery
as every other run (checkpoint.py: atomic tmp+``os.replace``, content
``save_id``/``parent_sig`` links, the input cursor riding inside the
same publish), but the payload spans both tiers:

  * **full save** — dense leaves + the ENTIRE hot tier (``table`` /
    ``table_accum`` are the ``[H, D]``/``[H, A]`` device arrays, so
    ``latest_step``/``checkpoint_save_id``/``read_input_cursor`` and the
    chain reader all work unchanged) + the residency set
    (``tier_hot_ids``) + every pending-writeback row
    (``tier_cold_idx/rows/accum``) + the store identity
    (``tier_store``: fingerprint, shape).  The cold BULK never re-writes:
    the store file on disk IS the base for non-resident rows.
  * **delta save** — the window's touched rows as LOGICAL rows through
    the existing ``save_delta`` format: touched hot slots gather from
    the device, pending rows come off the overlay; a delta is
    layout-agnostic, so the chain reader needs nothing new.

Crash-consistency invariant 7 (DESIGN "Tiered parameter store"): store
writes happen ONLY after the boundary npz carrying the same rows is
durable, so a row's latest value is always recoverable from exactly one
tier plus the chain — restore replays base + chain and re-scatters every
chain row into the store (idempotent redo), which also repairs a kill
mid-apply.  The one undecidable window — a full save killed between
unlinking the old chain and renaming the new base, with store applies
from the vanished chain — is DETECTED (``applied_sig`` names a save the
chain no longer contains) and refused loudly, never silently mixed."""

from __future__ import annotations

import json
import os
import time

import numpy as np

from fast_tffm_tpu.paramstore.store import ColdStore

__all__ = ["write_tiered_full", "restore_tiered", "is_tiered_checkpoint"]

_TIER_MARKER = "tier_hot_ids"

# Superseded-sig lineage cap: prev-sig lists carry forward across full
# saves so a crash between a full publish and its store apply (the store
# still stamped with a sig from the just-unlinked chain) stays
# distinguishable from a genuinely replaced store.  Applied sigs advance
# monotonically, so only the recent tail can ever reappear.
_PREV_SIGS_MAX = 256


def _superseded_sigs(path: str) -> list[str]:
    """Every save_id the on-disk base+deltas (and their own recorded
    lineage) carry RIGHT NOW — the set the store's ``applied_sig`` could
    legitimately name after this publish unlinks them.  Tolerant reads:
    a torn file contributes nothing (its sig could never have been
    applied)."""
    from fast_tffm_tpu.checkpoint import _npz_string, _open_npz, delta_paths

    sigs: list[str] = []

    def add(s):
        if s and s not in sigs:
            sigs.append(s)

    if os.path.isfile(path):
        try:
            with _open_npz(path) as z:
                if "tier_prev_sigs" in getattr(z, "files", ()):
                    for s in json.loads(
                        bytes(np.asarray(z["tier_prev_sigs"]).tobytes()).decode()
                    ):
                        add(s)
                add(_npz_string(z, "save_id"))
        except (ValueError, OSError):
            pass
        for dp in delta_paths(path):
            try:
                with _open_npz(dp) as z:
                    add(_npz_string(z, "save_id"))
            except (ValueError, OSError):
                pass
    return sigs[-_PREV_SIGS_MAX:]


def is_tiered_checkpoint(z) -> bool:
    """True when an open npz holds a tiered (paramstore) checkpoint."""
    return _TIER_MARKER in getattr(z, "files", ())


def write_tiered_full(
    path: str,
    server,
    state,
    step: int,
    *,
    save_id: str,
    cursor: dict | None = None,
    chunk_bytes: int | None = None,
) -> int:
    """Atomic tiered full save (see module docstring).  The caller must
    have flushed the writeback first (``server.flush_writeback``) so the
    pending overlay names the latest value of every non-resident touched
    row.  Mirrors checkpoint._save_npz's publish ordering exactly:
    tmp write → unlink old deltas → chaos hook → ``os.replace``."""
    from fast_tffm_tpu.checkpoint import (
        DEFAULT_CHUNK_BYTES,
        _cursor_entry,
        _maybe_publish_fault,
        _write_npz_streaming,
        delta_paths,
    )

    hot_t, hot_a = server.hot_rows_host(state)
    cold_idx, cold_t, cold_a = server.pending_snapshot()
    store_meta = {
        "fingerprint": server.store.fingerprint,
        "vocab": server.store.vocab,
        "row_dim": server.row_dim,
        "accum_width": server.accum_width,
        "hot_rows": server.hot_rows,
    }
    entries = {
        "table": hot_t,
        "table_accum": hot_a,
        "step": np.asarray(state.step),
        "save_id": np.frombuffer(save_id.encode(), np.uint8),
        "published_at": np.float64(time.time()),
        _TIER_MARKER: np.asarray(server.residency.hot_ids, np.int64),
        "tier_cold_idx": cold_idx,
        "tier_cold_rows": cold_t,
        "tier_cold_accum": cold_a,
        "tier_store": np.frombuffer(
            json.dumps(store_meta, sort_keys=True).encode(), np.uint8
        ),
        # The sigs this publish supersedes (crash between the rename and
        # the store apply leaves applied_sig naming one of these — still
        # fully recoverable, since THIS base's tier_cold rows are the
        # redo for everything pending since the last apply).
        "tier_prev_sigs": np.frombuffer(
            json.dumps(_superseded_sigs(path)).encode(), np.uint8
        ),
    }
    if cursor is not None:
        entries["input_cursor"] = _cursor_entry(cursor)
    dense_leaves = list(_leaves(state.dense))
    dacc_leaves = list(_leaves(state.dense_opt.accum))
    for i, (p, a) in enumerate(zip(dense_leaves, dacc_leaves)):
        entries[f"dense_{i}"] = p
        entries[f"dense_accum_{i}"] = a
    tmp = path + ".tmp"
    dirpart = os.path.dirname(path)
    if dirpart:
        os.makedirs(dirpart, exist_ok=True)
    with open(tmp, "wb") as f:
        nbytes = _write_npz_streaming(
            f, entries, chunk_bytes or DEFAULT_CHUNK_BYTES
        )
    for dp in delta_paths(path):
        try:
            os.remove(dp)
        except OSError:
            pass
    _maybe_publish_fault(path)
    os.replace(tmp, path)
    return nbytes


def _leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def restore_tiered(path: str, store: ColdStore, n_dense: int) -> dict:
    """Replay base + chain into (hot tier arrays, dense leaves, step),
    re-scattering every chain row's cold half into the store (idempotent
    redo — also the repair for a kill mid-apply).  Returns a dict with
    hot_ids / hot_t / hot_a / dense / dense_accum / step."""
    from fast_tffm_tpu.checkpoint import (
        _open_npz,
        load_delta,
        read_delta_chain,
    )

    with _open_npz(path) as z:
        if not is_tiered_checkpoint(z):
            raise ValueError(
                f"{path!r} is not a tiered (paramstore) checkpoint — it has "
                "no residency members.  Resume it without [ParamStore] "
                "enabled, or start the tiered run fresh."
            )
        meta = json.loads(bytes(np.asarray(z["tier_store"]).tobytes()).decode())
        if meta.get("fingerprint") != store.fingerprint:
            raise ValueError(
                f"tiered checkpoint {path!r} was saved against parameter "
                f"store {meta.get('fingerprint')!r}, but {store.path!r} is "
                f"{store.fingerprint!r} — the store was replaced or "
                "recreated since this checkpoint; restore the original "
                "store directory or start fresh"
            )
        hot_ids = np.asarray(z[_TIER_MARKER], np.int64)
        hot_t = np.array(z["table"], np.float32)
        hot_a = np.array(z["table_accum"], np.float32)
        step = np.asarray(z["step"])
        dense = [np.asarray(z[f"dense_{i}"]) for i in range(n_dense)]
        dacc = [np.asarray(z[f"dense_accum_{i}"]) for i in range(n_dense)]
        cold_idx = np.asarray(z["tier_cold_idx"], np.int64)
        cold_t = np.asarray(z["tier_cold_rows"], np.float32)
        cold_a = np.asarray(z["tier_cold_accum"], np.float32)
        prev_sigs: list = []
        if "tier_prev_sigs" in z.files:
            prev_sigs = json.loads(
                bytes(np.asarray(z["tier_prev_sigs"]).tobytes()).decode()
            )
    base_sig, chain = read_delta_chain(path)
    sigs = {m["save_id"] for m in chain}
    sigs.update(prev_sigs)
    if base_sig:
        sigs.add(base_sig)
    applied = store.applied_sig
    if applied is not None and applied not in sigs:
        raise ValueError(
            f"parameter store {store.path!r} has boundary {applied!r} "
            "applied, but the checkpoint chain at "
            f"{path!r} no longer contains that save — the store is AHEAD "
            "of the chain (crash inside a full-save publish window?).  "
            "The tiers cannot be mixed consistently; start the run fresh "
            "(or restore a matching store backup)."
        )
    if cold_idx.size:
        store.write_rows(cold_idx, cold_t, cold_a)
    h = hot_ids.size
    for m in chain:
        d = load_delta(m["path"], n_dense)
        idx = np.asarray(d["idx"], np.int64)
        pos = np.searchsorted(hot_ids, idx)
        pos_c = np.minimum(pos, max(0, h - 1))
        is_hot = (pos < h) & (hot_ids[pos_c] == idx) if h else np.zeros(idx.shape, bool)
        if is_hot.any():
            hot_t[pos_c[is_hot]] = d["table_rows"][is_hot]
            hot_a[pos_c[is_hot]] = d["accum_rows"][is_hot]
        if (~is_hot).any():
            store.write_rows(
                idx[~is_hot], d["table_rows"][~is_hot], d["accum_rows"][~is_hot]
            )
        dense = d["dense"]
        dacc = d["dense_accum"]
        step = d["step"]
    head = chain[-1]["save_id"] if chain else base_sig
    store.flush()
    store.set_applied(head)
    return {
        "hot_ids": hot_ids,
        "hot_t": hot_t,
        "hot_a": hot_a,
        "dense": dense,
        "dense_accum": dacc,
        "step": step,
    }
