"""Two-tier parameter server: device-resident hot rows + host cold store.

The tentpole of ISSUE 12 (ROADMAP item 3): the device holds a COMPACT
``[C, D]`` table (C = hot_rows + miss_rows), the host holds the full
logical table (paramstore/store.py), and every (super)batch is resolved
ahead of dispatch:

  1. **resolve** (prefetch thread) — dedup the batch's logical ids,
     split hit/miss against the residency map (paramstore/residency.py),
     remap every id to a device slot: hot ids to their rank slot in
     ``[0, H)``, each unique missed id to a staging slot ``[H, C)``.
     Dedup-before-gather falls out here for free: the 0.291 dedup ratio
     PROBE_IDSTATS_r09 measured means ~71% of would-be gather bytes
     never exist as wire or staging traffic.
  2. **ship** — the remapped batch packs onto the EXISTING packed wire
     (data/wire.py, spec'd at the capacity C so ids narrow to the
     compact range), and the missed rows' table+accumulator values ride
     the SAME coalesced buffer; one ``device_put``, one jitted unpack.
  3. **stage + step** — a donated ``dynamic_update_slice`` drops the
     miss rows into the staging region, then the UNCHANGED jitted train
     step (trainer.train_step_body over the compact table with remapped
     ids) runs — the math is the resident path's math on the same
     values, which is why tiered-vs-resident losses pin bit-identical at
     overlapping vocab.
  4. **writeback** (next dispatch) — the staging region's updated rows
     are fetched D2H and recorded in the PENDING overlay (host RAM).
     Pending rows reach the cold store only at checkpoint boundaries,
     AFTER the boundary's npz (which carries the same rows) publishes —
     every store write is chain-replayable redo, so no update is ever
     lost to a crash (crash-consistency invariant 7, DESIGN).

Coherency: resolution happens in the prefetch thread against a
versioned snapshot of pending; if a writeback lands between a payload's
resolution and its dispatch for one of ITS miss ids, the dispatch-side
check re-reads just that payload's values (a counted ``restage``) —
the fast path stays fully producer-resolved, the slow path stays
correct.  The hot tier absorbs repeats by construction, so restages are
rare exactly when the residency policy is doing its job."""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from typing import NamedTuple

import numpy as np

from fast_tffm_tpu.paramstore.residency import ResidencyMap
from fast_tffm_tpu.paramstore.store import ColdStore

__all__ = ["TieredParamServer", "TieredBatch", "TieredConverter"]


class _RemappedParsed(NamedTuple):
    """ParsedBatch shim with remapped (local-slot) ids — what the packed
    wire packer consumes."""

    batch_size: int
    max_nnz: int
    labels: np.ndarray
    nnz: np.ndarray
    ids: np.ndarray
    vals: np.ndarray
    fields: np.ndarray


def _remap(parsed, local_ids: np.ndarray) -> _RemappedParsed:
    return _RemappedParsed(
        batch_size=parsed.batch_size,
        max_nnz=parsed.max_nnz,
        labels=parsed.labels,
        nnz=parsed.nnz,
        ids=local_ids,
        vals=parsed.vals,
        fields=parsed.fields,
    )


class TieredBatch(NamedTuple):
    """One resolved dispatch payload: the remapped device batch plus the
    staged miss rows and the host-side bookkeeping the step wrapper and
    the delta machinery need.  ``.ids`` mirrors Batch so the
    touched-row marker (AsyncCheckpointer.note_batch) works unchanged."""

    batch: object  # device Batch (remapped local ids), [K, B, ...] or [B, ...]
    miss_t: object  # [M, D] staged table rows (device)
    miss_a: object  # [M, A] staged accumulator rows (device)
    miss_ids: np.ndarray  # [m] unique missed LOGICAL ids (host, sorted)
    version: int  # pending-overlay version the values were read at

    @property
    def ids(self):
        return self.batch.ids


@functools.lru_cache(maxsize=None)
def _make_tiered_unpacker(spec, miss_rows: int, row_dim: int, accum_width: int):
    """Jitted ``unpack(buf) -> (Batch, miss_t, miss_a)`` for ONE combined
    uint8 buffer: ``[K*L batch wire section][M*D f32][M*A f32]``.  The
    batch section reuses the packed-wire unpacker verbatim; K is read
    off the buffer length (one compiled program per (K, L) shape —
    epoch-tail K' compiles once, priced as warmup like every tail)."""
    import jax
    import jax.numpy as jnp

    from fast_tffm_tpu.data.wire import make_unpacker

    inner = make_unpacker(spec)
    mt_bytes = miss_rows * row_dim * 4
    ma_bytes = miss_rows * accum_width * 4

    def as_f32(x, rows, cols):
        u8 = x.reshape(-1, 4).astype(jnp.uint32)
        u32 = (
            u8[:, 0]
            | (u8[:, 1] << 8)
            | (u8[:, 2] << 16)
            | (u8[:, 3] << 24)
        )
        return jax.lax.bitcast_convert_type(u32, jnp.float32).reshape(rows, cols)

    @functools.partial(jax.jit, static_argnums=(1,))
    def unpack(buf, k: int):
        total = buf.shape[0]
        batch_bytes = total - mt_bytes - ma_bytes
        bsec = jax.lax.slice_in_dim(buf, 0, batch_bytes, axis=0)
        if k > 0:  # superbatch: [K, L] -> Batch [K, B, ...]
            b = inner(bsec.reshape(k, batch_bytes // k))
        else:  # single batch: [L] -> Batch [B, ...]
            b = inner(bsec)
        mt = as_f32(
            jax.lax.slice_in_dim(buf, batch_bytes, batch_bytes + mt_bytes, axis=0),
            miss_rows, row_dim,
        )
        ma = as_f32(
            jax.lax.slice_in_dim(buf, batch_bytes + mt_bytes, total, axis=0),
            miss_rows, accum_width,
        )
        return b, mt, ma

    return unpack


class _TierStats:
    """Per-run tiering counters, drained into ``kind=tiering`` records at
    every log point (and totals onto kind=summary)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reset()
        # Run totals (never reset).
        self.total_miss_rows = 0
        self.total_writeback_rows = 0
        self.total_restages = 0

    def _reset(self):
        self.steps = 0
        self.hit_slots = 0
        self.total_slots = 0
        self.unique_ids = 0
        self.miss_rows = 0
        self.miss_bytes = 0
        self.wire_bytes = 0
        self.resolve_s = 0.0
        self.writeback_rows = 0
        self.writeback_bytes = 0
        self.writeback_s = 0.0
        self.restages = 0
        self.apply_rows = 0
        self.apply_s = 0.0

    def note_resolve(self, res, wire_bytes, miss_bytes, seconds, steps):
        with self._lock:
            self.steps += steps
            self.hit_slots += res.hit_slots
            self.total_slots += res.total_slots
            self.unique_ids += res.unique_ids
            self.miss_rows += int(res.miss_ids.size)
            self.miss_bytes += miss_bytes
            self.wire_bytes += wire_bytes
            self.resolve_s += seconds
            self.total_miss_rows += int(res.miss_ids.size)

    def note_writeback(self, rows, nbytes, seconds):
        with self._lock:
            self.writeback_rows += rows
            self.writeback_bytes += nbytes
            self.writeback_s += seconds
            self.total_writeback_rows += rows

    def note_restage(self):
        with self._lock:
            self.restages += 1
            self.total_restages += 1

    def note_apply(self, rows, seconds):
        with self._lock:
            self.apply_rows += rows
            self.apply_s += seconds

    def drain(self, pending_rows: int, hot_rows: int) -> dict:
        with self._lock:
            if not self.steps:
                return {}
            out = {
                "hit_rate": round(self.hit_slots / max(1, self.total_slots), 4),
                "miss_rows": self.miss_rows,
                "miss_rows_per_step": round(self.miss_rows / self.steps, 1),
                "miss_bytes_per_step": int(self.miss_bytes / self.steps),
                "wire_bytes_per_step": int(self.wire_bytes / self.steps),
                "dedup_ratio": round(
                    self.unique_ids / max(1, self.total_slots), 4
                ),
                "writeback_rows": self.writeback_rows,
                "writeback_ms": round(1e3 * self.writeback_s, 3),
                "resolve_ms": round(1e3 * self.resolve_s, 3),
                "restages": self.restages,
                "pending_rows": pending_rows,
                "hot_rows": hot_rows,
                "apply_rows": self.apply_rows,
                "apply_ms": round(1e3 * self.apply_s, 3),
            }
            self._reset()
        return out


class TieredParamServer:
    """Owns one run's residency map, cold store, pending overlay, and the
    device staging/fetch programs (see module docstring)."""

    def __init__(
        self,
        store: ColdStore,
        hot_ids: np.ndarray,
        miss_rows: int,
        model,
        *,
        init_accum: float,
    ):
        self.store = store
        self.residency = ResidencyMap(hot_ids)
        self.hot_rows = self.residency.hot_rows
        self.miss_rows = max(1, int(miss_rows))
        self.capacity = self.hot_rows + self.miss_rows
        self.model = model
        self.row_dim = int(model.row_dim)
        self.accum_width = store.accum_width
        self.init_accum = float(init_accum)
        self.stats = _TierStats()
        # Pending writeback overlay: logical id -> (table row, accum row)
        # host arrays; versioned so producer-side resolution can be
        # checked for staleness at dispatch.  _recent keeps the last few
        # writeback id-sets for that check (older payloads restage
        # conservatively — the queue depth bounds how old one can be).
        self._pending: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        self._version = 0
        self._recent: deque = deque(maxlen=64)
        self._lock = threading.Lock()
        self._last_staged: np.ndarray | None = None
        self._applies = 0
        self._jits_built = False

    # -- device programs ---------------------------------------------------

    def _build_jits(self):
        if self._jits_built:
            return
        import jax
        from functools import partial

        h, m = self.hot_rows, self.miss_rows

        @partial(jax.jit, donate_argnums=(0,))
        def stage(state, mt, ma):
            table = jax.lax.dynamic_update_slice(state.table, mt, (h, 0))
            accum = jax.lax.dynamic_update_slice(
                state.table_opt.accum, ma, (h, 0)
            )
            return state._replace(
                table=table, table_opt=state.table_opt._replace(accum=accum)
            )

        @jax.jit
        def fetch(state):
            return state.table[h : h + m], state.table_opt.accum[h : h + m]

        @jax.jit
        def hot_slice(state):
            return state.table[:h], state.table_opt.accum[:h]

        model = self.model

        @jax.jit
        def predict(state, batch, mt):
            import jax.numpy as jnp

            ids = batch.ids
            hot_g = state.table[jnp.minimum(ids, max(0, h - 1))]
            miss_g = mt[jnp.clip(ids - h, 0, m - 1)]
            rows = jnp.where((ids < h)[..., None], hot_g, miss_g)
            return jax.nn.sigmoid(model.score(rows, state.dense, batch))

        self._stage, self._fetch = stage, fetch
        self._hot_slice, self._predict_jit = hot_slice, predict
        self._jits_built = True

    # -- pending overlay ---------------------------------------------------

    def read_latest(self, ids: np.ndarray) -> tuple[np.ndarray, np.ndarray, int]:
        """(table rows, accum rows, version) for logical ``ids`` — the
        pending overlay over the cold store.  Thread-safe (called from
        the prefetch thread on the fast path, the loop thread on
        restage)."""
        ids = np.asarray(ids, np.int64)
        with self._lock:
            version = self._version
            hits = [self._pending.get(int(i)) for i in ids]
        cold = np.array([r is None for r in hits], bool)
        t = np.empty((ids.size, self.row_dim), np.float32)
        a = np.empty((ids.size, self.accum_width), np.float32)
        if cold.any():
            # Only the rows the overlay does NOT cover touch the store —
            # a high-pending window would otherwise pay a discarded
            # memmap/lazy-init read per overlaid row.
            t[cold], a[cold] = self.store.read_rows(ids[cold])
        for j, row in enumerate(hits):
            if row is not None:
                t[j], a[j] = row
        return t, a, version

    @property
    def pending_rows(self) -> int:
        with self._lock:
            return len(self._pending)

    def flush_writeback(self, state) -> None:
        """Fetch the previous dispatch's staged rows D2H into the pending
        overlay.  Called before every staging (the slots are about to be
        reused) and at every checkpoint boundary (pending must name the
        latest value of every non-resident touched row)."""
        ids = self._last_staged
        if ids is None or ids.size == 0:
            self._last_staged = None
            return
        self._build_jits()
        t0 = time.perf_counter()
        mt, ma = self._fetch(state)
        n = int(ids.size)
        mt = np.asarray(mt)[:n]
        ma = np.asarray(ma)[:n]
        with self._lock:
            self._version += 1
            for j, lid in enumerate(ids.tolist()):
                self._pending[lid] = (mt[j], ma[j])
            self._recent.append((self._version, ids))
        self._last_staged = None
        self.stats.note_writeback(
            n, n * 4 * (self.row_dim + self.accum_width),
            time.perf_counter() - t0,
        )

    def _stale(self, tb: TieredBatch) -> bool:
        if tb.miss_ids.size == 0:
            return False
        with self._lock:
            if tb.version == self._version:
                return False
            oldest = self._recent[0][0] if self._recent else self._version
            if tb.version < oldest - 1:
                return True  # too old to check precisely — be conservative
            newer = [ids for v, ids in self._recent if v > tb.version]
        for ids in newer:
            # Both sorted & unique — intersect cheaply.
            if np.intersect1d(tb.miss_ids, ids, assume_unique=True).size:
                return True
        return False

    # -- step wrapping -----------------------------------------------------

    def wrap_step(self, inner_step):
        """The residency-aware step: flush previous writeback, stage this
        payload's miss rows (re-read fresh on a coherency miss), run the
        UNCHANGED inner jitted step on the remapped batch."""
        import jax

        self._build_jits()

        def step(state, tb: TieredBatch):
            self.flush_writeback(state)
            mt, ma = tb.miss_t, tb.miss_a
            if self._stale(tb):
                # A writeback since resolution changed one of this
                # payload's rows: re-read the latest values (pending
                # overlay) and restage — correctness over the fast path.
                self.stats.note_restage()
                t, a, _ = self.read_latest(tb.miss_ids)
                mt = jax.device_put(_pad_rows(t, self.miss_rows))
                ma = jax.device_put(_pad_rows(a, self.miss_rows, self.init_accum))
            state = self._stage(state, mt, ma)
            state, loss = inner_step(state, tb.batch)
            self._last_staged = tb.miss_ids
            return state, loss

        if hasattr(inner_step, "lower"):
            # analysis: ok recompile-hazard delegated CostLedger .lower hook, not a second compile
            step.lower = lambda st, tb: inner_step.lower(st, tb.batch)
        return step

    def predict(self, state, parsed, w):
        """Residency-aware scoring for validation: resolve (read-only),
        gather hot rows from the live state and miss rows from a staged
        side buffer — no state mutation, no donation.  Call
        ``flush_writeback(state)`` once before an evaluation pass."""
        import jax

        from fast_tffm_tpu.models.base import Batch

        self._build_jits()
        res = self.residency.resolve([parsed.ids], self.miss_rows)
        t, _a, _v = self.read_latest(res.miss_ids)
        mt = jax.device_put(_pad_rows(t, self.miss_rows))
        b = Batch.from_parsed(
            _remap(parsed, res.remapped[0]), w,
            with_fields=self.model.uses_fields,
        )
        return self._predict_jit(state, b, mt)

    # -- checkpoint integration (called by AsyncCheckpointer) --------------

    def hot_logical_ids(self, slots: np.ndarray) -> np.ndarray:
        """Device slots (< hot_rows) -> logical ids."""
        return self.residency.hot_ids[np.asarray(slots, np.int64)]

    def pending_snapshot(self):
        """(ids [n], table rows [n, D], accum rows [n, A]) of the pending
        overlay, sorted by id — the cold half of every boundary save."""
        with self._lock:
            items = sorted(self._pending.items())
        if not items:
            return (
                np.zeros((0,), np.int64),
                np.zeros((0, self.row_dim), np.float32),
                np.zeros((0, self.accum_width), np.float32),
            )
        ids = np.array([i for i, _ in items], np.int64)
        t = np.stack([r[0] for _, r in items])
        a = np.stack([r[1] for _, r in items])
        return ids, t, a

    def apply_pending(self, save_id: str) -> None:
        """Post-publish apply: move the pending overlay into the cold
        store (redo the chain can replay) and stamp the boundary.  The
        chaos hook fires BETWEEN chunks — a kill here must leave the
        chain loadable with no lost or stale rows (test-pinned)."""
        from fast_tffm_tpu.resilience import maybe_writeback_fault

        t0 = time.perf_counter()
        ids, t, a = self.pending_snapshot()
        self._applies += 1
        n = int(ids.size)
        if n:
            chunk = max(1, (16 << 20) // max(1, self.row_dim * 4))
            first = True
            for lo in range(0, n, chunk):
                hi = min(n, lo + chunk)
                self.store.write_rows(ids[lo:hi], t[lo:hi], a[lo:hi])
                if first:
                    # The kill-during-eviction-writeback window: some
                    # store pages dirty, the boundary not yet stamped.
                    maybe_writeback_fault(self._applies)
                    first = False
            if first:
                maybe_writeback_fault(self._applies)
        else:
            maybe_writeback_fault(self._applies)
        self.store.flush()
        self.store.set_applied(save_id)
        with self._lock:
            for lid in ids.tolist():
                self._pending.pop(lid, None)
        self.stats.note_apply(n, time.perf_counter() - t0)

    def hot_rows_host(self, state) -> tuple[np.ndarray, np.ndarray]:
        """(hot table [H, D], hot accum [H, A]) fetched D2H — the hot half
        of a full boundary save."""
        self._build_jits()
        t, a = self._hot_slice(state)
        return np.asarray(t), np.asarray(a)

    def summary(self) -> dict:
        s = self.stats
        return {
            k: v
            for k, v in {
                "tier_miss_rows": s.total_miss_rows,
                "tier_writeback_rows": s.total_writeback_rows,
                "tier_restages": s.total_restages,
                "tier_pending_rows": self.pending_rows,
            }.items()
            if v
        }


def _pad_rows(rows: np.ndarray, cap: int, fill: float = 0.0) -> np.ndarray:
    out = np.full((cap, rows.shape[1]), np.float32(fill), np.float32)
    out[: rows.shape[0]] = rows
    return out


class TieredConverter:
    """``to_batch``-compatible resolver+shipper (prefetch thread): remap
    ids, read miss values through the pending overlay, pack the remapped
    batch on the packed wire WITH the miss rows in the same buffer, ship
    with ONE device_put, unpack jitted.  Mirrors WireConverter's
    accounting contract (last_nbytes / calls) so kind=input stays
    truthful."""

    def __init__(self, server: TieredParamServer, spec):
        import jax

        self.server = server
        self.spec = spec
        self._put = jax.device_put
        self._unpack = _make_tiered_unpacker(
            spec, server.miss_rows, server.row_dim, server.accum_width
        )
        self.uses_fields = server.model.uses_fields
        self.wire_capable = False  # _stream must NOT swap in WireConverter
        self.last_nbytes = 0
        self.calls = 0

    def __call__(self, parsed, w) -> TieredBatch:
        from fast_tffm_tpu.data.wire import pack_batch, pack_superbatch

        t0 = time.perf_counter()
        srv = self.server
        seq = parsed if isinstance(parsed, list) else [parsed]
        res = srv.residency.resolve([p.ids for p in seq], srv.miss_rows)
        t, a, version = srv.read_latest(res.miss_ids)
        mt = _pad_rows(t, srv.miss_rows)
        ma = _pad_rows(a, srv.miss_rows, srv.init_accum)
        remapped = [_remap(p, r) for p, r in zip(seq, res.remapped)]
        if isinstance(parsed, list):
            wire = pack_superbatch(
                self.spec, remapped, w, verify_ids=False
            ).reshape(-1)
            k = len(seq)
        else:
            ww = (
                np.ones((parsed.batch_size,), np.float32) if w is None else w
            )
            wire = pack_batch(self.spec, remapped[0], ww, verify_ids=False)
            k = 0
        buf = np.concatenate(
            [wire, mt.view(np.uint8).reshape(-1), ma.view(np.uint8).reshape(-1)]
        )
        b, mt_d, ma_d = self._unpack(self._put(buf), k)
        miss_bytes = int(res.miss_ids.size) * 4 * (srv.row_dim + srv.accum_width)
        self.last_nbytes = int(buf.nbytes)
        self.calls += 1
        srv.stats.note_resolve(
            res, int(buf.nbytes), miss_bytes, time.perf_counter() - t0, len(seq)
        )
        return TieredBatch(
            batch=b, miss_t=mt_d, miss_a=ma_d,
            miss_ids=res.miss_ids, version=version,
        )
