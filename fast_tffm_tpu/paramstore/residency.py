"""Hot-tier residency: which logical rows live on device, and the
host-side id resolution every batch goes through.

The hot set is chosen ONCE per run (deterministically — ``--resume``
restores the exact set from the checkpoint, so a resumed run's
residency, and therefore its remapped-id programs and its loss
sequence, are identical to the uninterrupted run's):

  * ``sample`` (default) — exact frequency count over the first N
    batches of the train stream, top-K by (count desc, id asc).  This is
    the PR-9 heavy-hitter telemetry's exact twin: the committed coverage
    curve (top-4096 rows absorb 59% of gathers at the Zipf(1.1) scale
    shape) is precisely what this policy caches.
  * ``first`` — ids [0, K): the degenerate deterministic policy (useful
    when the id space is already frequency-ranked, e.g. hashed ranks).
  * ``file:PATH`` — an id array (.npy, or one id per line) exported from
    telemetry; the first K ids win.

Resolution (``ResidencyMap.resolve``) is pure numpy over sorted hot ids:
hot id -> its rank (= its device slot), miss id -> a per-superbatch
staging slot.  Slots are ranks in SORTED order, so the mapping is a pure
function of the hot set — no insertion-order state to drift."""

from __future__ import annotations

import os
from typing import NamedTuple

import numpy as np

__all__ = ["ResidencyMap", "choose_hot_ids", "Resolved"]


class Resolved(NamedTuple):
    """One (super)batch's residency resolution (host side)."""

    remapped: list  # per-micro-batch [B, N] int32 LOCAL ids (slots)
    miss_ids: np.ndarray  # unique missed LOGICAL ids (sorted), [m]
    hit_slots: int  # gather slots that hit the hot tier
    total_slots: int  # all gather slots (B*N per micro batch)
    unique_ids: int  # unique logical ids across the superbatch


class ResidencyMap:
    def __init__(self, hot_ids: np.ndarray):
        hot = np.unique(np.asarray(hot_ids, np.int64))
        if hot.size != np.asarray(hot_ids).size:
            raise ValueError("hot_ids must be unique")
        self.hot_ids = hot  # sorted; slot of hot_ids[i] is i
        self.hot_rows = int(hot.size)

    def lookup(self, ids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(hit mask, hot slot per id) for flat logical ``ids``."""
        pos = np.searchsorted(self.hot_ids, ids)
        pos_c = np.minimum(pos, max(0, self.hot_rows - 1))
        hit = (
            (pos < self.hot_rows) & (self.hot_ids[pos_c] == ids)
            if self.hot_rows
            else np.zeros(ids.shape, bool)
        )
        return hit, pos_c.astype(np.int64)

    def resolve(self, ids_seq: list[np.ndarray], miss_capacity: int) -> Resolved:
        """Remap a superbatch's logical ids to device slots.

        Hot ids map to their rank slot; every unique missed id gets a
        staging slot ``hot_rows + rank`` (rank within the sorted unique
        miss set of THIS superbatch).  Dedup-before-gather falls out for
        free: a miss row is staged (and its bytes cross the wire) once
        per superbatch no matter how many slots repeat it."""
        flats = [np.asarray(a).reshape(-1) for a in ids_seq]
        all_flat = np.concatenate(flats) if len(flats) > 1 else flats[0]
        hit_all, _ = self.lookup(all_flat)
        miss_ids = np.unique(all_flat[~hit_all])
        if miss_ids.size > miss_capacity:
            raise ValueError(
                f"paramstore: a superbatch touches {miss_ids.size} unique "
                f"non-resident rows, over the staging capacity "
                f"{miss_capacity} — raise [ParamStore] miss_rows (or "
                "hot_rows), or lower batch_size/steps_per_call"
            )
        remapped = []
        for a, flat in zip(ids_seq, flats):
            hit, slot = self.lookup(flat)
            miss_rank = np.searchsorted(miss_ids, flat)
            local = np.where(
                hit, slot, self.hot_rows + np.minimum(miss_rank, max(0, miss_ids.size - 1))
            )
            remapped.append(local.astype(np.int32).reshape(np.asarray(a).shape))
        uniq = int(np.unique(all_flat).size)
        return Resolved(
            remapped=remapped,
            miss_ids=miss_ids,
            hit_slots=int(hit_all.sum()),
            total_slots=int(all_flat.size),
            unique_ids=uniq,
        )


def choose_hot_ids(
    policy: str,
    hot_rows: int,
    vocab: int,
    *,
    sample_batches=None,
) -> np.ndarray:
    """The run-start residency decision (see module docstring).
    ``sample_batches`` is an iterator of host id arrays for the
    ``sample`` policy (the driver hands it the first N parsed batches of
    the train stream)."""
    k = min(int(hot_rows), int(vocab))
    if policy == "first":
        return np.arange(k, dtype=np.int64)
    if policy.startswith("file:"):
        path = policy[len("file:"):]
        if not os.path.exists(path):
            raise ValueError(f"[ParamStore] residency file not found: {path!r}")
        if path.endswith(".npy"):
            ids = np.load(path).astype(np.int64).reshape(-1)
        else:
            with open(path) as f:
                ids = np.array(
                    [int(x) for x in f.read().split() if x.strip()], np.int64
                )
        ids = ids[(ids >= 0) & (ids < vocab)]
        uniq = np.unique(ids)
        if uniq.size < k:
            raise ValueError(
                f"[ParamStore] residency file {path!r} holds {uniq.size} "
                f"distinct in-range ids, fewer than hot_rows = {k}"
            )
        # Preserve the file's ranking: first K distinct ids in file order.
        seen: set = set()
        out = []
        for i in ids.tolist():
            if i not in seen:
                seen.add(i)
                out.append(i)
                if len(out) == k:
                    break
        return np.array(out, np.int64)
    if policy != "sample":
        raise ValueError(
            f"unknown [ParamStore] residency policy {policy!r} "
            "(sample | first | file:PATH)"
        )
    counts: dict = {}
    ids_all = []
    n = 0
    for arr in sample_batches or ():
        ids_all.append(np.asarray(arr, np.int64).reshape(-1))
        n += 1
    if not ids_all:
        # No sample available (empty stream): fall back to the first-K
        # deterministic set rather than failing a run that would work.
        return np.arange(k, dtype=np.int64)
    flat = np.concatenate(ids_all)
    uniq, cnt = np.unique(flat, return_counts=True)
    # Top-K by (count desc, id asc) — a full deterministic order, so ties
    # cannot reshuffle residency between runs.
    order = np.lexsort((uniq, -cnt))
    top = uniq[order[:k]]
    if top.size < k:
        # Fewer distinct ids than hot_rows in the sample: fill with the
        # smallest unseen ids (deterministic).
        fill = np.setdiff1d(np.arange(min(vocab, k * 2), dtype=np.int64), top)
        top = np.concatenate([top, fill[: k - top.size]])
    return np.sort(top)
