"""Tiered host/device parameter store (ISSUE 12 tentpole; ROADMAP 3).

Subsystem layout:

  * ``store.py``     — the cold tier: memmap-backed full logical table
                       (sparse files + lazy row init, so 2^30+ rows cost
                       disk/RAM only for rows actually touched);
  * ``residency.py`` — hot-set selection (PR-9 heavy-hitter twin) and
                       the per-batch id resolution / remap;
  * ``tiered.py``    — the runtime: TieredParamServer (staging,
                       writeback, pending overlay, coherency),
                       TieredConverter (prefetch-thread resolve + packed
                       wire shipping);
  * ``ckpt.py``      — both tiers through the one atomic-publish chain
                       (crash-consistency invariant 7).

``open_tiered_run`` is the driver entry: it builds (server, compact
TrainState, resume cursor) for training.py's tiered branch."""

from __future__ import annotations

import os

import numpy as np

from fast_tffm_tpu.paramstore.ckpt import (
    is_tiered_checkpoint,
    restore_tiered,
    write_tiered_full,
)
from fast_tffm_tpu.paramstore.residency import ResidencyMap, choose_hot_ids
from fast_tffm_tpu.paramstore.store import ColdStore, hashed_uniform_rows
from fast_tffm_tpu.paramstore.tiered import (
    TieredBatch,
    TieredConverter,
    TieredParamServer,
)

__all__ = [
    "ColdStore",
    "ResidencyMap",
    "TieredBatch",
    "TieredConverter",
    "TieredParamServer",
    "choose_hot_ids",
    "hashed_uniform_rows",
    "is_tiered_checkpoint",
    "open_tiered_run",
    "restore_tiered",
    "write_tiered_full",
]

# auto-materialize threshold: vocabs at or under this row count write the
# exact jax init draw into the store (bit-identity with the resident
# path); larger vocabs stay lazy (hashed per-row init — the resident
# path cannot exist there anyway).
MATERIALIZE_MAX_ROWS = 1 << 21


def _sample_ids(cfg, max_nnz: int, n_batches: int):
    """First N parsed train batches' id arrays — the exact-frequency
    sample the default residency policy counts (deterministic for a
    fixed file set)."""
    from fast_tffm_tpu.data.native import best_parser
    from fast_tffm_tpu.data.pipeline import batch_stream

    raw = batch_stream(
        tuple(cfg.train_files),
        batch_size=cfg.batch_size,
        vocabulary_size=cfg.vocabulary_size,
        hash_feature_id=cfg.hash_feature_id,
        max_nnz=max_nnz,
        epochs=1,
        parser=best_parser(cfg.thread_num),
    )
    for i, (p, _w) in enumerate(raw):
        if i >= n_batches:
            break
        yield p.ids


def open_tiered_run(cfg, model, max_nnz: int, *, resume: bool, log=print):
    """(server, compact TrainState, start_cursor) for a tiered run.

    Fresh runs (re)create the store — materialized with the exact
    ``init_state`` draw at small vocab, lazy beyond — and choose the hot
    set per ``[ParamStore] residency``.  Resume restores BOTH tiers from
    the chain (paramstore.ckpt.restore_tiered) and takes residency from
    the checkpoint, so a resumed run's remapping (and loss sequence) is
    identical to the uninterrupted run's."""
    import jax
    import jax.numpy as jnp

    from fast_tffm_tpu.checkpoint import read_input_cursor
    from fast_tffm_tpu.optim import AdagradState, init_adagrad
    from fast_tffm_tpu.trainer import TrainState, init_state

    vocab = int(cfg.vocabulary_size)
    accum_width = model.row_dim if cfg.adagrad_accumulator == "element" else 1
    store_dir = cfg.paramstore_dir or cfg.model_file + ".store"
    miss_rows = cfg.paramstore_miss_rows or (
        cfg.batch_size * max_nnz * cfg.steps_per_call
    )
    init_acc = float(cfg.init_accumulator_value)

    if resume and not os.path.isfile(cfg.model_file):
        # Mirror dist_train's stance: a supervised relaunch can race a
        # crash before the first publish — same absence, same fresh start.
        log(
            f"warning: --resume but no checkpoint at {cfg.model_file} — "
            "starting fresh (crash before the first publish?)"
        )
        resume = False
    if resume:
        store = ColdStore.open(store_dir)
        # Dense template: leaf count + treedef for reassembly.
        _k1, k2 = jax.random.split(jax.random.key(0))
        dense_tpl = model.init_dense(k2)
        leaves_tpl, treedef = jax.tree.flatten(dense_tpl)
        rec = restore_tiered(cfg.model_file, store, len(leaves_tpl))
        hot_ids = rec["hot_ids"]
        if int(hot_ids.size) != int(cfg.paramstore_hot_rows):
            log(
                f"note: resuming with the checkpoint's residency "
                f"({hot_ids.size} hot rows; [ParamStore] hot_rows = "
                f"{cfg.paramstore_hot_rows} ignored for this run)"
            )
        server = TieredParamServer(
            store, hot_ids, miss_rows, model, init_accum=init_acc
        )
        dense = jax.tree.unflatten(treedef, [jnp.asarray(x) for x in rec["dense"]])
        dense_acc = jax.tree.unflatten(
            treedef, [jnp.asarray(x) for x in rec["dense_accum"]]
        )
        state = _compact_state(
            server, rec["hot_t"], rec["hot_a"], dense,
            AdagradState(dense_acc), int(rec["step"]), init_acc,
        )
        log(
            f"resumed tiered run from {cfg.model_file} at step "
            f"{int(rec['step'])} (hot {server.hot_rows} rows, store "
            f"{store.vocab} rows)"
        )
        return server, state, read_input_cursor(cfg.model_file)

    materialize = cfg.paramstore_materialize == "always" or (
        cfg.paramstore_materialize == "auto" and vocab <= MATERIALIZE_MAX_ROWS
    )
    if materialize:
        logical = init_state(
            model, jax.random.key(0), init_acc, cfg.adagrad_accumulator
        )
        store = ColdStore.create(
            store_dir,
            vocab=vocab, row_dim=model.row_dim, accum_width=accum_width,
            seed=0, init_range=float(getattr(model, "init_value_range", 0.01)),
            init_accum=init_acc,
            init_table=np.asarray(logical.table),
            init_accum_arr=np.asarray(logical.table_opt.accum),
        )
        dense, dense_opt = logical.dense, logical.dense_opt
        step0 = int(logical.step)
        del logical
    else:
        store = ColdStore.create(
            store_dir,
            vocab=vocab, row_dim=model.row_dim, accum_width=accum_width,
            seed=0, init_range=float(getattr(model, "init_value_range", 0.01)),
            init_accum=init_acc,
        )
        # Dense init must still match init_state's key split exactly.
        _k1, k2 = jax.random.split(jax.random.key(0))
        dense = model.init_dense(k2)
        dense_opt = init_adagrad(dense, init_acc)
        step0 = 0
        log(
            f"paramstore: lazy cold store for {vocab} rows "
            f"(beyond the {MATERIALIZE_MAX_ROWS}-row materialize bound; "
            "rows init on first touch)"
        )
    policy = cfg.paramstore_residency
    hot_ids = choose_hot_ids(
        policy, cfg.paramstore_hot_rows, vocab,
        sample_batches=(
            _sample_ids(cfg, max_nnz, cfg.paramstore_sample_batches)
            if policy == "sample"
            else None
        ),
    )
    server = TieredParamServer(
        store, hot_ids, miss_rows, model, init_accum=init_acc
    )
    hot_t, hot_a = store.read_rows(server.residency.hot_ids)
    state = _compact_state(
        server, hot_t, hot_a, dense, dense_opt, step0, init_acc
    )
    log(
        f"paramstore: hot tier {server.hot_rows} rows + staging "
        f"{server.miss_rows} rows on device "
        f"({server.capacity * (model.row_dim + accum_width) * 4 / 2**20:.1f} "
        f"MiB), cold store {vocab} rows at {store_dir}"
    )
    return server, state, None


def _compact_state(server, hot_t, hot_a, dense, dense_opt, step, init_acc):
    import jax.numpy as jnp

    from fast_tffm_tpu.optim import AdagradState
    from fast_tffm_tpu.trainer import TrainState

    c, d, a = server.capacity, server.row_dim, server.accum_width
    table = np.zeros((c, d), np.float32)
    table[: server.hot_rows] = hot_t
    accum = np.full((c, a), np.float32(init_acc), np.float32)
    accum[: server.hot_rows] = hot_a
    return TrainState(
        table=jnp.asarray(table),
        table_opt=AdagradState(jnp.asarray(accum)),
        dense=dense,
        dense_opt=dense_opt,
        step=jnp.asarray(np.int32(step)),
    )
