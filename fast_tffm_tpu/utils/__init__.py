from fast_tffm_tpu.utils.prefetch import prefetch  # noqa: F401
