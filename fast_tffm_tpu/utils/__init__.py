from fast_tffm_tpu.utils.prefetch import parallel_map, prefetch  # noqa: F401
