"""Profiling, step annotation, and structured metrics.

The reference had no tracing beyond periodic loss prints (SURVEY.md §5:
TF-1.x RunMetadata existed but was never wired).  The TPU build makes the
profiler a config key away:

  * ``maybe_trace(trace_dir)`` — wraps a training run in a
    ``jax.profiler`` trace when ``trace_dir`` is configured (viewable in
    TensorBoard/XProf; captures XLA ops, fusion, HBM traffic);
  * ``step_trace(name, step)`` — per-step TraceAnnotation so device steps
    line up with host timeline rows;
  * ``MetricsLogger`` — optional JSONL sink for step metrics (loss,
    examples/sec, AUC) next to the stdout log, one object per line.
"""

from __future__ import annotations

import contextlib
import json
import os
import time

import jax

__all__ = ["maybe_trace", "WindowTracer", "step_trace", "MetricsLogger"]


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None):
    """jax.profiler.trace(trace_dir) when set; no-op otherwise.

    Wraps whatever the caller scopes it to — prefer WindowTracer for long
    training runs (whole-run traces are multi-GB and skew throughput).
    """
    if not trace_dir:
        yield
        return
    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield


class WindowTracer:
    """Trace a bounded step window [skip, skip + count) of a long run.

    Whole-run profiler traces are unusable (GBs, XProf won't load them)
    and their host-side overhead skews the throughput being measured, so
    tracing starts after ``skip`` steps (letting compilation and warmup
    fall outside the window) and stops after ``count`` traced steps.
    No-op when ``trace_dir`` is empty.
    """

    def __init__(self, trace_dir: str | None, *, skip: int = 5, count: int = 20):
        self._dir = trace_dir or None
        self._skip = skip
        self._count = count
        self._seen = 0
        self._active = False

    def on_step(self) -> None:
        """Call once per train step (before/after — consistency is all)."""
        if self._dir is None:
            return
        if not self._active and self._seen == self._skip:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
        elif self._active and self._seen >= self._skip + self._count:
            jax.profiler.stop_trace()
            self._active = False
            self._dir = None  # one window per run
        self._seen += 1

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._dir = None


def step_trace(name: str, step: int):
    """Annotate one train/eval step on the profiler timeline."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


class MetricsLogger:
    """Append-only JSONL metrics sink (no-op when path is empty)."""

    def __init__(self, path: str | None):
        self._f = None
        if path:
            dirpart = os.path.dirname(path)
            if dirpart:
                os.makedirs(dirpart, exist_ok=True)
            self._f = open(path, "a", buffering=1)

    def log(self, **fields) -> None:
        if self._f is None:
            return
        fields.setdefault("ts", round(time.time(), 3))
        self._f.write(json.dumps(fields) + "\n")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
