"""Profiling, step annotation, and structured metrics.

The reference had no tracing beyond periodic loss prints (SURVEY.md §5:
TF-1.x RunMetadata existed but was never wired).  The TPU build makes the
profiler a config key away:

  * ``maybe_trace(trace_dir)`` — wraps a training run in a
    ``jax.profiler`` trace when ``trace_dir`` is configured (viewable in
    TensorBoard/XProf; captures XLA ops, fusion, HBM traffic);
  * ``step_trace(name, step)`` — per-step TraceAnnotation so device steps
    line up with host timeline rows;
  * ``MetricsLogger`` — optional JSONL sink for step metrics (loss,
    examples/sec, AUC) next to the stdout log, one object per line.

jax imports stay inside the profiler helpers: ``MetricsLogger`` is the
sink under telemetry.RunMonitor, whose module must be importable before
``import jax`` (the hang-exit watchdog contract — see telemetry.py).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time

__all__ = ["maybe_trace", "WindowTracer", "step_trace", "MetricsLogger"]


def _jsonsafe(v):
    """Non-finite floats become their string names ('nan'/'inf'/'-inf'):
    Python's json would emit bare NaN/Infinity tokens, which strict JSON
    readers (jq, JSON.parse) reject — and the records carrying them
    (anomaly losses, single-class validation AUCs) are exactly the ones
    an external dashboard most wants.  float(...) round-trips the
    strings for numeric consumers."""
    if isinstance(v, float) and not math.isfinite(v):
        return str(v)
    if isinstance(v, dict):
        return {k: _jsonsafe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonsafe(x) for x in v]
    return v


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None):
    """jax.profiler.trace(trace_dir) when set; no-op otherwise.

    Wraps whatever the caller scopes it to — prefer WindowTracer for long
    training runs (whole-run traces are multi-GB and skew throughput).
    """
    if not trace_dir:
        yield
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield


class WindowTracer:
    """Trace a bounded step window [skip, skip + count) of a long run.

    Whole-run profiler traces are unusable (GBs, XProf won't load them)
    and their host-side overhead skews the throughput being measured, so
    tracing starts after ``skip`` steps (letting compilation and warmup
    fall outside the window) and stops after ``count`` traced steps.
    No-op when ``trace_dir`` is empty.
    """

    def __init__(self, trace_dir: str | None, *, skip: int = 5, count: int = 20):
        self._dir = trace_dir or None
        self._skip = skip
        self._count = count
        self._seen = 0
        self._active = False

    def on_step(self) -> None:
        """Call once per train step (before/after — consistency is all)."""
        if self._dir is None:
            return
        import jax

        if not self._active and self._seen == self._skip:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
        elif self._active and self._seen >= self._skip + self._count:
            jax.profiler.stop_trace()
            self._active = False
            self._dir = None  # one window per run
        self._seen += 1

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._dir = None


def step_trace(name: str, step: int):
    """Annotate one train/eval step on the profiler timeline."""
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step)


class MetricsLogger:
    """Append-only JSONL metrics sink (no-op when path is empty).

    Thread-safe: the telemetry watchdog and memory sampler write from
    their own threads concurrently with the driver loop's records, and
    two interleaved half-lines would corrupt the JSONL for every reader
    downstream (tools/report.py).
    """

    def __init__(self, path: str | None):
        self._f = None
        self._lock = threading.Lock()
        if path:
            dirpart = os.path.dirname(path)
            if dirpart:
                os.makedirs(dirpart, exist_ok=True)
            self._f = open(path, "a", buffering=1)

    @property
    def active(self) -> bool:
        return self._f is not None

    def log(self, **fields) -> None:
        if self._f is None:  # cheap no-op path; re-checked under the lock
            return
        fields.setdefault("ts", round(time.time(), 3))
        line = json.dumps(_jsonsafe(fields), allow_nan=False) + "\n"
        with self._lock:
            if self._f is None:
                return
            self._f.write(line)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
