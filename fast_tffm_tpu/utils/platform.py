"""Make the JAX_PLATFORMS env var authoritative.

Some TPU deployments register an ambient PJRT plugin at interpreter
startup (via sitecustomize) that wins backend selection even when the
user exported ``JAX_PLATFORMS=cpu`` — the env var survives but the plugin
overrides the platform choice.  Re-applying the env value through
``jax.config`` after import restores the documented env-var contract.

Must run before the backend initializes (before the first
``jax.devices()`` / array creation); afterwards it is a silent no-op.
"""

from __future__ import annotations

import os

import jax

__all__ = ["apply_platform_env"]


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` even under ambient PJRT plugin overrides."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass  # backend already initialized; selection is fixed now
