"""Host-side pipeline concurrency: background prefetch + parallel parsing.

The JAX-era replacement for the reference's TF queue runners
(`renyi533/fast_tffm` :: trainer module: filename/string queues with
cfg-driven thread and queue sizes).  Two pieces:

  * ``prefetch(it, depth)`` — run an iterator in a daemon thread with a
    bounded queue so host parsing overlaps device steps;
  * ``ParallelMapIterator`` — order-preserving parallel map over an
    iterator with a worker pool (used to spread libsvm parsing over
    ``thread_num`` workers; the C++ parser releases the GIL implicitly by
    doing its work in a single ctypes call, so threads scale).
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterable, Iterator
from concurrent.futures import ThreadPoolExecutor

__all__ = ["prefetch", "parallel_map"]

_SENTINEL = object()


def prefetch(it: Iterable, depth: int = 8) -> Iterator:
    """Iterate ``it`` in a background thread, keeping ``depth`` items ready."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    err: list[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _SENTINEL:
            if err:
                raise err[0]
            return
        yield item


def parallel_map(fn, it: Iterable, workers: int, depth: int = 8) -> Iterator:
    """Order-preserving parallel ``map(fn, it)`` with ``workers`` threads."""
    if workers <= 1:
        yield from map(fn, it)
        return
    with ThreadPoolExecutor(max_workers=workers) as pool:
        pending: queue.Queue = queue.Queue()
        it = iter(it)

        def submit_next() -> bool:
            try:
                item = next(it)
            except StopIteration:
                return False
            pending.put(pool.submit(fn, item))
            return True

        live = True
        for _ in range(max(1, depth)):
            live = submit_next()
            if not live:
                break
        while not pending.empty():
            fut = pending.get()
            submit_next()
            yield fut.result()
