"""Host-side pipeline concurrency: background prefetch.

The JAX-era replacement for the reference's TF queue runners
(`renyi533/fast_tffm` :: trainer module: filename/string queues with
cfg-driven thread and queue sizes): ``prefetch(it, depth)`` runs an
iterator in a daemon thread with a bounded queue so host parsing overlaps
device steps.  Parse-thread parallelism (the cfg ``thread_num``) lives
inside the C++ kernel's std::thread pool (csrc/libsvm_parser.cpp), not in
Python — a Python-side thread map cannot beat the GIL for the pure-Python
fallback parser and is redundant for the GIL-releasing native one.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterable, Iterator

__all__ = ["prefetch", "chunk", "grouped_pairs", "InputStream", "PrefetchError"]

_SENTINEL = object()


class PrefetchError(RuntimeError):
    """The prefetch producer thread failed (or died without signaling).

    The loud, NAMED form of an input-pipeline death: before this, a
    producer exception only surfaced after the queue's buffered items
    drained, and a producer that died without its sentinel (interpreter
    teardown, a kill landing mid-put) left the consumer blocked on
    ``q.get()`` forever — a wedge the stall watchdog could only report,
    not break.  The original exception rides as ``__cause__``."""


class InputStream:
    """An input iterator plus its InputStats (data/wire.py): the driver
    iterates it like the bare generator it wraps, drains ``.stats`` into
    kind=input metrics records at log points, and hands
    ``.queue_depth`` / ``.producer_alive`` to the telemetry stall
    watchdog (live occupancy + thread liveness — readable mid-stall,
    when the consumer loop itself is frozen)."""

    def __init__(self, it: Iterable, stats):
        self._it = it
        self.stats = stats

    def __iter__(self) -> Iterator:
        return iter(self._it)

    def queue_depth(self) -> int | None:
        return self.stats.queue_depth() if self.stats is not None else None

    def producer_alive(self) -> bool | None:
        """Liveness of the prefetch producer thread (None before the
        first iteration binds one) — the watchdog's 'is input-starved
        because the producer is DEAD' signal."""
        fn = getattr(self.stats, "producer_alive", None)
        return fn() if fn is not None else None

    def stream_idle(self) -> bool | None:
        """Whether a tail-following input stream is idle-polling a quiet
        append-only file (None for non-follow streams) — the watchdog's
        'input-starved (stream-idle)' signal (data/stream.py)."""
        fn = getattr(self.stats, "stream_idle", None)
        return fn() if fn is not None else None


def chunk(it: Iterable, k: int) -> Iterator[list]:
    """Group consecutive items into lists of length ``k`` (the final list
    may be shorter — the epoch-tail remainder).

    The step-fusion staging primitive (``steps_per_call``): composed UNDER
    ``prefetch`` by the input streams, the grouping — and any superbatch
    stacking mapped over it — runs inside the prefetch thread, overlapping
    the K-batch assembly with the consumer's fused-step dispatch.
    """
    if k < 1:
        raise ValueError(f"chunk size must be >= 1, got {k}")
    buf: list = []
    for item in it:
        buf.append(item)
        if len(buf) == k:
            yield buf
            buf = []
    if buf:
        yield buf


def grouped_pairs(pairs: Iterable, k: int) -> Iterator[tuple[list, list]]:
    """Group a ``(parsed, weights)`` stream into ``([parsed]*k, [w]*k)``
    lists — THE steps_per_call grouping rule, shared by every input
    stream builder (batch _stream and the online follow stream) so the
    superbatch pairing cannot diverge between them."""
    for items in chunk(pairs, k):
        yield [p for p, _ in items], [w for _, w in items]


def prefetch(it: Iterable, depth: int = 8, stats=None) -> Iterator:
    """Iterate ``it`` in a background thread, keeping ``depth`` items ready.

    ``stats`` (an object with ``on_queue_depth(int)``) samples the queue
    occupancy at every consumer pop — the overlap-efficiency signal the
    kind=input metrics records carry (depth ~0 = producer-bound, depth at
    the cap = consumer-bound).  The queue — and the producer THREAD —
    are also bound onto ``stats`` (``bind_queue`` / ``bind_producer``)
    so the telemetry watchdog can read the LIVE depth and the thread's
    liveness from its own thread while the consumer is wedged.

    Failure contract: a producer exception surfaces in the consumer as a
    ``PrefetchError`` (the original as ``__cause__``) naming the thread —
    a loud, attributable input-pipeline death instead of a wedge.  The
    consumer polls with a timeout, so even a producer that dies WITHOUT
    reaching its sentinel (interpreter teardown, a signal mid-put) is
    detected within ~1s rather than blocking ``q.get()`` forever."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    if stats is not None and hasattr(stats, "bind_queue"):
        stats.bind_queue(q)
    err: list[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, name="input-prefetch", daemon=True)
    if stats is not None and hasattr(stats, "bind_producer"):
        stats.bind_producer(t)
    t.start()

    def fail(reason: str):
        e = PrefetchError(
            f"input pipeline failed: prefetch producer thread "
            f"{t.name!r} {reason}"
        )
        e.__cause__ = err[0] if err else None
        return e

    need_sample = True
    while True:
        if stats is not None and need_sample:
            # ONE depth sample per consumer pop (the pre-pop occupancy
            # the overlap metric is defined over) — not one per 1s
            # timeout retry, which would flood the average with zeros
            # exactly when the producer is slow and skew the
            # producer-bound signal.
            stats.on_queue_depth(q.qsize())
            need_sample = False
        try:
            item = q.get(timeout=1.0)
        except queue.Empty:
            if not t.is_alive() and q.empty():
                # Died without its sentinel: the finally was never
                # reached (teardown/kill).  Without this check the
                # consumer blocks forever — the wedge this fixes.
                raise fail(
                    f"raised {err[0]!r}" if err else "died without signaling"
                )
            continue
        need_sample = True
        if item is _SENTINEL:
            if err:
                raise fail(f"raised {err[0]!r}")
            return
        yield item


