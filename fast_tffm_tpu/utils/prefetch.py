"""Host-side pipeline concurrency: background prefetch.

The JAX-era replacement for the reference's TF queue runners
(`renyi533/fast_tffm` :: trainer module: filename/string queues with
cfg-driven thread and queue sizes): ``prefetch(it, depth)`` runs an
iterator in a daemon thread with a bounded queue so host parsing overlaps
device steps.  Parse-thread parallelism (the cfg ``thread_num``) lives
inside the C++ kernel's std::thread pool (csrc/libsvm_parser.cpp), not in
Python — a Python-side thread map cannot beat the GIL for the pure-Python
fallback parser and is redundant for the GIL-releasing native one.
"""

from __future__ import annotations

import queue
import threading
from collections.abc import Iterable, Iterator

__all__ = ["prefetch", "chunk", "InputStream"]

_SENTINEL = object()


class InputStream:
    """An input iterator plus its InputStats (data/wire.py): the driver
    iterates it like the bare generator it wraps, drains ``.stats`` into
    kind=input metrics records at log points, and hands
    ``.queue_depth`` to the telemetry stall watchdog (live occupancy —
    readable mid-stall, when the consumer loop itself is frozen)."""

    def __init__(self, it: Iterable, stats):
        self._it = it
        self.stats = stats

    def __iter__(self) -> Iterator:
        return iter(self._it)

    def queue_depth(self) -> int | None:
        return self.stats.queue_depth() if self.stats is not None else None


def chunk(it: Iterable, k: int) -> Iterator[list]:
    """Group consecutive items into lists of length ``k`` (the final list
    may be shorter — the epoch-tail remainder).

    The step-fusion staging primitive (``steps_per_call``): composed UNDER
    ``prefetch`` by the input streams, the grouping — and any superbatch
    stacking mapped over it — runs inside the prefetch thread, overlapping
    the K-batch assembly with the consumer's fused-step dispatch.
    """
    if k < 1:
        raise ValueError(f"chunk size must be >= 1, got {k}")
    buf: list = []
    for item in it:
        buf.append(item)
        if len(buf) == k:
            yield buf
            buf = []
    if buf:
        yield buf


def prefetch(it: Iterable, depth: int = 8, stats=None) -> Iterator:
    """Iterate ``it`` in a background thread, keeping ``depth`` items ready.

    ``stats`` (an object with ``on_queue_depth(int)``) samples the queue
    occupancy at every consumer pop — the overlap-efficiency signal the
    kind=input metrics records carry (depth ~0 = producer-bound, depth at
    the cap = consumer-bound).  The queue itself is also bound onto
    ``stats`` (``bind_queue``) so the telemetry watchdog can read the
    LIVE depth from its own thread while the consumer is wedged."""
    q: queue.Queue = queue.Queue(maxsize=max(1, depth))
    if stats is not None and hasattr(stats, "bind_queue"):
        stats.bind_queue(q)
    err: list[BaseException] = []

    def worker():
        try:
            for item in it:
                q.put(item)
        except BaseException as e:  # propagate into the consumer
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    while True:
        if stats is not None:
            stats.on_queue_depth(q.qsize())
        item = q.get()
        if item is _SENTINEL:
            if err:
                raise err[0]
            return
        yield item


