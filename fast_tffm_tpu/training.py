"""Training drivers: local and mesh-distributed.

Capability parity with the reference's train/dist_train entrypoints
(`renyi533/fast_tffm` :: py/ trainer: session loop over sess.run(train_op),
periodic loss logging, Saver checkpoints; dist variant on a ps/worker
cluster with async Hogwild updates).  Differences, all TPU-first:

  * one jitted step (gather → fused scorer → loss → sparse Adagrad) instead
    of a TF graph; host parsing overlaps device compute via prefetch;
  * dist_train is the SAME program on a ('data','row') mesh — synchronous
    deterministic updates over ICI replace Hogwild (SURVEY.md §5);
  * metrics: step loss, examples/sec (/chip), validation AUC per epoch.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time

import jax
import numpy as np

from fast_tffm_tpu.checkpoint import read_input_cursor, restore_checkpoint
from fast_tffm_tpu.config import Config, build_model
from fast_tffm_tpu.data.native import best_parser
from fast_tffm_tpu.data.pipeline import batch_stream
from fast_tffm_tpu.metrics import StreamingAUC, Throughput
from fast_tffm_tpu.models.base import Batch
from fast_tffm_tpu.resilience import (
    NonFiniteLossError,
    active_faults,
    drain_fault_counters,
    drain_fault_events,
)
from fast_tffm_tpu.telemetry import RunMonitor
from fast_tffm_tpu.trainer import init_state, make_predict_step, make_train_step
from fast_tffm_tpu.utils.prefetch import PrefetchError, prefetch
from fast_tffm_tpu.utils.tracing import WindowTracer, step_trace

__all__ = ["train", "dist_train", "scan_max_nnz"]


def scan_max_nnz(cfg: Config) -> int:
    """Fix the static feature width: cfg.max_nnz, or a scan of the files
    (one C++ streaming pass per file when the native parser is built).
    FMS stream files (online follow input — data/stream.py) contribute
    their header width instead of a scan: an append-only stream's widest
    FUTURE row is unknowable, so the writer-declared width is the bound."""
    if cfg.max_nnz > 0:
        return cfg.max_nnz
    from fast_tffm_tpu.data.native import scan_files
    from fast_tffm_tpu.data.stream import is_fms, read_fms_header

    paths = (*cfg.train_files, *cfg.validation_files, *cfg.predict_files)
    fms_widths = [read_fms_header(p)["width"] for p in paths if is_fms(p)]
    rest = tuple(p for p in paths if not is_fms(p))
    widest = scan_files(rest)[1] if rest else 0
    return max(1, widest, *fms_widths)


def _check_finite(
    loss: float, cfg: Config, monitor=None, step=0, state=None, cursor=None
) -> None:
    """Abort on a non-finite loss instead of training on (and eventually
    checkpointing) poisoned state.  With a ``monitor``, the divergence
    lands in the telemetry stream as a structured ``kind=anomaly`` record
    (step, loss, first non-finite tensor path) BEFORE the raise, so
    tools/report.py can flag the run without log-grepping.

    Raises ``NonFiniteLossError`` carrying the input ``cursor`` at
    detection time: under ``on_nan = rollback`` the driver restores the
    last checkpoint and resumes input AT this cursor — skipping the
    window whose data diverged instead of replaying it."""
    if not np.isfinite(loss):
        if monitor is not None:
            monitor.emit_anomaly(step, loss, state=state)
        # Under lookup_overflow=fallback an overflow cannot produce NaN
        # (the step reran via allgather) — divergence is the only cause.
        hint = (
            "an alltoall-lookup capacity overflow — raise "
            "lookup_capacity_factor, set lookup_overflow = fallback, or "
            "use lookup=allgather"
            if cfg.lookup == "alltoall" and cfg.lookup_overflow == "abort"
            else "a diverged model — lower learning_rate"
        )
        raise NonFiniteLossError(
            f"training loss is {loss}; likely {hint}.  Aborting before the "
            "next checkpoint overwrites the last good state.",
            step=int(step),
            loss=float(loss),
            cursor=cursor,
        )


_TRAIN_WEIGHTS = object()  # sentinel: apply cfg.weight_files (train files only)


def _batch_converter(uses_fields: bool):
    """The drivers' host→device batch assembly: a single ParsedBatch
    converts via ``Batch.from_parsed``; a LIST of K grouped batches
    (steps_per_call > 1 streams) stacks into one [K, B, ...] superbatch.
    One definition shared by train() and dist_train() so the stacking
    rule cannot diverge between the local and distributed drivers.

    ``wire_capable`` marks converters the packed wire format can feed —
    this local one ships one coalesced buffer to the local device; the
    multi-host global-batch closures additionally carry
    ``make_wire_converter`` so _stream builds the host-local
    pack/unpack + global-assembly shipper instead
    (parallel.WireGlobalConverter)."""

    def to_batch(parsed, w):
        if isinstance(parsed, list):
            return Batch.stack_parsed(parsed, w, with_fields=uses_fields)
        return Batch.from_parsed(parsed, w, with_fields=uses_fields)

    to_batch.uses_fields = uses_fields
    to_batch.wire_capable = True
    return to_batch


def binary_input(files) -> bool:
    """True when every file in the (cache-resolved) list is FMB — i.e. the
    stream will be memmap-backed, not parsed."""
    from fast_tffm_tpu.data.binary import is_fmb

    return bool(files) and all(is_fmb(f) for f in files)


def _stream(
    cfg: Config,
    files,
    max_nnz,
    epochs,
    batch_size=None,
    weights=_TRAIN_WEIGHTS,
    to_batch=None,
    shuffle_epoch=None,
    steps_per_call=1,
    skip_batches=0,
    dedup_guard=False,
    **shard_kw,
):
    """Prefetched input stream yielding ``(batch_or_None, parsed, w)``.

    ``skip_batches`` reopens the stream mid-epoch at that batch offset
    (the exact-position resume seek — cursors count batches, and the
    underlying streams seek in rows); with ``steps_per_call`` > 1 the
    skip is applied BEFORE grouping, so a K-aligned resume reproduces
    the uninterrupted run's superbatch boundaries exactly.

    With FMB-backed input and a ``to_batch``, the host→device conversion
    runs INSIDE the prefetch thread, overlapping the transfer with the
    consumer's step dispatch (measured ~3× end-to-end on a transfer-bound
    host — the memmap producer is cheap, unlike the text parse, which
    needs the thread to itself and keeps conversion in the consumer; see
    DESIGN.md §6).  Callers convert when the first element is None.

    ``steps_per_call`` > 1 groups K consecutive batches per item: ``parsed``
    and ``w`` become LISTS of K entries (epoch tail shorter), and the
    drivers' list-aware ``to_batch`` stacks them into one [K, B, ...]
    superbatch — ONE H2D transfer and one fused-step dispatch per K steps.
    The grouping (and, for FMB input, the stacking + transfer) runs inside
    the prefetch thread, exactly like the single-batch conversion above.
    """
    if weights is _TRAIN_WEIGHTS:
        weights = cfg.weight_files if cfg.weight_files else None
    files = tuple(files)
    parser = best_parser(cfg.thread_num)
    if cfg.binary_cache:
        # Resolve the cache HERE (not inside batch_stream) so the
        # conversion-placement decision below sees the actual outcome:
        # an unwritable cache falls back to text files, and text input
        # must keep the prefetch thread for the parse.
        from fast_tffm_tpu.data.binary import ensure_fmb_cache

        files = ensure_fmb_cache(
            files,
            vocabulary_size=cfg.vocabulary_size,
            hash_feature_id=cfg.hash_feature_id,
            max_nnz=max_nnz,
            parser=parser,
            # Pod etiquette: on a shared filesystem only the lead process
            # builds a stale cache; the rest wait for it (and build their
            # own copy after the timeout when disks are host-local).
            # Shard-disjoint file assignment is the exception: each host
            # OWNS its files, so waiting for a peer build would stall a
            # non-lead host for the whole timeout on a cache nobody else
            # will ever write.
            wait_for_peer=(
                cfg.binary_cache_wait
                if jax.process_index() != 0 and cfg.input_assignment != "files"
                else 0.0
            ),
        )
    # Per-epoch shuffle (train streams only — drivers create one stream per
    # epoch and pass its index).  The seed folds the epoch so every epoch
    # draws a fresh permutation, identically on every process.
    from fast_tffm_tpu.data.binary import fold_epoch_seed

    shuffle_seed = (
        fold_epoch_seed(cfg.shuffle_seed, shuffle_epoch)
        if cfg.shuffle and shuffle_epoch is not None
        else None
    )
    if shuffle_seed is not None and cfg.binary_cache and not binary_input(files):
        if jax.process_count() > 1:
            # The fallback decision is PER-PROCESS (host-local disks can
            # fail on some hosts only).  A process streaming its shard
            # sequentially while its peers follow the epoch permutation
            # would let make_global_batch stitch shards drawn from
            # different row orderings into one global batch — silently
            # duplicating/dropping examples for the whole run.  Die loudly
            # instead; every process either shuffles or none do.
            raise RuntimeError(
                "shuffle with binary_cache on a multi-process run: this "
                "process could not build/reach the binary cache (text "
                "fallback), and a per-host shuffle fallback would silently "
                "misalign the global batches — fix the cache location on "
                "every host (or pre-convert the files, or disable shuffle)"
            )
        # Single process: the cache fell back to text (unwritable
        # location); binary_cache is an accelerator and must keep
        # degrading gracefully — drop the shuffle for this run rather
        # than dying on batch_stream's "set binary_cache = true" (which
        # the user already did).
        import warnings

        warnings.warn(
            "shuffle disabled: the binary cache is unavailable (text "
            "fallback) and text streaming cannot reorder rows",
            RuntimeWarning,
            stacklevel=2,
        )
        shuffle_seed = None
    bs = batch_size if batch_size is not None else cfg.batch_size
    raw = batch_stream(
        files,
        batch_size=bs,
        vocabulary_size=cfg.vocabulary_size,
        hash_feature_id=cfg.hash_feature_id,
        max_nnz=max_nnz,
        epochs=epochs,
        weights=weights,
        parser=parser,
        shuffle_seed=shuffle_seed,
        skip_rows=skip_batches * bs,
        io_retries=cfg.io_retries,
        io_retry_backoff_s=cfg.io_retry_backoff_s,
        **shard_kw,
    )
    if dedup_guard and cfg.dedup_gather_rows > 0:
        # Verified-never-trusted (the wire packer's stance): the jitted
        # dedup gather (trainer.make_dedup_body) silently TRUNCATES a
        # unique set past its static cap, so every batch is checked on
        # the host before it ships — a too-small cap is a loud error
        # naming the knob, never corrupted training.
        raw = _dedup_cap_guard(raw, cfg.dedup_gather_rows)
    if steps_per_call > 1:
        from fast_tffm_tpu.utils.prefetch import grouped_pairs

        raw = grouped_pairs(raw, steps_per_call)
    from fast_tffm_tpu.data.wire import InputStats
    from fast_tffm_tpu.utils.prefetch import InputStream

    convert = None
    if to_batch is not None and binary_input(files):
        convert = to_batch
        wire_ok = getattr(to_batch, "wire_capable", False)
        if cfg.wire_format == "packed" and wire_ok and max_nnz:
            # Packed wire: ONE coalesced byte buffer per (super)batch with
            # device-side reconstruction, instead of one device_put per
            # tensor.  Elision decisions are PER STREAM, from facts about
            # THESE files: all-ones vals come off the FMB v2 header flags
            # (ANDed; verified again per batch by the packer), fields
            # follow the model's uses_fields rule, weights elide when the
            # per-file example weights are uniform.
            from fast_tffm_tpu.data.binary import fmb_wire_flags
            from fast_tffm_tpu.data.wire import WireConverter, make_spec

            all_ones, _ = fmb_wire_flags(files)
            uniform_w = weights is None or all(float(x) == 1.0 for x in weights)
            spec = make_spec(
                cfg.vocabulary_size,
                max_nnz,
                with_vals=not all_ones,
                with_fields=to_batch.uses_fields,
                with_weights=not uniform_w,
            )
            # Multi-host converters supply their own wire shipper (the
            # host-local pack + per-device unpack + global assembly —
            # parallel.WireGlobalConverter); local converters take the
            # plain single-device one.
            maker = getattr(to_batch, "make_wire_converter", None)
            convert = maker(spec) if maker is not None else WireConverter(spec)
    stats = InputStats()
    gen = stats.timed(raw, convert)
    # Each queued item holds steps_per_call batches, so scale the depth
    # down to keep the in-flight memory (device superbatches for FMB
    # input, host staging for text) at the K=1 level — one or two
    # superbatches in flight already keep the consumer overlapped.
    depth = max(1, cfg.queue_size // max(1, steps_per_call))
    return InputStream(prefetch(gen, depth=depth, stats=stats), stats)


def _dedup_cap_guard(raw, cap: int):
    """Per-batch unique-id bound check for ``dedup_gather_rows`` (runs in
    the prefetch thread — overlapped like the parse it rides)."""
    for p, w in raw:
        u = int(np.unique(p.ids).size)
        if u > cap:
            raise ValueError(
                f"dedup_gather_rows = {cap} but a batch carries {u} unique "
                "ids — the jitted dedup gather would silently drop rows.  "
                "Raise dedup_gather_rows (or set 0 to disable)."
            )
        yield p, w


def _evaluate(
    cfg: Config, predict_step, state, files, max_nnz, stream=None, to_batch=None, fetch=None
) -> float:
    """AUC over ``files``.  ``stream``/``to_batch``/``fetch`` parameterize the
    multi-host sharded path (sharded input, global-array stitching, device
    all-gather of the label/weight vectors); defaults are the local path.

    Bounded memory: per-batch scores fold into a fixed-bucket streaming
    AUC (metrics.StreamingAUC) instead of accumulating every score/label
    on the host — a Criteo-scale validation split evaluates in O(bins).

    weight_files aligns with TRAIN files; validation examples weigh 1.0
    (only batch-padding rows carry 0, and the AUC drops them)."""
    if to_batch is None:
        to_batch = Batch.from_parsed
    if stream is None:
        stream = _stream(cfg, files, max_nnz, epochs=1, weights=None, to_batch=to_batch)
    if fetch is None:
        fetch = lambda b, parsed, w: (parsed.labels, w)
    meter = StreamingAUC()
    for b, parsed, w in stream:
        if b is None:
            b = to_batch(parsed, w)
        scores = np.asarray(predict_step(state, b))
        lab, ww = fetch(b, parsed, w)
        meter.add(lab, scores, ww)
    return meter.value()


def _follow_stream(cfg: Config, files, max_nnz, to_batch, skip_batches=0, stop=None):
    """Tail-following input stream for ``[Online] follow = true``: the
    FMS reader (data/stream.py) polls the append-only train file for
    growth at EOF instead of ending the epoch; conversion runs in the
    prefetch thread (the memmap-cheap producer, like FMB input), and the
    stream's idle Event feeds the stall watchdog so a starved loop
    classifies ``input-starved (stream-idle)``.  ``skip_batches`` is the
    exact-position resume seek — one O(1) file seek."""
    from fast_tffm_tpu.data.stream import fms_follow_stream
    from fast_tffm_tpu.data.wire import InputStats
    from fast_tffm_tpu.utils.prefetch import InputStream, prefetch

    idle = threading.Event()
    raw = fms_follow_stream(
        files[0],
        batch_size=cfg.batch_size,
        vocabulary_size=cfg.vocabulary_size,
        hash_feature_id=cfg.hash_feature_id,
        max_nnz=max_nnz,
        poll_s=cfg.online_poll_s,
        idle_timeout_s=cfg.online_idle_timeout_s,
        max_batches=cfg.online_max_batches,
        skip_batches=skip_batches,
        idle_flag=idle,
        # The driver's SIGTERM handler sets this: an UNBOUNDED follow
        # stream (idle_timeout_s = 0) must end at the next poll so the
        # graceful checkpoint-and-exit path actually runs — without it a
        # stop request while the stream is idle would block forever on
        # an empty prefetch queue.
        stop=stop,
    )
    if cfg.steps_per_call > 1:
        from fast_tffm_tpu.utils.prefetch import grouped_pairs

        raw = grouped_pairs(raw, cfg.steps_per_call)
    stats = InputStats()
    stats.bind_stream_idle(idle)
    gen = stats.timed(raw, to_batch)
    depth = max(1, cfg.queue_size // max(1, cfg.steps_per_call))
    return InputStream(prefetch(gen, depth=depth, stats=stats), stats)


def _files_fingerprint(files) -> str:
    """Input-dataset identity for the resume cursor: the train file list
    plus each file's size.  A cursor's batch offset only means something
    against the exact data it was saved over — if the files changed (the
    online-append scenario: rows landing between crash and resume shift
    every later row, and a shuffled epoch's permutation is drawn over
    the TOTAL row count), resuming at the old offset would silently
    misalign data and weights.  Size is the cheap stat-only proxy:
    append/truncate/replace all move it; a byte-for-byte same-size edit
    does not, but that is not a failure mode a crash produces."""
    import hashlib

    h = hashlib.md5()
    for p in files:
        try:
            size = os.path.getsize(p)
        except OSError:
            size = -1
        h.update(f"{p}:{size}\n".encode())
    return h.hexdigest()[:16]


def _resolve_cursor(cfg: Config, cursor, log) -> tuple[int, int]:
    """(start_epoch, start_batch) from a restored input cursor.

    The cursor must describe THIS run's input identity (batch size,
    shuffle settings, train-file fingerprint) — anything else falls
    back, with a warning, to the legacy start-of-data behavior rather
    than resuming at a position that means something different now.  A
    cursor at or past ``epoch_num`` is a COMPLETED run: resume then
    keeps its historical meaning of "train epoch_num more epochs"
    (test-pinned), so it also starts at (0, 0)."""
    if not cursor:
        return 0, 0
    exact = bool(cursor.pop("_exact", False))
    if int(cursor.get("version", 0)) > 1:
        log(
            "warning: checkpoint input cursor has a newer version "
            f"({cursor.get('version')}) than this build understands — "
            "resuming at the start of the data (legacy behavior)"
        )
        return 0, 0
    # Multi-host cursor vector: the chain head carries every host's exact
    # position (hosts[p]); resume hands each host back ITS entry.  A
    # topology change (different process count) or an internally
    # disagreeing vector cannot be resumed exactly — loud legacy fallback.
    hosts = cursor.pop("hosts", None)
    saved_pcount = cursor.pop("process_count", None)
    if hosts is not None:
        pcount, p = jax.process_count(), jax.process_index()
        if (saved_pcount or len(hosts)) != pcount or p >= len(hosts):
            log(
                "warning: checkpoint cursor vector was saved by "
                f"{saved_pcount or len(hosts)} host(s), this run has "
                f"{pcount} — resuming at the start of the data (legacy "
                "behavior)"
            )
            return 0, 0
        entries = [
            ((h or {}).get("epoch"), (h or {}).get("batch_in_epoch")) for h in hosts
        ]
        if any(e != entries[0] for e in entries[1:]):
            log(
                "warning: checkpoint cursor vector disagrees across hosts "
                f"({entries}) — resuming at the start of the data (legacy "
                "behavior)"
            )
            return 0, 0
        mine = hosts[p] or {}
        if mine.get("epoch") is not None:
            cursor["epoch"] = int(mine["epoch"])
            cursor["batch_in_epoch"] = int(mine.get("batch_in_epoch") or 0)
    follow = bool(cfg.online_follow)
    if follow and cursor.get("follow"):
        # Append-only stream identity is PREFIX-based (growth is the
        # normal case — data/stream.py): re-hash exactly the prefix
        # window the cursor recorded.  A mismatch means the file was
        # REPLACED, rewritten, or truncated: the cursor's batch offset
        # now points into different data, and "resume at the start"
        # would silently re-train the whole stream — fail LOUDLY instead
        # (unlike the batch paths' warn-and-restart, there is no safe
        # fallback here).
        from fast_tffm_tpu.data.stream import stream_prefix_matches

        if cursor.get("files") is not None and not stream_prefix_matches(
            cfg.train_files, cursor["files"]
        ):
            raise ValueError(
                "online resume: the train stream's PREFIX changed since "
                "this cursor was saved (file replaced/rewritten/truncated, "
                "not appended?) — the saved batch offset no longer names "
                "the same data.  Start fresh (drop --resume) or restore "
                "the original stream file."
            )
        # Prefix verified; exclude "files" from the equality table below
        # (the re-hash IS the check — fingerprints of a grown file
        # legitimately differ).
        want_files = cursor.get("files")
    elif follow:
        # A batch-run cursor under a follow config (mode switch): the
        # fingerprint flavors can never match — legacy fallback below.
        want_files = object()
    else:
        want_files = _files_fingerprint(cfg.train_files)
    mismatched = [
        f"{key} {cursor.get(key)!r} != {want!r}"
        for key, want in (
            ("batch_size", int(cfg.batch_size)),
            ("shuffle", bool(cfg.shuffle)),
            ("shuffle_seed", int(cfg.shuffle_seed) if cfg.shuffle else cursor.get("shuffle_seed")),
            ("follow", follow if "follow" in cursor else follow or None),
            ("files", want_files),
        )
        if cursor.get(key) != want
    ]
    if mismatched:
        log(
            "warning: checkpoint input cursor does not match this config "
            f"({'; '.join(mismatched)}) — resuming at the start of the "
            "data (legacy behavior)"
        )
        return 0, 0
    e = max(0, int(cursor.get("epoch", 0)))
    b = max(0, int(cursor.get("batch_in_epoch", 0)))
    if e >= cfg.epoch_num:
        if exact:
            # A rollback cursor is a literal position, never "train more
            # epochs": at/past the end it means "no input left" (the run
            # finishes with the final save alone).
            return cfg.epoch_num, 0
        return 0, 0
    log(f"resuming input at epoch {e}, batch {b} (exact-position cursor)")
    return e, b


def _run_training(
    cfg: Config,
    state,
    step_fn,
    predict_step,
    max_nnz,
    log=print,
    train_stream=None,
    to_batch=None,
    examples_per_step=None,
    evaluate=None,
    extra_metrics=None,
    saveable=None,
    step_hook=None,
    row_dim=0,
    mark_touched=None,
    start_cursor=None,
    rollback=None,
    runtime=None,
    mesh=None,
    datastats_ids=None,
    accum_restart=None,
    stream_stop=None,
    paramstore=None,
):
    """Shared step loop.  ``train_stream(epoch)`` overrides the per-epoch
    input stream, ``to_batch(parsed, w)`` the host→device batch assembly,
    and ``evaluate`` the validation pass — the multi-host path plugs in
    sharded input + global-array stitching here without forking the loop.

    ``step_hook(step_num)`` (optional) runs in the LOOP THREAD after every
    dispatch, before the graceful-stop check — a deterministic injection
    point for tests (e.g. raising SIGTERM at an exact step instead of
    racing a wall-clock timer) and for external schedulers.  It must be
    cheap: it sits on the hot path.

    Step fusion (``steps_per_call`` > 1) needs no fork either: a fused
    ``step_fn`` returns a PER-MICRO-STEP loss vector [K] instead of a
    scalar, and the loop reads K off the loss shape — step counting,
    throughput accounting, loss logging, and the NaN check all keep
    per-step granularity (every micro-step loss lands in the log window's
    mean).  The graceful-stop signal and the log cadence are only CHECKED
    between dispatches, so stop/checkpoint boundaries and log-window edges
    become K-step-aligned — the documented cost of fusing away the
    per-step host round-trip.
    ``extra_metrics()`` (optional) is drained at every log point and its
    dict merged into the stdout line and the JSONL record (dist_train uses
    it to report alltoall overflow-fallback step counts).  ``saveable``
    (optional) converts the live state to its checkpoint form before
    every save — the packed table layout uses it to store LOGICAL [V, D]
    arrays, keeping packed and rows checkpoints interchangeable.
    ``row_dim`` (the model's logical row width) and ``mark_touched`` (an
    optional custom touched-row bitmap marker — the device-cache drivers
    mark from their resident id arrays) parameterize the async/delta
    checkpoint subsystem (checkpoint_async.AsyncCheckpointer).

    ``datastats_ids`` (optional ``batch -> device ids``) lets the sampled
    id-statistics collector read a device-cache batch's ids straight off
    the resident arrays; streamed paths feed it the host-side ``parsed``
    rows instead (profiling.DataStatsCollector).

    ``start_cursor`` (a dict from checkpoint.read_input_cursor) resumes
    the INPUT at the exact saved position: the epoch loop starts at the
    cursor's epoch and the first stream opens at its batch offset, so a
    resumed run consumes precisely the batches an uninterrupted run
    would have — its loss sequence matches (bit-identically when the
    XLA program is the same).  Every save boundary embeds the live
    cursor back into the checkpoint.  ``train_stream(epoch,
    skip_batches)`` must honor the skip.  ``rollback`` (a note dict from
    the on_nan=rollback driver loop) is recorded as a kind=anomaly
    event=rollback at run start."""
    if saveable is None:
        saveable = lambda st: st
    if train_stream is None:
        train_stream = lambda epoch, skip_batches=0: _stream(
            cfg, cfg.train_files, max_nnz, epochs=1, to_batch=to_batch,
            shuffle_epoch=epoch, steps_per_call=cfg.steps_per_call,
            skip_batches=skip_batches, dedup_guard=True,
        )
    if to_batch is None:
        to_batch = Batch.from_parsed
    if evaluate is None:
        # Validation ships batches the same way training does (in particular
        # the fields-skipping transfer for models that never read fields).
        def evaluate(cfg, predict_step, state, files, max_nnz):
            return _evaluate(cfg, predict_step, state, files, max_nnz, to_batch=to_batch)
    n_chips = jax.device_count()
    meter = Throughput()
    losses = []
    pending_steps = 0  # micro-steps since the last log point
    start_step = step_num = int(state.step)
    # On multi-host pods every process runs this loop; process 0 owns the
    # profiler trace, and each host writes its OWN telemetry file
    # (host_metrics_path — tools/report.py merges them per run_id).
    is_lead = jax.process_index() == 0
    ckpt_format = cfg.checkpoint_format
    if ckpt_format == "npz" and os.path.isdir(cfg.model_file):
        # model_file already holds an orbax directory (e.g. an earlier
        # orbax run): an npz os.replace onto it would crash at save
        # time, after training.  Stay in the format the path already has.
        log(f"note: {cfg.model_file} is an orbax checkpoint dir — keeping orbax format")
        ckpt_format = "orbax"
    elif jax.process_count() > 1 and ckpt_format == "npz":
        # Multi-host npz runs the single-writer protocol: the state
        # replicates to every host (dist_train supplies the replicating
        # saveable), process 0 alone publishes full+delta files, and every
        # other host synchronizes on the published content signature.
        # The memory bill is the full logical table per host — orbax stays
        # the format for beyond-host tables (DESIGN §8).
        log(
            "note: multi-host npz checkpoints — process 0 is the sole "
            "writer; peers barrier on each publish's content signature"
        )
    tracer = WindowTracer(cfg.trace_dir if is_lead else None, count=cfg.trace_steps)
    # Unified telemetry: every record (train/input/validation/compile/mem/
    # stall/anomaly/summary) shares one run_id and the envelope schema
    # (telemetry.SCHEMAS); the compile sentinel drains per dispatch, the
    # liveness watchdog fires kind=stall with thread stacks when the loop
    # wedges, and the close() record documents the run's totals.
    from fast_tffm_tpu.distributed import host_metrics_path

    run_id = cfg.telemetry_run_id
    if runtime is not None and runtime.active and not run_id:
        # One run identity across the pod: the lead draws it, everyone
        # else adopts it — tools/report.py groups per-host files by it.
        from fast_tffm_tpu.telemetry import new_run_id

        run_id = runtime.broadcast("run_id", new_run_id() if runtime.is_lead else None)
    monitor = RunMonitor(
        host_metrics_path(cfg.metrics_path) if cfg.metrics_path else None,
        run_id=run_id,
        source="train",
        stall_timeout_s=cfg.telemetry_stall_timeout_s,
        mem_every_s=cfg.telemetry_mem_every_s,
        log=log,
    )
    # Deep observability (profiling.py): the on-demand step-window trace,
    # the per-compiled-program measured cost ledger (kind=profile — the
    # evidence column next to the modeled HBM floor), and the sampled
    # id-traffic statistics (kind=datastats — the dedup/heavy-hitter
    # numbers ROADMAP item 3 sizes against).  All compiles these issue
    # attribute as warmup; the trace is lead-host-only like WindowTracer.
    from fast_tffm_tpu.profiling import (
        CostLedger,
        DataStatsCollector,
        StepProfiler,
        modeled_step_bytes,
    )

    profiler = StepProfiler(
        cfg.telemetry_profile_steps if is_lead else "",
        cfg.trace_dir or (cfg.model_file + ".profile"),
        monitor=monitor,
        log=log,
    )
    ledger = CostLedger(monitor, source="train") if cfg.telemetry_profile_costs else None
    datastats = None
    if cfg.telemetry_datastats_every_steps > 0:
        datastats = DataStatsCollector(
            monitor,
            vocab=cfg.vocabulary_size,
            row_dim=max(1, row_dim),
            every_steps=cfg.telemetry_datastats_every_steps,
            heavy_hitter_k=cfg.telemetry_heavy_hitter_k,
            ids_fn=datastats_ids,
        )
    accum_cols = max(1, row_dim) if cfg.adagrad_accumulator == "element" else 1

    def _stage_step_profile(b, parsed):
        """First-dispatch capture: abstract shapes (before donation) plus
        the modeled HBM floor for THIS batch's ids — measured and modeled
        land on one kind=profile record."""
        modeled = None
        ex = None
        if isinstance(parsed, list):
            ex = sum(p.batch_size for p in parsed)
            modeled = sum(
                modeled_step_bytes(p.ids, max(1, row_dim), accum_cols)[0]
                for p in parsed
            )
        elif parsed is not None and hasattr(parsed, "ids"):
            ex = parsed.batch_size
            modeled, _ = modeled_step_bytes(parsed.ids, max(1, row_dim), accum_cols)
        elif examples_per_step is not None:
            k_hint = 1
            shape = tuple(getattr(b, "shape", ()) or ())
            if shape:
                k_hint = int(np.prod(shape))
            ex = examples_per_step * k_hint
            if datastats_ids is not None:
                try:
                    # One-time D2H of one batch's ids: the modeled floor
                    # needs the host-side unique count (setup cost only).
                    # The slicer returns the whole dispatch's rows (all K
                    # batches of a scan chunk); whole-window unique only
                    # UNDERSTATES the per-batch RMW term — still a floor.
                    ids_host = np.asarray(datastats_ids(b))
                    modeled, _ = modeled_step_bytes(
                        ids_host, max(1, row_dim), accum_cols
                    )
                except Exception:
                    modeled = None
        ledger.stage(
            "train_step", step_fn, (state, b), examples=ex, modeled_bytes=modeled,
        )

    # Pod liveness: this host's heartbeat (armed at bring-up) starts
    # carrying the step counter, and a peer-heartbeat monitor classifies a
    # stale host as a host-level kind=stall long before jax's own
    # coordination-service timeout would notice.
    heartbeat = getattr(runtime, "heartbeat", None) if runtime is not None else None
    host_monitor = None
    if (
        runtime is not None
        and runtime.process_count > 1
        and getattr(runtime, "runtime_dir", None)
        and cfg.host_stall_timeout_s > 0
    ):
        from fast_tffm_tpu.distributed import HostMonitor

        def _on_host_stall(peer, classification, detail):
            monitor.emit(
                "stall",
                step=step_num,
                deadline_s=cfg.host_stall_timeout_s,
                since_last_step_s=detail.get("age_s"),
                classification=classification,
                prefetch_queue_depth=None,
                stacks={},
                peer=peer,
                peer_last_step=detail.get("last_step"),
            )

        host_monitor = HostMonitor(
            runtime.runtime_dir,
            runtime.process_index,
            runtime.process_count,
            cfg.host_stall_timeout_s,
            _on_host_stall,
            poll_s=min(1.0, cfg.host_stall_timeout_s / 4.0),
        )
    if rollback is not None:
        # The failed attempt's monitor already recorded the non-finite
        # loss; THIS record documents the recovery decision (restored
        # step, skipped-to position, rollback ordinal) in the new run.
        monitor.emit_anomaly(
            int(rollback.get("step", 0)), rollback.get("loss"),
            event="rollback", **{k: v for k, v in rollback.items()
                                 if k not in ("step", "loss")},
        )
    # Deterministic fault injection (resilience.py): a CLI-armed plan
    # kills via the step_hook the driver already passed; nan faults
    # poison the loss below; io/torn faults fire inside the reader and
    # checkpoint writer.  ``faults`` is None on every normal run.
    faults = active_faults()
    # Accumulator window-restart grid: ABSOLUTE step multiples of N, so a
    # crash-resumed run fires its resets at the same steps the
    # uninterrupted run would have (an anchor relative to the resumed
    # start would shift every later reset).  K-aligned like every other
    # boundary: the reset fires at the first dispatch crossing a multiple.
    if accum_restart is not None:
        _n = max(1, int(cfg.online_accum_restart_steps))
        next_restart = (start_step // _n + 1) * _n
    else:
        next_restart = None
    # Exact-position input cursor: tracked per dispatch, embedded in
    # every checkpoint (full, delta, final) so a crash-resume reopens
    # the input mid-epoch at the precise saved batch.
    start_epoch, start_batch = _resolve_cursor(cfg, start_cursor, log)
    cur = {"epoch": start_epoch, "batch": start_batch}
    # Dataset identity, stamped once: cursors saved by this run describe
    # THIS file set; a resume against changed files must not trust them.
    # Follow mode uses the append-stable PREFIX fingerprint (growth is
    # the normal case); the batch paths keep the size-based one.
    if cfg.online_follow:
        from fast_tffm_tpu.data.stream import stream_prefix_fingerprint

        files_fp = stream_prefix_fingerprint(cfg.train_files)
    else:
        files_fp = _files_fingerprint(cfg.train_files)

    def input_cursor() -> dict:
        c = {
            "version": 1,
            "epoch": int(cur["epoch"]),
            "batch_in_epoch": int(cur["batch"]),
            "batch_size": int(cfg.batch_size),
            "shuffle": bool(cfg.shuffle),
            "shuffle_seed": int(cfg.shuffle_seed),
            "steps_per_call": int(cfg.steps_per_call),
            "files": files_fp,
        }
        if cfg.online_follow:
            c["follow"] = True
        return c
    # Save boundaries (full + delta) go through ONE owner: async full saves
    # snapshot on device and hand the convert/D2H/write to a writer thread
    # (at most one in flight, back-pressure counted); delta saves ship only
    # the touched-row window; every save emits a kind=ckpt record.  The
    # SIGTERM/final paths below stay synchronous (sync=True), so the
    # last-good-state guarantee is exactly the old one.
    from fast_tffm_tpu.checkpoint_async import AsyncCheckpointer

    if cfg.delta_every_steps > 0 and ckpt_format != "npz":
        raise ValueError(
            "delta_every_steps > 0 requires npz checkpoints — this run "
            "resolved checkpoint_format to orbax (model_file already "
            "holds an orbax dir); disable delta saves or point "
            "model_file at a fresh npz path"
        )
    if cfg.async_save and ckpt_format != "npz":
        log("note: async_save applies to npz checkpoints — orbax saves stay synchronous")
    ckpt = AsyncCheckpointer(
        cfg.model_file,
        ckpt_format,
        monitor=monitor,
        log=log,
        chunk_bytes=cfg.checkpoint_chunk_mb << 20,
        async_save=cfg.async_save,
        delta_every_steps=cfg.delta_every_steps,
        delta_chain_max=cfg.delta_chain_max,
        full_every_s=cfg.delta_full_every_s,
        chain_max_bytes=cfg.delta_chain_max_bytes,
        # Tiered runs size the touched-row bitmap at the COMPACT device
        # capacity (slots), not the logical vocab — a 2^30-row bitmap
        # would itself be a gigabyte.
        vocab=(paramstore.capacity if paramstore is not None else cfg.vocabulary_size),
        paramstore=paramstore,
        table_layout=cfg.table_layout,
        row_dim=row_dim,
        mark_fn=mark_touched,
        start_step=start_step,
        cursor_fn=input_cursor,
        runtime=runtime,
        mesh=mesh,
    )
    # Preemption-safe shutdown (the reference's only recovery story was
    # Supervisor restart-from-checkpoint; cloud TPU maintenance sends
    # SIGTERM): first signal finishes the current step, checkpoints, and
    # exits cleanly; a second signal falls through to the default handler.
    stop_requested = threading.Event()
    restore_handlers = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            log(f"received signal {signum}: checkpointing after current step")
            stop_requested.set()
            if stream_stop is not None:
                # Unbounded follow streams end at their next poll so the
                # loop (blocked on an idle stream's empty queue) wakes up
                # to take the graceful checkpoint-and-exit path.
                # ``stream_stop`` is a one-slot holder of the LIVE
                # stream's Event (a fresh one per stream — see train()).
                stream_stop[0].set()
            signal.signal(signum, restore_handlers[signum])

        for sig in (signal.SIGTERM, signal.SIGINT):
            restore_handlers[sig] = signal.signal(sig, _on_signal)
    try:
        for epoch in range(start_epoch, cfg.epoch_num):
            if stop_requested.is_set():
                break
            # A resumed first epoch reopens mid-stream at the cursor's
            # batch offset; every later epoch starts at 0 as usual.
            epoch_stream = train_stream(
                epoch, cur["batch"] if epoch == start_epoch else 0
            )
            # Streamed inputs carry per-stream InputStats (wire bytes,
            # parse/H2D ms, prefetch depth — data/wire.py); drained into
            # kind=input records at every log point.  Device-cached
            # streams are bare generators (no stats — no per-step wire).
            input_stats = getattr(epoch_stream, "stats", None)
            # Each epoch's stream owns a fresh prefetch queue; point the
            # stall watchdog's depth + producer-liveness probes at it.
            monitor.set_queue_depth_fn(getattr(epoch_stream, "queue_depth", None))
            monitor.set_producer_alive_fn(
                getattr(epoch_stream, "producer_alive", None)
            )
            monitor.set_stream_idle_fn(getattr(epoch_stream, "stream_idle", None))
            for b, parsed, w in epoch_stream:
                if b is None:
                    b = to_batch(parsed, w)
                tracer.on_step()
                if ledger is not None and ledger.want("train_step"):
                    # Abstract shapes must be captured BEFORE the dispatch
                    # donates the state buffers.
                    _stage_step_profile(b, parsed)
                with step_trace("train", step_num):
                    state, loss = step_fn(state, b)
                # A fused call returns per-micro-step losses [K]; K=1
                # returns the classic scalar.  The shape is static — no
                # device sync happens here.
                k = int(loss.shape[0]) if getattr(loss, "ndim", 0) else 1
                first_call = step_num == start_step
                step_num += k
                cur["batch"] += k  # cursor: k micro-batches consumed
                if first_call:
                    # Call 1 paid the XLA compile; a meter window that
                    # includes it reads as a throughput collapse.
                    jax.block_until_ready(loss)
                    meter.reset()
                # Heartbeat + compile-sentinel drain + due mem sample.
                # The FIRST epoch this process runs (epoch 0, or the
                # cursor's epoch on a resume — a fresh process pays its
                # XLA compiles regardless of where the input reopens) is
                # the shape-discovery pass: the first dispatch AND the
                # epoch-tail remainder shape (steps_per_call > 1 ships a
                # shorter [K', B, ...] superbatch) legitimately compile
                # once — all priced in as warmup.  Every shape recurs
                # identically from the next epoch on, so any later
                # kind=compile event is a steady-state recompile — the
                # thing the serving bucket ladder pins to zero, now
                # visible on the train path too.
                monitor.on_dispatch(step_num, warmup=(epoch == start_epoch))
                if heartbeat is not None:
                    heartbeat.set_step(step_num)
                # Deep-observability hooks, all cheap no-ops when idle:
                # the trace window check, the (once-per-program) measured
                # cost flush, and the sampled id-stats reducer.
                profiler.on_step(step_num)
                if ledger is not None:
                    ledger.flush(step_num)
                if datastats is not None:
                    datastats.note(step_num, parsed=parsed, batch=b)
                if next_restart is not None and step_num >= next_restart:
                    # Window restart ([Online] accum_restart_steps): reset
                    # every Adagrad accumulator to the init value.  The
                    # reset program's one-time compile is priced as warmup.
                    next_restart = (step_num // _n + 1) * _n
                    with monitor.warmup_window():
                        state = accum_restart(state)
                if ckpt.delta_enabled:
                    # OR this batch's rows into the device bitmap; at a
                    # delta boundary, ship the touched window (writer
                    # thread) and resume immediately.
                    ckpt.note_batch(b)
                    if ckpt.delta_due(step_num) and not stop_requested.is_set():
                        with monitor.suspended():
                            ckpt.delta_boundary(state, saveable, step_num)
                losses.append(loss)  # device value(s); only sync at log points
                if faults is not None and faults.nan_due(step_num):
                    # Chaos: poison this window's loss so the finite
                    # check (and the on_nan policy) fire deterministically.
                    losses[-1] = np.float32("nan")
                pending_steps += k
                if examples_per_step is not None:
                    meter.add(examples_per_step * k)
                elif isinstance(parsed, list):
                    meter.add(sum(p.batch_size for p in parsed))
                else:
                    meter.add(parsed.batch_size)
                if step_hook is not None:
                    # Before the stop check: a hook that raises a signal
                    # here is honored on THIS iteration (the handler sets
                    # stop_requested in this same thread).
                    step_hook(step_num)
                if stop_requested.is_set():
                    break
                if pending_steps >= cfg.log_every:
                    pending_steps = 0
                    rate = meter.rate()
                    mean_loss = float(
                        np.mean(
                            np.concatenate(
                                [np.atleast_1d(np.asarray(l)) for l in losses]
                            )
                        )
                    )
                    _check_finite(
                        mean_loss, cfg, monitor=monitor,
                        step=int(state.step), state=state,
                        cursor=input_cursor(),
                    )
                    for ev in drain_fault_events():
                        monitor.emit("fault", step=int(state.step), **ev)
                    extra = extra_metrics() if extra_metrics is not None else {}
                    extra_txt = "".join(f" {k} {v}" for k, v in extra.items() if v)
                    log(
                        f"step {int(state.step)} epoch {epoch} "
                        f"loss {mean_loss:.5f} "
                        f"examples/sec {rate:,.0f} (/chip {rate / n_chips:,.0f})"
                        f"{extra_txt}"
                    )
                    monitor.emit(
                        "train",
                        step=int(state.step),
                        epoch=epoch,
                        loss=round(float(mean_loss), 6),
                        examples_per_sec=round(rate, 1),
                        examples_per_sec_per_chip=round(rate / n_chips, 1),
                        **extra,
                    )
                    if input_stats is not None:
                        rec = input_stats.drain()
                        if rec:
                            monitor.emit(
                                "input", step=int(state.step), epoch=epoch, **rec
                            )
                    if paramstore is not None:
                        trec = paramstore.stats.drain(
                            paramstore.pending_rows, paramstore.hot_rows
                        )
                        if trec:
                            monitor.emit(
                                "tiering", step=int(state.step), epoch=epoch,
                                **trec,
                            )
                    losses.clear()
                    meter.reset()
            if stop_requested.is_set():
                break
            # Epoch complete: the cursor now names the NEXT epoch's start
            # (the position the epoch-end save below must embed).  Follow
            # mode is the exception: its one endless epoch never
            # "completes" — the stream merely went quiet (idle timeout /
            # max_batches bound), and the cursor must keep naming the
            # batch offset so the next ``--resume`` continues EXACTLY
            # where this run stopped once more rows land.
            if not cfg.online_follow:
                cur["epoch"], cur["batch"] = epoch + 1, 0
            if input_stats is not None:
                # Epoch-tail drain: the stream (and its stats) dies here,
                # and a run (or tail) shorter than log_every would
                # otherwise never emit its kind=input record at all.
                rec = input_stats.drain()
                if rec:
                    monitor.emit("input", step=int(state.step), epoch=epoch, **rec)
            if paramstore is not None:
                # Same epoch-tail rule for the tiering record.
                trec = paramstore.stats.drain(
                    paramstore.pending_rows, paramstore.hot_rows
                )
                if trec:
                    monitor.emit(
                        "tiering", step=int(state.step), epoch=epoch, **trec
                    )
            if losses:
                # Epoch boundary syncs anyway (validation / checkpoint); a
                # poisoned state must abort BEFORE the save below replaces
                # the last good checkpoint.  Check the whole unlogged
                # tail window (it is at most log_every entries, once per
                # epoch): a REAL NaN propagates into every later loss,
                # but an INJECTED one poisons a single host-side entry —
                # the last entry alone would miss it mid-window.
                _check_finite(
                    float(
                        np.mean(
                            np.concatenate(
                                [np.atleast_1d(np.asarray(l)) for l in losses]
                            )
                        )
                    ),
                    cfg, monitor=monitor, step=int(state.step), state=state,
                    cursor=input_cursor(),
                )
            if cfg.validation_files:
                # No train dispatches complete during validation — a long
                # pass must not read as a stall (watchdog suspended).
                with monitor.suspended():
                    val_auc = evaluate(
                        cfg, predict_step, state, cfg.validation_files, max_nnz
                    )
                log(f"epoch {epoch} validation auc {val_auc:.5f}")
                monitor.emit(
                    "validation",
                    step=int(state.step),
                    epoch=epoch,
                    validation_auc=round(val_auc, 6),
                )
                # Drain the validation pass's compiles: this process's
                # first epoch's predict compile is priced in (warmup); a
                # LATER epoch compiling again is a genuine steady-state
                # recompile.
                monitor.on_dispatch(int(state.step), warmup=(epoch == start_epoch))
            if cfg.save_every_epochs and (epoch + 1) % cfg.save_every_epochs == 0:
                with monitor.suspended():  # the loop dispatches nothing here
                    # Async mode: snapshot + hand off to the writer; the
                    # loop resumes while the save converts/transfers/writes.
                    ckpt.save_boundary(state, saveable, int(state.step))
                log(f"epoch {epoch} checkpoint -> {cfg.model_file}")
    except PrefetchError as e:
        # The prefetch producer died: surface it as a structured anomaly
        # (the supervisor's restart is the recovery path) — the loud,
        # named failure the old silent wedge never produced.
        monitor.emit_anomaly(
            step_num, None, event="input_pipeline_failure", error=str(e)
        )
        raise
    finally:
        if stream_stop is not None:
            # Abandoned follow producers (exception paths) must stop
            # polling/producing rather than linger for the process's life.
            stream_stop[0].set()
        summary_extra = {}
        if extra_metrics is not None:
            # Drain events from the final partial log window (run end,
            # SIGTERM stop, abort) — a skew burst at the end must still
            # reach the metrics file; it rides the kind=summary record.
            summary_extra = {k: v for k, v in extra_metrics().items() if v}
        # Join any in-flight async write BEFORE the final sync save below:
        # an older publish must never land after (and clobber) a newer one.
        ckpt.finalize()
        summary_extra.update(ckpt.summary())
        # Fault events from the final partial window (io retries, injected
        # faults) + their per-run counter totals onto the summary record.
        for ev in drain_fault_events():
            try:
                monitor.emit("fault", step=int(state.step), **ev)
            except Exception:
                pass
        summary_extra.update(
            {f"fault_{k}": v for k, v in drain_fault_counters().items() if v}
        )
        if ledger is not None:
            summary_extra.update(ledger.summary())
        if datastats is not None:
            summary_extra.update(datastats.summary())
        if paramstore is not None:
            summary_extra.update(paramstore.summary())
        profiler.close(step_num)
        tracer.close()
        if host_monitor is not None:
            host_monitor.close()
        monitor.close(**summary_extra)
        for sig, handler in restore_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, TypeError):
                pass
    # The last save is SYNCHRONOUS regardless of async_save: SIGTERM stop,
    # run end — when this returns, the state on disk IS the state returned.
    ckpt.save_boundary(state, saveable, int(state.step), sync=True, emit=False)
    if stop_requested.is_set():
        log(
            f"stopped on signal at step {int(state.step)}, model -> {cfg.model_file} "
            "(resume with --resume)"
        )
    else:
        log(f"training done: steps {start_step}->{int(state.step)}, model -> {cfg.model_file}")
    return state


def train(cfg: Config, *, resume: bool = False, log=print, step_hook=None):
    """Local (single-device) training — the reference's `train` mode."""
    if not cfg.train_files:
        raise ValueError("no train_files configured")
    if cfg.weight_files and len(cfg.weight_files) != len(cfg.train_files):
        # Checked here, not in Config.validate: a shared config must still
        # LOAD on predict-only machines where train-file globs match
        # differently (or not at all).
        raise ValueError(
            f"weight_files has {len(cfg.weight_files)} entries for "
            f"{len(cfg.train_files)} train_files (they align per-file)"
        )
    if cfg.paramstore:
        # Beyond-HBM tables: the tiered host/device parameter store
        # (paramstore/) — its own driver branch because the input path,
        # the step, validation scoring, and every checkpoint boundary
        # are residency-aware.
        return _tiered_train(cfg, resume=resume, log=log, step_hook=step_hook)
    model = build_model(cfg)
    max_nnz = scan_max_nnz(cfg)
    packed = cfg.table_layout == "packed"
    fused = cfg.adagrad_accumulator == "fused"
    saveable = None
    if packed:
        from fast_tffm_tpu.ops.packed_table import (
            unpack_accum_any,
            unpack_fused,
            unpack_table,
        )
        from fast_tffm_tpu.trainer import (
            init_packed_state,
            make_packed_predict_step,
            make_packed_train_step,
            packed_train_step_body,
        )

        v, d = model.vocabulary_size, model.row_dim

        def saveable(st):
            # Checkpoints always hold the LOGICAL arrays ([V, D] table;
            # [V, D] or [V, 1] accumulator by granularity), so packed,
            # fused and rows runs restore each other's models freely.
            if fused:
                t, a = unpack_fused(st.table, v, d)
                return st._replace(
                    table=t, table_opt=st.table_opt._replace(accum=a)
                )
            return st._replace(
                table=unpack_table(st.table, v, d),
                table_opt=st.table_opt._replace(
                    accum=unpack_accum_any(st.table_opt.accum, v, d)
                ),
            )

    def restore_state():
        """model_file -> this run's live layout.  Shared by --resume and
        the on_nan=rollback recovery below.  Packed runs restore the
        LOGICAL checkpoint first and pack it — branching BEFORE
        allocating a fresh packed state, which would peak at packed + 2x
        logical on exactly the large vocabs where OOMs were measured
        (dist_train's packed resume is structured the same way)."""
        logical = restore_checkpoint(
            cfg.model_file,
            init_state(
                model, jax.random.key(0), cfg.init_accumulator_value,
                cfg.adagrad_accumulator,
            ),
            chunk_bytes=cfg.checkpoint_chunk_mb << 20,
        )
        if packed:
            from fast_tffm_tpu.trainer import pack_state

            return pack_state(logical, cfg.init_accumulator_value, fused=fused)
        return logical

    start_cursor = None
    if resume:
        state = restore_state()
        log(
            f"resumed from {cfg.model_file} at step {int(state.step)}"
            + (" (packed)" if packed else "")
        )
        # Exact-position resume: the chain head's input cursor names the
        # batch the restored state stopped at; without one (a pre-cursor
        # checkpoint) the input restarts at the first file, as it always
        # did — forward compatibility, warned about, never an error.
        start_cursor = read_input_cursor(cfg.model_file)
        if start_cursor is None:
            log(
                "note: checkpoint carries no input cursor (pre-resilience "
                "format) — input restarts at the first file (legacy resume)"
            )
    elif packed:
        state = init_packed_state(
            model, jax.random.key(0), cfg.init_accumulator_value,
            cfg.adagrad_accumulator,
        )
    else:
        state = init_state(
            model, jax.random.key(0), cfg.init_accumulator_value, cfg.adagrad_accumulator
        )
    # [Train] tail: resolve auto → pallas-on-TPU / xla-elsewhere ONCE, up
    # front, so every step factory below (packed, rows, scanned, device
    # cache) sees the same resolved choice.  The Pallas tail applies to
    # the fused packed layout and the rows layout; auto quietly keeps xla
    # where the kernel has no contract (split packed accumulators,
    # dedup_gather_rows) — an EXPLICIT pallas there is a config error.
    from fast_tffm_tpu.ops.pallas_common import resolve_tail

    tail = resolve_tail(cfg.tail)
    if packed:
        predict_step = make_packed_predict_step(model, fused=fused)
        packed_tail = tail if fused else "xla"
        if packed_tail == "pallas":
            log("sparse tail: pallas (fused one-pass gather→Adagrad→scatter)")
        step_body = lambda mdl, lr, st, b: packed_train_step_body(
            mdl, lr, st, b, cfg.packed_update, cfg.packed_compact_cap,
            packed_tail,
        )
        step_fn = make_packed_train_step(
            model, cfg.learning_rate, cfg.packed_update,
            compact_cap=cfg.packed_compact_cap, tail=packed_tail,
        )
    else:
        predict_step = make_predict_step(model)
        # [Online] adagrad_decay: γ bakes into the step at trace time
        # (γ=1.0 leaves the classic program byte-for-byte — the
        # bit-identity the online tests pin).  Packed layouts reject
        # γ < 1 at config.validate, so the packed bodies stay untouched.
        decay = float(cfg.online_adagrad_decay)
        from fast_tffm_tpu.trainer import (
            make_decayed_body,
            make_dedup_body,
            make_pallas_tail_body,
        )

        if cfg.dedup_gather_rows > 0:
            # Device-side dedup-before-gather (ROADMAP item 2(a)): the
            # forward gather touches each unique row once; the stream's
            # host-side guard (_dedup_cap_guard) pins the cap.  Values —
            # and therefore losses — are bit-identical (test-pinned).
            step_body = make_dedup_body(cfg.dedup_gather_rows, decay)
        elif tail == "pallas":
            step_body = make_pallas_tail_body(decay)
            log("sparse tail: pallas (rows one-pass gather→Adagrad→scatter)")
        elif decay != 1.0:
            step_body = make_decayed_body(decay)
        else:
            step_body = None
        step_fn = make_train_step(
            model, cfg.learning_rate, decay=decay, body=step_body
        )
    if cfg.steps_per_call > 1 and not cfg.device_cache:
        # Streamed step fusion: ONE dispatch (and one H2D superbatch
        # transfer) per K steps.  The scan body is the same step body the
        # K=1 jit uses (packed or rows) — bit-identical per-step results.
        from fast_tffm_tpu.trainer import make_scanned_train_step

        step_fn = make_scanned_train_step(model, cfg.learning_rate, body=step_body)
    to_batch = _batch_converter(model.uses_fields)
    run_kwargs = dict(
        to_batch=to_batch, saveable=saveable, step_hook=step_hook,
        row_dim=model.row_dim,
    )
    if cfg.online_accum_restart_steps > 0:
        from fast_tffm_tpu.trainer import make_accum_restart

        run_kwargs["accum_restart"] = make_accum_restart(
            cfg.init_accumulator_value
        )
    if cfg.online_follow:
        # Tail-following online mode: the train file is an append-only
        # FMS stream (data/stream.py) — at EOF the reader polls for
        # growth instead of ending the epoch; bounded by
        # [Online] max_batches / idle_timeout_s, or by SIGTERM.
        from fast_tffm_tpu.data.stream import is_fms

        if len(cfg.train_files) != 1:
            raise ValueError(
                "[Online] follow = true takes exactly ONE train file (an "
                f"append-only FMS stream), got {len(cfg.train_files)}"
            )
        if not is_fms(cfg.train_files[0]):
            raise ValueError(
                f"[Online] follow = true needs an FMS stream file; "
                f"{cfg.train_files[0]!r} is not one (create and append "
                "with fast_tffm_tpu.data.stream.StreamWriter)"
            )
        # One stop Event PER STREAM, published through a shared holder:
        # the signal handler sets whichever stream is live, and an
        # abandoned stream (rollback re-entry) keeps its own latched
        # event — no clear() that could race the old producer's next
        # check.
        follow_stop_ref = [threading.Event()]
        run_kwargs["stream_stop"] = follow_stop_ref

        def _follow_train_stream(epoch, skip_batches=0):
            follow_stop_ref[0] = threading.Event()
            return _follow_stream(
                cfg, cfg.train_files, max_nnz, to_batch, skip_batches,
                stop=follow_stop_ref[0],
            )

        run_kwargs["train_stream"] = _follow_train_stream
    if cfg.device_cache:
        step_fn, train_stream, examples_per_step, mark_touched, ids_fn = (
            _device_cached_input(cfg, model, max_nnz, log, body=step_body)
        )
        run_kwargs.update(
            train_stream=train_stream, examples_per_step=examples_per_step,
            mark_touched=mark_touched, datastats_ids=ids_fn,
        )
    # on_nan = rollback: a non-finite loss restores the last checkpoint
    # and resumes input AT the detection cursor — the diverged window's
    # data is skipped, not replayed (bounded by max_rollbacks; abort mode
    # and a run with no checkpoint yet keep the loud-raise behavior).
    rollbacks = 0
    rollback_note = None
    while True:
        try:
            return _run_training(
                cfg, state, step_fn, predict_step, max_nnz, log,
                start_cursor=start_cursor, rollback=rollback_note,
                **run_kwargs,
            )
        except NonFiniteLossError as e:
            from fast_tffm_tpu.checkpoint import latest_step

            if (
                cfg.on_nan != "rollback"
                or rollbacks >= cfg.max_rollbacks
                or e.cursor is None
                or latest_step(cfg.model_file) is None
            ):
                raise
            rollbacks += 1
            state = restore_state()
            start_cursor = dict(e.cursor, _exact=True)
            rollback_note = {
                "step": e.step,
                "loss": e.loss,
                "rollback_n": rollbacks,
                "restored_step": int(state.step),
                "skip_to_epoch": int(e.cursor.get("epoch", 0)),
                "skip_to_batch": int(e.cursor.get("batch_in_epoch", 0)),
            }
            log(
                f"on_nan = rollback: non-finite loss at step {e.step}; "
                f"restored {cfg.model_file} (step {int(state.step)}), "
                f"skipping input to epoch {rollback_note['skip_to_epoch']} "
                f"batch {rollback_note['skip_to_batch']} "
                f"(rollback {rollbacks}/{cfg.max_rollbacks})"
            )


def _tiered_train(cfg: Config, *, resume: bool, log=print, step_hook=None):
    """[ParamStore] driver: local training over the two-tier parameter
    store (paramstore/) — a device-resident hot tier + the full logical
    table in a memmap-backed host cold store.  The jitted step is the
    UNCHANGED trainer step over the compact [C, D] table; everything
    tiered happens around it: the prefetch thread resolves each
    superbatch (dedup → hit/miss split → remap; paramstore.residency),
    miss rows ride the packed wire alongside the batch
    (paramstore.TieredConverter), updated staging rows write back through
    the pending overlay, and every checkpoint boundary spans both tiers
    (checkpoint_async + paramstore.ckpt).  Converts the scale ladder from
    "what fits in HBM" to "what fits on the host": 2^30+ rows on one
    chip, bit-identical to the resident path at overlapping vocab."""
    from fast_tffm_tpu.data.wire import make_spec
    from fast_tffm_tpu.ops.pallas_common import resolve_tail
    from fast_tffm_tpu.paramstore import TieredConverter, open_tiered_run
    from fast_tffm_tpu.trainer import (
        make_decayed_body,
        make_pallas_tail_body,
        make_scanned_train_step,
        make_train_step,
    )

    model = build_model(cfg)
    max_nnz = scan_max_nnz(cfg)
    server, state, start_cursor = open_tiered_run(
        cfg, model, max_nnz, resume=resume, log=log
    )
    decay = float(cfg.online_adagrad_decay)
    if resolve_tail(cfg.tail) == "pallas":
        # The tiered inner step already runs over the compact [C, D]
        # staging table with remapped slot ids — exactly the rows-layout
        # operands the kernel takes, so the SAME body serves both tiers.
        body = make_pallas_tail_body(decay)
        log("sparse tail: pallas (one-pass kernel over the compact tier)")
    else:
        body = make_decayed_body(decay) if decay != 1.0 else None
    if cfg.steps_per_call > 1:
        inner = make_scanned_train_step(model, cfg.learning_rate, body=body)
    else:
        inner = make_train_step(model, cfg.learning_rate, decay=decay, body=body)
    step_fn = server.wrap_step(inner)
    # The wire spec lives at the COMPACT capacity: ids narrow to the
    # local slot range (e.g. 3 bytes for a 2^30 logical vocab whose
    # compact tier holds < 2^24 slots).
    spec = make_spec(
        server.capacity, max_nnz,
        with_vals=True, with_fields=model.uses_fields, with_weights=True,
    )
    to_batch = TieredConverter(server, spec)

    def train_stream(epoch, skip_batches=0):
        return _stream(
            cfg, cfg.train_files, max_nnz, epochs=1, to_batch=to_batch,
            shuffle_epoch=epoch, steps_per_call=cfg.steps_per_call,
            skip_batches=skip_batches,
        )

    def evaluate(cfg_, _predict_step, st, files, max_nnz_):
        # Residency-aware scoring: hot rows off the live compact state,
        # miss rows staged read-only through the pending overlay — no
        # state mutation, so the train state threads through untouched.
        server.flush_writeback(st)
        stream = _stream(cfg_, files, max_nnz_, epochs=1, weights=None)
        meter = StreamingAUC()
        for _b, parsed, w in stream:
            scores = np.asarray(server.predict(st, parsed, w))
            ww = np.ones_like(parsed.labels) if w is None else np.asarray(w)
            meter.add(parsed.labels, scores, ww)
        return meter.value()

    def predict_step(_state, _batch):  # pragma: no cover - guard only
        raise RuntimeError(
            "tiered runs score through the residency-aware evaluate path"
        )

    return _run_training(
        cfg, state, step_fn, predict_step, max_nnz, log,
        train_stream=train_stream, to_batch=to_batch, evaluate=evaluate,
        step_hook=step_hook, row_dim=model.row_dim,
        start_cursor=start_cursor, paramstore=server,
    )


def _device_cached_input(cfg: Config, model, max_nnz: int, log, body=None):
    """device_cache = true: the train set becomes device-resident arrays
    sliced on-chip per step — zero per-step host→device bytes (the
    streamed alternative moves every batch through the host every epoch;
    on the bench regime that is a ~300× throughput gap, README
    "Benchmarks").  Input must be FMB-backed: .fmb train_files directly,
    or binary_cache = true to convert text once.  Returns
    ``(step_fn, train_stream, examples_per_step)`` for _run_training; the
    emitted "batch" is a device batch-index scalar and the jitted step
    fuses the batch slice (or the shuffled gather) with the model step.
    """
    from fast_tffm_tpu.data.device_cache import (
        epoch_index_chunks,
        full_epoch_perm,
        load_device_dataset,
        make_cached_ids_slicer,
        make_cached_scan_train_step,
        make_cached_touched_marker,
        make_cached_train_step,
    )

    files = tuple(cfg.train_files)
    if cfg.binary_cache:
        from fast_tffm_tpu.data.binary import ensure_fmb_cache

        files = ensure_fmb_cache(
            files,
            vocabulary_size=cfg.vocabulary_size,
            hash_feature_id=cfg.hash_feature_id,
            max_nnz=max_nnz,
            parser=best_parser(cfg.thread_num),
        )
    if not binary_input(files):
        raise ValueError(
            "device_cache = true needs FMB-backed input: list .fmb files in "
            "train_files, or set binary_cache = true to convert text once"
        )
    data = load_device_dataset(
        files,
        batch_size=cfg.batch_size,
        vocabulary_size=cfg.vocabulary_size,
        hash_feature_id=cfg.hash_feature_id,
        max_nnz=max_nnz,
        weights=cfg.weight_files if cfg.weight_files else None,
        with_fields=model.uses_fields,
    )
    log(
        f"device cache: {data.n_rows} rows resident "
        f"({data.nbytes / 2**20:.1f} MiB, {data.batches} batches/epoch)"
    )
    perm_ref = [None]

    def _maybe_draw_perm(epoch):
        if cfg.shuffle:
            perm_ref[0] = jax.device_put(
                full_epoch_perm(data, cfg.shuffle_seed, epoch)
            )

    # Delta-checkpoint touched-row marking: the per-step "batch" here is a
    # resident index (scalar or [K] chunk), so the marker slices the ids
    # ON DEVICE (through the epoch permutation when shuffled) — handles
    # both the per-step and the scan-fused stream shapes.
    _mark, _mark_shuffled = make_cached_touched_marker(data)

    def mark_touched(bitmap, i):
        if perm_ref[0] is not None:
            return _mark_shuffled(bitmap, perm_ref[0], i)
        return _mark(bitmap, i)

    if cfg.steps_per_call > 1:
        # Scan-fused epochs: the per-call "input" is a pre-placed [K]
        # index vector (remainder-tail vector included), so an epoch is
        # ceil(batches/K) dispatches with zero host involvement between
        # the K resident-slice steps inside each one.
        stepk, stepk_shuffled = make_cached_scan_train_step(
            model, cfg.learning_rate, data, body=body
        )
        chunks = epoch_index_chunks(data.batches, cfg.steps_per_call)

        def train_stream(epoch, skip_batches=0):
            _maybe_draw_perm(epoch)
            # Resume seek: regenerate the chunk list from the cursor's
            # batch (same K-grid, so full chunks re-hit compiled shapes).
            use = (
                chunks
                if not skip_batches
                else epoch_index_chunks(
                    data.batches, cfg.steps_per_call, start=skip_batches
                )
            )
            return ((c, None, None) for c in use)

        def step_fn(state, idxs):
            if perm_ref[0] is not None:
                return stepk_shuffled(state, perm_ref[0], idxs)
            return stepk(state, idxs)

        def _lower_k(st, idxs):
            # Measured-cost hook (profiling.CostLedger): expose the inner
            # jit's .lower so the closure stays profileable.
            if perm_ref[0] is not None:
                return stepk_shuffled.lower(st, perm_ref[0], idxs)  # analysis: ok recompile-hazard this IS the ledger's delegated .lower hook
            return stepk.lower(st, idxs)  # analysis: ok recompile-hazard this IS the ledger's delegated .lower hook

        step_fn.lower = _lower_k
        return (
            step_fn, train_stream, cfg.batch_size, mark_touched,
            make_cached_ids_slicer(data),
        )

    cached_step, cached_step_shuffled = make_cached_train_step(
        model, cfg.learning_rate, data, body=body
    )
    # Batch indices as pre-placed device scalars: the per-step "input" is
    # an index that is already on device — no per-step H2D at all.
    idx = [jax.device_put(np.int32(i)) for i in range(data.batches)]

    def train_stream(epoch, skip_batches=0):
        _maybe_draw_perm(epoch)
        return ((idx[i], None, None) for i in range(skip_batches, data.batches))

    def step_fn(state, i):
        if perm_ref[0] is not None:
            return cached_step_shuffled(state, perm_ref[0], i)
        return cached_step(state, i)

    def _lower(st, i):
        if perm_ref[0] is not None:
            return cached_step_shuffled.lower(st, perm_ref[0], i)  # analysis: ok recompile-hazard this IS the ledger's delegated .lower hook
        return cached_step.lower(st, i)  # analysis: ok recompile-hazard this IS the ledger's delegated .lower hook

    step_fn.lower = _lower
    return (
        step_fn, train_stream, cfg.batch_size, mark_touched,
        make_cached_ids_slicer(data),
    )


def dist_train(cfg: Config, *, resume: bool = False, log=print, mesh=None, step_hook=None):
    """Mesh-distributed training — the reference's `dist_train` mode.

    One SPMD program over all visible chips; no job_name/task_index because
    there is no ps/worker split to schedule — the mesh IS the cluster.

    Multi-host pods additionally shard the INPUT: process p parses only
    rows [p·B/P, (p+1)·B/P) of each global batch (block-cyclic line
    sharding), and the per-process chunks are stitched into global arrays —
    host parse throughput scales with the host count, the way the
    reference spread input files across its workers.  The global non-blank
    line count is taken up front so every process runs the same number of
    collective steps per epoch (short shards pad with weight-0 batches).
    """
    from fast_tffm_tpu.parallel import (
        check_batch_divides,
        init_sharded_state,
        make_global_batch,
        make_mesh,
        make_replicator,
        make_sharded_predict_step,
        make_sharded_train_step,
    )
    from fast_tffm_tpu.distributed import initialize_runtime

    if not cfg.train_files:
        raise ValueError("no train_files configured")
    if cfg.tail == "pallas":
        # Loud, not silent: a run that pins the Pallas tail but launches
        # the sharded driver would measure the XLA tail and call it
        # pallas.  (``auto`` resolves to xla here — the sharded step's
        # collective tail is not the kernel's contract yet.)
        raise ValueError(
            "tail = pallas is not supported by dist_train yet (the "
            "sharded step keeps the XLA sparse tail); use tail = auto "
            "or xla for distributed runs"
        )
    if cfg.weight_files and len(cfg.weight_files) != len(cfg.train_files):
        # Checked here, not in Config.validate: a shared config must still
        # LOAD on predict-only machines where train-file globs match
        # differently (or not at all).
        raise ValueError(
            f"weight_files has {len(cfg.weight_files)} entries for "
            f"{len(cfg.train_files)} train_files (they align per-file)"
        )
    # Pod bring-up: jax.distributed initialize (config keys, TPU metadata,
    # or the supervisor's generation file), gloo CPU collectives, the
    # coordination runtime (KV + barriers), heartbeats, and — under the
    # pod supervisor — the generation watcher that re-execs this host into
    # the next pod incarnation when a peer is replaced.
    runtime = initialize_runtime(cfg, log=log)
    if cfg.paramstore:
        # The tiered store's residency/writeback protocol is single-host
        # (the pending overlay and the cold store live on ONE host);
        # sharding the hot tier over a mesh is ROADMAP follow-up work.
        raise ValueError(
            "[ParamStore] is local-train only; dist_train shards the "
            "table over the mesh instead (drop [ParamStore] enabled, or "
            "run `train`)"
        )
    if cfg.dedup_gather_rows > 0:
        # The sharded step's gather happens inside the lookup collectives
        # (allgather/alltoall) — the local dedup body does not apply.
        raise ValueError(
            "dedup_gather_rows is local-train only (the sharded lookup "
            "collectives have their own dedup story)"
        )
    if cfg.online_follow:
        # The follow reader is single-process by construction: an
        # append-only stream has no stable row count to shard, and the
        # fixed-steps-per-epoch padding multi-host input relies on cannot
        # exist for a file that grows.  (ROADMAP item 5's per-tenant delta
        # streams are the multi-host follow-up.)
        raise ValueError(
            "[Online] follow = true is single-process (train); dist_train "
            "cannot shard an append-only stream"
        )
    if cfg.online_accum_restart_steps > 0:
        # The reset program's output sharding is not pinned to the mesh
        # layout yet — reject loudly rather than risk a silent reshard.
        raise ValueError(
            "[Online] accum_restart_steps is single-process (train) for "
            "now; use adagrad_decay on pods"
        )
    if cfg.device_cache and cfg.shuffle:
        # A shuffled gather across the mesh-sharded batch dim would move
        # rows between chips every step — exactly the per-step traffic
        # this mode exists to eliminate.  (Local `train` shuffles fine.)
        raise ValueError(
            "device_cache with shuffle is local-train only; dist_train "
            "slices the resident epoch sequentially (drop shuffle, or "
            "pre-shuffle at convert time)"
        )
    model = build_model(cfg)
    max_nnz = scan_max_nnz(cfg)
    if mesh is None:
        row = cfg.row_parallel or cfg.vocabulary_block_num
        data = cfg.data_parallel or None
        mesh = make_mesh(data, row)
    log(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))} on {mesh.devices.size} devices")
    check_batch_divides(cfg.batch_size, mesh)
    def restore_state():
        """model_file -> this run's live sharded layout.  Shared by
        --resume and the on_nan=rollback recovery loop below.  Packed
        runs restore the LOGICAL checkpoint into a rows-layout template
        and convert per shard ON DEVICE — no throwaway packed random
        init, no host gather (multi-host packed resume works: each
        process restores and packs only its own shards).  The template
        uses the PACKED padding so a same-mesh packed checkpoint
        restores in place; other paddings go through restore's re-pad
        path (single-host) or its loud multi-host shape error."""
        if cfg.table_layout == "packed":
            from fast_tffm_tpu.parallel import pack_sharded_on_device
            from fast_tffm_tpu.parallel.train_step import packed_shard_meta

            fused_acc = cfg.adagrad_accumulator == "fused"
            padded_model, _, _ = packed_shard_meta(model, mesh, fused=fused_acc)
            logical = restore_checkpoint(
                cfg.model_file,
                init_sharded_state(
                    padded_model, mesh, jax.random.key(0), cfg.init_accumulator_value,
                    cfg.adagrad_accumulator,
                ),
                chunk_bytes=cfg.checkpoint_chunk_mb << 20,
            )
            return pack_sharded_on_device(
                logical, model, mesh, cfg.init_accumulator_value, fused=fused_acc
            )
        return restore_checkpoint(
            cfg.model_file,
            init_sharded_state(
                model, mesh, jax.random.key(0), cfg.init_accumulator_value,
                cfg.adagrad_accumulator, table_layout=cfg.table_layout,
            ),
            chunk_bytes=cfg.checkpoint_chunk_mb << 20,
        )

    if resume and not (
        os.path.isfile(cfg.model_file) or os.path.isdir(cfg.model_file)
    ):
        # A pod relaunch/re-exec forces --resume unconditionally, but a
        # crash DURING the very first publish legitimately leaves no
        # checkpoint at all (only a tmp file) — every host observes the
        # same absence on the shared filesystem and starts fresh; the
        # restore agreement below pins that they all did.
        log(
            f"warning: --resume but no checkpoint at {cfg.model_file} — "
            "starting fresh (crash before the first publish?)"
        )
        resume = False
    start_cursor = None
    if resume:
        state = restore_state()
        log(f"resumed from {cfg.model_file} at step {int(state.step)}")
        # Exact-position resume (every process reads the same shared
        # cursor vector, so all shards reopen at the same global batch).
        start_cursor = read_input_cursor(cfg.model_file)
        if start_cursor is None:
            log(
                "note: checkpoint carries no input cursor (pre-resilience "
                "format) — input restarts at the first file (legacy resume)"
            )
    else:
        state = init_sharded_state(
            model, mesh, jax.random.key(0), cfg.init_accumulator_value,
            cfg.adagrad_accumulator, table_layout=cfg.table_layout,
        )
    ckpt_is_npz = cfg.checkpoint_format == "npz" and not os.path.isdir(cfg.model_file)
    if runtime.active:
        # Restore barrier: no host proceeds into collectives until every
        # host holds the SAME restored step, chain head, and cursor — a
        # desynced pod must die here, loudly, not train garbage.
        head = None
        if ckpt_is_npz and resume:
            from fast_tffm_tpu.checkpoint import read_delta_chain

            try:
                base_sig, chain = read_delta_chain(cfg.model_file)
                head = chain[-1]["save_id"] if chain else base_sig
            except (ValueError, OSError):
                head = None
        runtime.agree(
            "restore",
            {
                "step": int(state.step),
                "head": head,
                "cursor": [
                    (start_cursor or {}).get("epoch"),
                    (start_cursor or {}).get("batch_in_epoch"),
                ],
            },
        )
    step_fn = make_sharded_train_step(
        model, cfg.learning_rate, mesh,
        lookup=cfg.lookup, capacity_factor=cfg.lookup_capacity_factor,
        overflow_mode=cfg.lookup_overflow, table_layout=cfg.table_layout,
        packed_update=cfg.packed_update,
        accumulator=cfg.adagrad_accumulator,
        compact_cap=cfg.packed_compact_cap,
        # With device_cache the scan lives in the cached wrapper below
        # (it slices resident batches); the raw SPMD step stays per-batch.
        steps_per_call=(1 if cfg.device_cache else cfg.steps_per_call),
        adagrad_decay=cfg.online_adagrad_decay,
    )
    predict_step = make_sharded_predict_step(
        model, mesh, lookup=cfg.lookup, capacity_factor=cfg.lookup_capacity_factor,
        overflow_mode=cfg.lookup_overflow, table_layout=cfg.table_layout,
        accumulator=cfg.adagrad_accumulator,
    )
    dist_saveable = None
    if cfg.table_layout == "packed":
        # Checkpoints hold LOGICAL [V, D] arrays.  Multi-process: unpack
        # per shard ON DEVICE — the result is a row-sharded logical state
        # orbax writes per host in parallel (no host gather of
        # non-addressable shards).  Single-process: unpack through HOST
        # RAM instead — the on-device unpack would materialize a full
        # logical copy of table+accumulator NEXT TO the live packed state
        # at every save, a ~2× transient HBM peak that OOMs exactly the
        # big-table runs (ADVICE r4).
        from fast_tffm_tpu.parallel import (
            unpack_sharded_on_device,
            unpack_sharded_to_logical,
        )

        if jax.process_count() > 1:
            def dist_saveable(st):
                return unpack_sharded_on_device(st, model, mesh)
        else:
            def dist_saveable(st):
                return unpack_sharded_to_logical(st, model, mesh)

    if jax.process_count() > 1 and ckpt_is_npz:
        # Multi-host npz single-writer protocol: the saveable additionally
        # REPLICATES the logical state (one collective every host
        # dispatches) so process 0 holds complete arrays to stream to
        # disk.  Full-table-per-host memory — the modest-table path; use
        # orbax beyond that (DESIGN §8).
        replicate = make_replicator(mesh)
        inner_saveable = dist_saveable

        if inner_saveable is not None:
            def dist_saveable(st, _inner=inner_saveable):
                return replicate(_inner(st))
        else:
            dist_saveable = replicate

    cached_data = None
    if cfg.device_cache:
        # Mesh-sharded resident dataset: same zero-per-step-H2D contract
        # as the local path, with each batch's rows sharded over every
        # chip and the slice fused into the SPMD step.  Wraps the RAW
        # jitted step (the slice traces inside jit); the overflow
        # accumulator below then wraps at the Python level as usual.
        from fast_tffm_tpu.data.device_cache import (
            load_sharded_device_dataset,
            make_cached_sharded_train_step,
        )

        files = tuple(cfg.train_files)
        if cfg.binary_cache:
            from fast_tffm_tpu.data.binary import ensure_fmb_cache

            files = ensure_fmb_cache(
                files,
                vocabulary_size=cfg.vocabulary_size,
                hash_feature_id=cfg.hash_feature_id,
                max_nnz=max_nnz,
                parser=best_parser(cfg.thread_num),
            )
        if not binary_input(files):
            raise ValueError(
                "device_cache = true needs FMB-backed input: list .fmb "
                "files in train_files, or set binary_cache = true"
            )
        cached_data = load_sharded_device_dataset(
            files,
            mesh=mesh,
            batch_size=cfg.batch_size,
            vocabulary_size=cfg.vocabulary_size,
            hash_feature_id=cfg.hash_feature_id,
            max_nnz=max_nnz,
            weights=cfg.weight_files if cfg.weight_files else None,
            with_fields=model.uses_fields,
        )
        log(
            f"device cache: {cached_data.n_rows} rows resident, sharded "
            f"over {mesh.devices.size} devices "
            f"({cached_data.nbytes / 2**20:.1f} MiB total, "
            f"{cached_data.batches} batches/epoch)"
        )
        step_fn = make_cached_sharded_train_step(
            step_fn, cached_data, steps_per_call=cfg.steps_per_call,
            overflow_flagged=(
                cfg.lookup == "alltoall" and cfg.lookup_overflow == "fallback"
            ),
        )

    mark_touched = None
    if cached_data is not None and cfg.delta_every_steps > 0:
        # Delta checkpoints on the resident path mark touched rows from
        # the sharded id arrays on device (dist_train disallows shuffle,
        # so the plain sequential-slice marker is the only one needed).
        from fast_tffm_tpu.data.device_cache import make_cached_touched_marker

        mark_touched, _ = make_cached_touched_marker(cached_data)

    extra_metrics = None
    if cfg.lookup == "alltoall" and cfg.lookup_overflow == "fallback":
        # The fallback step returns a replicated overflow flag; fold it into
        # ONE running device scalar (no host sync, no per-step buffer — a
        # pending list would pin a live device scalar per step between log
        # points) and fetch/reset it only at log points.
        raw_step = step_fn
        overflow_sum = [None]

        def step_fn(state, b):
            state, loss, overflowed = raw_step(state, b)
            overflow_sum[0] = (
                overflowed if overflow_sum[0] is None else overflow_sum[0] + overflowed
            )
            return state, loss

        if hasattr(raw_step, "lower"):
            # Keep the wrapped step profileable (measured cost ledger).
            step_fn.lower = raw_step.lower

        def extra_metrics():
            n = int(overflow_sum[0]) if overflow_sum[0] is not None else 0
            overflow_sum[0] = None
            return {"lookup_overflow_steps": n}

    train_stream = examples_per_step = evaluate = None
    to_batch = _batch_converter(model.uses_fields)
    if cached_data is not None:
        if cfg.steps_per_call > 1:
            # Per-call "input" is a pre-placed [K] index vector (tail
            # remainder included) — epoch_index_chunks as on the local
            # cached path.
            from fast_tffm_tpu.data.device_cache import epoch_index_chunks

            chunks = epoch_index_chunks(cached_data.batches, cfg.steps_per_call)

            def train_stream(epoch, skip_batches=0):
                use = (
                    chunks
                    if not skip_batches
                    else epoch_index_chunks(
                        cached_data.batches, cfg.steps_per_call,
                        start=skip_batches,
                    )
                )
                return ((c, None, None) for c in use)

        else:
            # Per-step "input" is a pre-placed device index scalar.
            idx = [jax.device_put(np.int32(i)) for i in range(cached_data.batches)]

            def train_stream(epoch, skip_batches=0):
                return (
                    (idx[i], None, None)
                    for i in range(skip_batches, cached_data.batches)
                )

        examples_per_step = cfg.batch_size
    nproc = jax.process_count()
    if nproc > 1:
        from fast_tffm_tpu.data.native import count_lines

        if cfg.batch_size % nproc:
            raise ValueError(
                f"batch_size {cfg.batch_size} not divisible by "
                f"{nproc} processes (it is the GLOBAL batch)"
            )
        local_bs = cfg.batch_size // nproc
        pid = jax.process_index()

        if cached_data is None:
            # (device_cache keeps its resident index stream — each
            # process already staged only its rows at load time; only
            # the STREAMED path shards the text/FMB stream per step, and
            # only it needs the up-front row counts for the fixed
            # steps-per-epoch padding.)
            if cfg.input_assignment == "files":
                # Shard-disjoint FILE assignment: host p streams files
                # [p::P] whole — each host opens and reads only its own
                # files (no cross-file seeking through the peers' data),
                # the pod-scale input shape.  Global batch k is the
                # stitch of every host's k-th local batch; short hosts
                # pad the epoch tail with weight-0 batches so every host
                # runs the same number of collective steps.
                files_all = tuple(cfg.train_files)
                if len(files_all) < nproc:
                    raise ValueError(
                        f"input_assignment = files needs at least one train "
                        f"file per process ({len(files_all)} files, {nproc} "
                        "processes) — split the dataset or use "
                        "input_assignment = rows"
                    )
                my_files = files_all[pid::nproc]
                # Per-file example weights align with the FULL train file
                # list; this host's stream sees only its own files, so the
                # weights slice with the same stride.
                my_weights = (
                    tuple(cfg.weight_files)[pid::nproc]
                    if cfg.weight_files
                    else None
                )
                # Each host counts only ITS files (the mode's whole point
                # is not touching the peers' data) and the per-host row
                # counts meet through the pod KV store; without a
                # coordination backend, fall back to counting everything.
                if runtime.active:
                    per_host_rows = [
                        int(r)
                        for r in runtime.allgather(
                            "files-rows", count_lines(my_files)
                        )
                    ]
                else:
                    per_host_rows = [
                        count_lines(files_all[p::nproc]) for p in range(nproc)
                    ]
                steps_per_epoch = max(-(-r // local_bs) for r in per_host_rows)
                log(
                    "input sharding: shard-disjoint files — host "
                    f"{pid} owns {len(my_files)} file(s) / "
                    f"{per_host_rows[pid]} rows, {steps_per_epoch} "
                    f"steps/epoch, {local_bs} rows/process/step"
                )

                def train_stream(epoch, skip_batches=0):
                    return _stream(
                        cfg,
                        my_files,
                        max_nnz,
                        epochs=1,
                        batch_size=local_bs,
                        weights=my_weights,
                        pad_to_batches=steps_per_epoch,
                        to_batch=to_batch,
                        shuffle_epoch=epoch,
                        steps_per_call=cfg.steps_per_call,
                        skip_batches=skip_batches,
                    )

            else:
                total = count_lines(cfg.train_files)
                steps_per_epoch = -(-total // cfg.batch_size)  # ceil
                log(
                    f"input sharding: {total} rows over {nproc} processes, "
                    f"{steps_per_epoch} steps/epoch, {local_bs} rows/process/step"
                )

                def train_stream(epoch, skip_batches=0):
                    return _stream(
                        cfg,
                        cfg.train_files,
                        max_nnz,
                        epochs=1,
                        batch_size=local_bs,
                        shard_index=pid,
                        shard_count=nproc,
                        shard_block=local_bs,
                        pad_to_batches=steps_per_epoch,
                        to_batch=to_batch,
                        shuffle_epoch=epoch,
                        steps_per_call=cfg.steps_per_call,
                        skip_batches=skip_batches,
                    )

        def to_batch(parsed, w):
            if isinstance(parsed, list):  # K local chunks -> [K, B, ...] global
                from fast_tffm_tpu.parallel import make_global_superbatch

                return make_global_superbatch(
                    mesh, parsed, w, with_fields=model.uses_fields
                )
            return make_global_batch(mesh, parsed, w, with_fields=model.uses_fields)

        to_batch.uses_fields = model.uses_fields
        # Host-local packed-wire staging (PR 3's wire, already per-host by
        # construction): when the stream is FMB-backed and wire_format =
        # packed, _stream swaps this stitch for a WireGlobalConverter —
        # each host ships ONE coalesced buffer to its own devices and the
        # per-device shards assemble straight into the global batch.
        to_batch.wire_capable = True

        def _make_wire(spec):
            from fast_tffm_tpu.parallel import WireGlobalConverter

            return WireGlobalConverter(mesh, spec)

        to_batch.make_wire_converter = _make_wire

        examples_per_step = cfg.batch_size

        # Validation is sharded the same way.  Scores come back replicated
        # from the sharded predict step; the (tiny, [B]) label/weight
        # vectors are resharded to replicated on device so every process
        # can compute the GLOBAL AUC (weight-0 padding rows drop out).
        from jax.sharding import NamedSharding, PartitionSpec

        replicate = jax.jit(
            lambda x: x, out_shardings=NamedSharding(mesh, PartitionSpec())
        )
        val_steps = (
            -(-count_lines(cfg.validation_files) // cfg.batch_size)
            if cfg.validation_files
            else 0
        )

        def evaluate(cfg, predict_step, state, files, max_nnz):
            return _evaluate(
                cfg,
                predict_step,
                state,
                files,
                max_nnz,
                stream=_stream(
                    cfg,
                    files,
                    max_nnz,
                    epochs=1,
                    weights=None,
                    batch_size=local_bs,
                    shard_index=pid,
                    shard_count=nproc,
                    shard_block=local_bs,
                    pad_to_batches=val_steps,
                    to_batch=to_batch,
                ),
                to_batch=to_batch,
                fetch=lambda b, parsed, w: (
                    np.asarray(replicate(b.labels)),
                    np.asarray(replicate(b.weights)),
                ),
            )

    run_kwargs = dict(
        train_stream=train_stream,
        to_batch=to_batch,
        examples_per_step=examples_per_step,
        evaluate=evaluate,
        extra_metrics=extra_metrics,
        saveable=dist_saveable,
        step_hook=step_hook,
        row_dim=model.row_dim,
        mark_touched=mark_touched,
        runtime=runtime,
        mesh=mesh,
    )
    # on_nan = rollback, now legal under dist_train: the loss every host
    # checks is REPLICATED (identical), so every host raises
    # NonFiniteLossError at the same step with the same cursor; the
    # rollback barrier below makes the agreement explicit before any host
    # touches the checkpoint, then all processes restore the same chain
    # head and resume input at the same cursor vector.
    rollbacks = 0
    rollback_note = None
    while True:
        try:
            return _run_training(
                cfg, state, step_fn, predict_step, max_nnz, log,
                start_cursor=start_cursor, rollback=rollback_note,
                **run_kwargs,
            )
        except NonFiniteLossError as e:
            from fast_tffm_tpu.checkpoint import latest_step

            if (
                cfg.on_nan != "rollback"
                or rollbacks >= cfg.max_rollbacks
                or e.cursor is None
                or latest_step(cfg.model_file) is None
            ):
                raise
            rollbacks += 1
            # The cross-process rollback barrier: rendezvous BEFORE the
            # restore so no host can re-enter collectives against peers
            # still unwinding the failed attempt.
            runtime.barrier(f"rollback-{rollbacks}")
            state = restore_state()
            head = None
            if ckpt_is_npz:
                from fast_tffm_tpu.checkpoint import read_delta_chain

                try:
                    base_sig, chain = read_delta_chain(cfg.model_file)
                    head = chain[-1]["save_id"] if chain else base_sig
                except (ValueError, OSError):
                    head = None
            runtime.agree(
                f"rollback-head-{rollbacks}",
                {
                    "step": int(state.step),
                    "head": head,
                    "cursor": [
                        e.cursor.get("epoch"),
                        e.cursor.get("batch_in_epoch"),
                    ],
                },
            )
            # Fresh KV namespace: the next attempt's checkpoint boundary
            # ordinals must not collide with the aborted attempt's keys.
            runtime.advance_namespace()
            start_cursor = dict(e.cursor, _exact=True)
            rollback_note = {
                "step": e.step,
                "loss": e.loss,
                "rollback_n": rollbacks,
                "restored_step": int(state.step),
                "skip_to_epoch": int(e.cursor.get("epoch", 0)),
                "skip_to_batch": int(e.cursor.get("batch_in_epoch", 0)),
            }
            log(
                f"on_nan = rollback: non-finite loss at step {e.step}; "
                f"restored {cfg.model_file} (step {int(state.step)}), "
                f"skipping input to epoch {rollback_note['skip_to_epoch']} "
                f"batch {rollback_note['skip_to_batch']} "
                f"(rollback {rollbacks}/{cfg.max_rollbacks})"
            )
