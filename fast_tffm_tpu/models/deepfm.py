"""DeepFM: shared-embedding FM + MLP head (BASELINE.json config #4).

An extension target in the reference project's lineage, built natively: the
FM half is the fused order-2 kernel over the shared embedding table; the
deep half is a 3-layer MLP over the value-weighted embedding vectors of the
example's (fixed-count) feature slots — dense XLA matmuls that land on the
MXU.  Both halves read the SAME table rows, so one gather and one sparse
Adagrad scatter serve both (the SparseCore-lookup + dense-XLA-MLP split in
BASELINE.json's config #4).

Requires a fixed slot count per example (max_nnz = field count, the Criteo
shape); padding slots contribute zero embeddings.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fast_tffm_tpu.models.base import Batch, masked_l2
from fast_tffm_tpu.ops.fm import fm_score


@dataclasses.dataclass(frozen=True)
class DeepFMModel:
    vocabulary_size: int
    num_fields: int  # fixed feature slots per example (= max_nnz)
    factor_num: int = 8
    hidden_dims: tuple[int, ...] = (400, 400, 400)  # 3-layer MLP head
    init_value_range: float = 0.01
    factor_lambda: float = 0.0
    bias_lambda: float = 0.0
    # MXU-native precision for the MLP matmuls: params/optimizer state stay
    # float32 (master weights); activations and weights are cast per-matmul
    # and products accumulate in float32 (preferred_element_type).  The FM
    # half and the embedding table are untouched — they are HBM-bound
    # gathers + VPU elementwise work, not MXU work.
    compute_dtype: str = "float32"  # float32 | bfloat16

    uses_fields = False  # slots are positional (num_fields = max_nnz)

    @property
    def row_dim(self) -> int:
        return 1 + self.factor_num

    def init_table(self, key: jax.Array) -> jax.Array:
        factors = jax.random.uniform(
            key,
            (self.vocabulary_size, self.factor_num),
            minval=-self.init_value_range,
            maxval=self.init_value_range,
            dtype=jnp.float32,
        )
        bias = jnp.zeros((self.vocabulary_size, 1), jnp.float32)
        return jnp.concatenate([bias, factors], axis=-1)

    def init_dense(self, key: jax.Array):
        dims = (self.num_fields * self.factor_num, *self.hidden_dims, 1)
        params = {}
        for li, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
            key, wk = jax.random.split(key)
            # He init for the ReLU stack.
            params[f"w{li}"] = jax.random.normal(wk, (d_in, d_out), jnp.float32) * jnp.sqrt(
                2.0 / d_in
            )
            params[f"b{li}"] = jnp.zeros((d_out,), jnp.float32)
        return params

    def _mlp(self, dense, x: jax.Array) -> jax.Array:
        n_layers = len(self.hidden_dims) + 1
        dt = jnp.dtype(self.compute_dtype)
        for li in range(n_layers):
            x = jnp.dot(
                x.astype(dt),
                dense[f"w{li}"].astype(dt),
                preferred_element_type=jnp.float32,
            ) + dense[f"b{li}"]
            if li < n_layers - 1:
                x = jax.nn.relu(x)
        return x[..., 0]  # [B]

    def score(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        B, N = batch.vals.shape
        fm_part = fm_score(rows, batch.vals, order=2)
        emb = rows[..., 1:] * batch.vals[..., None]  # [B, N, k] value-weighted
        deep_part = self._mlp(dense, emb.reshape(B, N * self.factor_num))
        return fm_part + deep_part

    def regularization(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        del dense  # reference regularizes only the FM parameters
        return masked_l2(rows, batch.vals, self.bias_lambda, self.factor_lambda)
