"""Model interface shared by FM / FFM / DeepFM.

Every model owns one sparse parameter table ``[vocabulary_size, row_dim]``
(the reference's block-partitioned embedding-parameter variable — bias and
factors packed per row, `renyi533/fast_tffm` :: model-graph builder) plus an
optional pytree of dense parameters (empty for FM/FFM; the MLP for DeepFM).

The training loop is model-agnostic: it gathers rows for a batch, calls
``score(rows, dense, batch)``, and routes row gradients into the sparse
Adagrad path and dense gradients into the dense path.  Keeping the gather
OUTSIDE the model is the same narrow waist the reference draws between its
lookup and its scorer op — and it is what lets the parallel layer swap in a
mesh-sharded gather without touching the models.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Batch:
    """Device-side mirror of data.libsvm.ParsedBatch (jnp arrays)."""

    labels: jax.Array  # [B] f32
    ids: jax.Array  # [B, N] i32
    vals: jax.Array  # [B, N] f32 (0 = padding)
    fields: jax.Array  # [B, N] i32 ([B, 0] when the model ignores fields)
    weights: jax.Array  # [B] f32 example weights (0 = padded row)

    @staticmethod
    def from_parsed(parsed, weights=None, *, with_fields: bool = True):
        """Host ParsedBatch → device Batch (the per-step H2D transfer).

        ``with_fields=False`` ships a [B, 0] placeholder instead of the
        [B, N] field matrix — only FFM reads ``fields`` (Model.uses_fields),
        and for the other models the all-zero int32 matrix is a third of
        the transferred bytes on an input-bound host.
        """
        import numpy as np

        w = np.ones_like(parsed.labels) if weights is None else weights
        fields = (
            parsed.fields
            if with_fields
            else np.zeros((parsed.fields.shape[0], 0), np.int32)
        )
        return Batch(
            labels=jnp.asarray(parsed.labels),
            ids=jnp.asarray(parsed.ids.astype(np.int32, copy=False)),
            vals=jnp.asarray(parsed.vals),
            fields=jnp.asarray(fields),
            weights=jnp.asarray(w),
        )

    @staticmethod
    def stack_parsed(parsed_seq, weights_seq=None, *, with_fields: bool = True):
        """K host ParsedBatches → ONE device superbatch [K, B, ...].

        The step-fusion staging path (``steps_per_call`` > 1): the K
        batches are stacked on the HOST first, so each field crosses the
        host→device link once per K steps instead of once per step — the
        transfer analog of the scan's one-dispatch-per-K.  Fields follow
        ``from_parsed``'s skipping rule ([K, B, 0] when unused).  The
        scanned train step (trainer.make_scanned_train_step) slices
        micro-batch k back out on device via ``lax.scan``.
        """
        import numpy as np

        if weights_seq is None:
            weights_seq = [None] * len(parsed_seq)
        k = len(parsed_seq)
        b = parsed_seq[0].labels.shape[0]
        return Batch(
            labels=jnp.asarray(np.stack([p.labels for p in parsed_seq])),
            ids=jnp.asarray(
                np.stack([p.ids.astype(np.int32, copy=False) for p in parsed_seq])
            ),
            vals=jnp.asarray(np.stack([p.vals for p in parsed_seq])),
            fields=jnp.asarray(
                np.stack([p.fields for p in parsed_seq])
                if with_fields
                else np.zeros((k, b, 0), np.int32)
            ),
            weights=jnp.asarray(
                np.stack(
                    [
                        np.ones_like(p.labels) if w is None else np.asarray(w)
                        for p, w in zip(parsed_seq, weights_seq)
                    ]
                )
            ),
        )


class Model(Protocol):
    vocabulary_size: int
    uses_fields: bool  # True when score() reads batch.fields (FFM only)

    @property
    def row_dim(self) -> int:
        """Width of one sparse-table row."""
        ...

    def init_table(self, key: jax.Array) -> jax.Array:
        """[vocabulary_size, row_dim] initial sparse table."""
        ...

    def init_dense(self, key: jax.Array):
        """Dense parameter pytree ({} if none)."""
        ...

    def score(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        """[B] raw scores from gathered rows [B, N, row_dim]."""
        ...

    def regularization(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        """Scalar L2 penalty (reference: factor_lambda/bias_lambda terms)."""
        ...


def masked_l2(rows: jax.Array, vals: jax.Array, bias_lambda: float, factor_lambda: float):
    """Reference-style L2 over the batch's gathered rows, col 0 = bias.

    Padding slots (vals == 0) gather row 0 arbitrarily and must not be
    penalized, hence the mask.  Duplicate occurrences are each penalized,
    matching a per-batch ‖params‖² over the gathered (not deduped) rows.
    """
    mask = (vals != 0.0).astype(rows.dtype)[..., None]
    masked = rows * mask
    bias_term = jnp.sum(masked[..., 0] ** 2)
    factor_term = jnp.sum(masked[..., 1:] ** 2)
    return bias_lambda * bias_term + factor_lambda * factor_term


def logistic_loss(scores: jax.Array, labels: jax.Array, weights: jax.Array) -> jax.Array:
    """Weighted mean sigmoid cross-entropy (the reference's training loss)."""
    # log(1 + e^{-yx}) in the stable log-sum-exp form.
    per = jnp.maximum(scores, 0.0) - scores * labels + jnp.log1p(jnp.exp(-jnp.abs(scores)))
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(per * weights) / denom
