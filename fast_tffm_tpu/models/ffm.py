"""Field-aware factorization machine (BASELINE.json config #3).

An extension target named by the reference project's roadmap (FFM support in
`renyi533/fast_tffm`'s lineage); built here natively.  Each feature i keeps
one factor vector *per field*: score =

    Σᵢ wᵢxᵢ + Σ_{i<j} ⟨v_{i, field_j}, v_{j, field_i}⟩ xᵢxⱼ

Row layout [1 + num_fields·k]: col 0 bias, then the per-field factor blocks.

TPU-first evaluation: the pairwise sum is re-associated into a field-pair
tensor  T[a, b] = Σ_{i: fᵢ=a} z_i[b]  (z = v·x) so the double sum becomes

    ½ (Σ_{a,b} ⟨T[a,b], T[b,a]⟩ − Σᵢ ⟨z_i[fᵢ], z_i[fᵢ]⟩)

— one one-hot einsum (an MXU matmul) + elementwise math, instead of an
O(N²) gather loop.  Padding (x=0) contributes z=0 and is exactly neutral.

The vals factor x folds into the ONE-HOT operand (w[b,n,a] = x·1[f=a]),
not into v: z = v·x as a separate [B, N, F, k] array is ~0.5 GB written
+ read per direction at the benchmark shape (B=65536, 22 fields), and
the fold removes that HBM round-trip while computing the identical
per-term products (measured r5 — the cfg3p gap driver, VERDICT r4 #4).

``compute_dtype='bfloat16'`` additionally runs the interaction einsums
with bf16 INPUTS and f32 MXU accumulation (preferred_element_type):
halves the bytes of the dominant [B, N, F, k] reads.  Scores move by
O(1e-3) relative — fine for CTR ranking, so it is the bench's choice —
while the default stays float32 (bit-parity with the oracle tests).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fast_tffm_tpu.models.base import Batch, masked_l2


@dataclasses.dataclass(frozen=True)
class FFMModel:
    vocabulary_size: int
    num_fields: int
    factor_num: int = 4
    init_value_range: float = 0.01
    factor_lambda: float = 0.0
    bias_lambda: float = 0.0
    compute_dtype: str = "float32"  # interaction einsum inputs (float32|bfloat16)

    uses_fields = True  # score() one-hots batch.fields per slot

    @property
    def row_dim(self) -> int:
        return 1 + self.num_fields * self.factor_num

    def init_table(self, key: jax.Array) -> jax.Array:
        factors = jax.random.uniform(
            key,
            (self.vocabulary_size, self.num_fields * self.factor_num),
            minval=-self.init_value_range,
            maxval=self.init_value_range,
            dtype=jnp.float32,
        )
        bias = jnp.zeros((self.vocabulary_size, 1), jnp.float32)
        return jnp.concatenate([bias, factors], axis=-1)

    def init_dense(self, key: jax.Array):
        return {}

    def score(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        del dense
        B, N = batch.vals.shape
        F, k = self.num_fields, self.factor_num
        bias = rows[..., 0]
        v = rows[..., 1:].reshape(B, N, F, k)  # v[b, i, partner_field, :]
        linear = jnp.sum(bias * batch.vals, axis=-1)
        dt = jnp.dtype(self.compute_dtype)
        vc = v.astype(dt)
        # x folds into the one-hot operand (w = x·1[f=a]) so z = v·x never
        # materializes as [B, N, F, k]; same per-term products (module doc).
        woh = jax.nn.one_hot(batch.fields, F, dtype=dt) * batch.vals[
            ..., None
        ].astype(dt)
        # T[b, a, g, :] = Σ_{i: field_i = a} x_i · v[b, i, g, :]
        T = jnp.einsum(
            "bna,bngk->bagk", woh, vc, preferred_element_type=jnp.float32
        )
        cross = jnp.einsum("bagk,bgak->b", T, T)
        # Diagonal (i == j) correction: z_i[f_i] per nonzero.
        z_self = jnp.einsum(
            "bnfk,bnf->bnk", vc, woh, preferred_element_type=jnp.float32
        )
        diag = jnp.sum(z_self * z_self, axis=(1, 2))
        return linear + 0.5 * (cross - diag)

    def regularization(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        del dense
        return masked_l2(rows, batch.vals, self.bias_lambda, self.factor_lambda)
