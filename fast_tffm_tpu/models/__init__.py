from fast_tffm_tpu.models.base import Batch, logistic_loss, masked_l2  # noqa: F401
from fast_tffm_tpu.models.deepfm import DeepFMModel  # noqa: F401
from fast_tffm_tpu.models.ffm import FFMModel  # noqa: F401
from fast_tffm_tpu.models.fm import FMModel  # noqa: F401
