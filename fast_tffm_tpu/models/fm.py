"""Factorization machine (arbitrary order) — the reference's core model.

Row layout [1 + factor_num]: col 0 bias wᵢ, cols 1: factors vᵢ — the packed
bias+factor parameter row of `renyi533/fast_tffm`'s model-graph builder.
Scoring runs through the fused kernels in ops/fm.py (order 2: (Σv)²−Σv²
trick; order ≥ 3: ANOVA DP), each with a hand-written VJP.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from fast_tffm_tpu.models.base import Batch, masked_l2
from fast_tffm_tpu.ops.fm import fm_score


@dataclasses.dataclass(frozen=True)
class FMModel:
    vocabulary_size: int
    factor_num: int = 8
    order: int = 2
    init_value_range: float = 0.01  # reference cfg key: uniform factor init
    factor_lambda: float = 0.0
    bias_lambda: float = 0.0

    uses_fields = False  # score() never reads batch.fields

    @property
    def row_dim(self) -> int:
        return 1 + self.factor_num

    def init_table(self, key: jax.Array) -> jax.Array:
        factors = jax.random.uniform(
            key,
            (self.vocabulary_size, self.factor_num),
            minval=-self.init_value_range,
            maxval=self.init_value_range,
            dtype=jnp.float32,
        )
        bias = jnp.zeros((self.vocabulary_size, 1), jnp.float32)
        return jnp.concatenate([bias, factors], axis=-1)

    def init_dense(self, key: jax.Array):
        return {}

    def score(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        del dense
        return fm_score(rows, batch.vals, order=self.order)

    def regularization(self, rows: jax.Array, dense, batch: Batch) -> jax.Array:
        del dense
        return masked_l2(rows, batch.vals, self.bias_lambda, self.factor_lambda)
