"""Pallas TPU kernel for the arbitrary-order ANOVA interaction sum.

The BASELINE config #5 component: the order-k ANOVA-kernel dynamic program
of the reference's scorer/grad op pair (`renyi533/fast_tffm` :: cc/ scorer:
per-example DP a[m] += z_j * a[m-1] over the example's nonzeros, and the
hand-written reverse DP in its grad op), as a TPU kernel instead of a C++
CPU loop.

Why a kernel at all: the lax.scan formulation materializes the per-step
carries ``[N, B, order+1, k]`` to HBM for the backward pass and runs N tiny
fused ops per batch.  Here the whole DP lives in VMEM:

  * layout — z is transposed to ``[k, N, B]`` so the *batch* dimension is
    the 128-lane vector axis (k is small — 4..16 — and would waste 15/16
    lanes); the DP state is an ``[8, 128]`` tile: degree on sublanes,
    examples on lanes, one shift-and-fma per consumed feature;
  * grid ``(B/128, k)`` with k innermost, so each output tile stays
    resident in VMEM while all k factor dims accumulate into it;
  * the backward kernel RECOMPUTES the forward carries into a VMEM scratch
    (N·8·128 floats ≈ 160 KB) instead of reading them from HBM — the DP is
    a few fma's per element, far cheaper than the round-trip.

Padded lanes (batch rows beyond B) and padded degree sublanes (beyond
``order``) carry zeros/ignored values and are sliced away outside.

Only the DP itself is custom-VJP'd; the cheap surrounding math (z = v·x,
linear term) stays in plain jnp where XLA's autodiff is already optimal.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["anova_inter", "anova_inter_reference"]

_LANES = 128


def _rows_for(order: int) -> int:
    """Sublane count for the DP state: degrees 0..order, padded to 8k."""
    return max(8, ((order + 1 + 7) // 8) * 8)


def _row_iota(rows: int) -> jax.Array:
    # In-kernel .at[].set lowers to an unsupported scatter on TPU, so all
    # row masking is done with broadcasted-iota compares instead.
    return lax.broadcasted_iota(jnp.int32, (rows, _LANES), 0)


def _shift_up(a: jax.Array) -> jax.Array:
    """shifted[m] = a[m-1], shifted[0] = 0  (degree-raising shift)."""
    return jnp.where(_row_iota(a.shape[0]) == 0, 0.0, jnp.roll(a, 1, axis=0))


def _shift_down(a: jax.Array) -> jax.Array:
    """down[m] = a[m+1], down[-1] = 0  (adjoint of _shift_up)."""
    return jnp.where(_row_iota(a.shape[0]) == a.shape[0] - 1, 0.0, jnp.roll(a, -1, axis=0))


def _fwd_kernel(z_ref, out_ref, *, order: int, rows: int):
    """One (batch-tile, factor-dim) program: run the DP, accumulate degrees."""
    f = pl.program_id(1)
    n = z_ref.shape[1]
    ri = _row_iota(rows)
    a0 = jnp.where(ri == 0, 1.0, 0.0)

    def body(j, a):
        z_j = z_ref[0, j, :]  # [LANES]
        return a + _shift_up(a) * z_j[None, :]

    a = lax.fori_loop(0, n, body, a0)
    # Degrees 2..order, [LANES] (masked sum — static slices of odd heights
    # re-tile poorly on TPU).
    part = jnp.sum(jnp.where((ri >= 2) & (ri <= order), a, 0.0), axis=0)

    @pl.when(f == 0)
    def _():
        out_ref[0, :] = part

    @pl.when(f > 0)
    def _():
        out_ref[0, :] = out_ref[0, :] + part


def _bwd_kernel(z_ref, g_ref, zbar_ref, aprev_ref, *, order: int, rows: int):
    """Recompute the forward carries in VMEM, then run the reverse DP.

    Reverse recurrence (the reference FmGrad's general-order adjoint):
      z̄_j  = Σ_m ā[m] · a_prev_j[m-1]
      ā    ← ā + shift_down(ā) · z_j
    seeded with ā[m] = g for m ∈ [2, order].
    """
    n = z_ref.shape[1]
    ri = _row_iota(rows)
    a0 = jnp.where(ri == 0, 1.0, 0.0)

    def fwd_body(j, a):
        aprev_ref[j, :, :] = a
        z_j = z_ref[0, j, :]
        return a + _shift_up(a) * z_j[None, :]

    lax.fori_loop(0, n, fwd_body, a0)

    g = g_ref[0, :]  # [LANES]
    abar0 = jnp.where((ri >= 2) & (ri <= order), g[None, :], 0.0)

    def bwd_body(t, abar):
        j = n - 1 - t
        z_j = z_ref[0, j, :]
        a_prev = aprev_ref[j, :, :]
        zbar_ref[0, j, :] = jnp.sum(abar * _shift_up(a_prev), axis=0)
        return abar + _shift_down(abar) * z_j[None, :]

    lax.fori_loop(0, n, bwd_body, abar0)


def _pad_transpose(z: jax.Array) -> tuple[jax.Array, int]:
    """[B, N, k] → ([k, N, B_padded], B_padded)."""
    b = z.shape[0]
    bp = ((b + _LANES - 1) // _LANES) * _LANES
    if bp != b:
        z = jnp.pad(z, ((0, bp - b), (0, 0), (0, 0)))
    return jnp.transpose(z, (2, 1, 0)), bp


def _fwd_impl(z: jax.Array, order: int, interpret: bool) -> jax.Array:
    b, n, k = z.shape
    rows = _rows_for(order)
    z_t, bp = _pad_transpose(z.astype(jnp.float32))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, order=order, rows=rows),
        grid=(bp // _LANES, k),
        in_specs=[
            pl.BlockSpec((1, n, _LANES), lambda i, f: (f, 0, i), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec((1, _LANES), lambda i, f: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, bp), jnp.float32),
        interpret=interpret,
    )(z_t)
    return out[0, :b]


def _bwd_impl(z: jax.Array, g: jax.Array, order: int, interpret: bool) -> jax.Array:
    b, n, k = z.shape
    rows = _rows_for(order)
    z_t, bp = _pad_transpose(z.astype(jnp.float32))
    g_p = jnp.pad(g.astype(jnp.float32), (0, bp - b))[None, :]  # [1, BP]
    zbar_t = pl.pallas_call(
        functools.partial(_bwd_kernel, order=order, rows=rows),
        grid=(bp // _LANES, k),
        in_specs=[
            pl.BlockSpec((1, n, _LANES), lambda i, f: (f, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _LANES), lambda i, f: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, n, _LANES), lambda i, f: (f, 0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, n, bp), jnp.float32),
        scratch_shapes=[pltpu.VMEM((n, rows, _LANES), jnp.float32)],
        interpret=interpret,
    )(z_t, g_p)
    return jnp.transpose(zbar_t, (2, 1, 0))[:b]  # [B, N, k]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def anova_inter(z: jax.Array, order: int, interpret: bool = False) -> jax.Array:
    """Σ_{m=2..order} Σ_f ANOVA_m(z[·, ·, f]) per example.  z: [B, N, k] → [B].

    ``interpret=True`` runs the kernels in the Pallas interpreter (CPU
    testing); on TPU leave it False.
    """
    return _fwd_impl(z, order, interpret)


def _anova_inter_fwd(z, order, interpret):
    return _fwd_impl(z, order, interpret), z


def _anova_inter_bwd(order, interpret, z, g):
    return (_bwd_impl(z, g, order, interpret),)


anova_inter.defvjp(_anova_inter_fwd, _anova_inter_bwd)


def anova_inter_reference(z: jax.Array, order: int) -> jax.Array:
    """Brute-force oracle: sum over all m-subsets, for tests (O(N^order))."""
    import itertools

    import numpy as np

    z = np.asarray(z, np.float64)
    b, n, k = z.shape
    out = np.zeros(b)
    for m in range(2, order + 1):
        for subset in itertools.combinations(range(n), m):
            out += np.prod(z[:, subset, :], axis=1).sum(-1)
    return out
