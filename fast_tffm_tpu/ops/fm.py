"""Fused factorization-machine scoring kernels with hand-written backward passes.

TPU-native replacement for the reference's native scorer/grad op pair
(`renyi533/fast_tffm` :: cc/ FmScorer + FmGrad kernels, loaded through
py/fm_ops.py's RegisterGradient glue).  Instead of a C++ CPU kernel driven by
a TF graph, the score is a pure jnp function compiled by XLA, with the
backward pass supplied explicitly through `jax.custom_vjp` — mirroring the
reference's hand-written FmGrad op rather than relying on autodiff.

Batch layout (the "narrow waist" of the framework, see SURVEY.md §2):
instead of the reference's flat CSR (flat ids/vals + row offsets), batches
are *padded dense* ``[batch, max_nnz]`` — static shapes are what XLA/TPU
want, and FM score terms all scale multiplicatively with the feature value
``x_i``, so zero-valued padding slots are exactly neutral in both the
forward and the backward pass (no masks needed).

Parameters arrive *gathered*: ``rows[batch, max_nnz, 1 + k]`` where column 0
is the per-feature bias w_i and columns 1: are the factor vector v_i.  The
caller (model layer) does the gather/scatter; these kernels are dense math
only — the same separation the reference draws between its embedding
lookup and its scorer op.

Math:
  order 2:   score = Σᵢ wᵢxᵢ + ½ Σ_f [(Σᵢ vᵢf xᵢ)² − Σᵢ (vᵢf xᵢ)²]
  order t≥3: score = Σᵢ wᵢxᵢ + Σ_{m=2}^{t} Σ_f ANOVA_m(z·f)  where z = v·x,
             ANOVA via the dynamic program  a[j][m] = a[j-1][m] + z_j·a[j-1][m-1]
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["fm_score", "anova_kernel", "fm_score_order2_raw", "fm_score_anova_raw"]


# ---------------------------------------------------------------------------
# Order-2: the (Σv)² − Σv² trick
# ---------------------------------------------------------------------------


def _order2_fwd_math(rows: jax.Array, vals: jax.Array):
    """Shared forward math. rows: [B, N, 1+k], vals: [B, N] → scores [B]."""
    bias = rows[..., 0]  # [B, N]
    v = rows[..., 1:]  # [B, N, k]
    linear = jnp.sum(bias * vals, axis=-1)  # [B]
    vx = v * vals[..., None]  # [B, N, k]
    s1 = jnp.sum(vx, axis=1)  # [B, k]
    s2 = jnp.sum(vx * vx, axis=1)  # [B, k]
    pairwise = 0.5 * jnp.sum(s1 * s1 - s2, axis=-1)  # [B]
    return linear + pairwise, (bias, v, vx, s1)


@jax.custom_vjp
def _fm_score_order2(rows: jax.Array, vals: jax.Array) -> jax.Array:
    return _order2_fwd_math(rows, vals)[0]


def _fm_score_order2_fwd(rows, vals):
    score, (bias, v, _vx, s1) = _order2_fwd_math(rows, vals)
    # vx is one fused multiply away from (v, vals); recompute in bwd rather
    # than holding a second [B, N, k] residual across the fwd→bwd gap.
    return score, (bias, v, s1, vals)


def _fm_score_order2_bwd(res, g):
    """Hand-derived backward (the reference's FmGrad, order 2).

    ∂score/∂wᵢ   = xᵢ
    ∂score/∂vᵢ   = xᵢ · (s1 − vᵢxᵢ)
    ∂score/∂xᵢ   = wᵢ + vᵢ·(s1 − vᵢxᵢ)
    """
    bias, v, s1, vals = res
    vx = v * vals[..., None]
    g_ = g[:, None]  # [B, 1]
    d_bias = g_ * vals  # [B, N]
    resid = s1[:, None, :] - vx  # [B, N, k]
    d_v = g_[..., None] * vals[..., None] * resid  # [B, N, k]
    d_rows = jnp.concatenate([d_bias[..., None], d_v], axis=-1)
    d_vals = g_ * (bias + jnp.sum(v * resid, axis=-1))  # [B, N]
    return d_rows, d_vals


_fm_score_order2.defvjp(_fm_score_order2_fwd, _fm_score_order2_bwd)


def fm_score_order2_raw(rows: jax.Array, vals: jax.Array) -> jax.Array:
    """Order-2 forward without the custom VJP (autodiff reference for tests)."""
    return _order2_fwd_math(rows, vals)[0]


# ---------------------------------------------------------------------------
# Arbitrary order: ANOVA-kernel dynamic program
# ---------------------------------------------------------------------------


def _anova_scan_fwd(z: jax.Array, order: int):
    """Forward DP.  z: [B, N, k] → a_final [B, order+1, k], a_prevs [N, B, order+1, k].

    Carry a[m] = ANOVA kernel of degree m over the features consumed so far
    (per batch row, per factor dim).  a[0] ≡ 1.
    """
    B, N, k = z.shape
    a0 = jnp.zeros((B, order + 1, k), z.dtype).at[:, 0, :].set(1.0)

    def step(a, z_j):  # z_j: [B, k]
        # a_new[m] = a[m] + z_j * a[m-1]  (m >= 1); shift-and-fma.
        shifted = jnp.roll(a, 1, axis=1).at[:, 0, :].set(0.0)
        a_new = a + z_j[:, None, :] * shifted
        return a_new, a  # store the *pre-step* carry for the backward DP

    a_final, a_prevs = lax.scan(step, a0, jnp.moveaxis(z, 1, 0))
    return a_final, a_prevs


def anova_kernel(z: jax.Array, order: int) -> jax.Array:
    """Σ over factor dims of the degree-``order`` ANOVA kernel.  z: [B,N,k] → [B]."""
    a_final, _ = _anova_scan_fwd(z, order)
    return jnp.sum(a_final[:, order, :], axis=-1)


def _anova_fwd_math(rows: jax.Array, vals: jax.Array, order: int):
    bias = rows[..., 0]
    v = rows[..., 1:]
    linear = jnp.sum(bias * vals, axis=-1)
    z = v * vals[..., None]  # [B, N, k]
    a_final, a_prevs = _anova_scan_fwd(z, order)
    # Sum of all interaction degrees 2..order (reference: arbitrary-order FM
    # evaluates every degree with the single shared factor set).
    inter = jnp.sum(a_final[:, 2:, :], axis=(1, 2))
    return linear + inter, (bias, v, z, a_prevs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fm_score_anova(rows: jax.Array, vals: jax.Array, order: int) -> jax.Array:
    return _anova_fwd_math(rows, vals, order)[0]


def _fm_score_anova_fwd(rows, vals, order):
    score, res = _anova_fwd_math(rows, vals, order)
    return score, (*res, vals)


def _fm_score_anova_bwd(order, res, g):
    """Hand-written adjoint of the ANOVA DP (the reference's FmGrad, general order).

    Reverse scan over features.  ā is the cotangent of the DP carry:
      z̄_j    = Σ_m ā[m] · a_prev_j[m-1]
      ā[m-1] += ā[m] · z_j           (i.e. ā ← ā + shift⁻¹(ā)·z_j)
    seeded with ā[m] = g for m ∈ [2, order] (every degree contributes to the
    score with unit weight).
    """
    bias, v, z, a_prevs, vals = res
    B, N, k = z.shape
    abar0 = jnp.zeros((B, order + 1, k), z.dtype)
    abar0 = abar0.at[:, 2:, :].set(g[:, None, None])

    def step(abar, xs):
        z_j, a_prev = xs  # [B, k], [B, order+1, k]
        shifted_prev = jnp.roll(a_prev, 1, axis=1).at[:, 0, :].set(0.0)
        zbar_j = jnp.sum(abar * shifted_prev, axis=1)  # [B, k]
        # ā[m-1] += ā[m] * z_j  → add the down-shifted ā scaled by z_j.
        down = jnp.roll(abar, -1, axis=1).at[:, -1, :].set(0.0)
        abar_new = abar + down * z_j[:, None, :]
        return abar_new, zbar_j

    _, zbars = lax.scan(step, abar0, (jnp.moveaxis(z, 1, 0), a_prevs), reverse=True)
    zbar = jnp.moveaxis(zbars, 0, 1)  # [B, N, k]

    d_bias = g[:, None] * vals
    d_v = zbar * vals[..., None]
    d_rows = jnp.concatenate([d_bias[..., None], d_v], axis=-1)
    d_vals = g[:, None] * bias + jnp.sum(zbar * v, axis=-1)
    return d_rows, d_vals


_fm_score_anova.defvjp(_fm_score_anova_fwd, _fm_score_anova_bwd)


def fm_score_anova_raw(rows: jax.Array, vals: jax.Array, order: int) -> jax.Array:
    """General-order forward without the custom VJP (autodiff reference)."""
    return _anova_fwd_math(rows, vals, order)[0]


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------


def fm_score(
    rows: jax.Array, vals: jax.Array, order: int = 2, *, use_pallas: bool | None = None
) -> jax.Array:
    """FM score for a padded batch.

    Args:
      rows:  [batch, max_nnz, 1 + factor_num] gathered parameter rows
             (col 0 = bias wᵢ, cols 1: = factors vᵢ).
      vals:  [batch, max_nnz] feature values; 0.0 marks padding slots.
      order: interaction order ≥ 2.  order=2 uses the fused (Σv)²−Σv² path;
             order≥3 the ANOVA dynamic program.  Both carry hand-written VJPs.
      use_pallas: route the order≥3 interaction DP through the Pallas TPU
             kernel (ops/pallas_anova.py).  None = auto (TPU backend only).

    Returns:
      [batch] raw (pre-sigmoid) scores.
    """
    if order < 2:
        raise ValueError(f"FM order must be >= 2, got {order}")
    if order == 2:
        return _fm_score_order2(rows, vals)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from fast_tffm_tpu.ops.pallas_anova import anova_inter
        from fast_tffm_tpu.ops.pallas_common import default_interpret

        # Only the DP carries a hand-written (kernel) VJP; the linear term
        # and z = v·x are cheap elementwise ops XLA autodiff handles best.
        # Off-TPU the kernel runs in the Pallas interpreter
        # (ops.pallas_common), keeping this public path testable on the
        # CPU mesh.
        linear = jnp.sum(rows[..., 0] * vals, axis=-1)
        z = rows[..., 1:] * vals[..., None]
        return linear + anova_inter(z, order, default_interpret())
    return _fm_score_anova(rows, vals, order)
