from fast_tffm_tpu.ops.fm import (  # noqa: F401
    anova_kernel,
    fm_score,
    fm_score_anova_raw,
    fm_score_order2_raw,
)

# fast_tffm_tpu.ops.pallas_anova is imported lazily (inside fm_score's
# pallas branch) so CPU-only runs never load jax.experimental.pallas.
