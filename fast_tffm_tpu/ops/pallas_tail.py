"""Fused Pallas sparse tail: one-pass gather→Adagrad→scatter.

The XLA sparse tail is a CHAIN of programs — grad lane-spread, bitmap/
cumsum (or sort) compaction, RMW gather, RMW scatter — each of which
walks its own descriptor stream over the same touched rows (~16 ns/row
each; BENCH_r05's 201M-row rung spends its step there, at ~3% of nominal
HBM bandwidth).  This module replaces the tail with ONE Pallas TPU
kernel per table layout:

  * dedup ONCE at **logical-row** granularity (optim.dedup_rows — the
    sort/segment-sum pipeline the rows-layout classic update already
    uses, so the compacted gradients are bit-identical to it), then
  * a single kernel pass: per deduped row, DMA **only the touched
    lanes** HBM→VMEM (for the fused ``[VPf, 128]`` layout that is the
    row's own ``D+1``-lane slot — params + its in-row accumulator — not
    the whole 128-lane tile row), apply the Adagrad update in VMEM, and
    DMA the result straight back.  Gather and scatter ride the same
    pass, double-buffered two row-blocks deep: block ``i+1``'s gather
    DMAs issue while block ``i`` computes, and block ``i``'s scatter
    DMAs drain while ``i+1`` computes.
  * the output aliases the table operand (``input_output_aliases``), so
    the update is in place — untouched rows are never read or written.

Decay-γ (``[Online] adagrad_decay``) threads through exactly like
``trainer.make_decayed_body``: γ=1.0 is a TRACE-TIME branch back to the
classic expression (``accum += g²``), so the default program — and its
bits — are untouched; γ<1 decays lazily, and *only the deduped touched
rows* ever reach the kernel, which is precisely the lazy-decay contract.
Correctness of the slot-slice RMW rests on the zero-grad identity: a row
(or lane) with zero summed gradient maps to exactly itself
(``acc+0 = acc``; ``w − lr·0/√acc = w``), so rows the batch doesn't
touch can simply never enter the kernel.

Layouts served:

  * ``fused_tail_adagrad_update`` — the resident fused layout
    (``ops.packed_table.pack_fused``, ``[VPf, 128]``, P = 128//(D+1)
    logical rows per tile row; accumulator in lane ``s·(D+1)+D``).
  * ``rows_tail_adagrad_update`` — a plain ``[V, D]`` table with a
    separate ``[V, D]`` (element) or ``[V, 1]`` (row) accumulator: the
    resident rows layout AND the tiered paramstore's compact ``[C, D]``
    device table (the staging region already holds exactly the operand
    shape the kernel wants — remapped slot ids against a compact table).

Both run under ``interpret=`` for CPU tier-1 (ops.pallas_common resolves
the flag off the backend, same pattern as ops/pallas_anova.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fast_tffm_tpu.optim import dedup_rows
from fast_tffm_tpu.ops.pallas_common import resolve_interpret

__all__ = [
    "fused_tail_adagrad_update",
    "rows_tail_adagrad_update",
    "DEFAULT_BLOCK_ROWS",
]

DEFAULT_BLOCK_ROWS = 256  # rows per grid step; 2 buffers × 256 × ≤128 lanes


def _nblocks(k: int, blk: int) -> int:
    return max(1, -(-k // blk))


def _pad_ids(uids: jax.Array, total: int, sentinel: int) -> jax.Array:
    k = uids.shape[0]
    if total == k:
        return uids
    return jnp.pad(uids, (0, total - k), constant_values=sentinel)


def _schedule(i, nblocks, start_in, wait_in, start_out, wait_out, compute):
    """The shared double-buffer schedule for one grid step ``i``.

    Slot ``i % 2`` holds block ``i``; while it computes, block ``i+1``
    gathers into the other slot, whose previous occupant's (block
    ``i−1``'s) scatter DMAs are drained first.  All four DMA phases are
    per-row-predicated identically, so semaphore starts and waits always
    pair up."""
    slot = lax.rem(i, 2)
    other = lax.rem(i + 1, 2)

    @pl.when(i == 0)
    def _():
        start_in(i, slot)

    @pl.when(i >= 1)
    def _():
        wait_out(i - 1, other)

    @pl.when(i + 1 < nblocks)
    def _():
        start_in(i + 1, other)

    wait_in(i, slot)
    compute(slot)
    start_out(i, slot)

    @pl.when(i == nblocks - 1)
    def _():
        wait_out(i, slot)


# --------------------------------------------------------------------------
# fused [VPf, 128] layout (ops.packed_table.pack_fused)
# --------------------------------------------------------------------------


def _fused_kernel(
    uids_ref, nrows_ref, g_ref, fused_ref, out_ref, buf, in_sem, out_sem,
    *, lr: float, decay: float, p: int, d: int, blk: int, nblocks: int,
    vmax: int,
):
    i = pl.program_id(0)
    nrows = nrows_ref[0]
    d1 = d + 1

    def slot_slice(row):
        """Touched-lane address of deduped logical row ``row``: the
        (tile row, first lane) of its D+1-lane slot."""
        lid = jnp.minimum(uids_ref[row], vmax - 1)  # clamp pad sentinels
        return lid // p, (lid % p) * d1

    def _run(block, slot, *, outward, wait):
        base = block * blk

        def body(j, _):
            @pl.when(base + j < nrows)
            def _():
                phys, lane0 = slot_slice(base + j)
                vref = buf.at[slot, j]
                href = (out_ref if outward else fused_ref).at[
                    phys, pl.ds(lane0, d1)
                ]
                src, dst = (vref, href) if outward else (href, vref)
                cp = pltpu.make_async_copy(
                    src, dst, (out_sem if outward else in_sem).at[slot]
                )
                cp.wait() if wait else cp.start()
            return 0

        @pl.when(base < nrows)
        def _():
            lax.fori_loop(0, blk, body, 0)

    def compute(slot):
        cur = buf[slot]  # [blk, d+1]: d params + the row accumulator
        g = g_ref[...]  # [blk, d] deduped summed gradients
        w, acc0 = cur[:, :d], cur[:, d]
        gsq = jnp.sum(g * g, axis=-1)
        if decay == 1.0:  # trace-time: the exact classic program
            acc2 = acc0 + gsq
        else:  # lazy decay — every deduped row here WAS touched
            acc2 = decay * acc0 + gsq
        new_w = w - lr * g / jnp.sqrt(acc2)[:, None]
        buf[slot] = jnp.concatenate([new_w, acc2[:, None]], axis=-1)

    _schedule(
        i, nblocks,
        start_in=lambda b, s: _run(b, s, outward=False, wait=False),
        wait_in=lambda b, s: _run(b, s, outward=False, wait=True),
        start_out=lambda b, s: _run(b, s, outward=True, wait=False),
        wait_out=lambda b, s: _run(b, s, outward=True, wait=True),
        compute=compute,
    )


def _fused_rmw(fused, uids, nrows, gsum, *, lr, decay, p, d, interpret, blk):
    """One-pass RMW over ``K = uids.shape[0]`` deduped logical rows."""
    k = uids.shape[0]
    nblocks = _nblocks(k, blk)
    vmax = fused.shape[0] * p  # any lid ≥ vmax is a pad sentinel
    uids = _pad_ids(uids.astype(jnp.int32), nblocks * blk, vmax)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((blk, d), lambda i, *_: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, blk, d + 1), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(
        _fused_kernel, lr=float(lr), decay=float(decay), p=p, d=d, blk=blk,
        nblocks=nblocks, vmax=vmax,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(fused.shape, fused.dtype),
        input_output_aliases={3: 0},  # fused table updates in place
        interpret=interpret,
    )(uids, nrows, gsum, fused)


def fused_tail_adagrad_update(
    fused: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    *,
    decay: float = 1.0,
    k_cap: int = 0,
    interpret: bool | None = None,
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> jax.Array:
    """Adagrad over the fused ``[VPf, 128]`` layout in one kernel pass.

    Semantically ``ops.packed_table.apply_fused_update`` (row-granularity
    accumulator): dedup to unique logical rows, ``acc ← γ·acc + ‖g‖²``,
    ``w ← w − lr·g/√acc``.  The dedup is ``optim.dedup_rows`` — the SAME
    sort/segment pipeline the rows-layout classic update uses, so at
    γ=1.0 the result is bit-identical to ``sparse_adagrad_update`` with
    a row accumulator on the logical arrays (test-pinned); against the
    scatter-add-built XLA fused tails it is allclose (summation order).

    ``k_cap`` mirrors ``packed_compact_cap``: cap the kernel's deduped
    row span, with an exact full-span ``lax.cond`` fallback when a batch
    touches more rows — never silent truncation.
    """
    interpret = resolve_interpret(interpret)
    d = row_grads.shape[-1]
    p = 128 // (d + 1)
    v = fused.shape[0] * p
    flat = ids.reshape(-1)
    uids, gsum = dedup_rows(flat, row_grads.reshape(-1, d), v)
    m = uids.shape[0]
    nrows = jnp.sum(uids < v).astype(jnp.int32)[None]
    blk = max(8, min(block_rows, m))
    run = functools.partial(
        _fused_rmw, lr=lr, decay=decay, p=p, d=d, interpret=interpret,
        blk=blk,
    )
    if k_cap and k_cap < m:
        # Exact-capacity fallback, same shape as the XLA compact tail's:
        # overflowing batches pay the full span, never lose updates.
        return lax.cond(
            nrows[0] <= k_cap,
            lambda f: run(f, uids[:k_cap], nrows, gsum[:k_cap]),
            lambda f: run(f, uids, nrows, gsum),
            fused,
        )
    return run(fused, uids, nrows, gsum)


# --------------------------------------------------------------------------
# rows [V, D] (+ separate [V, A] accumulator) layout — resident rows path
# and the tiered paramstore's compact [C, D] device table
# --------------------------------------------------------------------------


def _rows_kernel(
    uids_ref, nrows_ref, g_ref, table_ref, accum_ref, t_out_ref, a_out_ref,
    tbuf, abuf, tin_sem, ain_sem, tout_sem, aout_sem,
    *, lr: float, decay: float, d: int, a: int, blk: int, nblocks: int,
    vmax: int,
):
    i = pl.program_id(0)
    nrows = nrows_ref[0]

    def _run(block, slot, *, outward, wait):
        base = block * blk

        def body(j, _):
            @pl.when(base + j < nrows)
            def _():
                row = jnp.minimum(uids_ref[base + j], vmax - 1)
                for hbm_in, hbm_out, vbuf, isem, osem in (
                    (table_ref, t_out_ref, tbuf, tin_sem, tout_sem),
                    (accum_ref, a_out_ref, abuf, ain_sem, aout_sem),
                ):
                    vref = vbuf.at[slot, j]
                    href = (hbm_out if outward else hbm_in).at[row]
                    src, dst = (vref, href) if outward else (href, vref)
                    cp = pltpu.make_async_copy(
                        src, dst, (osem if outward else isem).at[slot]
                    )
                    cp.wait() if wait else cp.start()
            return 0

        @pl.when(base < nrows)
        def _():
            lax.fori_loop(0, blk, body, 0)

    def compute(slot):
        w = tbuf[slot]  # [blk, d]
        acc = abuf[slot]  # [blk, a]
        g = g_ref[...]  # [blk, d]
        if a == 1:  # row-granularity accumulator
            asq = jnp.sum(g * g, axis=-1, keepdims=True)
        else:  # element granularity (TF-Adagrad parity)
            asq = g * g
        acc_prev = acc if decay == 1.0 else decay * acc
        acc2 = acc_prev + asq
        tbuf[slot] = w - lr * g / jnp.sqrt(acc2)
        abuf[slot] = acc2

    _schedule(
        i, nblocks,
        start_in=lambda b, s: _run(b, s, outward=False, wait=False),
        wait_in=lambda b, s: _run(b, s, outward=False, wait=True),
        start_out=lambda b, s: _run(b, s, outward=True, wait=False),
        wait_out=lambda b, s: _run(b, s, outward=True, wait=True),
        compute=compute,
    )


def rows_tail_adagrad_update(
    table: jax.Array,
    accum: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    *,
    decay: float = 1.0,
    interpret: bool | None = None,
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> tuple[jax.Array, jax.Array]:
    """``optim.sparse_adagrad_update`` as one kernel pass.

    Same dedup (``optim.dedup_rows``), same update expressions, same
    lazy-decay semantics — bit-identical at γ=1.0 AND γ<1 (test-pinned);
    the only change is HOW the unique rows move: one double-buffered
    DMA pass instead of the gather program + scatter program pair.
    """
    interpret = resolve_interpret(interpret)
    v, d = table.shape
    a = accum.shape[-1]
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, d), v)
    m = uids.shape[0]
    nrows = jnp.sum(uids < v).astype(jnp.int32)[None]
    blk = max(8, min(block_rows, m))
    nblocks = _nblocks(m, blk)
    uids = _pad_ids(uids.astype(jnp.int32), nblocks * blk, v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((blk, d), lambda i, *_: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, blk, d), jnp.float32),
            pltpu.VMEM((2, blk, a), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(
        _rows_kernel, lr=float(lr), decay=float(decay), d=d, a=a, blk=blk,
        nblocks=nblocks, vmax=v,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(table.shape, table.dtype),
            jax.ShapeDtypeStruct(accum.shape, accum.dtype),
        ),
        input_output_aliases={3: 0, 4: 1},  # table and accum in place
        interpret=interpret,
    )(uids, nrows, gsum, table, accum)
