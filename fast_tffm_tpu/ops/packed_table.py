"""Lane-packed embedding table: P logical [D] rows per 128-lane tile row.

WHY (measured on this environment's chip, DESIGN §6 round-3 correction):
a TPU f32 array is tiled (8, 128); a narrow embedding row (D = 1+k = 9
for the flagship FM) occupies 9 of a tile row's 128 lanes, so every
random-row scatter is a masked partial-lane read-modify-write — measured
~104 ns/row (~0.35 GB/s payload), 7.5× slower than scattering full
128-lane rows and ~70× slower than 1-D scatters.  The sparse Adagrad
update, not compute, dominates the train step.

The fix is physical layout, not a new algorithm: store the table as
``[ceil(V/P), 128]`` with ``P = 128 // D`` logical rows packed per
physical row (P=14 at D=9 → 126/128 lanes used).  Then:

  * the LOOKUP gathers full 128-lane physical rows (measured ~271 GB/s
    vs ~6 GB/s for narrow rows) and extracts each id's D-lane slice with
    P static masked slices (dense VPU work);
  * the UPDATE dedups ONCE at physical-row granularity *in lane space*:
    per-occurrence grads are inserted into their slot lanes, sorted by
    id (ids sorted ⇒ physical rows sorted), segment-summed at full 128
    width, and applied with one wide gather + one wide scatter per
    array.  Element-wise Adagrad with a zero gradient is the identity,
    so writing whole 128-lane rows is EXACT — untouched neighbors in a
    shared tile row read and write back their current values.

Semantics are identical to the rows layout (same sums in the same
order — test-pinned exactly); only bytes move differently.  Reference
capability parity: this replaces the same TF sparse-Adagrad scatter the
rows layout replaces (`renyi533/fast_tffm` :: graph builder's
AdagradOptimizer sparse path); the layout itself has no reference analog
because CPUs don't have lane tiles.

Constraints: element-granularity accumulator (it packs identically and
zero-grad identity makes whole-row RMW exact); D ≤ 128 (64 < D ≤ 128
degrades to P=1 — one padded row per tile row, memory ×128/D, still the
fast full-width scatter path; FFM at 22 fields × k=4 has D=89).
Checkpoints always store the LOGICAL [V, D] table (pack/unpack below),
so packed and rows checkpoints are interchangeable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "LANES",
    "DENSE_G_MAX_BYTES",
    "rows_per_tile",
    "packed_rows",
    "pack_table",
    "pack_accum",
    "pack_accum_rows",
    "pack_accum_any",
    "unpack_table",
    "unpack_accum_rows",
    "unpack_accum_any",
    "packed_gather",
    "packed_accum_gather_any",
    "fused_accum_gather",
    "scatter_logical_rows",
    "lane_spread",
    "packed_dense_grad",
    "packed_dense_adagrad_update",
    "packed_compact_adagrad_update",
    "packed_sparse_adagrad_update",
    "resolve_packed_update",
    "PACKED_UPDATE_FNS",
    "fused_rows_per_tile",
    "fused_packed_rows",
    "pack_fused",
    "unpack_fused",
    "fused_gather",
    "fused_dense_adagrad_update",
    "fused_compact_adagrad_update",
    "resolve_fused_update",
    "apply_fused_update",
    "FUSED_UPDATE_FNS",
]

LANES = 128


def rows_per_tile(d: int) -> int:
    """Logical rows per 128-lane physical row.  P >= 2 packs multiple
    rows per tile row; 64 < D <= 128 degrades to P = 1 — one logical row
    padded to the full tile row (memory ×128/D, e.g. 1.44× for FFM's
    D=89) which still converts every partial-lane scatter into the fast
    full-width path."""
    if d > LANES:
        raise ValueError(f"packed layout needs D <= {LANES}, got {d}")
    return max(1, LANES // d)


def packed_rows(vocab: int, d: int) -> int:
    return -(-vocab // rows_per_tile(d))


_CHUNK_LOGICAL_ROWS = 1 << 21  # chunked packing granularity (rounded to P)


def _pack_block(block: jax.Array, p: int, pad_value: float) -> jax.Array:
    """[n·P, D] logical rows -> [n, 128] packed rows (spare lanes carry
    ``pad_value``)."""
    n = block.shape[0] // p
    d = block.shape[1]
    out = jnp.full((n, LANES), pad_value, block.dtype)
    return out.at[:, : p * d].set(block.reshape(n, p * d))


from functools import partial as _partial


@_partial(jax.jit, donate_argnums=(0,), static_argnums=(4,))
def _chunk_write(buf, block, start_phys, pad_value, p):
    """One donated chunk write.  ``start_phys`` and ``pad_value`` are
    traced (ONE compile covers every full-size chunk; the ragged tail's
    different block shape costs a second) — a static start would
    recompile per chunk, ~112 times at a 235M-row table."""
    return jax.lax.dynamic_update_slice_in_dim(
        buf, _pack_block(block, p, pad_value), start_phys, axis=0
    )


def pack_table(table: jax.Array, pad_value: float = 0.0) -> jax.Array:
    """[V, D] logical -> [VP, 128] packed (pad lanes/rows = pad_value).

    Large tables pack in chunks through a donated accumulator so the
    transient device-memory peak stays ~logical+packed (measured: the
    whole-array path's extra flat copy OOMs 16M-row vocabs on a busy
    shared chip)."""
    v, d = table.shape
    p = rows_per_tile(d)
    vp = packed_rows(v, d)
    chunk = (_CHUNK_LOGICAL_ROWS // p) * p
    if v <= chunk:
        flat = jnp.full((vp * p, d), pad_value, table.dtype).at[:v].set(table)
        return _pack_block(flat, p, pad_value)
    packed = jnp.full((vp, LANES), pad_value, table.dtype)
    for lo in range(0, v, chunk):
        hi = min(lo + chunk, v)
        block = table[lo:hi]
        if (hi - lo) % p:
            pad = p - (hi - lo) % p
            block = jnp.concatenate(
                [block, jnp.full((pad, d), pad_value, table.dtype)]
            )
        packed = _chunk_write(
            packed, block, jnp.int32(lo // p), jnp.asarray(pad_value, table.dtype), p
        )
    return packed


def pack_accum(accum: jax.Array, init_value: float) -> jax.Array:
    """pack_table for ACCUMULATORS: padding lanes/rows carry
    ``init_value``, never zero — the whole-tile-row Adagrad RMW divides
    by sqrt(acc), and a zero pad would turn 0/sqrt(0) into NaN the first
    time a partially-used physical row updates."""
    return pack_table(accum, pad_value=init_value)


def unpack_table(packed: jax.Array, vocab: int, d: int) -> jax.Array:
    """[VP, 128] packed -> [V, D] logical."""
    p = rows_per_tile(d)
    vp = packed.shape[0]
    return packed[:, : p * d].reshape(vp * p, d)[:vocab]


def packed_gather(packed: jax.Array, ids: jax.Array, d: int) -> jax.Array:
    """rows[..., D] for logical ``ids`` from a packed table.

    One wide gather of [M, 128] physical rows, then P static masked
    slices sum into the [..., D] result (each id has exactly one live
    slot, so the sum just selects)."""
    p = rows_per_tile(d)
    phys = ids // p
    slot = ids % p
    rows128 = packed[phys]  # [..., 128] full-tile-row gather
    out = jnp.zeros(ids.shape + (d,), packed.dtype)
    for s in range(p):
        piece = rows128[..., s * d : (s + 1) * d]
        out = out + jnp.where((slot == s)[..., None], piece, 0)
    return out


def packed_accum_gather_any(
    acc_packed: jax.Array, ids: jax.Array, d: int
) -> jax.Array:
    """Logical accumulator rows for ``ids`` from a packed accumulator of
    either granularity: [VP, 128] element → [..., D] (same packing as the
    table, so the table gather serves it), [VP, P] row → [..., 1] slot
    scalars.  The checkpoint delta writer's accumulator twin of
    ``packed_gather`` — deltas store LOGICAL rows, so packed and rows
    checkpoints stay interchangeable link by link."""
    p = rows_per_tile(d)
    if acc_packed.shape[-1] == LANES and p != LANES:
        return packed_gather(acc_packed, ids, d)
    return acc_packed[ids // p, ids % p][..., None]


def fused_accum_gather(fused: jax.Array, ids: jax.Array, d: int) -> jax.Array:
    """[..., 1] row-accumulator scalars for logical ``ids`` from a FUSED
    tile-row table (the accumulator lane at slot offset s·(D+1)+D)."""
    p = fused_rows_per_tile(d)
    d1 = d + 1
    phys = ids // p
    slot = ids % p
    rows128 = fused[phys]
    out = jnp.zeros(ids.shape, fused.dtype)
    for s in range(p):
        out = out + jnp.where(slot == s, rows128[..., s * d1 + d], 0)
    return out[..., None]


def scatter_logical_rows(
    packed: jax.Array, ids: jax.Array, rows: jax.Array, d: int
) -> jax.Array:
    """Write logical rows INTO a packed table: the inverse of
    ``packed_gather``, used by the serving hot-reload watcher to apply a
    checkpoint delta in place instead of re-reading the full table.

    ``ids`` must be sorted ascending and unique (delta files store
    ``np.flatnonzero`` output, which is both by construction).  Logical
    rows sharing a physical tile row occupy DISJOINT lane ranges, so a
    segment-SUM of per-occurrence (mask, payload) lane images merges them
    exactly; untouched neighbor lanes keep their current values through
    the mask.  One wide gather + one wide scatter (unique + sorted
    indices by construction — the round-5 declaration that skips XLA's
    sort-based scatter dedup)."""
    p = rows_per_tile(d)
    vp = packed.shape[0]
    flat = ids.reshape(-1).astype(jnp.int32)
    m = flat.shape[0]
    r = rows.reshape(m, d).astype(packed.dtype)
    slot = (flat % p).astype(jnp.int32)
    phys = jnp.minimum((flat // p).astype(jnp.int32), vp)
    pay128 = lane_spread(r, slot, p, d)
    mask128 = lane_spread(jnp.ones_like(r), slot, p, d)
    # Segment per physical row (ids sorted ⇒ phys sorted): disjoint-lane
    # sums merge the row's occupants; representatives get unique ascending
    # uphys exactly as packed_sparse_adagrad_update builds them.
    is_new = jnp.concatenate([jnp.ones((1,), bool), phys[1:] != phys[:-1]])
    seg = jnp.cumsum(is_new) - 1
    paysum = jax.ops.segment_sum(pay128, seg, num_segments=m)
    masksum = jax.ops.segment_sum(mask128, seg, num_segments=m)
    uphys = (jnp.int32(vp) + jnp.arange(m, dtype=jnp.int32)).at[seg].set(phys)
    cur = packed[jnp.minimum(uphys, vp - 1)]
    new = cur * (1 - masksum) + paysum
    return packed.at[uphys].set(
        new, mode="drop", unique_indices=True, indices_are_sorted=True
    )


def lane_spread(row_grads: jax.Array, slot: jax.Array, p: int, d: int) -> jax.Array:
    """[M, D] per-occurrence values -> [M, 128] tile rows with each
    value's D lanes at its slot offset — ONE one-hot broadcast pass
    ([M, P] ⊗ [M, D] reshaped), not P masked-slice passes over [M, 128]
    (measured: the slice-per-slot build is a visible share of the packed
    step at P=14)."""
    m = row_grads.shape[0]
    oh = jax.nn.one_hot(slot, p, dtype=row_grads.dtype)  # [M, P]
    g128 = (oh[:, :, None] * row_grads[:, None, :]).reshape(m, p * d)
    if p * d < LANES:
        g128 = jnp.pad(g128, ((0, 0), (0, LANES - p * d)))
    return g128


def packed_dense_grad(vp: int, ids: jax.Array, row_grads: jax.Array) -> jax.Array:
    """Dense [VP, 128] occurrence-summed gradient via ONE wide scatter-add.

    Duplicate ids sum in the scatter (in flat-occurrence order — the
    same order the stable-sorted segment-sum uses, so sums are
    bit-identical to the sorted path's); ids at or past vp·P act as drop
    sentinels.  This trades the sorted pipeline's 5 sparse M-row ops
    (argsort, permutation gather, segment-sum, RMW gather, second
    scatter) for one M-row scatter-add plus O(VP·128) dense traffic —
    measured 3.5× faster on the whole step at vocab 2^24 (DESIGN §6
    round-4 entry).
    """
    d = row_grads.shape[-1]
    p = rows_per_tile(d)
    flat = ids.reshape(-1)
    g = row_grads.reshape(flat.shape[0], d)
    slot = (flat % p).astype(jnp.int32)
    phys = (flat // p).astype(jnp.int32)
    g128 = lane_spread(g, slot, p, d)
    return jnp.zeros((vp, LANES), g.dtype).at[phys].add(g128, mode="drop")


def _adagrad_apply(cur, acc, G, lr, p: int, d: int):
    """(new_rows, new_acc) for one Adagrad application of occurrence-summed
    wide grads ``G`` to tile rows ``cur`` with accumulator ``acc`` of either
    granularity (trailing dim 128 = element, P = row).  The ONE place the
    packed Adagrad formulas live — the dense sweep and the compact RMW both
    call it, so their results are bit-identical by construction."""
    if acc.shape[-1] == LANES:  # element granularity
        acc2 = acc + G * G
        return cur - lr * G / jnp.sqrt(acc2), acc2
    if acc.shape[-1] != p:
        raise ValueError(
            f"accumulator trailing dim {acc.shape[-1]} is neither "
            f"{LANES} (element) nor P={p} (row)"
        )
    grow = G[:, : p * d].reshape(-1, p, d)
    acc2 = acc + jnp.sum(grow * grow, axis=-1)  # [*, P]
    # (lr·G)/sqrt — the same association order as optim's row-mode update,
    # so results are bit-identical, not just close.  Pad lanes divide by 1.
    denom = jnp.sqrt(acc2)[:, :, None] * jnp.ones((1, 1, d), cur.dtype)
    denom128 = jnp.pad(
        denom.reshape(-1, p * d), ((0, 0), (0, LANES - p * d)),
        constant_values=1.0,
    )
    return cur - lr * G / denom128, acc2


def packed_dense_adagrad_update(
    packed: jax.Array,
    accum_packed: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
):
    """Sparse Adagrad on the packed table via a DENSE gradient buffer.

    One wide scatter-add builds the occurrence-summed [VP, 128] gradient
    G, then a dense elementwise sweep applies Adagrad to the WHOLE
    table: untouched elements see G == 0 — `accum += 0²; param -= lr·0`
    is the exact identity — so the dense sweep changes nothing it
    shouldn't (the same zero-grad identity that makes whole-tile-row
    writes exact makes the whole-TABLE write exact).  O(VP·128) dense
    traffic replaces the sorted pipeline's sparse tail; use
    ``resolve_packed_update`` to fall back to the compact path when VP
    is so large the dense sweep (and the G buffer's memory) stops
    paying.

    ``accum_packed`` granularity is declared by its trailing dim:
    128 lanes = element accumulator (``pack_accum``), P slots = per-ROW
    scalar accumulator (``pack_accum_rows``) — `accum += ‖ΣG_row‖²`,
    one sqrt per logical row, the D×-smaller optimizer state the 10B-row
    regime needs (optim.py row mode; semantics matched exactly).
    """
    d = row_grads.shape[-1]
    p = rows_per_tile(d)
    G = packed_dense_grad(packed.shape[0], ids, row_grads)
    return _adagrad_apply(packed, accum_packed, G, lr, p, d)


def packed_compact_adagrad_update(
    packed: jax.Array,
    accum_packed: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
):
    """Sparse Adagrad via SORT-FREE compaction of the touched physical rows.

    The giant-vocab middle path between the dense sweep and the sorted
    tail (DESIGN §6 round-5 entry): the sorted tail pays an argsort over
    M occurrences plus a segment pipeline (measured 98.9k ex/s at vocab
    201M — descriptor-bound, 0.09% of HBM bandwidth), while the dense
    sweep pays a table-sized G buffer and O(VP·128) traffic (dies past
    DENSE_G_MAX_BYTES).  This path keeps the dense tail's scatter-ADD
    dedup but compacts the gradient buffer to K = min(VP, M) tile rows
    using a touched-row bitmap + prefix sum over [VP] — O(VP) 1-byte/4-byte
    1-D traffic, 128× less than the dense sweep — and NO sort:

      touched[phys] = 1                      1-D int8 scatter over [VP]
      slot = cumsum(touched)[phys] - 1       each touched row → dense slot
      G[slot] += g128                        wide scatter-add; duplicates
                                             sum in flat occurrence order,
                                             exactly as the dense G does
      RMW rows uphys[slot]                   wide gather → Adagrad → scatter

    ids at or past VP·P act as drop sentinels (slot = K, dropped), the
    same convention as the dense and sorted paths.  Works with BOTH
    accumulator granularities — element [VP, 128] and row [VP, P] — which
    makes it the giant-vocab path for row mode (the sorted tail cannot
    serve row mode).  The Adagrad formulas are shared with the dense
    sweep (``_adagrad_apply``), so results are bit-identical to
    ``packed_dense_adagrad_update`` on the same inputs (test-pinned).
    """
    d = row_grads.shape[-1]
    p = rows_per_tile(d)
    vp = packed.shape[0]
    flat = ids.reshape(-1)
    m = flat.shape[0]
    g = row_grads.reshape(m, d)
    slot_lane = (flat % p).astype(jnp.int32)
    phys = (flat // p).astype(jnp.int32)
    g128 = lane_spread(g, slot_lane, p, d)

    k = min(vp, m)  # exact worst case: every occurrence touches a new row
    touched = jnp.zeros((vp,), jnp.int8).at[phys].set(1, mode="drop")
    csum = jnp.cumsum(touched, dtype=jnp.int32)
    valid = phys < vp
    # Valid occurrences: csum[phys] ∈ [1, #touched] and #touched <= K, so
    # slot <= K-1.  Sentinels get slot K and drop from every scatter below.
    slot = jnp.where(valid, csum[jnp.minimum(phys, vp - 1)] - 1, k)
    G = jnp.zeros((k, LANES), g.dtype).at[slot].add(g128, mode="drop")
    # Slot s is the s-th touched physical row in ASCENDING phys order (csum
    # is monotone), and unused trailing slots get vp + s — so uphys is
    # strictly ascending and duplicate-free BY CONSTRUCTION.  Telling XLA
    # so (unique + sorted) skips the sort-based dedup it otherwise wraps
    # around every scatter (visible as a fused sort in the step's HLO —
    # DESIGN §6 round 5), which is most of the sorted tail's cost.
    uphys = (jnp.int32(vp) + jnp.arange(k, dtype=jnp.int32)).at[slot].set(
        phys, mode="drop"
    )
    safe = jnp.minimum(uphys, vp - 1)
    new, acc2 = _adagrad_apply(packed[safe], accum_packed[safe], G, lr, p, d)
    packed = packed.at[uphys].set(
        new, mode="drop", unique_indices=True, indices_are_sorted=True
    )
    accum_packed = accum_packed.at[uphys].set(
        acc2, mode="drop", unique_indices=True, indices_are_sorted=True
    )
    return packed, accum_packed


# Default ceiling for the dense-G buffer: beyond this the O(VP·128)
# sweep + the extra table-sized temporary lose to the sorted sparse
# tail (and to HBM).  2 GiB ≈ 4.2M physical rows ≈ 58M logical rows at
# P=14 — far above every benchmark config; the 134M+-row single-chip
# regime stays on the sorted path unless forced.
DENSE_G_MAX_BYTES = 2 << 30


def resolve_packed_update(update: str, vp: int, accum_trailing: int) -> str:
    """'auto' | 'dense' | 'compact' | 'sorted' -> the concrete update.

    auto: dense while the G buffer stays under DENSE_G_MAX_BYTES (the
    fastest tail where its O(VP·128) sweep fits — measured 3.5× sorted at
    vocab 2^24), else compact (sort-free touched-row compaction: O(M)
    buffers, O(VP) bitmap traffic — measured ~5× sorted at vocab 201M).
    Both serve BOTH accumulator granularities.  'sorted' stays available
    explicitly (element accumulator only) as the bit-parity reference and
    for A/B probes; auto never picks it."""
    if update not in ("auto", "dense", "compact", "sorted"):
        raise ValueError(
            f"unknown packed update {update!r} (auto | dense | compact | sorted)"
        )
    if update == "sorted":
        if accum_trailing != LANES:
            raise ValueError("packed_update=sorted requires the element accumulator")
        return "sorted"
    if update in ("dense", "compact"):
        return update
    return "dense" if vp * LANES * 4 <= DENSE_G_MAX_BYTES else "compact"


def pack_accum_rows(accum: jax.Array, d: int, init_value: float) -> jax.Array:
    """[V, 1] ROW-granularity accumulator -> [VP, P] (one scalar slot per
    logical row; pad slots carry ``init_value``, never zero — the dense
    sweep divides by sqrt of every slot)."""
    p = rows_per_tile(d)
    v = accum.shape[0]
    vp = packed_rows(v, d)
    flat = jnp.full((vp * p, 1), init_value, accum.dtype).at[:v].set(accum)
    return flat.reshape(vp, p)


def unpack_accum_rows(acc_packed: jax.Array, vocab: int, d: int) -> jax.Array:
    """[VP, P] packed row accumulator -> [V, 1] logical."""
    p = rows_per_tile(d)
    return acc_packed.reshape(acc_packed.shape[0] * p, 1)[:vocab]


def pack_accum_any(accum: jax.Array, d: int, init_value: float) -> jax.Array:
    """Pack a LOGICAL accumulator of either granularity — [V, D] element
    (→ [VP, 128]) or [V, 1] row (→ [VP, P]).  The trailing-dim sniff
    lives HERE, next to the packers whose convention it encodes; callers
    (trainer.pack_state, train_step.pack_sharded_on_device, ...) must
    not re-implement it."""
    if accum.shape[-1] == 1:
        return pack_accum_rows(accum, d, init_value)
    return pack_accum(accum, init_value)


def unpack_accum_any(acc_packed: jax.Array, vocab: int, d: int) -> jax.Array:
    """Inverse of pack_accum_any: [VP, 128] → [V, D] or [VP, P] → [V, 1].

    NOTE d == 1 makes P == LANES and the two conventions coincide — then
    both branches compute the same reshape-and-slice, so the ambiguity is
    harmless by construction, not by luck."""
    if acc_packed.shape[-1] == LANES and rows_per_tile(d) != LANES:
        return unpack_table(acc_packed, vocab, d)
    return unpack_accum_rows(acc_packed, vocab, d)


def packed_sparse_adagrad_update(
    packed: jax.Array,
    accum_packed: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
):
    """Sparse Adagrad on the packed table — one-pass lane-space dedup.

    ids: [...] logical ids (ids >= packed.shape[0] * rows_per_tile(D) act
    as drop sentinels — their physical row lands past the last packed row
    and the scatter drops it; the sharded update relies on this for
    unowned ids).  Returns (packed, accum_packed).  Per-element semantics match
    optim.sparse_adagrad_update with the element accumulator: every
    element sees the occurrence-summed gradient exactly once
    (duplicate ids land in the same lanes of the same physical segment
    and sum there); untouched elements see gradient 0 — the Adagrad
    identity — so whole-row writes are exact.
    """
    d = row_grads.shape[-1]
    p = rows_per_tile(d)
    vp = packed.shape[0]
    flat_ids = ids.reshape(-1)
    m = flat_ids.shape[0]
    g = row_grads.reshape(m, d)

    # Insert each occurrence's grad into its slot lanes: [M, 128].
    slot = (flat_ids % p).astype(jnp.int32)
    g128 = lane_spread(g, slot, p, d)

    # Sort occurrences by id => physical rows grouped; WIDE permutation
    # gather moves the [M, 128] payload (full-lane rows, fast path).
    # Sentinel phys CLAMPS to exactly vp: distinct far sentinels would
    # otherwise form separate segments whose written uphys values could
    # collide with the vp+slot trailing fill below, breaking the
    # unique+sorted declaration on the RMW scatters (undefined behavior).
    order = jnp.argsort(flat_ids)
    sphys = jnp.minimum((flat_ids[order] // p).astype(jnp.int32), vp)
    g128 = g128[order]

    # Segment-sum per physical row at full width.
    is_new = jnp.concatenate([jnp.ones((1,), bool), sphys[1:] != sphys[:-1]])
    seg = jnp.cumsum(is_new) - 1
    gsum = jax.ops.segment_sum(g128, seg, num_segments=m)  # [M, 128]
    # Segment representative WITHOUT segment_max (measured ~9 ms as a 1-D
    # scatter-max): every occurrence in a segment writes the SAME sphys
    # value, so a plain scatter-set is correct regardless of which
    # duplicate wins; unwritten trailing slots get vp + slot — ascending
    # past-the-end sentinels, so uphys is strictly ascending and
    # duplicate-free (seg is monotone over sorted sphys) and the RMW
    # scatters can declare unique + sorted indices, skipping XLA's
    # sort-based scatter dedup (DESIGN §6 round 5).
    uphys = (jnp.int32(vp) + jnp.arange(m, dtype=jnp.int32)).at[seg].set(sphys)

    # RMW: one wide gather + elementwise Adagrad + one wide scatter each.
    # No validity masking needed: sentinel slots carry gsum == 0 (the
    # Adagrad identity, new == cur) and their scatter drops anyway.
    safe = jnp.minimum(uphys, vp - 1)
    cur = packed[safe]
    acc = accum_packed[safe]
    acc2 = acc + gsum * gsum
    new = cur - lr * gsum / jnp.sqrt(acc2)
    packed = packed.at[uphys].set(
        new, mode="drop", unique_indices=True, indices_are_sorted=True
    )
    accum_packed = accum_packed.at[uphys].set(
        acc2, mode="drop", unique_indices=True, indices_are_sorted=True
    )
    return packed, accum_packed


# Concrete update strategy -> implementation.  The ONE mapping every
# dispatcher uses (trainer, sharded allgather, routed alltoall) — its keys
# are exactly resolve_packed_update's outputs, so a new strategy is added
# here and in the resolver, nowhere else.
PACKED_UPDATE_FNS = {
    "dense": packed_dense_adagrad_update,
    "compact": packed_compact_adagrad_update,
    "sorted": packed_sparse_adagrad_update,
}


# --- fused row-accumulator layout (round 5) -------------------------------
#
# WHY (PROBE_UPDATE_OPS_r05): random wide gathers/scatters on this chip are
# DESCRIPTOR-bound — a [K, 256] gather costs the same as [K, 128] (10.5 vs
# 10.0 ms at K=639k) — so the sparse tail's cost is the NUMBER of random
# row ops, not their bytes.  The separate-accumulator RMW needs 4 of them
# (gather cur, gather acc, scatter new, scatter acc2); fusing the ROW
# accumulator scalar into each logical row's own tile-row slot (stride
# D+1: D row lanes + 1 accumulator lane per slot, P = 128 // (D+1) slots)
# collapses the RMW to ONE gather + ONE scatter over a single array, and
# shrinks total optimizer+param state to ~(D+1)/D of the table (the 10B-row
# regime's pairing).  Semantics are EXACTLY the row-granularity Adagrad
# (optim.py row mode: accum += ||sum-G row||², one sqrt per row) — only the
# storage address of the scalar moved.  Checkpoints stay LOGICAL ([V, D]
# table + [V, 1] accumulator), so fused runs interchange checkpoints with
# rows-layout and packed row-mode runs.


def fused_rows_per_tile(d: int) -> int:
    """Slots per 128-lane row in the fused layout: P = 128 // (D + 1)."""
    if d + 1 > LANES:
        raise ValueError(f"fused layout needs D + 1 <= {LANES}, got D={d}")
    return LANES // (d + 1)


def fused_packed_rows(vocab: int, d: int) -> int:
    return -(-vocab // fused_rows_per_tile(d))


def pack_fused(
    table: jax.Array, accum: jax.Array, init_value: float
) -> jax.Array:
    """[V, D] table + [V, 1] row accumulator -> [VPf, 128] fused rows.

    Slot s of a physical row occupies lanes [s·(D+1), s·(D+1)+D) for the
    parameter row and lane s·(D+1)+D for its accumulator scalar.  Pad
    slots and tail lanes carry ``init_value`` in the accumulator position
    and 0 in row positions (the dense sweep divides by sqrt of every
    accumulator lane, and zero-grad identity keeps pads inert)."""
    if accum.shape[-1] != 1:
        raise ValueError(
            f"fused layout packs a ROW accumulator [V, 1], got {accum.shape}"
        )
    merged = jnp.concatenate([table, accum.astype(table.dtype)], axis=-1)
    d1 = merged.shape[-1]
    p = fused_rows_per_tile(table.shape[-1])  # raises the clear D+1 > 128 error
    vp = -(-table.shape[0] // p)
    flat = jnp.full((vp * p, d1), 0.0, table.dtype).at[:, d1 - 1].set(init_value)
    flat = flat.at[: table.shape[0]].set(merged)
    out = jnp.full((vp, LANES), init_value, table.dtype)
    return out.at[:, : p * d1].set(flat.reshape(vp, p * d1))


def unpack_fused(fused: jax.Array, vocab: int, d: int):
    """[VPf, 128] fused -> ([V, D] table, [V, 1] accumulator)."""
    p = fused_rows_per_tile(d)
    d1 = d + 1
    flat = fused[:, : p * d1].reshape(fused.shape[0] * p, d1)[:vocab]
    return flat[:, :d], flat[:, d:]


def fused_gather(fused: jax.Array, ids: jax.Array, d: int) -> jax.Array:
    """rows[..., D] for logical ``ids`` from a fused table (wide gather +
    static masked slot extraction, accumulator lanes skipped)."""
    p = fused_rows_per_tile(d)
    d1 = d + 1
    phys = ids // p
    slot = ids % p
    rows128 = fused[phys]
    out = jnp.zeros(ids.shape + (d,), fused.dtype)
    for s in range(p):
        piece = rows128[..., s * d1 : s * d1 + d]
        out = out + jnp.where((slot == s)[..., None], piece, 0)
    return out


def _fused_apply(cur128, G128, lr, p: int, d: int):
    """One row-granularity Adagrad application on fused tile rows.

    cur128/G128: [*, 128] (G's accumulator lanes are zero by
    construction).  Returns the updated [*, 128] rows.  Formulas match
    optim.py row mode exactly: acc2 = acc + Σ g²; new = row − lr·g/√acc2."""
    d1 = d + 1
    used = p * d1
    view = cur128[..., :used].reshape(cur128.shape[:-1] + (p, d1))
    gview = G128[..., :used].reshape(G128.shape[:-1] + (p, d1))
    grow = gview[..., :d]
    acc2 = view[..., d] + jnp.sum(grow * grow, axis=-1)
    new_rows = view[..., :d] - lr * grow / jnp.sqrt(acc2)[..., None]
    new = jnp.concatenate([new_rows, acc2[..., None]], axis=-1)
    new = new.reshape(cur128.shape[:-1] + (used,))
    return jnp.concatenate([new, cur128[..., used:]], axis=-1)


def fused_grad128(ids: jax.Array, row_grads: jax.Array, p: int):
    """Per-occurrence [M, 128] tile rows with grads at fused slot offsets
    (accumulator lanes zero), plus the physical row per occurrence."""
    d = row_grads.shape[-1]
    flat = ids.reshape(-1)
    g = row_grads.reshape(flat.shape[0], d)
    slot = (flat % p).astype(jnp.int32)
    phys = (flat // p).astype(jnp.int32)
    gpad = jnp.pad(g, ((0, 0), (0, 1)))  # zero accumulator lane
    return lane_spread(gpad, slot, p, d + 1), phys


def fused_dense_adagrad_update(
    fused: jax.Array, ids: jax.Array, row_grads: jax.Array, lr: float
) -> jax.Array:
    """Fused-layout Adagrad via the dense-G sweep (small-vocab regime):
    one wide scatter-add into [VPf, 128], one contiguous pass over the
    fused array.  Zero-grad slots see acc2 == acc and row − 0 — the exact
    identity, so sweeping everything is exact (pad accumulator lanes hold
    init_value > 0, never 0)."""
    d = row_grads.shape[-1]
    p = fused_rows_per_tile(d)
    vp = fused.shape[0]
    g128, phys = fused_grad128(ids, row_grads, p)
    G = jnp.zeros((vp, LANES), g128.dtype).at[phys].add(g128, mode="drop")
    return _fused_apply(fused, G, lr, p, d)


def _fused_compact_k(fused, g128, phys, csum, lr, p, d, k):
    """The compaction + RMW for one static capacity ``k``: slots beyond
    k-1 drop from every scatter (only reachable when #touched > k — the
    caller's overflow cond guarantees the exact-capacity branch runs)."""
    vp = fused.shape[0]
    valid = phys < vp
    slot = jnp.where(valid, csum[jnp.minimum(phys, vp - 1)] - 1, k)
    slot = jnp.minimum(slot, k)  # overflow slots -> drop sentinel
    G = jnp.zeros((k, LANES), g128.dtype).at[slot].add(g128, mode="drop")
    uphys = (jnp.int32(vp) + jnp.arange(k, dtype=jnp.int32)).at[slot].set(
        phys, mode="drop"
    )
    cur = fused[jnp.minimum(uphys, vp - 1)]
    new = _fused_apply(cur, G, lr, p, d)
    return fused.at[uphys].set(
        new, mode="drop", unique_indices=True, indices_are_sorted=True
    )


def fused_compact_adagrad_update(
    fused: jax.Array, ids: jax.Array, row_grads: jax.Array, lr: float,
    k_cap: int = 0,
) -> jax.Array:
    """Fused-layout Adagrad via sort-free touched-row compaction — the
    giant-vocab production tail: bitmap + prefix-sum compaction (as
    packed_compact_adagrad_update), then ONE wide gather + ONE wide
    scatter (unique + sorted indices by construction) instead of the
    separate-accumulator path's four random row ops.

    ``k_cap`` > 0 additionally CAPS the compacted buffer below the exact
    worst case min(VP, M): the RMW then processes k_cap rows instead of M
    (CTR ids are Zipf — measured ~170k unique physical rows per 639k
    occurrences — so the exact cap wastes ~3× the RMW's descriptor-bound
    row ops).  Correctness is unconditional: the touched count is known
    from the prefix sum, and a batch that overflows the cap takes the
    exact-capacity branch under ``lax.cond`` — never a dropped update.
    Skew helps, uniform ids just fall back every step (the cond prices
    one compare + both compiled branches, not wrong results).  Results
    are numerically (not bitwise) equal to k_cap=0: XLA's scatter-add
    associates duplicate contributions in a shape-dependent order, so a
    smaller G buffer can sum the same addends differently (~1e-5;
    test-pinned allclose)."""
    d = row_grads.shape[-1]
    p = fused_rows_per_tile(d)
    vp = fused.shape[0]
    g128, phys = fused_grad128(ids, row_grads, p)
    m = phys.shape[0]

    k_full = min(vp, m)
    touched = jnp.zeros((vp,), jnp.int8).at[phys].set(1, mode="drop")
    csum = jnp.cumsum(touched, dtype=jnp.int32)
    if k_cap <= 0 or k_cap >= k_full:
        return _fused_compact_k(fused, g128, phys, csum, lr, p, d, k_full)
    n_touched = csum[-1]
    return jax.lax.cond(
        n_touched <= k_cap,
        lambda f: _fused_compact_k(f, g128, phys, csum, lr, p, d, k_cap),
        lambda f: _fused_compact_k(f, g128, phys, csum, lr, p, d, k_full),
        fused,
    )


def resolve_fused_update(update: str, vp: int) -> str:
    """'auto' | 'dense' | 'compact' -> the concrete fused-layout tail.

    Same size rule as resolve_packed_update; 'sorted' has no fused
    implementation (the compact path subsumes it — no sort to keep)."""
    if update == "sorted":
        raise ValueError(
            "packed_update=sorted has no fused-layout implementation "
            "(use auto, dense or compact with adagrad_accumulator=fused)"
        )
    if update not in ("auto", "dense", "compact"):
        raise ValueError(
            f"unknown packed update {update!r} (auto | dense | compact)"
        )
    if update != "auto":
        return update
    return "dense" if vp * LANES * 4 <= DENSE_G_MAX_BYTES else "compact"


FUSED_UPDATE_FNS = {
    "dense": fused_dense_adagrad_update,
    "compact": fused_compact_adagrad_update,
}


def apply_fused_update(
    fused: jax.Array, ids: jax.Array, row_grads: jax.Array, lr: float,
    mode: str, k_cap: int = 0,
) -> jax.Array:
    """The ONE fused-tail dispatch (mode -> dense | compact with its cap).
    Every fused apply site (local trainer, allgather shard update, routed
    alltoall update) calls this, so the tails cannot silently diverge."""
    if mode == "compact":
        return fused_compact_adagrad_update(fused, ids, row_grads, lr, k_cap)
    if mode != "dense":
        raise ValueError(f"unknown fused update mode {mode!r} (dense | compact)")
    return fused_dense_adagrad_update(fused, ids, row_grads, lr)
