"""Shared Pallas kernel plumbing: interpret-mode resolution.

Every Pallas kernel in this package takes an ``interpret`` flag so the
CPU tier-1 suite can run it in the Pallas interpreter.  The detection
used to be duplicated at each call site (``jax.default_backend() !=
"tpu"``); it lives here once so (a) production modules never spell
``interpret=True`` (the static-analysis suite flags the literal outside
this module — a compiled path silently running interpreted is a
throughput bug, not an error), and (b) tests need no per-test plumbing:
off-TPU the kernels interpret themselves automatically.
"""

from __future__ import annotations

import jax

__all__ = ["default_interpret", "resolve_interpret", "resolve_tail"]


def default_interpret() -> bool:
    """True off-TPU: run Pallas kernels in the interpreter (CPU tests)."""
    return jax.default_backend() != "tpu"


def resolve_interpret(interpret: bool | None) -> bool:
    """``None`` (the wrapper default) → auto-detect; a bool is explicit.

    Tests pass ``interpret=True`` explicitly; production call sites pass
    ``None`` and inherit the backend detection — the one CPU branch the
    analysis suite sanctions.
    """
    return default_interpret() if interpret is None else bool(interpret)


def resolve_tail(tail: str) -> str:
    """``[Train] tail`` → effective sparse-tail implementation.

    ``auto`` picks the Pallas tail on TPU and the XLA tail elsewhere —
    off-TPU the kernel would run interpreted (orders of magnitude slower
    than compiled XLA), so auto never selects it there.  An explicit
    ``pallas`` is honored anywhere (off-TPU it interprets — that is what
    the tier-1 parity tests run).
    """
    if tail == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return tail
