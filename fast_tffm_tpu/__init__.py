"""fast_tffm_tpu: a TPU-native factorization-machine training framework.

Built from scratch on JAX/XLA/Pallas with the capabilities of
`renyi533/fast_tffm` (TF-1.x + custom C++ ops): train/predict entrypoints
driven by an INI config, libsvm input with optional feature-id hashing,
fused arbitrary-order FM scoring kernels with hand-written backward passes,
sparse Adagrad with L2 regularization, and row-sharded embedding tables
across a TPU device mesh (the reference's `vocabulary_block_num`
parameter-server sharding, redone as `jax.sharding` + collectives).
"""

__version__ = "0.1.0"

from fast_tffm_tpu.config import Config, build_model, load_config  # noqa: F401
from fast_tffm_tpu.models import Batch, DeepFMModel, FFMModel, FMModel  # noqa: F401
from fast_tffm_tpu.ops.fm import fm_score  # noqa: F401

__all__ = [
    "Batch",
    "Config",
    "DeepFMModel",
    "FFMModel",
    "FMModel",
    "build_model",
    "fm_score",
    "load_config",
    "train",
    "dist_train",
    "predict",
    "dist_predict",
]


def __getattr__(name):
    # train/predict drivers import lazily: they pull the full driver stack
    # (checkpointing, pipelines), which library users of just the kernels
    # and models should not pay for at import time.
    if name in ("train", "dist_train"):
        import fast_tffm_tpu.train as _t

        return getattr(_t, name)
    if name in ("predict", "dist_predict"):
        import fast_tffm_tpu.predict as _p

        return getattr(_p, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
