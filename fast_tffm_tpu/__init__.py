"""fast_tffm_tpu: a TPU-native factorization-machine training framework.

Built from scratch on JAX/XLA/Pallas with the capabilities of
`renyi533/fast_tffm` (TF-1.x + custom C++ ops): train/predict entrypoints
driven by an INI config, libsvm input with optional feature-id hashing,
fused arbitrary-order FM scoring kernels with hand-written backward passes,
sparse Adagrad with L2 regularization, and row-sharded embedding tables
across a TPU device mesh (the reference's `vocabulary_block_num`
parameter-server sharding, redone as `jax.sharding` + collectives).
"""

__version__ = "0.1.0"

import importlib

# PEP 562 lazy exports.  Two reasons this is a name table and not a block
# of eager imports:
#
#   * the package exports pull in jax (models, drivers) — but the
#     telemetry module's hang-exit watchdog must be armable BEFORE
#     ``import jax`` (backend init behind a dead TPU tunnel is itself a
#     known hang point, bench.py's headnote), so
#     ``import fast_tffm_tpu.telemetry`` has to stay jax-free, which
#     means THIS module has to stay jax-free;
#   * CLI startup (`--help`, config errors) stops paying backend-init
#     latency on paths that never touch a device.
#
# Driver modules are named training/prediction — NOT train/predict — so
# the package-level FUNCTIONS (the reference's entrypoint vocabulary)
# never collide with a submodule attribute: `from fast_tffm_tpu import
# train` is always the function, and `fast_tffm_tpu.training.scan_max_nnz`
# -style module access keeps working.  Heavy optional deps (orbax) stay
# lazy inside the driver modules.
_EXPORTS = {
    "Config": "fast_tffm_tpu.config",
    "build_model": "fast_tffm_tpu.config",
    "load_config": "fast_tffm_tpu.config",
    "open_fmb": "fast_tffm_tpu.data.binary",
    "write_fmb": "fast_tffm_tpu.data.binary",
    "StreamingAUC": "fast_tffm_tpu.metrics",
    "auc": "fast_tffm_tpu.metrics",
    "AsyncCheckpointer": "fast_tffm_tpu.checkpoint_async",
    "save_checkpoint": "fast_tffm_tpu.checkpoint",
    "restore_checkpoint": "fast_tffm_tpu.checkpoint",
    "Batch": "fast_tffm_tpu.models",
    "DeepFMModel": "fast_tffm_tpu.models",
    "FFMModel": "fast_tffm_tpu.models",
    "FMModel": "fast_tffm_tpu.models",
    "fm_score": "fast_tffm_tpu.ops.fm",
    "predict": "fast_tffm_tpu.prediction",
    "dist_predict": "fast_tffm_tpu.prediction",
    "ServingEngine": "fast_tffm_tpu.serving",
    "serve_lines": "fast_tffm_tpu.serving",
    "RunMonitor": "fast_tffm_tpu.telemetry",
    "train": "fast_tffm_tpu.training",
    "dist_train": "fast_tffm_tpu.training",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is not None:
        value = getattr(importlib.import_module(mod), name)
    else:
        # The eager imports used to bind submodules as package attributes
        # (`fast_tffm_tpu.training.scan_max_nnz`-style access, documented
        # above) — keep that working lazily too.
        try:
            value = importlib.import_module(f"{__name__}.{name}")
        except ModuleNotFoundError as e:
            if e.name != f"{__name__}.{name}":
                raise  # the submodule EXISTS but one of its deps is missing
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r}"
            ) from None
    globals()[name] = value  # cache: resolve each name once
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
