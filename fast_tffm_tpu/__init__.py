"""fast_tffm_tpu: a TPU-native factorization-machine training framework.

Built from scratch on JAX/XLA/Pallas with the capabilities of
`renyi533/fast_tffm` (TF-1.x + custom C++ ops): train/predict entrypoints
driven by an INI config, libsvm input with optional feature-id hashing,
fused arbitrary-order FM scoring kernels with hand-written backward passes,
sparse Adagrad with L2 regularization, and row-sharded embedding tables
across a TPU device mesh (the reference's `vocabulary_block_num`
parameter-server sharding, redone as `jax.sharding` + collectives).
"""

__version__ = "0.1.0"

from fast_tffm_tpu.config import Config, build_model, load_config  # noqa: F401
from fast_tffm_tpu.data.binary import open_fmb, write_fmb  # noqa: F401
from fast_tffm_tpu.metrics import StreamingAUC, auc  # noqa: F401
from fast_tffm_tpu.models import Batch, DeepFMModel, FFMModel, FMModel  # noqa: F401
from fast_tffm_tpu.ops.fm import fm_score  # noqa: F401

__all__ = [
    "Batch",
    "Config",
    "DeepFMModel",
    "FFMModel",
    "FMModel",
    "StreamingAUC",
    "auc",
    "build_model",
    "fm_score",
    "load_config",
    "open_fmb",
    "write_fmb",
    "train",
    "dist_train",
    "predict",
    "dist_predict",
    "ServingEngine",
    "serve_lines",
]


# Driver modules are named training/prediction — NOT train/predict — so the
# package-level FUNCTIONS (the reference's entrypoint vocabulary) never
# collide with a submodule attribute: `from fast_tffm_tpu import train` is
# always the function, and `fast_tffm_tpu.training.scan_max_nnz`-style
# module access keeps working.  Heavy optional deps (orbax) stay lazy
# inside the driver modules.
from fast_tffm_tpu.prediction import dist_predict, predict  # noqa: F401, E402
from fast_tffm_tpu.serving import ServingEngine, serve_lines  # noqa: F401, E402
from fast_tffm_tpu.training import dist_train, train  # noqa: F401, E402
