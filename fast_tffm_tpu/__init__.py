"""fast_tffm_tpu: a TPU-native factorization-machine training framework.

Built from scratch on JAX/XLA/Pallas with the capabilities of
`renyi533/fast_tffm` (TF-1.x + custom C++ ops): train/predict entrypoints
driven by an INI config, libsvm input with optional feature-id hashing,
fused arbitrary-order FM scoring kernels with hand-written backward passes,
sparse Adagrad with L2 regularization, and row-sharded embedding tables
across a TPU device mesh (the reference's `vocabulary_block_num`
parameter-server sharding, redone as `jax.sharding` + collectives).
"""

__version__ = "0.1.0"

from fast_tffm_tpu.ops.fm import fm_score  # noqa: F401
