"""Fault-tolerant training runtime: supervised restart + fault injection.

The reference's only recovery story was TF Supervisor restart-from-
checkpoint (SURVEY.md §5), and until this module a crashed trainer here
was strictly worse: a restart silently re-trained on already-seen data
(the step counter restored, the input stream restarted from file zero),
a dead prefetch thread wedged the loop, and NaN divergence could only
abort.  Three pieces close that:

  * **Supervisor** — relaunches a crashed trainer subprocess with
    bounded retries and exponential backoff, resuming from the latest
    full+delta checkpoint chain (quarantining a torn chain TAIL first —
    ``repair_delta_chain``).  Every crash emits a ``kind=fault`` record
    and every relaunch a ``kind=restart`` record carrying the measured
    MTTR (crash → first new training progress in the child's output).
    The CLI front end is ``train --supervised`` (cli.py) and the probe
    driver is tools/chaos.py.

  * **FaultPlan / FaultInjector** — a seeded, reproducible fault
    schedule (``kill@N``, ``io_error@N``, ``nan@A[:B]``,
    ``torn_delta@K``, or ``random:kill=2,...`` drawn from a seed) whose
    injection points thread through machinery that already exists: kill
    faults ride the driver ``step_hook``, IO faults raise inside the FMB
    reader's retry loop (data/binary.py), NaN faults poison the loss the
    driver's finite-check reads, torn-delta faults truncate a published
    delta file (checkpoint_async.py).  Same seed ⇒ byte-identical
    schedule (``FaultPlan.to_json``), so chaos tests replay exactly.
    Every fault is ONE-SHOT: it fires at most once per process, so a
    supervised restart does not re-crash on the same planned fault.

  * **fault event/counter sink** — module-level, so the reader and
    checkpoint threads can note retries/faults without owning a
    RunMonitor; the training loop drains them into ``kind=fault``
    records at log points and the run summary.

This module must import WITHOUT jax (the Supervisor runs in a process
that never touches a device); everything heavier is imported lazily.
"""

from __future__ import annotations

import glob as _glob_mod
import json
import os
import re
import signal
import statistics
import subprocess
import sys
import threading
import time

__all__ = [
    "FAULT_KINDS",
    "SERVING_FAULT_KINDS",
    "STREAM_FAULT_KINDS",
    "FaultPlan",
    "RestartPolicy",
    "FaultInjector",
    "install_faults",
    "active_faults",
    "clear_faults",
    "maybe_io_fault",
    "maybe_torn_delta",
    "maybe_publish_fault",
    "note_io_retry",
    "drain_fault_events",
    "drain_fault_counters",
    "NonFiniteLossError",
    "repair_delta_chain",
    "Supervisor",
]


class NonFiniteLossError(RuntimeError):
    """A non-finite training loss, carrying the input-position cursor at
    detection time so ``on_nan = rollback`` can restore the last
    checkpoint and SKIP the offending window (resume input at the
    detection cursor instead of replaying the data that diverged)."""

    def __init__(self, message: str, *, step: int = 0, loss=None, cursor=None):
        super().__init__(message)
        self.step = int(step)
        self.loss = loss
        self.cursor = cursor


# ---------------------------------------------------------------------------
# fault event / counter sink (module-level: writers live in reader and
# checkpoint threads that own no RunMonitor)
# ---------------------------------------------------------------------------

_sink_lock = threading.Lock()
_EVENTS: list[dict] = []
_COUNTERS: dict[str, int] = {}
_MAX_EVENTS = 256  # bounded: a pathological retry storm must not eat RAM


def _record(event: dict) -> None:
    with _sink_lock:
        _COUNTERS[event["event"]] = _COUNTERS.get(event["event"], 0) + 1
        if len(_EVENTS) < _MAX_EVENTS:
            _EVENTS.append(event)


def drain_fault_events() -> list[dict]:
    """Pop all pending fault events (dicts with an ``event`` key and
    detail fields — never ``step``, which the emitter's envelope owns)."""
    with _sink_lock:
        out, _EVENTS[:] = list(_EVENTS), []
        return out


def drain_fault_counters() -> dict[str, int]:
    """Snapshot-and-clear the per-event counters (run summary fields)."""
    with _sink_lock:
        out = dict(_COUNTERS)
        _COUNTERS.clear()
        return out


def note_io_retry(what: str, exc: Exception, attempt: int = 1) -> None:
    """A transient IO error was absorbed by retry (data/binary.py's FMB
    reader) — recorded so the run's telemetry shows the near-miss."""
    _record(
        {"event": "io_retry", "what": what, "error": repr(exc), "attempt": attempt}
    )


# ---------------------------------------------------------------------------
# fault plan / injector
# ---------------------------------------------------------------------------

# New kinds append LAST: the seeded grammar draws positions in
# FAULT_KINDS order, so inserting one earlier would silently reshuffle
# every existing seed's schedule (byte-identity is test-pinned).
FAULT_KINDS = (
    "kill",
    "io_error",
    "nan",
    "torn_delta",
    "kill_publish",
    "replica_kill",
    "replica_slow",
    "reload_corrupt",
    "stream_stall",
    "append_torn",
    "kill_writeback",
)

# Which ordinal each kind's ``@N`` counts (documented here, enforced by
# the injection points): kill/nan = absolute training step; io_error =
# Nth FMB read operation; torn_delta = Kth delta-file write; kill_publish
# = Kth npz publish (full or delta, in publish order) — SIGKILL between
# the finished tmp write and the atomic rename, the torn-publish window;
# kill_writeback (ISSUE 12, appended LAST) = Kth paramstore
# eviction-writeback apply — SIGKILL MID-apply (some cold-store pages
# dirty, the boundary not yet stamped), the exact window the tiered
# chain's redo invariant must survive.
#
# SERVING kinds (ISSUE 8; executed by tools/chaos.py --serve against a
# live front end, not by the in-process FaultInjector): ``@N`` is the
# REPLICA index (0-based, so >= 0 is legal for these alone).
# replica_kill@N = SIGKILL replica N; replica_slow@N:MS = inject MS ms of
# per-flush latency into replica N (the wedged-not-dead axis);
# reload_corrupt@N = corrupt the checkpoint file so the watcher fan-out's
# Nth reload wave fails (replicas must keep serving the loaded state).
SERVING_FAULT_KINDS = ("replica_kill", "replica_slow", "reload_corrupt")

# STREAM kinds (ISSUE 11; executed by the soak harness's stream WRITER —
# tools/soak.py / data/stream.py's StreamWriter — not by the in-process
# FaultInjector): stream_stall@N = the writer pauses N SECONDS mid-run
# (the trainer's follow reader must go idle, classify the starved loop
# as input-starved (stream-idle), and resume cleanly when bytes land);
# append_torn@K = the Kth append leaves a PARTIAL trailing record on
# disk for a while (the reader must wait it out, never parse it).
STREAM_FAULT_KINDS = ("stream_stall", "append_torn")


class FaultPlan:
    """A concrete, ordered fault schedule.  Byte-identical across runs
    for the same (spec, seed, horizon) — ``to_json`` is the pin."""

    def __init__(self, events: list[dict], *, spec: str = "", seed: int = 0):
        for e in events:
            if e.get("kind") not in FAULT_KINDS:
                raise ValueError(
                    f"unknown fault kind {e.get('kind')!r} (one of {FAULT_KINDS})"
                )
            floor = 0 if e["kind"] in SERVING_FAULT_KINDS else 1
            if int(e.get("at", floor - 1)) < floor:
                raise ValueError(f"fault position must be >= {floor}: {e}")
        self.events = sorted(
            (
                {k: int(v) if k in ("at", "until") else v for k, v in e.items()}
                for e in events
            ),
            key=lambda e: (e["at"], e["kind"]),
        )
        self.spec = spec
        self.seed = int(seed)

    @classmethod
    def parse(cls, spec: str, *, seed: int = 0, horizon: int = 1000) -> "FaultPlan":
        """Two grammars:

        * explicit — ``"kill@120,io_error@45,nan@200:210,torn_delta@1"``
          (``nan@A:B`` poisons the first checked step in [A, B));
        * seeded — ``"random:kill=2,io_error=3,nan=1"`` draws that many
          positions per kind in [1, horizon) from ``random.Random(seed)``
          (torn_delta positions draw in [1, max(2, horizon // 50))).

        Same (spec, seed, horizon) ⇒ the same schedule, byte for byte.
        """
        import random

        spec = spec.strip()
        if not spec:
            raise ValueError("empty fault plan spec")
        if spec.startswith("random:"):
            rng = random.Random(int(seed))
            counts: dict[str, int] = {}
            for tok in spec[len("random:") :].split(","):
                tok = tok.strip()
                if not tok:
                    continue
                kind, _, n = tok.partition("=")
                kind = kind.strip()
                if kind not in FAULT_KINDS:
                    raise ValueError(
                        f"unknown fault kind {kind!r} in {spec!r} (one of {FAULT_KINDS})"
                    )
                counts[kind] = int(n or 1)
            events = []
            # Fixed kind order: the draw sequence (and thus the schedule)
            # must not depend on dict/spec ordering.
            for kind in FAULT_KINDS:
                for _ in range(counts.get(kind, 0)):
                    if kind in SERVING_FAULT_KINDS:
                        # ``at`` is a replica INDEX; a 2-replica front end
                        # is the canonical chaos topology.  replica_slow
                        # also draws its per-flush latency.
                        e = {"kind": kind, "at": rng.randrange(0, 2)}
                        if kind == "replica_slow":
                            e["until"] = rng.randrange(50, 501)
                        events.append(e)
                        continue
                    if kind == "stream_stall":
                        # ``at`` is a pause in SECONDS — keep seeded
                        # schedules short enough for bounded soak runs.
                        events.append({"kind": kind, "at": rng.randrange(1, 6)})
                        continue
                    # Per-write/publish/append ordinals are small numbers;
                    # step ordinals span the horizon.
                    hi = (
                        max(2, horizon // 50)
                        if kind
                        in ("torn_delta", "kill_publish", "append_torn",
                            "kill_writeback")
                        else max(2, horizon)
                    )
                    events.append({"kind": kind, "at": rng.randrange(1, hi)})
            return cls(events, spec=spec, seed=seed)
        events = []
        for tok in spec.split(","):
            tok = tok.strip()
            if not tok:
                continue
            kind, _, pos = tok.partition("@")
            kind = kind.strip()
            if kind not in FAULT_KINDS or not pos:
                raise ValueError(
                    f"bad fault token {tok!r} (want kind@pos, kind one of {FAULT_KINDS})"
                )
            at, _, until = pos.partition(":")
            e = {"kind": kind, "at": int(at)}
            if until:
                # ``:`` suffixes: nan@A:B = step window [A, B);
                # replica_slow@N:MS = MS ms of injected per-flush latency.
                if kind == "nan":
                    e["until"] = int(until)
                    if e["until"] <= e["at"]:
                        # An inverted/empty window would parse fine and then
                        # never fire — a chaos run that silently tested
                        # nothing.
                        raise ValueError(
                            f"empty nan window {tok!r}: until must be > at"
                        )
                elif kind == "replica_slow":
                    e["until"] = int(until)
                    if e["until"] < 1:
                        raise ValueError(
                            f"replica_slow latency must be >= 1 ms: {tok!r}"
                        )
                else:
                    raise ValueError(
                        f"only nan faults take a window (and replica_slow a "
                        f"latency) after ':': {tok!r}"
                    )
            elif kind == "replica_slow":
                raise ValueError(
                    f"replica_slow needs a latency: replica_slow@N:MS, got {tok!r}"
                )
            events.append(e)
        return cls(events, spec=spec, seed=seed)

    def to_json(self) -> str:
        """Canonical serialization — the byte-identity acceptance pin."""
        return json.dumps(
            {"seed": self.seed, "spec": self.spec, "events": self.events},
            sort_keys=True,
            separators=(",", ":"),
        )

    def serving_events(self) -> list[dict]:
        """The serving-tier faults (replica_kill/slow, reload_corrupt) in
        schedule order — tools/chaos.py --serve executes these against a
        live front end; the in-process FaultInjector ignores them."""
        return [e for e in self.events if e["kind"] in SERVING_FAULT_KINDS]

    def stream_events(self) -> list[dict]:
        """The stream-writer faults (stream_stall, append_torn) in
        schedule order — executed by the soak harness's event writer
        (tools/soak.py); the in-process FaultInjector ignores them."""
        return [e for e in self.events if e["kind"] in STREAM_FAULT_KINDS]


class FaultInjector:
    """Executes a FaultPlan through the runtime's injection points.

    Thread-safe (the IO faults fire in the prefetch thread, torn-delta
    faults in the checkpoint writer thread, kill/nan in the loop
    thread).  Every fault is one-shot.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._kills = sorted(
            e["at"] for e in plan.events if e["kind"] == "kill"
        )
        self._nans = sorted(
            (e["at"], e.get("until", e["at"] + 1))
            for e in plan.events
            if e["kind"] == "nan"
        )
        self._io = {e["at"] for e in plan.events if e["kind"] == "io_error"}
        self._torn = {e["at"] for e in plan.events if e["kind"] == "torn_delta"}
        self._kill_publish = {
            e["at"] for e in plan.events if e["kind"] == "kill_publish"
        }
        self._kill_writeback = {
            e["at"] for e in plan.events if e["kind"] == "kill_writeback"
        }
        self._io_ops = 0
        self._delta_writes = 0
        self._publishes = 0

    # -- step-hook faults (loop thread) -----------------------------------

    def step_hook(self, step: int) -> None:
        """Driver ``step_hook``: SIGKILL the process at the first hooked
        step >= each planned kill (hooks fire K-step-aligned under step
        fusion, so >= not ==)."""
        fire = False
        with self._lock:
            while self._kills and step >= self._kills[0]:
                self._kills.pop(0)
                fire = True
        if fire:
            # No cleanup, no flush — SIGKILL is the point (the checkpoint
            # chain's crash-consistency is what the chaos test exercises).
            os.kill(os.getpid(), signal.SIGKILL)

    def nan_due(self, step: int) -> bool:
        """True exactly once for the first checked step inside a planned
        nan window (the driver then poisons that step's loss)."""
        with self._lock:
            while self._nans:
                at, until = self._nans[0]
                if step >= until:
                    self._nans.pop(0)  # window missed entirely (K-alignment)
                    continue
                if step >= at:
                    self._nans.pop(0)
                    _record({"event": "injected_nan", "at": step, "planned_at": at})
                    return True
                return False
        return False

    # -- reader faults (prefetch thread) ----------------------------------

    def on_io_op(self, what: str) -> None:
        """Called per FMB read operation; raises a synthetic transient
        OSError on planned ordinals (the reader's retry absorbs it)."""
        with self._lock:
            self._io_ops += 1
            n = self._io_ops
            due = n in self._io
            if due:
                self._io.discard(n)
        if due:
            _record({"event": "injected_io_error", "op": n, "what": what})
            raise OSError(f"injected transient IO fault (op #{n}, {what})")

    # -- checkpoint faults (writer thread) --------------------------------

    def on_publish(self, path: str) -> None:
        """Called by the npz writers between finishing the tmp file and
        the atomic rename; SIGKILLs the process on the Kth publish — a
        crash in the exact window where a non-atomic publish would tear.
        The chain head on disk must stay loadable (test-pinned)."""
        with self._lock:
            self._publishes += 1
            n = self._publishes
            due = n in self._kill_publish
            if due:
                self._kill_publish.discard(n)
        if due:
            _record({"event": "injected_kill_publish", "publish": n, "path": path})
            os.kill(os.getpid(), signal.SIGKILL)

    def on_writeback_apply(self, ordinal: int) -> None:
        """Called by the paramstore's post-publish store apply, AFTER the
        first chunk of cold-store row writes lands (dirty pages on disk,
        ``applied_sig`` not yet stamped); SIGKILLs on the Kth apply.  The
        chain must replay those rows idempotently on restore
        (test-pinned)."""
        with self._lock:
            due = ordinal in self._kill_writeback
            if due:
                self._kill_writeback.discard(ordinal)
        if due:
            _record({"event": "injected_kill_writeback", "apply": ordinal})
            os.kill(os.getpid(), signal.SIGKILL)

    def on_delta_write(self, path: str) -> None:
        """Called after each delta-file publish; truncates the Kth one to
        simulate a torn write (what a crash mid-copy on a non-atomic
        filesystem leaves behind)."""
        with self._lock:
            self._delta_writes += 1
            n = self._delta_writes
            due = n in self._torn
            if due:
                self._torn.discard(n)
        if not due:
            return
        try:
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(1, size // 3))
            _record({"event": "injected_torn_delta", "path": path, "write": n})
        except OSError:
            pass


_active_lock = threading.Lock()
_ACTIVE: FaultInjector | None = None


def install_faults(plan: FaultPlan) -> FaultInjector:
    """Arm ``plan`` process-wide; returns the injector (its
    ``step_hook`` is what the CLI passes to the driver)."""
    global _ACTIVE
    inj = FaultInjector(plan)
    with _active_lock:
        _ACTIVE = inj
    return inj


def active_faults() -> FaultInjector | None:
    return _ACTIVE


def clear_faults() -> None:
    global _ACTIVE
    with _active_lock:
        _ACTIVE = None


def maybe_io_fault(what: str) -> None:
    """FMB-reader injection point (no-op unless a plan is armed)."""
    inj = _ACTIVE
    if inj is not None:
        inj.on_io_op(what)


def maybe_torn_delta(path: str) -> None:
    """Delta-writer injection point (no-op unless a plan is armed)."""
    inj = _ACTIVE
    if inj is not None:
        inj.on_delta_write(path)


def maybe_publish_fault(path: str) -> None:
    """npz-publish injection point, called between the tmp write and the
    atomic rename (no-op unless a plan is armed)."""
    inj = _ACTIVE
    if inj is not None:
        inj.on_publish(path)


def maybe_writeback_fault(ordinal: int) -> None:
    """Paramstore writeback-apply injection point (no-op unless a plan is
    armed) — fires mid-apply on the Kth boundary apply."""
    inj = _ACTIVE
    if inj is not None:
        inj.on_writeback_apply(ordinal)


# ---------------------------------------------------------------------------
# delta-chain repair (crash recovery for torn tails)
# ---------------------------------------------------------------------------

_DELTA_RE = re.compile(r"\.delta-(\d{4})\.npz$")


def _delta_files(path: str) -> list[str]:
    out = []
    for p in _glob_mod.glob(_glob_mod.escape(path) + ".delta-*.npz"):
        m = _DELTA_RE.search(p)
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def _npz_str(z, key) -> str | None:
    import numpy as np

    if key not in getattr(z, "files", ()):
        return None
    return bytes(np.asarray(z[key]).tobytes()).decode()


def repair_delta_chain(path: str, log=print) -> list[str]:
    """Quarantine a torn/unchained delta-chain TAIL so resume can land on
    the last good link.

    ``restore_checkpoint`` is strict on purpose (a torn delta fails
    loudly naming the file); the SUPERVISOR calls this before each
    relaunch because a crash mid-delta-write legitimately leaves a
    truncated tail file behind on non-atomic filesystems (the npz
    publish is tmp+rename, but the chaos torn-delta fault — and a dying
    disk — are exactly what this guards).  Every delta from the first
    unreadable/unchained link ONWARD is renamed ``*.corrupt`` (later
    links chain from the bad one, so none of them can apply either);
    the input cursor stored in the new chain head keeps resumed
    training consistent — the quarantined windows' data simply
    re-trains.  Returns the quarantined paths (empty = chain healthy).

    numpy-only on purpose: the Supervisor process never imports jax.
    """
    import numpy as np

    if not os.path.isfile(path):
        return []
    try:
        with np.load(path, allow_pickle=False) as z:
            expect = _npz_str(z, "save_id")
    # analysis: ok exception-hygiene by contract ANY unreadable base means "nothing a tail repair can fix" — the strict restore path reports the corruption loudly
    except Exception:
        return []
    deltas = _delta_files(path)
    bad_from, reason = None, ""
    for i, dp in enumerate(deltas):
        try:
            with np.load(dp, allow_pickle=False) as z:
                for name in z.files:  # full read = CRC/truncation check
                    np.asarray(z[name])
                parent = _npz_str(z, "parent_sig")
                sid = _npz_str(z, "save_id")
        except Exception as e:
            bad_from, reason = i, f"unreadable ({type(e).__name__})"
            break
        if expect is None or parent != expect:
            bad_from, reason = i, "chain break (parent_sig mismatch)"
            break
        expect = sid
    if bad_from is None:
        return []
    quarantined = []
    for dp in deltas[bad_from:]:
        try:
            os.replace(dp, dp + ".corrupt")
            quarantined.append(dp + ".corrupt")
        except OSError:
            pass
    log(
        f"resilience: quarantined {len(quarantined)} delta file(s) from "
        f"{os.path.basename(deltas[bad_from])!r} on — {reason}; resuming "
        "from the last good chain link"
    )
    _record({"event": "chain_repair", "quarantined": len(quarantined), "reason": reason})
    return quarantined


# ---------------------------------------------------------------------------
# restart policy (shared by the training Supervisor and the serving router)
# ---------------------------------------------------------------------------


class RestartPolicy:
    """Bounded retries + exponential backoff, as data: ``backoff(attempt)``
    returns the pre-relaunch sleep for restart ``attempt`` (1-based), or
    None once the budget is spent.  The training Supervisor and the
    serving router (serving/router.py — the Supervisor's serving mode)
    must degrade identically, so the arithmetic lives in one place."""

    def __init__(self, max_restarts: int, backoff_s: float, backoff_max_s: float):
        self.max_restarts = max(0, int(max_restarts))
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)

    def backoff(self, attempt: int) -> float | None:
        if attempt > self.max_restarts:
            return None
        return min(self.backoff_s * (2.0 ** (attempt - 1)), self.backoff_max_s)


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

# Child-output lines that count as "training made new progress" — the
# MTTR clock (crash → first new step) stops at the first match AFTER a
# relaunch.  Step lines are the precise signal (resolution = the child's
# log_every); the checkpoint/done lines cover runs shorter than one log
# window.  "resumed from" is deliberately NOT here: restore completing
# is not yet a new step.
_STEP_RE = re.compile(r"^step (\d+) ")
_PROGRESS_MARKERS = ("checkpoint ->", "training done:", "stopped on signal")


class Supervisor:
    """Relaunch a crashed trainer with bounded retries + exponential
    backoff (the TF-Supervisor capability, process-level).

    ``build_cmd(attempt, resume)`` returns the child argv for launch
    ``attempt`` (0 = first); ``resume`` is True when a checkpoint exists
    to continue from (the caller appends ``--resume``).  Telemetry goes
    to ``metrics_path`` via a RunMonitor with ``source="supervisor"``:
    one ``kind=fault`` (event=crash) per child death, one
    ``kind=restart`` per relaunch carrying the backoff used and the
    measured MTTR; the close summary totals restarts and the MTTR
    median.  Exit code: the child's final rc (0 on eventual success).

    **Pod mode** (``processes = N > 1``): the supervisor manages all N
    hosts of one multi-process dist_train.  ``build_cmd(attempt, resume,
    process_index)`` then takes the child's process index, children get
    the FM_DIST_* env contract (distributed.py), and the supervisor owns
    the pod's *generation file*: when ONE child dies, only that child is
    relaunched — the survivors' GenerationWatcher threads see the bumped
    generation and re-exec in place (same PID) — and the whole pod
    rendezvouses on a fresh coordinator port, restores the shared chain
    head, and resumes at the saved cursor vector.  ``kind=fault`` /
    ``kind=restart`` records carry the child's process index; the
    bounded-restart and exponential-backoff semantics are exactly the
    single-child ones, counted per incident.  ``straggler_timeout_s``
    > 0 additionally SIGKILLs a child whose heartbeat file goes stale
    (a wedged-not-dead host — the collective-entry timeout), which then
    takes the normal relaunch path.
    """

    def __init__(
        self,
        build_cmd,
        *,
        model_file: str,
        max_restarts: int = 5,
        backoff_s: float = 1.0,
        backoff_max_s: float = 30.0,
        metrics_path: str | None = None,
        run_id: str = "",
        log=print,
        child_log=None,
        sleep=time.sleep,
        repair: bool = True,
        env: dict | None = None,
        processes: int = 1,
        runtime_dir: str | None = None,
        coordinator_host: str = "127.0.0.1",
        straggler_timeout_s: float = 0.0,
    ):
        self._build_cmd = build_cmd
        self._model_file = model_file
        self._policy = RestartPolicy(max_restarts, backoff_s, backoff_max_s)
        self._metrics_path = metrics_path
        self._run_id = run_id
        self._log = log
        self._child_log = child_log
        self._sleep = sleep
        self._repair = repair
        self._env = env
        self._processes = max(1, int(processes))
        self._runtime_dir = runtime_dir
        self._coordinator_host = coordinator_host
        self._straggler_timeout_s = float(straggler_timeout_s)
        if self._processes > 1 and not runtime_dir:
            raise ValueError("pod mode (processes > 1) requires runtime_dir")
        self.restarts = 0
        self.mttr_s: list[float] = []
        self.last_rc: int | None = None

    def _tail(self, proc, first_progress_t, last_step, on_progress=None) -> None:
        from fast_tffm_tpu.telemetry import log_quietly

        try:
            for line in proc.stdout:
                line = line.rstrip("\n")
                m = _STEP_RE.match(line)
                if m:
                    last_step[0] = int(m.group(1))
                if first_progress_t[0] is None and (
                    m or any(p in line for p in _PROGRESS_MARKERS)
                ):
                    first_progress_t[0] = time.monotonic()
                    if on_progress is not None:
                        try:
                            on_progress()
                        # analysis: ok exception-hygiene owner-injected progress callback; the tail thread must survive any callback bug (MTTR already stamped)
                        except Exception:
                            pass
                log_quietly(self._child_log, line)
        except (OSError, ValueError):
            pass  # a closed pipe on kill is expected, not an error

    def run(self, resume: bool = False) -> int:
        if self._processes > 1:
            return self._run_pod(resume=resume)
        from fast_tffm_tpu.telemetry import RunMonitor

        monitor = RunMonitor(
            self._metrics_path, run_id=self._run_id, source="supervisor",
            log=self._log,
        )
        attempt = 0
        crash_t = None
        prev_rc = None
        used_backoff = 0.0
        try:
            while True:
                do_resume = resume if attempt == 0 else os.path.exists(self._model_file)
                cmd = self._build_cmd(attempt, do_resume)
                self._log(
                    f"supervisor: launch attempt {attempt}"
                    f"{' (resume)' if do_resume else ''}: {' '.join(cmd)}"
                )
                first_progress_t = [None]
                last_step = [0]
                # The kind=restart record (and its MTTR) is emitted the
                # moment the relaunched child makes new progress — a
                # recovered trainer may then run for days, and a record
                # deferred to its exit would leave the crash unmatched in
                # the metrics stream that whole time.  A child that dies
                # again before ANY progress gets the record post-mortem
                # (mttr_s null) from the loop below.
                restart_lock = threading.Lock()
                restart_emitted = [False]

                def emit_restart(attempt=attempt, prev_rc=prev_rc,
                                 backoff=used_backoff, crash_t=crash_t):
                    with restart_lock:
                        if restart_emitted[0]:
                            return
                        restart_emitted[0] = True
                    # MTTR: previous crash -> this child's first new
                    # progress (includes the backoff sleep — that IS
                    # recovery time the fleet pays).
                    mttr = None
                    if first_progress_t[0] is not None and crash_t is not None:
                        mttr = round(first_progress_t[0] - crash_t, 3)
                        self.mttr_s.append(mttr)
                    monitor.emit(
                        "restart",
                        step=last_step[0],
                        attempt=attempt,
                        exit_code=prev_rc,
                        backoff_s=round(backoff, 3),
                        mttr_s=mttr,
                    )

                proc = subprocess.Popen(
                    cmd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    env=self._env,
                )
                reader = threading.Thread(
                    target=self._tail,
                    args=(proc, first_progress_t, last_step,
                          emit_restart if attempt > 0 else None),
                    name="supervisor-tail",
                    daemon=True,
                )
                reader.start()
                rc = proc.wait()
                reader.join(timeout=10.0)
                self.last_rc = rc
                if attempt > 0:
                    emit_restart()  # no-op when first progress already did
                if rc == 0:
                    self._log(
                        f"supervisor: trainer completed cleanly after "
                        f"{attempt} restart(s)"
                    )
                    return 0
                crash_t = time.monotonic()
                sig = -rc if rc < 0 else None
                monitor.emit(
                    "fault",
                    step=last_step[0],
                    event="crash",
                    exit_code=rc,
                    signal=sig,
                    attempt=attempt,
                )
                self._log(
                    f"supervisor: trainer died (rc={rc}"
                    + (f", signal {sig}" if sig else "")
                    + f") around step {last_step[0]}"
                )
                used_backoff = self._policy.backoff(attempt + 1)
                if used_backoff is None:
                    self._log(
                        f"supervisor: giving up after {attempt} restart(s) "
                        f"(restart_max = {self._policy.max_restarts})"
                    )
                    return rc
                if self._repair:
                    try:
                        repair_delta_chain(self._model_file, log=self._log)
                    except Exception as e:
                        self._log(f"supervisor: chain repair failed: {e!r}")
                if used_backoff > 0:
                    self._log(f"supervisor: backing off {used_backoff:.1f}s before relaunch")
                    self._sleep(used_backoff)
                prev_rc = rc
                attempt += 1
                self.restarts = attempt
        finally:
            summary: dict = {"supervisor_restarts": self.restarts}
            if self.mttr_s:
                summary["mttr_s_median"] = round(statistics.median(self.mttr_s), 3)
                summary["mttr_s_max"] = round(max(self.mttr_s), 3)
            monitor.close(**summary)

    # -- pod mode ----------------------------------------------------------

    def _clear_heartbeats(self) -> None:
        """Remove hb-* files so only THIS run's heartbeats are judged."""
        for p in range(self._processes):
            try:
                os.remove(os.path.join(self._runtime_dir, f"hb-{p}.json"))
            except OSError:
                pass

    def _pod_launch(self, p: int, attempt: int, resume: bool, generation: int, monitor):
        """Start child ``p`` into pod ``generation``; returns its record."""
        from fast_tffm_tpu.distributed import (
            ENV_GENERATION,
            ENV_PROCESS_ID,
            ENV_PROCESSES,
            ENV_RUNTIME_DIR,
        )

        cmd = self._build_cmd(attempt, resume, p)
        env = dict(self._env if self._env is not None else os.environ)
        env[ENV_RUNTIME_DIR] = self._runtime_dir
        env[ENV_PROCESS_ID] = str(p)
        env[ENV_PROCESSES] = str(self._processes)
        env[ENV_GENERATION] = str(generation)
        self._log(
            f"supervisor: launch host {p} attempt {attempt} gen {generation}"
            f"{' (resume)' if resume else ''}: {' '.join(cmd)}"
        )
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        rec = {
            "process": p,
            "proc": proc,
            "attempt": attempt,
            "launched_wall": time.time(),  # straggler grace anchor
            "first_progress_t": [None],
            "last_step": [0],
            "restart_emitted": [False],
            "crash_t": None,  # set by the incident that relaunched it
            "prev_rc": None,
            "backoff": 0.0,
        }

        def emit_restart():
            if rec["attempt"] == 0 or rec["restart_emitted"][0]:
                return
            rec["restart_emitted"][0] = True
            mttr = None
            if rec["first_progress_t"][0] is not None and rec["crash_t"] is not None:
                mttr = round(rec["first_progress_t"][0] - rec["crash_t"], 3)
                self.mttr_s.append(mttr)
            monitor.emit(
                "restart",
                step=rec["last_step"][0],
                attempt=rec["attempt"],
                exit_code=rec["prev_rc"],
                backoff_s=round(rec["backoff"], 3),
                mttr_s=mttr,
                process=p,
            )

        rec["emit_restart"] = emit_restart
        tail = threading.Thread(
            target=self._tail,
            args=(proc, rec["first_progress_t"], rec["last_step"],
                  emit_restart if attempt > 0 else None),
            name=f"supervisor-tail-{p}",
            daemon=True,
        )
        tail.start()
        rec["tail"] = tail
        return rec

    def _run_pod(self, resume: bool = False) -> int:
        """Supervise N pod hosts: on a crash, bump the generation (fresh
        coordinator port — survivors re-exec in place via their
        GenerationWatcher), repair the chain, relaunch ONLY the dead
        host(s), bounded by max_restarts incidents with exponential
        backoff.  Returns 0 when every host finishes cleanly."""
        from fast_tffm_tpu.distributed import (
            PEER_LOST_EXIT,
            free_port,
            read_heartbeat,
            write_generation,
        )
        from fast_tffm_tpu.telemetry import RunMonitor

        monitor = RunMonitor(
            self._metrics_path, run_id=self._run_id, source="supervisor",
            log=self._log,
        )
        generation = 0
        attempt = 0  # incident ordinal (bounded by max_restarts)
        n = self._processes
        self._clear_heartbeats()  # a previous run's stale files must not
        #   read as stragglers before the children even start
        write_generation(
            self._runtime_dir,
            {
                "generation": generation,
                "coordinator": f"{self._coordinator_host}:{free_port()}",
                "num_processes": n,
                "cause": "start",
            },
        )
        children = {
            p: self._pod_launch(p, 0, resume, generation, monitor) for p in range(n)
        }
        relaunched: list[dict] = []  # every attempt>0 rec, for post-mortems
        finished: dict[int, int] = {}
        final_rc = 0
        try:
            while children:
                time.sleep(0.2)
                dead = {
                    p: rec for p, rec in children.items()
                    if rec["proc"].poll() is not None
                }
                for p, rec in dead.items():
                    rec["tail"].join(timeout=5.0)
                crashed = {}
                for p, rec in dead.items():
                    rc = rec["proc"].returncode
                    self.last_rc = rc
                    del children[p]
                    if rc == 0:
                        finished[p] = 0
                        self._log(f"supervisor: host {p} completed cleanly")
                    else:
                        crashed[p] = (rec, rc)
                if crashed:
                    crash_t = time.monotonic()
                    for p, (rec, rc) in crashed.items():
                        sig = -rc if rc < 0 else None
                        monitor.emit(
                            "fault",
                            step=rec["last_step"][0],
                            event="crash",
                            exit_code=rc,
                            signal=sig,
                            attempt=attempt,
                            process=p,
                        )
                        self._log(
                            f"supervisor: host {p} died (rc={rc}"
                            + (f", signal {sig}" if sig else "")
                            + (" — peer-lost exit" if rc == PEER_LOST_EXIT else "")
                            + f") around step {rec['last_step'][0]}"
                        )
                    if finished:
                        # Part of the pod already finished the run: the
                        # relaunch could never re-form an N-process
                        # rendezvous.  Unrecoverable by relaunch.
                        final_rc = next(rc for _, rc in crashed.values())
                        self._log(
                            "supervisor: crash after other hosts finished — "
                            "cannot re-form the pod; giving up"
                        )
                        break
                    backoff = self._policy.backoff(attempt + 1)
                    if backoff is None:
                        final_rc = next(rc for _, rc in crashed.values())
                        self._log(
                            f"supervisor: giving up after {attempt} restart "
                            f"incident(s) (restart_max = "
                            f"{self._policy.max_restarts})"
                        )
                        break
                    attempt += 1
                    self.restarts = attempt
                    if self._repair:
                        try:
                            repair_delta_chain(self._model_file, log=self._log)
                        except Exception as e:
                            self._log(f"supervisor: chain repair failed: {e!r}")
                    generation += 1
                    write_generation(
                        self._runtime_dir,
                        {
                            "generation": generation,
                            "coordinator": f"{self._coordinator_host}:{free_port()}",
                            "num_processes": n,
                            "cause": f"host {sorted(crashed)} crashed",
                        },
                    )
                    if backoff > 0:
                        self._log(
                            f"supervisor: backing off {backoff:.1f}s before "
                            f"relaunching host(s) {sorted(crashed)}"
                        )
                        self._sleep(backoff)
                    do_resume = os.path.exists(self._model_file)
                    for p, (rec, rc) in crashed.items():
                        new = self._pod_launch(p, attempt, do_resume, generation, monitor)
                        new["crash_t"] = crash_t
                        new["prev_rc"] = rc
                        new["backoff"] = backoff
                        children[p] = new
                        relaunched.append(new)
                    continue
                if self._straggler_timeout_s > 0:
                    for p, rec in list(children.items()):
                        _, age = read_heartbeat(self._runtime_dir, p)
                        # Grace: only a heartbeat written by THIS
                        # incarnation (mtime after its launch) can go
                        # stale — bring-up (python + jax + rendezvous)
                        # writes nothing and must never read as a
                        # straggler, nor may a previous run's old file.
                        if (
                            age is not None
                            and age > self._straggler_timeout_s
                            and time.time() - age > rec["launched_wall"]
                        ):
                            monitor.emit(
                                "fault",
                                step=rec["last_step"][0],
                                event="straggler_kill",
                                process=p,
                                age_s=round(age, 3),
                            )
                            self._log(
                                f"supervisor: host {p} heartbeat stale "
                                f"{age:.1f}s > {self._straggler_timeout_s:.1f}s "
                                "— SIGKILLing the straggler"
                            )
                            try:
                                rec["proc"].kill()
                            except OSError:
                                pass
            else:
                self._log(
                    f"supervisor: pod completed cleanly after {attempt} "
                    "restart incident(s)"
                )
                return 0
            # Broken out of the loop: tear the remaining children down.
            for p, rec in children.items():
                if rec["proc"].poll() is None:
                    rec["proc"].kill()
            for p, rec in children.items():
                rec["proc"].wait()
                rec["tail"].join(timeout=5.0)
            return final_rc or 1
        finally:
            # Restart records not yet emitted at first progress (child
            # finished instantly, or died again before any progress) —
            # emit_restart is idempotent, so double emission cannot happen.
            for rec in relaunched:
                rec["emit_restart"]()
            summary: dict = {"supervisor_restarts": self.restarts}
            if self.mttr_s:
                summary["mttr_s_median"] = round(statistics.median(self.mttr_s), 3)
                summary["mttr_s_max"] = round(max(self.mttr_s), 3)
            monitor.close(**summary)
