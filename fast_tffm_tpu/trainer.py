"""Model-agnostic jitted train/predict steps (single device).

The TPU-native analog of the reference's session step loop
(`renyi533/fast_tffm` :: local trainer: sess.run(train_op) over the graph
parser → gather → scorer → loss → Adagrad scatter-add).  Here one jitted
function fuses gather → fused scorer (custom VJP) → loss → dedup →
sparse Adagrad scatter; XLA compiles the whole step into a single program.

The mesh-sharded variant lives in parallel/train_step.py and reuses these
loss pieces; this module is also its single-shard reference semantics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from fast_tffm_tpu.models.base import Batch, logistic_loss
from fast_tffm_tpu.optim import (
    AdagradState,
    dense_adagrad_update,
    init_adagrad,
    init_table_adagrad,
    sparse_adagrad_update,
)

__all__ = [
    "TrainState",
    "init_state",
    "train_step_body",
    "make_train_step",
    "make_decayed_body",
    "make_dedup_body",
    "make_pallas_tail_body",
    "make_accum_restart",
    "make_scanned_train_step",
    "make_predict_step",
    "pack_state",
    "init_packed_state",
    "packed_train_step_body",
    "make_packed_train_step",
    "make_packed_predict_step",
]


class TrainState(NamedTuple):
    table: jax.Array  # [V, D] sparse parameter table
    table_opt: AdagradState
    dense: Any  # dense params pytree ({} for FM/FFM)
    dense_opt: Any
    step: jax.Array  # i64 scalar


def init_state(
    model,
    key: jax.Array,
    init_accumulator_value: float = 0.1,
    accumulator: str = "element",
) -> TrainState:
    """``accumulator``: table-accumulator granularity — ``element`` ([V, D],
    TF-Adagrad parity) or ``row`` ([V, 1], D×-smaller optimizer state;
    measured speed-neutral — see optim.py).  The dense (MLP) path is
    always element-wise."""
    k1, k2 = jax.random.split(key)
    table = model.init_table(k1)
    dense = model.init_dense(k2)
    return TrainState(
        table=table,
        table_opt=init_table_adagrad(table, init_accumulator_value, accumulator),
        dense=dense,
        dense_opt=init_adagrad(dense, init_accumulator_value),
        step=jnp.zeros((), jnp.int32),
    )


def batch_loss(model, table_rows, dense, batch: Batch):
    """(total loss with L2, plain data loss) — shared with the sharded step."""
    scores = model.score(table_rows, dense, batch)
    data_loss = logistic_loss(scores, batch.labels, batch.weights)
    reg = model.regularization(table_rows, dense, batch)
    return data_loss + reg, data_loss


def train_step_body(
    model, learning_rate: float, state: TrainState, batch: Batch,
    decay: float = 1.0,
):
    """The (unjitted) single-device step: gather → fused scorer → loss →
    dedup → sparse Adagrad.  Shared verbatim by ``make_train_step`` and the
    device-cache step (data/device_cache.py) so the two paths are the SAME
    math on the same values — the bit-identity their parity test pins.

    ``decay`` is the online-learning ``[Online] adagrad_decay`` γ (lazy
    touched-row accumulator decay — optim.sparse_adagrad_update); γ=1.0
    branches back to the exact classic program at trace time."""
    rows = state.table[batch.ids]  # [B, N, D] gather of touched rows only

    grad_fn = jax.value_and_grad(
        partial(batch_loss, model), argnums=(0, 1), has_aux=True
    )
    (_, data_loss), (g_rows, g_dense) = grad_fn(rows, state.dense, batch)

    table, table_opt = sparse_adagrad_update(
        state.table, state.table_opt, batch.ids, g_rows, learning_rate,
        decay=decay,
    )
    dense, dense_opt = state.dense, state.dense_opt
    if jax.tree.leaves(state.dense):
        dense, dense_opt = dense_adagrad_update(
            state.dense, state.dense_opt, g_dense, learning_rate, decay=decay
        )
    return (
        TrainState(table, table_opt, dense, dense_opt, state.step + 1),
        data_loss,
    )


def make_train_step(model, learning_rate: float, decay: float = 1.0, body=None):
    """Returns jitted ``step(state, batch) -> (state, data_loss)``.

    The state is donated: the table/accumulator buffers update in place
    (XLA aliases input and output), so a step never copies the [V, D]
    table — the difference between O(nnz) and O(V) HBM traffic per step.
    Callers must rebind ``state`` to the returned value (all drivers do).

    ``body`` overrides the step body (same ``(model, lr, state, batch)``
    contract as the scanned/device-cache factories) — the dedup-gather
    variant plugs in here.
    """
    body = body or (
        lambda m, lr, st, b: train_step_body(m, lr, st, b, decay)
    )

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: Batch):
        return body(model, learning_rate, state, batch)

    return step


def make_decayed_body(decay: float):
    """``train_step_body`` with ``[Online] adagrad_decay`` γ baked in — the
    ``body`` shape the scanned and device-cache step factories take."""

    def body(model, learning_rate, state, batch):
        return train_step_body(model, learning_rate, state, batch, decay)

    return body


def make_pallas_tail_body(decay: float = 1.0, interpret: bool | None = None):
    """``train_step_body`` with the sparse Adagrad tail swapped for the
    one-pass Pallas kernel (``ops.pallas_tail.rows_tail_adagrad_update``):
    same gather → fused scorer → loss → dedup front, but the deduped rows
    move through ONE double-buffered DMA gather→update→scatter pass
    instead of the XLA gather program + scatter program pair.

    Same ``(model, lr, state, batch)`` body contract as the scanned /
    device-cache / tiered factories, so it plugs into
    ``make_train_step(body=...)``, ``make_scanned_train_step(body=...)``,
    and the tiered paramstore's ``wrap_step`` unchanged — the tiered
    compact ``[C, D]`` staging table is exactly the operand shape the
    kernel takes.  γ threads through like ``make_decayed_body`` (γ=1.0
    is a trace-time branch to the classic expressions — bit-identical,
    test-pinned).  ``interpret=None`` auto-resolves off the backend
    (ops.pallas_common); tests pass ``interpret=True`` explicitly."""

    def body(model, learning_rate, state: TrainState, batch: Batch):
        from fast_tffm_tpu.ops.pallas_tail import rows_tail_adagrad_update

        rows = state.table[batch.ids]
        grad_fn = jax.value_and_grad(
            partial(batch_loss, model), argnums=(0, 1), has_aux=True
        )
        (_, data_loss), (g_rows, g_dense) = grad_fn(rows, state.dense, batch)

        table, accum = rows_tail_adagrad_update(
            state.table,
            state.table_opt.accum,
            batch.ids,
            g_rows,
            learning_rate,
            decay=decay,
            interpret=interpret,
        )
        dense, dense_opt = state.dense, state.dense_opt
        if jax.tree.leaves(state.dense):
            dense, dense_opt = dense_adagrad_update(
                state.dense, state.dense_opt, g_dense, learning_rate,
                decay=decay,
            )
        return (
            TrainState(
                table, AdagradState(accum), dense, dense_opt, state.step + 1
            ),
            data_loss,
        )

    return body


def make_dedup_body(cap: int, decay: float = 1.0):
    """Device-side dedup-before-gather (ROADMAP item 2(a)): the forward
    gather reads each of the batch's ≤ ``cap`` UNIQUE rows from the
    [V, D] table exactly once; per-slot re-reads index a compact
    ``[cap, D]`` buffer instead of HBM.  At the measured Zipf(1.1) dedup
    ratio (PROBE_IDSTATS_r09: 0.291) that is ~71% of forward-gather
    bytes gone.  Gathered VALUES are identical to the direct gather, so
    the loss/grad pipeline — and the unchanged sparse Adagrad update —
    produce bit-identical results (test-pinned).

    ``cap`` must bound the batch's unique-id count; the input stream
    VERIFIES that per batch before shipping (training._stream's dedup
    guard), so a too-small cap is a loud error, never silent truncation
    (``jnp.unique(size=...)`` would otherwise drop the largest ids).
    Same ``body`` contract as the scanned/device-cache factories."""

    def body(model, learning_rate, state: TrainState, batch: Batch):
        import jax.numpy as jnp

        v, d = state.table.shape
        flat = batch.ids.reshape(-1)
        # Sorted unique ids padded with the out-of-range sentinel ``v``
        # (the gather clamps it to a row whose value is never used).
        uids = jnp.unique(flat, size=cap, fill_value=v)
        compact = state.table[jnp.minimum(uids, v - 1)]
        inv = jnp.searchsorted(uids, flat)
        rows = compact[inv].reshape(*batch.ids.shape, d)

        grad_fn = jax.value_and_grad(
            partial(batch_loss, model), argnums=(0, 1), has_aux=True
        )
        (_, data_loss), (g_rows, g_dense) = grad_fn(rows, state.dense, batch)

        table, table_opt = sparse_adagrad_update(
            state.table, state.table_opt, batch.ids, g_rows, learning_rate,
            decay=decay,
        )
        dense, dense_opt = state.dense, state.dense_opt
        if jax.tree.leaves(state.dense):
            dense, dense_opt = dense_adagrad_update(
                state.dense, state.dense_opt, g_dense, learning_rate,
                decay=decay,
            )
        return (
            TrainState(table, table_opt, dense, dense_opt, state.step + 1),
            data_loss,
        )

    return body


def make_accum_restart(init_accumulator_value: float):
    """Jitted ``state -> state`` resetting every Adagrad accumulator to
    the init value — the window-restart alternative to ``adagrad_decay``
    (``[Online] accum_restart_steps``): on a moving distribution, a hard
    periodic restart re-opens the step size for EVERY row at once.

    Exact for the rows layout and for packed element/row accumulators
    alike: ``pack_accum*`` fills padding slots with the init value, so a
    full ``full_like(accum, init)`` reproduces the packed init state
    bit-for-bit.  (The fused layout stores its accumulator inside the
    table's own tile rows — config.validate rejects the combination.)
    Donated, so the reset is an in-place sweep, no table copy."""

    @partial(jax.jit, donate_argnums=(0,))
    def reset(state: TrainState):
        table_acc = jnp.full_like(
            state.table_opt.accum, init_accumulator_value
        )
        dense_acc = jax.tree.map(
            lambda a: jnp.full_like(a, init_accumulator_value),
            state.dense_opt.accum,
        )
        return state._replace(
            table_opt=state.table_opt._replace(accum=table_acc),
            dense_opt=state.dense_opt._replace(accum=dense_acc),
        )

    return reset


def make_scanned_train_step(model, learning_rate: float, body=None):
    """Returns jitted ``step(state, superbatch) -> (state, losses [K])``
    fusing K consecutive train steps into ONE dispatch via ``lax.scan``.

    ``superbatch`` is a Batch whose every field carries a leading micro-step
    dim ([K, B], [K, B, N], ...) — Batch.stack_parsed's output.  K is read
    from the input shape, so one Python function serves both the main fused
    call and the epoch-tail remainder (batches % K) — each K compiles once.
    The scan body is ``body`` (default trainer.train_step_body; the packed
    driver passes its packed body), i.e. the SAME function the K=1 step
    jits, applied to the same values in the same order — per-step losses
    and the final state are bit-identical to K sequential K=1 steps
    (test-pinned in tests/test_steps_per_call.py).  State donation is
    preserved: the scan carry aliases the donated input buffers, so the
    [V, D] table still updates in place across all K micro-steps.
    """
    from jax import lax

    body = body or train_step_body

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, superbatch: Batch):
        def one(st, b):
            st, loss = body(model, learning_rate, st, b)
            return st, loss

        return lax.scan(one, state, superbatch)

    return step


def make_predict_step(model):
    """Returns jitted ``predict(state, batch) -> sigmoid scores [B]``."""

    @jax.jit
    def predict(state: TrainState, batch: Batch):
        rows = state.table[batch.ids]
        return jax.nn.sigmoid(model.score(rows, state.dense, batch))

    return predict


# --- lane-packed table variants (ops/packed_table.py; DESIGN §6) ---------


def pack_state(
    state: TrainState, init_accumulator_value: float = 0.1, fused: bool = False
) -> TrainState:
    """Lane-pack a LOGICAL TrainState (table via pack_table; the
    accumulator via pack_accum for element granularity [V, D] or
    pack_accum_rows for row granularity [V, 1] — padding slots hold the
    init value so packed Adagrad never divides by sqrt(0)).  Shared by
    init, resume, and the packed predict driver.  Packs ONE array at a
    time, dropping each logical original before the next — the transient
    device-memory peak is what OOMs big vocabs on a shared chip.

    ``fused=True`` (adagrad_accumulator = fused) stores the [V, 1] ROW
    accumulator inside each row's own tile-row slot (stride D+1 —
    ops.packed_table fused layout); ``table`` then holds the single fused
    array and ``table_opt.accum`` a [0, 1] sentinel whose emptiness IS the
    fused-state marker the step/predict/save paths dispatch on."""
    from fast_tffm_tpu.ops.packed_table import pack_accum_any, pack_fused, pack_table

    d = state.table.shape[-1]
    if fused:
        fused_arr = pack_fused(
            state.table, state.table_opt.accum, init_accumulator_value
        )
        return state._replace(
            table=fused_arr,
            table_opt=state.table_opt._replace(
                accum=jnp.zeros((0, 1), state.table.dtype)
            ),
        )
    state = state._replace(table=pack_table(state.table))
    packed_acc = pack_accum_any(state.table_opt.accum, d, init_accumulator_value)
    return state._replace(table_opt=state.table_opt._replace(accum=packed_acc))


def init_packed_state(
    model,
    key: jax.Array,
    init_accumulator_value: float = 0.1,
    accumulator: str = "element",
) -> TrainState:
    """init_state with the table and accumulator lane-packed.

    The packed layout keeps the logical init EXACTLY (pack of the same
    init_table draw), so packed and rows runs start from identical
    parameters.  ``accumulator`` follows init_state: ``element`` packs
    [V, D] → [VP, 128]; ``row`` packs [V, 1] → [VP, P]; ``fused`` stores
    the row accumulator inside the table's own tile rows ([VPf, 128],
    stride D+1 — the 2-random-op RMW layout, DESIGN §6 round 5)."""
    return pack_state(
        init_state(model, key, init_accumulator_value, accumulator),
        init_accumulator_value,
        fused=accumulator == "fused",
    )


def packed_train_step_body(
    model, learning_rate: float, state: TrainState, batch: Batch,
    update: str = "auto", compact_cap: int = 0, tail: str = "xla",
):
    """train_step_body on a lane-packed table: identical math, tile-row
    physical movement (the narrow-scatter cliff fix — DESIGN §6).
    Shared by make_packed_train_step and the device-cache step.

    ``update`` picks the sparse-tail strategy (resolve_packed_update):
    ``dense`` — one wide scatter-add into a [VP, 128] gradient buffer +
    a dense Adagrad sweep (measured 3.5× the sorted path at vocab 2^24);
    ``compact`` — sort-free touched-row compaction, O(M) buffers (the
    giant-vocab path); ``sorted`` — sort/segment-sum/RMW (bit-parity
    reference); ``auto`` — dense under DENSE_G_MAX_BYTES, else compact.

    ``tail = "pallas"`` (fused layout only — config.validate enforces it)
    replaces the whole XLA update chain with the one-pass Pallas kernel
    (ops.pallas_tail.fused_tail_adagrad_update); ``update`` is then moot
    and ``compact_cap`` becomes the kernel's deduped-row cap."""
    from fast_tffm_tpu.ops.packed_table import (
        FUSED_UPDATE_FNS,
        PACKED_UPDATE_FNS,
        fused_gather,
        packed_gather,
        resolve_fused_update,
        resolve_packed_update,
    )

    d = model.row_dim
    acc = state.table_opt.accum
    fused = acc.size == 0  # pack_state's fused-state marker
    if fused:
        rows = fused_gather(state.table, batch.ids, d)
    else:
        rows = packed_gather(state.table, batch.ids, d)

    grad_fn = jax.value_and_grad(
        partial(batch_loss, model), argnums=(0, 1), has_aux=True
    )
    (_, data_loss), (g_rows, g_dense) = grad_fn(rows, state.dense, batch)

    if fused:
        if tail == "pallas":
            from fast_tffm_tpu.ops.pallas_tail import fused_tail_adagrad_update

            table = fused_tail_adagrad_update(
                state.table, batch.ids, g_rows, learning_rate,
                k_cap=compact_cap,
            )
        else:
            from fast_tffm_tpu.ops.packed_table import apply_fused_update

            mode = resolve_fused_update(update, state.table.shape[0])
            table = apply_fused_update(
                state.table, batch.ids, g_rows, learning_rate, mode,
                compact_cap,
            )
        accum = acc
    else:
        mode = resolve_packed_update(update, state.table.shape[0], acc.shape[-1])
        update_fn = PACKED_UPDATE_FNS[mode]
        table, accum = update_fn(
            state.table, acc, batch.ids, g_rows, learning_rate
        )
    dense, dense_opt = state.dense, state.dense_opt
    if jax.tree.leaves(state.dense):
        dense, dense_opt = dense_adagrad_update(
            state.dense, state.dense_opt, g_dense, learning_rate
        )
    return (
        TrainState(table, AdagradState(accum), dense, dense_opt, state.step + 1),
        data_loss,
    )


def make_packed_train_step(
    model, learning_rate: float, update: str = "auto", compact_cap: int = 0,
    tail: str = "xla",
):
    """``compact_cap`` (fused compact tail only): cap the compacted-row
    buffer below the exact worst case, with an exact-capacity lax.cond
    fallback when a batch touches more rows (config: packed_compact_cap).
    ``tail``: resolved ``[Train] tail`` — ``pallas`` routes the fused
    layout through the one-pass Pallas kernel."""

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: Batch):
        return packed_train_step_body(
            model, learning_rate, state, batch, update, compact_cap, tail
        )

    return step


def make_packed_predict_step(model, fused: bool = False):
    """``fused`` selects the fused-layout gather (adagrad_accumulator =
    fused) — the state's table is then the [VPf, 128] fused array."""
    from fast_tffm_tpu.ops.packed_table import fused_gather, packed_gather

    d = model.row_dim
    gather = fused_gather if fused else packed_gather

    @jax.jit
    def predict(state: TrainState, batch: Batch):
        rows = gather(state.table, batch.ids, d)
        return jax.nn.sigmoid(model.score(rows, state.dense, batch))

    return predict
