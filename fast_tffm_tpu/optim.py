"""Adagrad with a sparse, dedup-on-device update path.

Capability parity with the reference's training update
(`renyi533/fast_tffm` :: model-graph builder: `tf.train.AdagradOptimizer`
whose sparse gradient path scatter-adds into the block-partitioned
parameter variables).  Semantics mirror TF Adagrad:

    accum += g²          (accum initialized to init_accumulator_value)
    param -= lr * g / sqrt(accum)

The sparse step is the BASELINE.json "dense-over-sparse optimizer step":
gradients arrive per *gathered occurrence* ``[batch, nnz, D]``; occurrences
of the same row id are summed on device (sort + segment-sum — static
shapes, no `jnp.unique`), then a single gather→update→scatter touches each
unique row exactly once.  Touching each row once matters: Adagrad is not
linear in g (accum += g² must see the *summed* gradient, and duplicate
scatter targets would race).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["AdagradState", "init_adagrad", "dense_adagrad_update", "sparse_adagrad_update", "dedup_rows"]


class AdagradState(NamedTuple):
    accum: Any  # pytree mirroring the tracked parameter pytree


def init_adagrad(param, init_accumulator_value: float) -> AdagradState:
    return AdagradState(
        jax.tree.map(lambda p: jnp.full_like(p, init_accumulator_value), param)
    )


def dense_adagrad_update(param, state: AdagradState, grad, lr: float):
    """Plain Adagrad over a parameter pytree (DeepFM's MLP head)."""
    accum = jax.tree.map(lambda a, g: a + g * g, state.accum, grad)
    new_param = jax.tree.map(
        lambda p, g, a: p - lr * g / jnp.sqrt(a), param, grad, accum
    )
    return new_param, AdagradState(accum)


def dedup_rows(ids: jax.Array, row_grads: jax.Array, num_rows: int):
    """Sum per-occurrence row gradients over duplicate ids.

    Args:
      ids:       [M] int row ids (flattened batch×nnz), may repeat.
      row_grads: [M, D] gradient per occurrence.
      num_rows:  table row count V (used as the drop sentinel).

    Returns:
      (uids [M], gsum [M, D]): unique ids with their summed gradients in the
      leading segments; trailing slots carry the sentinel id ``num_rows``
      (out of range → scattered with mode='drop') and zero gradients.
    """
    m = ids.shape[0]
    order = jnp.argsort(ids)
    sid = ids[order]
    sg = row_grads[order]
    is_new = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(is_new) - 1  # [M] segment index per occurrence
    gsum = jax.ops.segment_sum(sg, seg, num_segments=m)
    uids = jax.ops.segment_max(sid, seg, num_segments=m)
    n_unique = jnp.sum(is_new)
    valid = jnp.arange(m) < n_unique
    uids = jnp.where(valid, uids, num_rows)  # sentinel → dropped on scatter
    return uids, gsum


def sparse_adagrad_update(
    table: jax.Array,
    state: AdagradState,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
):
    """Sparse Adagrad step on a ``[V, D]`` table.

    ids: [...] int ids; row_grads: [..., D] matching occurrence grads.
    Only the unique touched rows are read and written.
    """
    D = table.shape[-1]
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, D), table.shape[0])
    acc_rows = state.accum[uids] + gsum * gsum  # gather clamps on the sentinel,
    new_acc_rows = acc_rows  # but mode='drop' below discards those lanes
    upd_rows = table[uids] - lr * gsum / jnp.sqrt(new_acc_rows)
    accum = state.accum.at[uids].set(new_acc_rows, mode="drop")
    table = table.at[uids].set(upd_rows, mode="drop")
    return table, AdagradState(accum)
