"""Adagrad with a sparse, dedup-on-device update path.

Capability parity with the reference's training update
(`renyi533/fast_tffm` :: model-graph builder: `tf.train.AdagradOptimizer`
whose sparse gradient path scatter-adds into the block-partitioned
parameter variables).  Semantics mirror TF Adagrad:

    accum += g²          (accum initialized to init_accumulator_value)
    param -= lr * g / sqrt(accum)

The sparse step is the BASELINE.json "dense-over-sparse optimizer step":
gradients arrive per *gathered occurrence* ``[batch, nnz, D]``; occurrences
of the same row id are summed on device (sort + segment-sum — static
shapes, no `jnp.unique`), then a single gather→update→scatter touches each
unique row exactly once.  Touching each row once matters: Adagrad is not
linear in g (accum += g² must see the *summed* gradient, and duplicate
scatter targets would race).

Accumulator granularity: the accumulator array's trailing dim selects the
variant — ``[V, D]`` is TF-Adagrad's per-element accumulator (parity
default), ``[V, 1]`` is a per-ROW scalar accumulator
(``accum += ‖g_row‖²``, one sqrt per row, broadcast over the row; the cfg
``adagrad_accumulator = row`` opt-in).  What the row variant buys is
OPTIMIZER-STATE MEMORY: accumulator HBM shrinks D× (at a 10B-parameter
table the element accumulator doubles memory; row cuts the optimizer
state to ~1/(1+k)).  Measured speed-neutral on one chip — the update's
gathers are descriptor-bound, not byte-bound (DESIGN.md §6) — and the
step size is coarser (grouped-AdaGrad-style), so element stays the
default.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["AdagradState", "init_adagrad", "dense_adagrad_update", "sparse_adagrad_update", "dedup_rows"]


class AdagradState(NamedTuple):
    accum: Any  # pytree mirroring the tracked parameter pytree


def init_adagrad(param, init_accumulator_value: float) -> AdagradState:
    return AdagradState(
        jax.tree.map(lambda p: jnp.full_like(p, init_accumulator_value), param)
    )


def init_table_adagrad(
    table: jax.Array, init_accumulator_value: float, accumulator: str = "element"
) -> AdagradState:
    """Accumulator for the sparse table: ``element`` ([V, D], TF parity) or
    ``row`` ([V, 1], grouped accumulator — see module docstring)."""
    if accumulator in ("row", "fused"):
        # "fused" has row-granularity SEMANTICS; the fused STORAGE happens
        # at pack time (ops.packed_table.pack_fused) — logically it is the
        # same [V, 1] accumulator.
        return AdagradState(
            jnp.full((table.shape[0], 1), init_accumulator_value, table.dtype)
        )
    if accumulator != "element":
        raise ValueError(
            f"unknown adagrad accumulator {accumulator!r} (element | row | fused)"
        )
    return init_adagrad(table, init_accumulator_value)


def accum_sq(accum: jax.Array, gsum: jax.Array) -> jax.Array:
    """g² in the granularity the accumulator's shape declares."""
    if accum.shape[-1] == 1 and gsum.shape[-1] != 1:
        return jnp.sum(gsum * gsum, axis=-1, keepdims=True)  # row mode
    return gsum * gsum  # element mode


def dense_adagrad_update(param, state: AdagradState, grad, lr: float, decay: float = 1.0):
    """Plain Adagrad over a parameter pytree (DeepFM's MLP head).

    ``decay`` γ < 1 is time-decayed Adagrad (``accum = γ·accum + g²``,
    RMSProp-shaped) — the online-learning knob that keeps old gradient
    history from freezing the step size on a moving distribution.  γ=1.0
    is a TRACE-TIME branch back to the exact classic expression, so the
    default path's XLA program (and its bits) are untouched."""
    if decay != 1.0:
        accum = jax.tree.map(
            lambda a, g: decay * a + g * g, state.accum, grad
        )
    else:
        accum = jax.tree.map(lambda a, g: a + g * g, state.accum, grad)
    new_param = jax.tree.map(
        lambda p, g, a: p - lr * g / jnp.sqrt(a), param, grad, accum
    )
    return new_param, AdagradState(accum)


def dedup_rows(ids: jax.Array, row_grads: jax.Array, num_rows: int):
    """Sum per-occurrence row gradients over duplicate ids.

    Args:
      ids:       [M] int row ids (flattened batch×nnz), may repeat.
      row_grads: [M, D] gradient per occurrence.
      num_rows:  table row count V (used as the drop sentinel).

    Returns:
      (uids [M], gsum [M, D]): unique ids with their summed gradients in the
      leading segments; trailing slots carry the sentinel id ``num_rows``
      (out of range → scattered with mode='drop') and zero gradients.
    """
    m = ids.shape[0]
    order = jnp.argsort(ids)
    sid = ids[order]
    sg = row_grads[order]
    is_new = jnp.concatenate([jnp.ones((1,), bool), sid[1:] != sid[:-1]])
    seg = jnp.cumsum(is_new) - 1  # [M] segment index per occurrence
    gsum = jax.ops.segment_sum(sg, seg, num_segments=m)
    # Segment representative via scatter-SET, not segment_max (measured
    # ~9 ms slower as a 1-D scatter-max on this backend): every
    # occurrence in a segment writes the SAME sid, so any duplicate
    # winning is correct; unwritten trailing slots keep the sentinel
    # ``num_rows`` (out of range → scattered with mode='drop').
    uids = jnp.full((m,), num_rows, sid.dtype).at[seg].set(sid)
    return uids, gsum


def sparse_adagrad_update(
    table: jax.Array,
    state: AdagradState,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    decay: float = 1.0,
):
    """Sparse Adagrad step on a ``[V, D]`` table.

    ids: [...] int ids; row_grads: [..., D] matching occurrence grads.
    Only the unique touched rows are read and written.

    ``decay`` γ < 1 decays the accumulator LAZILY — only the rows a step
    touches pay ``accum = γ·accum + g²`` (an untouched row's history is
    also its recency: decaying it would shrink step sizes for rows that
    saw no data, the opposite of what a moving distribution needs, and a
    per-step O(V) sweep would erase the sparse update's whole point).
    γ=1.0 is a trace-time branch to the exact classic expression — same
    XLA program, bit-identical results (test-pinned on all three train
    paths)."""
    D = table.shape[-1]
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, D), table.shape[0])
    acc_prev = state.accum[uids]
    if decay != 1.0:
        acc_prev = decay * acc_prev
    acc_rows = acc_prev + accum_sq(state.accum, gsum)  # sentinel lanes
    upd_rows = table[uids] - lr * gsum / jnp.sqrt(acc_rows)  # dropped below
    accum = state.accum.at[uids].set(acc_rows, mode="drop")
    table = table.at[uids].set(upd_rows, mode="drop")
    return table, AdagradState(accum)
