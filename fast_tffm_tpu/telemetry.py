"""Run telemetry: one envelope, three sentinels, one sink.

The reference's only observability was periodic loss prints (SURVEY.md
§5: TF RunMetadata existed but was never wired), and this repo had grown
three UNRELATED emitters on top of that — per-window ``kind=input``
stats, ``kind=serving`` histograms, and bare step records — sharing no
schema and carrying no run identity.  ``RunMonitor`` unifies them:

  * **envelope** — every record carries ``run_id`` (one per driver run),
    ``schema_version``, ``kind``, a monotonic ``step``, ``t`` (monotonic
    seconds since the run started — immune to wall-clock jumps) and
    ``ts`` (wall clock, for humans).  Per-kind required keys live in
    ``SCHEMAS`` and are pinned by tests/test_telemetry.py — schema drift
    is a test failure, not a silently broken dashboard.
  * **compile sentinel** — a process-wide ``jax.monitoring`` listener
    counts XLA backend compiles; ``on_dispatch`` drains the delta each
    driver dispatch, so a steady-state recompile in train/predict/serving
    surfaces as a ``kind=compile`` event (the generalization of the
    serving bucket-ladder's flat-jit-cache pin).  Compiles issued from
    the prefetch thread (packed-wire unpack programs) attribute to the
    next dispatch that drains.
  * **memory watermarks** — periodic ``kind=mem`` records with host RSS
    (/proc, with ru_maxrss as the peak floor) and device live-buffer
    bytes (``memory_stats`` where the runtime exposes it, live-array sum
    otherwise), plus peak-so-far; one final record is always emitted at
    close so every run documents its high-water mark.
  * **liveness watchdog** — a heartbeat thread: when no dispatch
    completes for ``stall_timeout_s``, it dumps every Python thread's
    stack and the prefetch queue depth as a ``kind=stall`` event,
    classified input-starved (empty queue: the producer is the
    bottleneck) vs device-bound (data ready, the consumer/device is
    wedged).  Armed by the first completed dispatch; suspended
    (``suspended()``) through phases that legitimately dispatch nothing
    (validation, checkpoint saves); defers while a stack shows an XLA
    compile in progress (slow, not stuck — up to 10x the deadline, then
    fires classified "compiling").  One event per stall episode; a
    recovered-then-stalled run fires again.

``arm_hang_exit`` is the absorbed ``_bench_watchdog.py``: the hard
os._exit timer the bench/probe tools arm BEFORE ``import jax`` (backend
init behind a dead TPU tunnel is itself a known hang point).  That
contract is why this module — and the package ``__init__`` — must import
without jax; everything jax-touching here is lazy and degrades to a
no-op when jax is absent.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import traceback

from fast_tffm_tpu.utils.tracing import MetricsLogger

__all__ = [
    "SCHEMA_VERSION",
    "ENVELOPE_FIELDS",
    "SCHEMAS",
    "RunMonitor",
    "CompileSentinel",
    "new_run_id",
    "artifact_stamp",
    "write_json_artifact",
    "thread_stacks",
    "classify_stall",
    "first_nonfinite_leaf",
    "arm_hang_exit",
    "enable_compilation_cache",
    "global_cache_hit_count",
]

SCHEMA_VERSION = 1

# Fields every record carries (ts is stamped by MetricsLogger).
# process_index/process_count identify the EMITTING host on multi-process
# pods (0/1 on single-process runs and device-free emitters like the
# supervisor), so tools/report.py can merge per-host JSONL files for one
# run_id into per-host columns.
ENVELOPE_FIELDS = (
    "run_id",
    "schema_version",
    "kind",
    "step",
    "t",
    "ts",
    "process_index",
    "process_count",
)

# kind -> keys REQUIRED on every record of that kind (beyond the
# envelope).  Values may be null when a source genuinely cannot measure
# them (e.g. device_bytes without a backend), but the key must be there —
# a missing key means the emitter and the readers have drifted.
# Extra keys are allowed (extra_metrics merges, serving counters).
SCHEMAS: dict[str, tuple[str, ...]] = {
    "train": ("epoch", "loss", "examples_per_sec", "examples_per_sec_per_chip"),
    "validation": ("epoch", "validation_auc"),
    "input": ("input_items", "input_steps", "input_examples", "parse_ms"),
    "predict": ("examples", "examples_per_sec"),
    "serving": ("requests", "flushes", "rows", "queue_ms", "compute_ms", "total_ms"),
    "compile": ("source", "compiles", "total_compiles", "warmup"),
    "mem": (
        "host_rss_bytes",
        "host_rss_peak_bytes",
        "device_bytes",
        "device_peak_bytes",
    ),
    "stall": (
        "deadline_s",
        "since_last_step_s",
        "classification",
        "prefetch_queue_depth",
        "stacks",
    ),
    "anomaly": ("event", "loss"),
    # Resilience layer (resilience.py): injected/observed faults (crash,
    # io_retry, injected_*, chain_repair) and supervised relaunches
    # (attempt ordinal, crashed child's exit code, backoff slept, MTTR =
    # crash -> first new training progress; null until measurable).
    "fault": ("event",),
    "restart": ("attempt", "exit_code", "backoff_s", "mttr_s"),
    "ckpt": (
        "mode",  # full (async) | delta | sync
        "snapshot_ms",
        "convert_ms",
        "d2h_ms",
        "write_ms",
        "bytes",
        "rows_written",
        "train_stall_ms",
    ),
    # Deep observability (profiling.py).  profile: one record per
    # measured compiled program (XLA cost analysis — bytes/flops null
    # only for trace start/stop event records, program="trace");
    # datastats: sampled device-side id-traffic statistics (dedup ratio,
    # heavy-hitter sketch mass, cumulative rows seen); freshness: the
    # publish→applied / publish→first-scored-with-new-rows SLO measured
    # at a serving reload swap (engine) or aggregated across a reload
    # fan-out (router — applied/scored keys null where it cannot see).
    "profile": ("program", "flops", "bytes_accessed"),
    "datastats": (
        "window_steps",
        "ids",
        "unique",
        "dedup_ratio",
        "rows_seen",
        "hh_k",
        "hh_topk_mass",
    ),
    "freshness": (
        "publish_step",
        "publish_to_applied_ms",
        "publish_to_first_scored_ms",
    ),
    # Online-learning loop (ISSUE 11).  quality: one record per replayed
    # backtest hour — the online trainer's held-out AUC next to the
    # batch-retrain reference's on the same hour (tools/backtest.py;
    # report.py --compare --strict gates on the gap).  soak: one record
    # per soak-harness sentinel tick — phase names the check window, ok
    # is the conjunction of that tick's sentinels (tools/soak.py).
    "quality": ("hour", "auc_online", "auc_batch"),
    "soak": ("phase", "elapsed_s", "ok"),
    # Tiered parameter store (ISSUE 12; paramstore/): one record per log
    # window — hot-tier hit rate over gather slots, staged miss rows and
    # their wire bytes, writeback (staging D2H -> pending overlay) and
    # resolve costs, coherency restages, and the pending-overlay depth
    # (rows awaiting their post-publish store apply).
    "tiering": (
        "hit_rate",
        "miss_rows",
        "miss_bytes_per_step",
        "writeback_rows",
        "writeback_ms",
        "resolve_ms",
        "restages",
        "pending_rows",
    ),
    "summary": ("total_compiles", "steady_compiles", "stalls", "anomalies"),
}


def new_run_id() -> str:
    """Sortable-by-start-time and collision-safe across processes."""
    return f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid():x}-{os.urandom(3).hex()}"


def artifact_stamp(run_id: str = "") -> dict:
    """The join keys every committed BENCH_*/PROBE_* JSON must carry so a
    bench artifact is joinable to the telemetry JSONL stream(s) it was
    measured from: the envelope ``run_id`` (pass the run's; a fresh one
    is drawn for tools that never started a monitored run) and the
    envelope ``schema_version`` the emitters wrote under."""
    return {"run_id": run_id or new_run_id(), "schema_version": SCHEMA_VERSION}


def write_json_artifact(path, obj, *, indent: int = 1, sort_keys: bool = True) -> None:
    """Atomically publish a committed BENCH_*/PROBE_*-style JSON artifact:
    full payload to a sibling tmp, then ``os.replace`` onto ``path`` — the
    same complete-or-previous contract every checkpoint publish honors
    (DESIGN crash-consistency invariant 1; gated by the atomic-publish
    checker).  A reader — a compare gate, a dashboard poller, a human
    mid-run — never sees a torn verdict."""
    tmp = f"{path}.{os.getpid():x}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=indent, sort_keys=sort_keys)
        f.write("\n")
    os.replace(tmp, path)


def log_quietly(log, msg: str) -> None:
    """Deliver ``msg`` to a caller-provided log callback, absorbing ANY
    failure the callback raises.  The one sanctioned sink for the
    "logging must never kill the worker" contract (collector threads,
    checkpoint writers, watchdogs): callbacks are injected by drivers and
    tests, so their failure surface is unknowable — and the message is
    always best-effort context for a diagnosis already recorded through
    a typed path (counter, typed error, telemetry record)."""
    if log is None:
        return
    try:
        log(msg)
    # analysis: ok exception-hygiene the sanctioned raising-log-callback sink — the diagnosis already traveled a typed path; see docstring
    except Exception:
        pass


# -- compile sentinel -----------------------------------------------------

# One process-wide counter fed by one jax.monitoring listener: jax has no
# listener UNregistration in its public API, so per-monitor listeners
# would leak across every test/run in a process.  Sentinels snapshot the
# counter instead.
_compile_lock = threading.Lock()
_compile_count = 0
_cache_hit_count = 0
_listener_state = [None]  # None = not tried, True/False = outcome

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# Fired by jax's persistent compilation cache on every read hit.  Counted
# separately so a kind=compile record can say "this 'compile' was served
# from the on-disk cache" — a cold serving warmup with a warm cache shows
# compiles=N cache_hits=N instead of looking like N real XLA compiles.
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


def _on_duration_event(event: str, duration: float, **kw) -> None:
    global _compile_count
    if event == _COMPILE_EVENT:
        with _compile_lock:
            _compile_count += 1


def _on_event(event: str, **kw) -> None:
    global _cache_hit_count
    if event == _CACHE_HIT_EVENT:
        with _compile_lock:
            _cache_hit_count += 1


def _ensure_compile_listener() -> bool:
    if _listener_state[0] is None:
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(_on_duration_event)
            _listener_state[0] = True
        # analysis: ok exception-hygiene jax-version probe: no listener API on this jax means the sentinel degrades to disabled (recorded in _listener_state)
        except Exception:
            _listener_state[0] = False
        try:
            import jax.monitoring

            jax.monitoring.register_event_listener(_on_event)
        # analysis: ok exception-hygiene jax-version probe: hit counting is additive — the compile count stands alone without it
        except Exception:
            pass
    return _listener_state[0]


def global_cache_hit_count() -> int:
    """Persistent-compilation-cache read hits observed process-wide."""
    with _compile_lock:
        return _cache_hit_count


def enable_compilation_cache(path: str) -> bool:
    """Point jax's persistent XLA compilation cache at ``path`` (config
    key ``[Telemetry] compilation_cache_dir``): repeated bench runs and
    serving cold-start warmups skip recompiles across processes.  The
    thresholds drop to zero so even the small CPU-test programs cache —
    the sentinel (cache_hits on kind=compile records) is how a run proves
    the cache worked.  Returns False (with no side effects) when this jax
    lacks the knobs."""
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # analysis: ok exception-hygiene older-jax compat probe: dir alone still caches the big programs
        except Exception:
            pass
        return True
    # analysis: ok exception-hygiene capability probe: False (no side effects) IS the documented no-cache outcome
    except Exception:
        return False


def global_compile_count() -> int:
    """XLA backend compiles observed process-wide since the first
    sentinel was created (0 before that)."""
    with _compile_lock:
        return _compile_count


class CompileSentinel:
    """Per-consumer view of the process-wide compile counter.

    ``drain()`` returns how many XLA backend compiles happened since the
    previous drain (or construction).  Concurrent consumers (a trainer
    and a serving engine in one process) each see every compile — the
    counter is global, attribution is the caller's framing.
    """

    def __init__(self):
        self._ok = _ensure_compile_listener()
        self._seen = global_compile_count()
        self._seen_hits = global_cache_hit_count()

    @property
    def available(self) -> bool:
        return bool(self._ok)

    def drain(self) -> int:
        if not self._ok:
            return 0
        n = global_compile_count()
        delta = n - self._seen
        self._seen = n
        return delta

    def drain_cache_hits(self) -> int:
        """Persistent-cache hits since the last drain — programs that
        LOOKED like cold compiles but were served from the on-disk cache
        (no backend_compile fires for them)."""
        n = global_cache_hit_count()
        delta = n - self._seen_hits
        self._seen_hits = n
        return delta


# -- memory watermarks ----------------------------------------------------


def host_rss_bytes() -> int | None:
    """Current resident set size (linux /proc; None where unreadable)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def _ru_maxrss_bytes() -> int | None:
    try:
        import resource

        v = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # Linux reports KiB; macOS reports bytes.
        return int(v) if sys.platform == "darwin" else int(v) * 1024
    # analysis: ok exception-hygiene resource probe degrades to None by documented contract ("None where unreadable")
    except Exception:
        return None


def device_live_bytes() -> int | None:
    """Live device-buffer bytes: runtime memory_stats where exposed
    (real TPU/GPU backends), falling back to summing live jax arrays
    (CPU backend exposes no allocator stats).  None without jax."""
    if "jax" not in sys.modules:
        # Never the import that drags the backend up — telemetry observes.
        return None
    try:
        import jax

        total, had_stats = 0, False
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            stats = ms() if callable(ms) else None
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                had_stats = True
        if had_stats:
            return total
        return int(sum(int(x.nbytes) for x in jax.live_arrays()))
    # analysis: ok exception-hygiene resource probe degrades to None by documented contract ("None where unreadable")
    except Exception:
        return None


class _MemWatermarks:
    """Sample-and-track-peaks; ru_maxrss floors the host peak so the
    watermark is honest even when sampling missed the actual spike."""

    def __init__(self):
        self._host_peak = 0
        self._dev_peak = 0

    def sample(self) -> dict:
        host = host_rss_bytes()
        dev = device_live_bytes()
        if host is not None:
            self._host_peak = max(self._host_peak, host)
        maxrss = _ru_maxrss_bytes()
        if maxrss is not None:
            self._host_peak = max(self._host_peak, maxrss)
        if dev is not None:
            self._dev_peak = max(self._dev_peak, dev)
        return {
            "host_rss_bytes": host,
            "host_rss_peak_bytes": self._host_peak or None,
            "device_bytes": dev,
            "device_peak_bytes": self._dev_peak if dev is not None else None,
        }


# -- stall forensics ------------------------------------------------------


def thread_stacks(max_frames: int = 25) -> dict[str, str]:
    """Formatted stack of every live Python thread (deepest frames kept),
    keyed by thread name — the watchdog's core forensic payload."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident) or f"thread-{ident}"
        lines = traceback.format_stack(frame)
        out[name] = "".join(lines[-max_frames:])
    return out


_DEVICE_MARKERS = (
    "block_until_ready",
    "backend_compile",
    "jaxlib",
    "_xla",
    "device_put",
)

# Frames visible (empirically, jax 0.4.37) while a jit cache miss is
# being traced/lowered/XLA-compiled.  A compile is SLOW, not stuck —
# the same reasoning that prices the first dispatch into warmup — so the
# watchdog defers while one is on a stack (up to a 10x-deadline cap:
# a compile that long is worth an event, classified "compiling").
_COMPILING_MARKERS = (
    "backend_compile",
    "compile_or_get_cached",
    "cache_miss",
    "_python_pjit_helper",
)


def compiling_now(stacks: dict[str, str]) -> bool:
    blob = "\n".join(stacks.values())
    return any(m in blob for m in _COMPILING_MARKERS)


def classify_stall(
    queue_depth: int | None, stacks: dict[str, str], producer_alive=None,
    stream_idle=None,
) -> str:
    """input-starved: the prefetch queue is empty, so the producer (parse
    / disk / conversion) is what everyone is waiting on — and when the
    producer THREAD is known dead, the classification says so (a dead
    producer is a fault to restart from, not a slow parse to wait out).
    ``stream_idle`` True (a tail-following input stream polling a quiet
    append-only file — data/stream.py) is the third flavor: the producer
    is alive and healthy, the UPSTREAM WRITER is what stopped — wait (or
    page whoever owns the event feed), don't restart.
    device-bound: data is ready (or there is no input queue) and a thread
    is inside the device runtime — the dispatch/compile/transfer is
    what's wedged."""
    if queue_depth == 0:
        if producer_alive is False:
            return "input-starved (producer-thread dead)"
        if stream_idle:
            return "input-starved (stream-idle)"
        return "input-starved"
    blob = "\n".join(stacks.values())
    if any(m in blob for m in _DEVICE_MARKERS):
        return "device-bound"
    if queue_depth is not None and queue_depth > 0:
        return "device-bound"
    return "unknown"


def first_nonfinite_leaf(tree) -> str | None:
    """Path of the first pytree leaf holding a NaN/Inf, or None.  "Cheap"
    only relative to an abort (it syncs every leaf to host) — call it on
    the way down, never on the hot path."""
    try:
        import jax
        import numpy as np

        for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
            arr = np.asarray(leaf)
            if arr.dtype.kind == "f" and arr.size and not np.isfinite(arr).all():
                return jax.tree_util.keystr(path)
    # analysis: ok exception-hygiene forensic probe on the way down to an abort — None just means "leaf unnamed", the anomaly record still lands
    except Exception:
        return None
    return None


# -- the monitor ----------------------------------------------------------


class RunMonitor:
    """Owns the MetricsLogger and stamps the shared envelope on every
    record; hosts the compile sentinel, the memory sampler, and the
    liveness watchdog.  Thread-safe: drivers emit from their loop thread,
    the watchdog from its own.

    ``source`` names the driver (train / predict / serving) on compile
    events.  ``queue_depth_fn`` (settable later via
    ``set_queue_depth_fn``) lets the stall classifier read the live
    prefetch-queue depth.  ``stall_timeout_s`` 0 disables the watchdog;
    ``mem_every_s`` 0 reduces kind=mem to the one guaranteed close()
    record.
    """

    def __init__(
        self,
        path: str | None = None,
        *,
        run_id: str = "",
        source: str = "train",
        stall_timeout_s: float = 0.0,
        mem_every_s: float = 0.0,
        queue_depth_fn=None,
        logger: MetricsLogger | None = None,
        replica: int | None = None,
        log=None,
    ):
        self._logger = logger if logger is not None else MetricsLogger(path)
        self._own_logger = logger is None
        self.run_id = run_id or new_run_id()
        self.source = source
        # Serving replica ordinal (None outside the replicated serving
        # tier): stamped on every record like process_index, so report.py
        # can split one run's stream into per-replica columns.
        self.replica = replica
        # Stamped once at construction: the monitor outlives any single
        # dispatch, and a host's identity cannot change mid-run.
        from fast_tffm_tpu.distributed import process_identity

        self.process_index, self.process_count = process_identity()
        self._log = log
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._step = 0
        self._closed = False

        self._sentinel = CompileSentinel()
        self.compiles_total = 0
        self.compiles_steady = 0  # compiles NOT marked warmup
        self._last_warmup = True  # nothing dispatched yet = startup/warmup
        self._warmup_depth = 0  # >0: inside a warmup_window() — compiles
        #   drained by ANY thread attribute as warmup (e.g. a serving
        #   reload's restore/apply programs, which run off the hot path
        #   and must not read as steady-state score-ladder recompiles)

        self._mem = _MemWatermarks()
        self._mem_every_s = float(mem_every_s)
        self._last_mem = self._t0

        self.stalls = 0
        self.anomalies = 0
        self._stall_timeout = float(stall_timeout_s)
        self._queue_depth_fn = queue_depth_fn
        self._producer_alive_fn = None
        self._stream_idle_fn = None
        # Armed by the FIRST heartbeat: the gap before dispatch 1 is
        # dominated by XLA compile (legitimately >> any stall deadline),
        # and startup hangs are arm_hang_exit's department.
        self._last_beat = None
        self._stall_fired = False
        self._suspended = 0
        self._stop = threading.Event()
        self._watchdog = None
        if self._stall_timeout > 0:
            self._watchdog = threading.Thread(
                target=self._watch, name="telemetry-watchdog", daemon=True
            )
            self._watchdog.start()

    @property
    def active(self) -> bool:
        """Whether records reach a file (sentinels run regardless)."""
        return self._logger.active

    def set_queue_depth_fn(self, fn) -> None:
        """Swap the prefetch-depth probe (drivers rebuild streams per
        epoch; the watchdog should read the CURRENT one)."""
        self._queue_depth_fn = fn

    def set_producer_alive_fn(self, fn) -> None:
        """Swap the prefetch-producer liveness probe (same per-epoch
        cadence as the depth probe): lets a stall classify as
        'input-starved (producer-thread dead)' instead of merely depth 0."""
        self._producer_alive_fn = fn

    def set_stream_idle_fn(self, fn) -> None:
        """Swap the tail-follow idleness probe (follow-mode input streams
        only — data/stream.py): a starved loop whose stream is idle-
        polling a quiet append-only file classifies as
        'input-starved (stream-idle)' — wait for the writer, don't
        restart the producer."""
        self._stream_idle_fn = fn

    # -- emission ---------------------------------------------------------

    def emit(self, kind: str, step: int | None = None, **fields) -> None:
        """Append one enveloped record.  ``kind`` must be registered in
        SCHEMAS — an unknown kind is a programming error the schema test
        could never catch, so it raises here."""
        if kind not in SCHEMAS:
            raise ValueError(f"unknown telemetry kind {kind!r} (register it in SCHEMAS)")
        envelope = dict(
            run_id=self.run_id,
            schema_version=SCHEMA_VERSION,
            kind=kind,
            step=self._step if step is None else int(step),
            t=round(time.monotonic() - self._t0, 3),
            process_index=self.process_index,
            process_count=self.process_count,
        )
        if self.replica is not None and "replica" not in fields:
            envelope["replica"] = self.replica
        self._logger.log(**envelope, **fields)

    def heartbeat(self, step: int) -> None:
        """The liveness signal: call whenever a dispatch completes."""
        with self._lock:
            self._step = int(step)
            self._last_beat = time.monotonic()
            self._stall_fired = False

    @contextlib.contextmanager
    def suspended(self):
        """Suspend the liveness watchdog for a phase that legitimately
        completes no dispatches (a long validation pass, a checkpoint
        save) — otherwise a healthy epoch boundary reads as a stall,
        misclassified input-starved because the drained train stream's
        queue depth is 0.  Re-entrant; the heartbeat clock restarts on
        exit."""
        with self._lock:
            self._suspended += 1
        try:
            yield
        finally:
            with self._lock:
                self._suspended -= 1
                if self._last_beat is not None:
                    self._last_beat = time.monotonic()
                self._stall_fired = False

    @contextlib.contextmanager
    def warmup_window(self):
        """Mark a window whose compiles are EXPECTED and off the hot path
        (a serving reload's restore/delta-apply programs): any compile
        drained while a thread is inside — including by a concurrent
        dispatch on another thread — attributes as warmup, not as a
        steady-state recompile.  The trailing drain on exit catches
        compiles nobody dispatched over.  (A genuine steady recompile
        landing inside the window is misattributed — accepted: windows
        are rare and short, and the alternative is a false alarm on
        every hot reload.)"""
        with self._lock:
            self._warmup_depth += 1
        try:
            yield
        finally:
            try:
                self.on_dispatch(self._step, warmup=True)
            except (OSError, ValueError):
                pass  # a failed drain is a lost record, not a broken window
            with self._lock:
                self._warmup_depth -= 1

    def on_dispatch(self, step: int, warmup: bool = False) -> None:
        """Per-dispatch hook for driver loops: heartbeat + compile drain +
        due memory sample.  ``warmup`` marks dispatches where a compile
        is EXPECTED (first call, bucket warmup) so steady-state recompiles
        are separable from the priced-in ones."""
        with self._lock:
            warmup = warmup or self._warmup_depth > 0
        self.heartbeat(step)
        delta = self._sentinel.drain()
        hits = self._sentinel.drain_cache_hits()
        self._last_warmup = bool(warmup)
        if delta or hits:
            # Persistent-cache hits ride the record distinctly: they are
            # programs that would have compiled but were read back from
            # the on-disk cache — never counted as steady recompiles.
            with self._lock:
                self.compiles_total += delta
                if not warmup:
                    self.compiles_steady += delta
            self.emit(
                "compile",
                step=step,
                source=self.source,
                compiles=delta,
                total_compiles=self.compiles_total,
                warmup=bool(warmup),
                cache_hits=hits,
            )
        if self._mem_every_s > 0:
            now = time.monotonic()
            if now - self._last_mem >= self._mem_every_s:
                self._last_mem = now
                self.emit_mem(step=step)

    def emit_mem(self, step: int | None = None) -> None:
        self.emit("mem", step=step, **self._mem.sample())

    def emit_anomaly(
        self, step: int, loss, event: str = "nonfinite_loss", state=None, **fields
    ) -> None:
        """Structured divergence record (the satellite): step, loss, and —
        when a state pytree is handed over — the first non-finite tensor's
        path, so report.py can say WHICH table diverged."""
        with self._lock:
            self.anomalies += 1
        if state is not None and "first_nonfinite" not in fields:
            fields["first_nonfinite"] = first_nonfinite_leaf(state)
        self.emit(
            "anomaly",
            step=step,
            event=event,
            loss=None if loss is None else float(loss),
            **fields,
        )

    # -- watchdog ---------------------------------------------------------

    def _watch(self) -> None:
        poll = max(0.02, min(self._stall_timeout / 4.0, 1.0))
        while not self._stop.wait(poll):
            with self._lock:
                if self._last_beat is None or self._suspended:
                    continue  # not armed yet / in a no-dispatch phase
                since = time.monotonic() - self._last_beat
                fired = self._stall_fired
                step = self._step
            if since < self._stall_timeout or fired:
                continue
            stacks = thread_stacks()
            stacks.pop("telemetry-watchdog", None)  # our own frame is noise
            compiling = compiling_now(stacks)
            if compiling and since < 10.0 * self._stall_timeout:
                # An XLA compile in progress (e.g. a new shape's warmup
                # program) is slow, not wedged — don't fire, don't latch;
                # re-check next poll.  Past 10x the deadline it IS worth
                # an event, classified "compiling".
                continue
            with self._lock:
                self._stall_fired = True
                self.stalls += 1
            depth = None
            if self._queue_depth_fn is not None:
                try:
                    depth = self._queue_depth_fn()
                # analysis: ok exception-hygiene driver-injected probe; the watchdog must survive any probe bug — depth=None still classifies
                except Exception:
                    depth = None
            alive = None
            if self._producer_alive_fn is not None:
                try:
                    alive = self._producer_alive_fn()
                # analysis: ok exception-hygiene driver-injected probe; the watchdog must survive any probe bug — alive=None still classifies
                except Exception:
                    alive = None
            s_idle = None
            if self._stream_idle_fn is not None:
                try:
                    s_idle = self._stream_idle_fn()
                # analysis: ok exception-hygiene driver-injected probe; the watchdog must survive any probe bug — s_idle=None still classifies
                except Exception:
                    s_idle = None
            cls = (
                "compiling"
                if compiling
                else classify_stall(depth, stacks, alive, s_idle)
            )
            try:
                self.emit(
                    "stall",
                    step=step,
                    deadline_s=self._stall_timeout,
                    since_last_step_s=round(since, 3),
                    classification=cls,
                    prefetch_queue_depth=depth,
                    producer_alive=alive,
                    stacks=stacks,
                )
            except (OSError, ValueError):
                pass  # a full metrics disk must not kill stall detection
            log_quietly(
                self._log,
                f"telemetry watchdog: no step for {since:.1f}s "
                f"(deadline {self._stall_timeout:.1f}s) at step {step} — "
                f"{cls}; thread stacks -> kind=stall record",
            )

    # -- shutdown ---------------------------------------------------------

    def close(self, **summary_fields) -> None:
        """Final drain: any unattributed compiles, the guaranteed last
        memory watermark, and the kind=summary totals (the compile
        sentinel's "final count").  Extra keyword fields merge into the
        summary record (drivers pass their end-of-run counters).
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2.0)
        delta = self._sentinel.drain()
        hits = self._sentinel.drain_cache_hits()
        if delta or hits:
            # Compiles landing between the last dispatch and close (e.g.
            # the prefetch thread mid-compiling an unpack program when a
            # SIGTERM stopped the loop) inherit the last dispatch's
            # warmup framing — a warmup-era run must not report them as
            # steady-state recompiles.
            warm = self._last_warmup
            with self._lock:
                self.compiles_total += delta
                if not warm:
                    self.compiles_steady += delta
            self.emit(
                "compile",
                source=self.source,
                compiles=delta,
                total_compiles=self.compiles_total,
                warmup=warm,
                cache_hits=hits,
            )
        self.emit_mem()
        self.emit(
            "summary",
            total_compiles=self.compiles_total,
            steady_compiles=self.compiles_steady,
            stalls=self.stalls,
            anomalies=self.anomalies,
            **summary_fields,
        )
        if self._own_logger:
            self._logger.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- the hang-exit watchdog (absorbed _bench_watchdog.py) -----------------

DEFAULT_HANG_EXIT_SECS = 600.0


def arm_hang_exit(seconds: float = DEFAULT_HANG_EXIT_SECS, what: str = "bench"):
    """Hard hang watchdog for batch tools: os._exit(2) with a stderr note
    if not cancelled within ``seconds``.

    The TPU here sits behind a tunnel that has been observed to hang
    outright (device RPCs block forever, load average ~0) — sometimes as
    early as backend initialization inside ``import jax``.  A hung
    benchmark is worse than a missing one: it stalls the whole harness.
    The bench/probe scripts arm this BEFORE importing jax/fast_tffm_tpu
    and cancel it once their last result line is printed — which is why
    this module (and the package __init__) must import jax-free.

    Unlike RunMonitor's liveness watchdog (observe, classify, keep
    running), this one KILLS: batch tools have nothing to salvage from a
    wedged backend.  Returns the armed ``threading.Timer`` (call
    ``.cancel()`` on success).
    """

    def fire():
        print(
            f"{what} watchdog: no result after {seconds:.0f}s — device "
            "backend appears hung (tunnel down?); aborting without a number",
            file=sys.stderr,
            flush=True,
        )
        os._exit(2)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t
