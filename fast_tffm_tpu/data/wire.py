"""Packed wire format: one contiguous H2D buffer per (super)batch.

The streamed input path is PCIe/DMA-bound on real TPU hosts (DESIGN §8
item 2; PROBE_INPUT_r05 measured 501k step-rate vs 44k end-to-end with
H2D as the entire gap), and the classic staging ships every batch as
five separate host arrays (labels/ids/vals/fields/weights — one
``device_put`` each).  This module cuts the wire two ways:

  * **coalescing** — every tensor of a (super)batch lands in ONE flat
    little-endian byte buffer, shipped with a single ``device_put``;
  * **elision** — tensors that are reconstructible on device are not
    shipped at all, and a jitted unpack (slice → byte-combine → bitcast
    → broadcast) rebuilds the exact ``Batch``:
      - ``vals`` when the stream is all-ones (the dominant CTR libsvm
        case, flagged per-file in the FMB v2 header): rebuilt as
        ``arange(N) < nnz`` — exactly the 1.0f/0.0f pattern the parser
        produced, so losses stay BIT-IDENTICAL;
      - ``fields`` for models that never read it (plain FM/DeepFM —
        the existing ``uses_fields`` rule, now saving wire bytes too);
      - ``weights`` when per-file example weights are uniform (1.0):
        rebuilt from a 4-byte per-batch real-row count (padding rows
        are always a weight-0 suffix);
      - ``ids`` ship at the minimal byte width for the vocabulary
        (3 bytes for a 2^24 Criteo-hash table instead of 4);
      - ``labels`` ship as one byte ({0, 1} is the parser contract) and
        ``nnz`` at the minimal width for ``max_nnz``.

Per micro-batch the flat layout is (all sections little-endian)::

    n_real   u32                1        weight-carrying row count
    labels   u8                 B
    nnz      u8|u16|u32         B        only when NOT with_vals (the
                                         elided-vals rebuild's input;
                                         dead bytes otherwise)
    weights  f32                B        only when with_weights
    ids      u8 x id_bytes      B*N
    vals     f32                B*N      only when with_vals
    fields   i32                B*N      only when with_fields

A superbatch is ``[K, L]`` (one such vector per micro-step); the
unpacker is shape-polymorphic over leading dims, so the same spec
serves K=1 batches, fused [K, B, ...] superbatches, and every serving
bucket.  Exactness is defensive, not assumed: the packer VERIFIES each
elision's reconstruction pattern against the host arrays and raises on
any mismatch, so a wrong per-file flag can never corrupt training.
"""

from __future__ import annotations

import functools
import sys
import time
from typing import NamedTuple

import numpy as np

__all__ = [
    "WireSpec",
    "make_spec",
    "bytes_for",
    "vals_all_ones",
    "pack_batch",
    "pack_superbatch",
    "make_unpacker",
    "WireConverter",
    "arrays_nbytes",
]

# The packed wire assumes a little-endian host (every TPU/GPU host is).
# Checked in make_spec — the pack-path gate — NOT at import time: this
# module also carries InputStats and the convert-time detection helpers,
# which training.py/binary.py import regardless of wire_format, and a
# module-level raise would make the "set wire_format = arrays" escape
# hatch itself crash on a big-endian host.
_LITTLE_ENDIAN = sys.byteorder == "little"


class WireSpec(NamedTuple):
    """Static facts of one packed-wire stream (one XLA unpack per spec
    per shape).  Shape-free on purpose: B and K come off the buffer."""

    nnz: int  # N, the static feature width of every batch
    id_bytes: int  # 1..4, minimal LE width for vocabulary_size - 1
    nnz_bytes: int  # 1..4, minimal LE width for nnz
    with_vals: bool  # False = all-ones stream, vals rebuilt on device
    with_fields: bool  # False = model never reads fields (FM/DeepFM)
    with_weights: bool  # False = uniform file weights, rebuilt from n_real

    @property
    def with_nnz(self) -> bool:
        """The nnz section rides the wire ONLY when something on device
        reconstructs from it (the elided-vals rebuild) — explicit-vals
        wires would ship dead bytes."""
        return not self.with_vals

    @property
    def row_bytes(self) -> int:
        n = self.nnz
        return (
            1  # label u8
            + (self.nnz_bytes if self.with_nnz else 0)
            + (4 if self.with_weights else 0)
            + n * self.id_bytes
            + (4 * n if self.with_vals else 0)
            + (4 * n if self.with_fields else 0)
        )

    def batch_nbytes(self, batch_size: int) -> int:
        """Wire bytes of one micro-batch (the 4-byte n_real included)."""
        return 4 + batch_size * self.row_bytes


def bytes_for(maxval: int) -> int:
    """Minimal little-endian byte width holding ``maxval`` (1..4)."""
    for k in (1, 2, 3):
        if maxval < 1 << (8 * k):
            return k
    return 4


def make_spec(
    vocabulary_size: int,
    max_nnz: int,
    *,
    with_vals: bool,
    with_fields: bool,
    with_weights: bool = False,
) -> WireSpec:
    if not _LITTLE_ENDIAN:  # pragma: no cover - no BE hosts in practice
        raise ValueError(
            "the packed wire format assumes a little-endian host (all "
            "TPU/GPU hosts are); set wire_format = arrays on this platform"
        )
    return WireSpec(
        nnz=int(max_nnz),
        id_bytes=bytes_for(max(1, int(vocabulary_size) - 1)),
        nnz_bytes=bytes_for(max(1, int(max_nnz))),
        with_vals=bool(with_vals),
        with_fields=bool(with_fields),
        with_weights=bool(with_weights),
    )


def arrays_nbytes(batch_size: int, nnz: int, with_fields: bool) -> int:
    """H2D bytes the classic array staging ships for the same batch
    (labels f32 + ids i32 + vals f32 + weights f32 [+ fields i32]) —
    the packed format's comparison baseline."""
    per_row = 4 + 4 * nnz + 4 * nnz + 4 + (4 * nnz if with_fields else 0)
    return batch_size * per_row


def vals_all_ones(vals, nnz) -> bool:
    """True when ``vals`` is exactly the all-ones pattern its ``nnz``
    implies: 1.0 in the first nnz[i] slots of row i, 0.0 beyond.  The
    reconstruction-eligibility check shared by the FMB converter
    (header flag), the packer's defensive verify, and --stats."""
    vals = np.asarray(vals, np.float32)
    nnz = np.asarray(nnz).reshape(-1, 1)
    expect = (np.arange(vals.shape[1]) < nnz).astype(np.float32)
    return bool(np.array_equal(vals, expect))


def _narrow_uint(a, k: int) -> np.ndarray:
    """Integer array → its ``k`` low little-endian bytes per element."""
    a32 = np.ascontiguousarray(a, dtype="<u4")
    b = a32.view(np.uint8).reshape(*a32.shape, 4)
    return b if k == 4 else np.ascontiguousarray(b[..., :k])


def _pack_one(spec: WireSpec, out: np.ndarray, parsed, w, verify_ids=True) -> None:
    """Fill one micro-batch's flat byte vector ``out`` (len row math).

    ``verify_ids=False`` skips the id-range scan for callers whose rows
    were ALREADY range-validated at admission (the serving engine's
    submit paths) — everything else about the verified-never-trusted
    stance (labels, weights, vals) stays on."""
    b, n = parsed.batch_size, spec.nnz
    if parsed.max_nnz != n:
        raise ValueError(
            f"packed wire: batch width {parsed.max_nnz} != spec nnz {n}"
        )
    labels = np.asarray(parsed.labels, np.float32)
    w = np.asarray(w, np.float32)
    n_real = int(np.count_nonzero(w))
    o = 0

    def put(a):
        nonlocal o
        flat = np.ascontiguousarray(a).view(np.uint8).reshape(-1)
        out[o : o + flat.size] = flat
        o += flat.size

    if not spec.with_weights and not np.array_equal(
        w, (np.arange(b) < n_real).astype(np.float32)
    ):
        raise ValueError(
            "packed wire: example weights are not the uniform 1.0-prefix "
            "pattern this spec elides (non-uniform weight_files need "
            "with_weights=True)"
        )
    put(np.array([n_real], "<u4"))
    lab8 = labels.astype(np.uint8)
    if not np.array_equal(lab8.astype(np.float32), labels):
        raise ValueError(
            "packed wire: labels outside {0, 1} — the parser contract the "
            "1-byte label section relies on"
        )
    put(lab8)
    if spec.with_nnz:
        put(_narrow_uint(parsed.nnz, spec.nnz_bytes))
    if spec.with_weights:
        put(w)
    if verify_ids and spec.id_bytes < 4 and parsed.ids.size:
        # Same verified-never-trusted stance as the elided sections: a
        # spec built for a smaller vocabulary than the ids actually
        # present must raise, not silently truncate onto a DIFFERENT
        # valid row.  (id_bytes == 4 round-trips any int32 bitwise.)
        lo, hi = int(parsed.ids.min()), int(parsed.ids.max())
        if lo < 0 or hi >= 1 << (8 * spec.id_bytes):
            raise ValueError(
                f"packed wire: ids span [{lo}, {hi}] but the spec's "
                f"id_bytes={spec.id_bytes} only holds "
                f"[0, {1 << (8 * spec.id_bytes)}) — spec built for the "
                "wrong vocabulary_size?"
            )
    put(_narrow_uint(parsed.ids, spec.id_bytes))
    if spec.with_vals:
        put(np.asarray(parsed.vals, np.float32))
    elif not vals_all_ones(parsed.vals, parsed.nnz):
        # Elision is VERIFIED, never trusted: a stale per-file flag (file
        # swapped under a fresh-looking header) must fail loudly here, not
        # train on reconstructed garbage.
        raise ValueError(
            "packed wire: vals are not the all-ones pattern this spec "
            "elides — re-convert the file (tools/convert_dataset.py) or "
            "set wire_format = arrays"
        )
    if spec.with_fields:
        put(np.ascontiguousarray(parsed.fields, dtype="<i4"))
    if o != out.size:
        raise AssertionError(f"wire layout mismatch: wrote {o} of {out.size}")


def pack_batch(spec: WireSpec, parsed, w, verify_ids=True) -> np.ndarray:
    """One ParsedBatch → flat uint8 wire vector ``[L]``."""
    out = np.empty(spec.batch_nbytes(parsed.batch_size), np.uint8)
    _pack_one(spec, out, parsed, w, verify_ids)
    return out


def pack_superbatch(spec: WireSpec, parsed_seq, w_seq, verify_ids=True) -> np.ndarray:
    """K ParsedBatches → ``[K, L]`` wire matrix (one row per micro-step;
    the epoch-tail group is simply shorter in K)."""
    k = len(parsed_seq)
    b = parsed_seq[0].batch_size
    out = np.empty((k, spec.batch_nbytes(b)), np.uint8)
    if w_seq is None:
        w_seq = [None] * k
    for i, (p, w) in enumerate(zip(parsed_seq, w_seq)):
        _pack_one(
            spec, out[i], p,
            np.ones((b,), np.float32) if w is None else w, verify_ids,
        )
    return out


@functools.lru_cache(maxsize=None)
def make_unpacker(spec: WireSpec):
    """Jitted ``unpack(buf uint8[..., L]) -> Batch`` — the device-side
    reconstruction.  Leading dims pass through ([L] → [B, ...] batch,
    [K, L] → [K, B, ...] superbatch), so the scanned train step consumes
    the output exactly like Batch.stack_parsed's.  Every rebuild is
    bit-exact: f32 sections round-trip by bitcast, elided vals/weights
    rebuild the verified 1.0/0.0 patterns, labels come back from the
    {0, 1} bytes.

    Memoized per spec: drivers build one stream (and one WireConverter)
    PER EPOCH, and a fresh jit function per epoch would re-trace and
    XLA-recompile the same unpack program every time — the cache keys on
    the (hashable) spec so every epoch reuses the compiled programs."""
    import jax
    import jax.numpy as jnp

    from fast_tffm_tpu.models.base import Batch

    n = spec.nnz
    rb = spec.row_bytes

    def combine(x, k):
        # uint8 [..., m*k] -> uint32 [..., m], little-endian.
        x = x.reshape(*x.shape[:-1], -1, k).astype(jnp.uint32)
        out = x[..., 0]
        for i in range(1, k):
            out = out | (x[..., i] << (8 * i))
        return out

    def as_f32(x):
        return jax.lax.bitcast_convert_type(combine(x, 4), jnp.float32)

    def as_i32(x, k):
        u = combine(x, k)
        if k == 4:  # a full word may carry a sign bit — bitcast, not cast
            return jax.lax.bitcast_convert_type(u, jnp.int32)
        return u.astype(jnp.int32)

    @jax.jit
    def unpack(buf):
        *lead, length = buf.shape
        lead = tuple(lead)
        b = (length - 4) // rb
        o = 0

        def take(nbytes):
            nonlocal o
            s = jax.lax.slice_in_dim(buf, o, o + nbytes, axis=-1)
            o += nbytes
            return s

        n_real = combine(take(4), 4).reshape(lead)
        labels = take(b).astype(jnp.float32)
        if spec.with_nnz:
            nnz = as_i32(take(b * spec.nnz_bytes), spec.nnz_bytes)
        if spec.with_weights:
            weights = as_f32(take(4 * b))
        else:
            weights = (jnp.arange(b) < n_real[..., None]).astype(jnp.float32)
        ids = as_i32(take(b * n * spec.id_bytes), spec.id_bytes).reshape(
            *lead, b, n
        )
        if spec.with_vals:
            vals = as_f32(take(4 * b * n)).reshape(*lead, b, n)
        else:
            vals = (jnp.arange(n) < nnz[..., None]).astype(jnp.float32)
        if spec.with_fields:
            fields = as_i32(take(4 * b * n), 4).reshape(*lead, b, n)
        else:
            fields = jnp.zeros((*lead, b, 0), jnp.int32)
        return Batch(
            labels=labels, ids=ids, vals=vals, fields=fields, weights=weights
        )

    return unpack


class WireConverter:
    """``to_batch``-compatible packed-wire shipper: pack on host, ONE
    ``device_put``, jitted unpack.  Accepts a single ParsedBatch or the
    step-fusion K-list, mirroring training._batch_converter's contract.
    Per-call byte/time accounting feeds the kind=input metrics records.
    """

    def __init__(self, spec: WireSpec, verify_ids: bool = True):
        import jax

        self.spec = spec
        self.verify_ids = verify_ids
        self._put = jax.device_put
        self._unpack = make_unpacker(spec)
        self.last_nbytes = 0  # wire bytes of the most recent call
        self.wire_bytes = 0  # cumulative
        self.calls = 0

    def pack(self, parsed, w) -> np.ndarray:
        if isinstance(parsed, list):
            return pack_superbatch(self.spec, parsed, w, self.verify_ids)
        return pack_batch(
            self.spec,
            parsed,
            np.ones((parsed.batch_size,), np.float32) if w is None else w,
            self.verify_ids,
        )

    def __call__(self, parsed, w):
        buf = self.pack(parsed, w)
        self.last_nbytes = buf.nbytes
        self.wire_bytes += buf.nbytes
        self.calls += 1
        return self._unpack(self._put(buf))


class InputStats:
    """Per-stream input-path accounting: parse/convert wall time, wire
    bytes, prefetch-queue depth.  The producer (prefetch thread) updates
    under a lock; the driver drains a snapshot at every log point into a
    ``kind=input`` JSONL record — overlap efficiency becomes first-class
    telemetry instead of probe-only archaeology (ISSUE 3 satellite)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._queue = None  # bound by prefetch(); live-depth probe
        self._producer = None  # bound by prefetch(); liveness probe
        self._stream_idle = None  # bound by follow streams; idle probe
        self.last_depth = None  # most recent consumer-pop sample
        self._reset()

    def bind_queue(self, q) -> None:
        """prefetch() hands over its queue so ``queue_depth`` can read
        LIVE occupancy (the stall watchdog asks from another thread,
        exactly when the consumer has stopped sampling)."""
        self._queue = q

    def bind_producer(self, thread) -> None:
        """prefetch() hands over its producer thread so the stall
        watchdog can distinguish 'input-starved because the producer is
        slow' from 'input-starved because the producer is DEAD'."""
        self._producer = thread

    def bind_stream_idle(self, event) -> None:
        """Follow-mode streams (data/stream.py) hand over their idle
        Event so a starved loop can classify as
        'input-starved (stream-idle)': producer alive, upstream writer
        quiet — wait, don't restart."""
        self._stream_idle = event

    def stream_idle(self) -> bool | None:
        e = self._stream_idle
        return e.is_set() if e is not None else None

    def producer_alive(self) -> bool | None:
        t = self._producer
        return t.is_alive() if t is not None else None

    def queue_depth(self) -> int | None:
        q = self._queue
        if q is not None:
            try:
                return int(q.qsize())
            except Exception:
                pass
        return self.last_depth

    def _reset(self):
        self.items = 0  # queue items (superbatch = 1 item)
        self.converted = 0  # items whose conversion ran in the producer
        self.steps = 0  # micro-steps covered
        self.examples = 0
        self.parse_s = 0.0  # producing (parse / memmap-assemble) time
        self.convert_s = 0.0  # pack + device_put + unpack dispatch time
        self.wire_bytes = 0
        self.q_depth_sum = 0
        self.q_samples = 0

    def timed(self, raw, convert):
        """Wrap the (parsed, w) stream, timing production and conversion.
        ``convert`` None keeps conversion in the consumer (text input) —
        parse time and queue depth still get measured."""
        t0 = time.perf_counter()
        for p, w in raw:
            t1 = time.perf_counter()
            if convert is None:
                b, nbytes, t2 = None, 0, t1
            else:
                b = convert(p, w)
                t2 = time.perf_counter()
                nbytes = getattr(convert, "last_nbytes", 0)
                if not nbytes:  # arrays converter: estimate from the host arrays
                    ps = p if isinstance(p, list) else [p]
                    # What actually ships depends on the CONVERTER's fields
                    # rule (from_parsed sends a [B, 0] placeholder when the
                    # model ignores fields), not on the parsed width.
                    wf = getattr(convert, "uses_fields", None)
                    nbytes = sum(
                        arrays_nbytes(
                            q.batch_size,
                            q.max_nnz,
                            bool(q.fields.shape[1]) if wf is None else wf,
                        )
                        for q in ps
                    )
            k = len(p) if isinstance(p, list) else 1
            ex = (
                sum(q.batch_size for q in p)
                if isinstance(p, list)
                else p.batch_size
            )
            with self._lock:
                self.items += 1
                self.converted += b is not None
                self.steps += k
                self.examples += ex
                self.parse_s += t1 - t0
                self.convert_s += t2 - t1
                self.wire_bytes += nbytes
            yield b, p, w
            t0 = time.perf_counter()

    def on_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.last_depth = depth
            self.q_depth_sum += depth
            self.q_samples += 1

    def drain(self) -> dict:
        """Snapshot-and-reset; {} when nothing flowed since last drain."""
        with self._lock:
            if not self.items:
                return {}
            # h2d/wire keys are None — not a misleading 0.0 — when
            # conversion ran in the CONSUMER (text input) and was simply
            # never measured here.
            measured = self.converted > 0
            out = {
                "input_items": self.items,
                "input_steps": self.steps,
                "input_examples": self.examples,
                "parse_ms": round(1e3 * self.parse_s / self.items, 3),
                "h2d_ms": (
                    round(1e3 * self.convert_s / self.items, 3)
                    if measured
                    else None
                ),
                "wire_bytes_per_step": (
                    int(self.wire_bytes / self.steps)
                    if measured and self.steps
                    else None
                ),
                "prefetch_queue_depth": (
                    round(self.q_depth_sum / self.q_samples, 2)
                    if self.q_samples
                    else None
                ),
            }
            self._reset()
        return out
