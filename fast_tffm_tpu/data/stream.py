"""FMS append-only stream container + the tail-following reader.

The online-learning input shape (`[Online] follow = true`): production CTR
events arrive continuously, and the trainer that serves traffic must
follow them.  FMB (data/binary.py) cannot be that file — its columnar
sections are sized by ``n_rows`` at write time, so appending one row would
shift every later section.  FMS is the row-major sibling: one 64-byte
header, then fixed-size row RECORDS appended forever::

    header  64 B   magic 'FMS1', version, width, vocabulary_size, hashed
    record         label f32 | nnz i32 | ids i32[W] | vals f32[W]
                   | fields i32[W]          (8 + 12·W bytes, little-endian)

Append = write one record's bytes + flush; the row count is derived from
the FILE SIZE, so a reader never needs a header rewrite to see new rows.
A partial trailing record (a writer crash, or a slow append caught
mid-write — the ``append_torn`` chaos fault) simply doesn't count toward
``(size - 64) // record_bytes`` and is re-examined on the next poll: the
reader waits it out and NEVER parses half a record.

``fms_follow_stream`` is the tail-following batch reader: at EOF it polls
the file size at a bounded interval instead of ending the epoch, marks
itself idle (the telemetry stall watchdog classifies a starved loop as
``input-starved (stream-idle)``), and resumes cleanly when bytes land.
It only ever emits FULL batches — every emitted batch consumed exactly
``batch_size`` rows, which is what keeps the exact-position resume cursor
(PR 6) a pure multiplication; leftover rows below one batch stay in the
file for the next poll (or the next resumed process).

Identity for resume is PREFIX-based, not size-based: an append-only file
GROWS between save and resume by design, so the PR-6 size fingerprint
would always mismatch.  ``stream_prefix_fingerprint`` hashes the header
plus the first 64 KiB of records — immutable under append — and a resume
against a file whose prefix changed (replaced, truncated, rewritten)
fails LOUDLY instead of silently misaligning the cursor.
"""

from __future__ import annotations

import hashlib
import os
import struct
import time
from collections.abc import Sequence

import numpy as np

from fast_tffm_tpu.data.libsvm import ParsedBatch

__all__ = [
    "FMS_MAGIC",
    "FMS_VERSION",
    "FMS_HEADER_BYTES",
    "is_fms",
    "fms_record_bytes",
    "read_fms_header",
    "fms_row_count",
    "StreamWriter",
    "read_fms_rows",
    "fms_follow_stream",
    "stream_prefix_fingerprint",
    "stream_prefix_matches",
]

FMS_MAGIC = b"FMS1"
FMS_VERSION = 1
FMS_HEADER_BYTES = 64
# magic, version, width, vocabulary_size, hashed, flags (reserved 0)
_HEADER = struct.Struct("<4sIqqBB")
assert _HEADER.size <= FMS_HEADER_BYTES
_PREFIX_HASH_BYTES = 64 << 10  # immutable-under-append identity window


def fms_record_bytes(width: int) -> int:
    """label f32 + nnz i32 + (ids + vals + fields) i32/f32[width]."""
    return 8 + 12 * int(width)


def is_fms(path) -> bool:
    """True when ``path`` starts with the FMS magic (missing file → False)."""
    try:
        with open(path, "rb") as f:
            return f.read(4) == FMS_MAGIC
    except OSError:
        return False


def read_fms_header(path) -> dict:
    with open(path, "rb") as f:
        raw = f.read(FMS_HEADER_BYTES)
    if len(raw) < FMS_HEADER_BYTES:
        raise ValueError(f"{path}: truncated FMS header")
    magic, version, width, vocab, hashed, _flags = _HEADER.unpack(
        raw[: _HEADER.size]
    )
    if magic != FMS_MAGIC:
        raise ValueError(f"{path}: not an FMS stream file")
    if version != FMS_VERSION:
        raise ValueError(f"{path}: unsupported FMS version {version}")
    if width < 1:
        raise ValueError(f"{path}: bad FMS width {width}")
    return {
        "path": os.fspath(path),
        "width": int(width),
        "vocabulary_size": int(vocab),
        "hashed": bool(hashed),
        "record_bytes": fms_record_bytes(width),
    }


def fms_row_count(path, width: int) -> int:
    """COMPLETE records currently in the file.  A partial trailing record
    (torn append) does not count — floor division is the wait-it-out."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    return max(0, (size - FMS_HEADER_BYTES)) // fms_record_bytes(width)


class StreamWriter:
    """Append-side of the FMS contract (tools/soak.py, tools/backtest.py,
    tests).  Creates the file with its header if absent; ``append``
    writes whole records + flush, so a reader polling the size only ever
    sees complete rows — except through ``append_torn``, the deliberate
    chaos hook that leaves a partial trailing record on disk (flushed!)
    until ``complete_torn`` lands the remainder, which is exactly the
    window the follow reader must wait out, never parse."""

    def __init__(
        self,
        path,
        *,
        width: int,
        vocabulary_size: int,
        hash_feature_id: bool = False,
    ):
        self.path = os.fspath(path)
        self.width = int(width)
        self.vocabulary_size = int(vocabulary_size)
        self.record_bytes = fms_record_bytes(self.width)
        self.appends = 0  # append ordinal (the append_torn@K counter)
        self._torn_rest: bytes | None = None
        if os.path.exists(self.path):
            hdr = read_fms_header(self.path)
            if hdr["width"] != self.width or hdr["vocabulary_size"] != self.vocabulary_size:
                raise ValueError(
                    f"{self.path}: existing stream has width={hdr['width']} "
                    f"vocab={hdr['vocabulary_size']}, writer wants "
                    f"{self.width}/{self.vocabulary_size}"
                )
            self._f = open(self.path, "ab")
        else:
            self._f = open(self.path, "wb")
            hdr = _HEADER.pack(
                FMS_MAGIC, FMS_VERSION, self.width, self.vocabulary_size,
                1 if hash_feature_id else 0, 0,
            )
            self._f.write(hdr + b"\0" * (FMS_HEADER_BYTES - len(hdr)))
            self._f.flush()

    def _encode(self, labels, ids, vals, fields, nnz) -> bytes:
        n = len(labels)
        w = self.width
        rec = np.zeros((n, self.record_bytes), np.uint8)
        rec[:, 0:4] = np.asarray(labels, "<f4").reshape(n, 1).view(np.uint8)
        nnz = np.asarray(nnz, "<i4")
        if nnz.size and (int(nnz.max()) > w or int(nnz.min()) < 0):
            raise ValueError(
                f"stream append: nnz out of [0, {w}] (got max {int(nnz.max())})"
            )
        id_arr = np.asarray(ids)
        if id_arr.size and (
            int(id_arr.max()) >= self.vocabulary_size or int(id_arr.min()) < 0
        ):
            # Same loud range rule as the text parsers: a clamped gather
            # downstream would train the wrong embedding row silently.
            raise ValueError(
                f"stream append: id out of [0, {self.vocabulary_size}) "
                f"(got max {int(id_arr.max())}, min {int(id_arr.min())})"
            )
        rec[:, 4:8] = nnz.reshape(n, 1).view(np.uint8)

        def put(col, arr, dtype):
            a = np.zeros((n, w), dtype)
            src = np.asarray(arr, dtype)
            cw = min(w, src.shape[1]) if src.ndim == 2 else 0
            if cw:
                a[:, :cw] = src[:, :cw]
            rec[:, col : col + 4 * w] = a.view(np.uint8).reshape(n, 4 * w)

        put(8, ids, "<i4")
        put(8 + 4 * w, vals, "<f4")
        put(8 + 8 * w, fields if fields is not None else np.zeros((n, w)), "<i4")
        return rec.tobytes()

    def append(self, labels, ids, vals, fields=None, nnz=None) -> int:
        """Append ``n`` whole rows; returns the append ordinal (1-based).
        A pending torn record (``append_torn``) is completed FIRST —
        appending into the middle of a partial record would misalign
        every later record in the file."""
        self.complete_torn()
        if nnz is None:
            nnz = (np.asarray(vals) != 0).sum(axis=1)
        self._f.write(self._encode(labels, ids, vals, fields, nnz))
        self._f.flush()
        self.appends += 1
        return self.appends

    def append_torn(self, labels, ids, vals, fields=None, nnz=None) -> int:
        """Chaos hook (``append_torn@K``): write only the FIRST HALF of
        the final record's bytes and flush — a torn trailing record a
        reader must never parse.  ``complete_torn`` lands the rest.  A
        PREVIOUS pending torn record is completed first (same alignment
        rule as ``append`` — dropping its remainder would misalign
        every later record)."""
        self.complete_torn()
        if nnz is None:
            nnz = (np.asarray(vals) != 0).sum(axis=1)
        blob = self._encode(labels, ids, vals, fields, nnz)
        cut = len(blob) - self.record_bytes // 2
        self._f.write(blob[:cut])
        self._f.flush()
        self._torn_rest = blob[cut:]
        self.appends += 1
        return self.appends

    def complete_torn(self) -> None:
        if self._torn_rest is not None:
            self._f.write(self._torn_rest)
            self._f.flush()
            self._torn_rest = None

    def close(self) -> None:
        self.complete_torn()
        try:
            self._f.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_fms_rows(path, start: int, count: int, *, header: dict | None = None):
    """Decode ``count`` complete records starting at row ``start`` into
    (labels, nnz, ids, vals, fields) host arrays.  The caller is
    responsible for ``start + count`` being within ``fms_row_count`` —
    this is a plain positional read, no tailing."""
    hdr = header or read_fms_header(path)
    w, rb = hdr["width"], hdr["record_bytes"]
    with open(path, "rb") as f:
        f.seek(FMS_HEADER_BYTES + start * rb)
        raw = f.read(count * rb)
    if len(raw) < count * rb:
        raise ValueError(
            f"{path}: short read at row {start} (+{count}) — writer "
            "truncated an append-only stream?"
        )
    rec = np.frombuffer(raw, np.uint8).reshape(count, rb)
    labels = rec[:, 0:4].copy().view("<f4").reshape(count)
    nnz = rec[:, 4:8].copy().view("<i4").reshape(count)
    ids = rec[:, 8 : 8 + 4 * w].copy().view("<i4").reshape(count, w)
    vals = rec[:, 8 + 4 * w : 8 + 8 * w].copy().view("<f4").reshape(count, w)
    fields = rec[:, 8 + 8 * w : 8 + 12 * w].copy().view("<i4").reshape(count, w)
    if count and (int(nnz.max()) > w or int(nnz.min()) < 0):
        # A complete-size record with an insane nnz is CORRUPTION, not a
        # torn tail (floor division already excluded partial records) —
        # fail loudly naming the row rather than train on garbage.
        bad = int(np.argmax((nnz > w) | (nnz < 0)))
        raise ValueError(
            f"{path}: corrupt stream record at row {start + bad} "
            f"(nnz {int(nnz[bad])} outside [0, {w}])"
        )
    vocab = hdr["vocabulary_size"]
    if count and (int(ids.max()) >= vocab or int(ids.min()) < 0):
        # Same rule the text parsers enforce: an out-of-range id would
        # silently clamp in the jitted gather and train the wrong row.
        bad = int(np.argmax(((ids >= vocab) | (ids < 0)).any(axis=1)))
        raise ValueError(
            f"{path}: corrupt stream record at row {start + bad} "
            f"(feature id outside [0, {vocab}))"
        )
    return labels, nnz, ids, vals, fields


def stream_prefix_fingerprint(files: Sequence[str]) -> str:
    """Append-stable input identity for the follow-mode resume cursor.

    Per file: ``<bytes-hashed>:<md5-prefix>`` over the header plus the
    first (up to 64 KiB of) record bytes AT FINGERPRINT TIME.  The
    hashed length rides inside the fingerprint because an append-only
    file GROWS: a later verification must re-hash exactly the same
    prefix window, not "the first 64 KiB of whatever is there now" —
    ``stream_prefix_matches`` is that verifier.  The PR-6 size
    fingerprint cannot serve here (growth is the normal case), but a
    REPLACED, rewritten, or truncated file still fails the prefix
    re-hash, and training._resolve_cursor fails loudly on it instead of
    resuming at a meaningless offset."""
    parts = []
    for p in files:
        try:
            with open(os.fspath(p), "rb") as f:
                blob = f.read(FMS_HEADER_BYTES + _PREFIX_HASH_BYTES)
        except OSError:
            blob = b""
        parts.append(f"{len(blob)}:{hashlib.md5(blob).hexdigest()[:16]}")
    return "fms1," + ",".join(parts)


def stream_prefix_matches(files: Sequence[str], fingerprint: str) -> bool:
    """Verify a ``stream_prefix_fingerprint`` against the CURRENT files:
    re-hash exactly the recorded prefix window of each.  False for a
    malformed/foreign fingerprint, a changed file count, a file now
    SHORTER than the recorded window (truncated — append-only files
    never shrink), or any hash mismatch."""
    if not isinstance(fingerprint, str) or not fingerprint.startswith("fms1,"):
        return False
    entries = fingerprint[len("fms1,") :].split(",")
    if len(entries) != len(files):
        return False
    for p, ent in zip(files, entries):
        n_s, sep, want = ent.partition(":")
        if not sep:
            return False
        try:
            n = int(n_s)
        except ValueError:
            return False
        try:
            with open(os.fspath(p), "rb") as f:
                blob = f.read(n)
        except OSError:
            return False
        if len(blob) != n or hashlib.md5(blob).hexdigest()[:16] != want:
            return False
    return True


def fms_follow_stream(
    path,
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    poll_s: float = 0.2,
    idle_timeout_s: float = 0.0,
    max_batches: int = 0,
    skip_batches: int = 0,
    weight: float = 1.0,
    stop=None,
    idle_flag=None,
):
    """Tail-follow ``path``, yielding ``(ParsedBatch, weights)`` full
    batches forever (or until bounded).

    Contract (the online-learning input mode):

    * Only FULL batches are emitted — batch k consumed rows
      ``[k·B, (k+1)·B)`` exactly, so the resume cursor's batch count maps
      to a byte offset by pure multiplication.  Rows below one batch stay
      in the file for the next poll (or the next resumed process).
    * At EOF the reader POLLS the file size every ``poll_s`` seconds
      instead of ending the epoch; ``idle_flag.set()/.clear()`` (any
      object with those methods) tracks the idle state so the telemetry
      watchdog can classify a starved train loop as
      ``input-starved (stream-idle)``.
    * A partial trailing record never parses (floor division of the size).
    * The file's IDENTITY is re-verified while following: the prefix
      fingerprint captured at open is re-hashed on every transition into
      idle and every few hundred batches, and a size that ever drops
      below the consumed offset fails immediately — a stream REPLACED,
      rewritten, or truncated mid-run (log rotation, an operator
      re-seeding the file) raises loudly instead of being silently
      consumed at a now-meaningless byte offset (the live twin of the
      resume-time ``stream_prefix_matches`` check).
    * ``skip_batches`` reopens mid-stream at that batch offset — the
      exact-position resume seek, O(1) (one file seek).
    * Bounds, for tools and tests: ``max_batches`` > 0 ends the stream
      once the TOTAL emitted batch index (skip included — the
      pad_to_batches convention) reaches it; ``idle_timeout_s`` > 0 ends
      it after that much continuous idleness; ``stop`` (an Event-like
      with ``is_set``) ends it at the next poll.  0/None = follow until
      the process is told to stop.
    """
    hdr = read_fms_header(path)
    if hdr["hashed"] != bool(hash_feature_id):
        raise ValueError(
            f"{path}: stream written with hash_feature_id={hdr['hashed']}, "
            f"requested {bool(hash_feature_id)}"
        )
    if hdr["hashed"] and hdr["vocabulary_size"] != vocabulary_size:
        raise ValueError(
            f"{path}: stream hashed into vocabulary_size="
            f"{hdr['vocabulary_size']}, requested {vocabulary_size}"
        )
    if not hdr["hashed"] and hdr["vocabulary_size"] > vocabulary_size:
        raise ValueError(
            f"{path}: stream ids validated against vocabulary_size="
            f"{hdr['vocabulary_size']} > requested {vocabulary_size}"
        )
    fw = hdr["width"]
    width = int(max_nnz) if max_nnz else fw
    cw = min(fw, width)
    if skip_batches < 0:
        raise ValueError(f"skip_batches must be >= 0, got {skip_batches}")
    poll_s = max(0.01, float(poll_s))
    fingerprint = stream_prefix_fingerprint([path])

    def check_identity():
        if not stream_prefix_matches([path], fingerprint):
            raise ValueError(
                f"{path}: stream PREFIX changed while following (file "
                "replaced/rewritten mid-run?) — the current byte offset "
                "no longer names the data it was advanced over"
            )

    emitted = skip_batches  # skipped batches COUNT (pad_to_batches rule)
    pos = skip_batches * batch_size
    idle_since = None
    since_check = 0
    while True:
        if max_batches and emitted >= max_batches:
            return
        avail = fms_row_count(path, fw)
        if avail < pos:
            # Append-only files never shrink: the consumed offset now
            # points past the end — truncated or replaced underneath us.
            raise ValueError(
                f"{path}: stream shrank below the consumed offset "
                f"({avail} rows < position {pos}) — truncated/replaced "
                "mid-run; append-only streams never shrink"
            )
        if avail - pos >= batch_size:
            if stop is not None and stop.is_set():
                # Checked on the data path too: an abandoned stream with
                # backlog must stop producing, not just stop polling.
                return
            since_check += 1
            if since_check >= 512:
                # Cheap periodic identity re-hash even while data flows
                # (a same-or-larger replacement never hits the EOF path).
                since_check = 0
                check_identity()
            if idle_flag is not None and idle_since is not None:
                idle_flag.clear()
            idle_since = None
            labels, nnz, ids, vals, fields = read_fms_rows(
                path, pos, batch_size, header=hdr
            )
            if cw < fw and int(nnz.max(initial=0)) > cw:
                raise ValueError(
                    f"{path}: stream rows up to {int(nnz.max())} features "
                    f"> max_nnz={width}"
                )
            out_ids = np.zeros((batch_size, width), np.int32)
            out_vals = np.zeros((batch_size, width), np.float32)
            out_flds = np.zeros((batch_size, width), np.int32)
            out_ids[:, :cw] = ids[:, :cw]
            out_vals[:, :cw] = vals[:, :cw]
            out_flds[:, :cw] = fields[:, :cw]
            w = np.full((batch_size,), float(weight), np.float32)
            pos += batch_size
            emitted += 1
            yield (
                ParsedBatch(
                    labels.astype(np.float32, copy=False),
                    out_ids,
                    out_vals,
                    out_flds,
                    nnz.astype(np.int32, copy=False),
                ),
                w,
            )
            continue
        # EOF (or a torn trailing record): poll for growth.
        if stop is not None and stop.is_set():
            return
        now = time.monotonic()
        if idle_since is None:
            idle_since = now
            if idle_flag is not None:
                idle_flag.set()
            # Entering idle: re-verify the file is still the one we have
            # been consuming (the cheap moment — no data is flowing).
            check_identity()
        elif idle_timeout_s > 0 and now - idle_since >= idle_timeout_s:
            return
        time.sleep(poll_s)
