"""FMB packed binary dataset format: parse libsvm text once, stream forever.

The reference re-parses libsvm text every epoch (its FmParser C++ op runs
inside the per-step graph; `renyi533/fast_tffm` :: cc/ parser kernel).  On a
TPU host the text parse is the end-to-end bottleneck — the jitted train step
consumes hundreds of millions of examples/sec while a CPU core parses well
under a million rows/sec.  FMB removes the bound: one streaming parse writes
the padded arrays the device batch needs (labels, ids, vals, fields, nnz) as
flat little-endian sections in a single file, and every later pass memmaps
the file and slices batches out at memcpy speed.

Layout (all offsets 64-byte aligned, little-endian):

    header  64 B   magic 'FMB1', version, n_rows, width, vocabulary_size,
                   hashed flag, ids itemsize, source (size, mtime_ns) for
                   cache staleness
    labels  f32[n_rows]
    nnz     i32[n_rows]
    ids     i32[n_rows, width]       (the device dtype — TPU gathers index
                                      with int32, and config caps
                                      vocabulary_size at int32 range)
    vals    f32[n_rows, width]
    fields  i32[n_rows, width]

Row order is exactly the text order (non-blank lines), so the block-cyclic
shard selection in ``fmb_batch_stream`` is bit-compatible with the text
pipelines in pipeline.py / native.py: global row index == global non-blank
line index.  Feature hashing is applied at WRITE time; the header records
the (vocabulary_size, hashed) pair the ids were produced under and readers
refuse a mismatched configuration rather than silently mixing id spaces.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import struct
import time
import uuid
from collections.abc import Iterator, Sequence

import numpy as np

from fast_tffm_tpu import resilience
from fast_tffm_tpu.data.libsvm import ParsedBatch

__all__ = [
    "FMB_MAGIC",
    "FMB_VERSION",
    "FLAG_VALS_ALL_ONES",
    "FLAG_FIELDS_ALL_ZERO",
    "FmbFile",
    "is_fmb",
    "open_fmb",
    "write_fmb",
    "fmb_batch_stream",
    "fmb_wire_flags",
    "fmb_stats",
    "ensure_fmb_cache",
    "fold_epoch_seed",
    "draw_permutation",
]

FMB_MAGIC = b"FMB1"
_ALIGN = 64
# magic, version, n_rows, width, vocabulary_size, hashed, ids_itemsize,
# flags, (pad), src_size, src_mtime_ns, max_row_nnz
# max_row_nnz is the file's WIDEST ACTUAL ROW — `width` is the converter's
# (possibly generous) --max-nnz padding choice.  Readers compare a
# requested max_nnz against the actual widest row, so a generously-padded
# file still serves a narrower training config.  0 = unknown (files
# written before the field existed; readers fall back to scanning nnz).
#
# ``flags`` is the v2 wire-compressibility byte, carved out of v1's pad
# region (v1 writers zeroed it, so v1 files read as flags=0 — i.e. "no
# elision promised", always safe).  Data sections are identical across
# versions; only the header gained meaning, so v1 stays fully readable.
_HEADER = struct.Struct("<4sIqqqBBB5xqqq")
assert _HEADER.size <= _ALIGN
FMB_VERSION = 2
# Per-file wire-elision facts, computed at convert time over EVERY row
# (data/wire.py consumes them to pick a packed wire spec per stream):
FLAG_VALS_ALL_ONES = 1  # every row's vals are the 1.0-prefix/0.0-pad pattern
FLAG_FIELDS_ALL_ZERO = 2  # no row carries a field id (plain libsvm input)


def _align(off: int) -> int:
    return (off + _ALIGN - 1) // _ALIGN * _ALIGN


def _section_offsets(n_rows: int, width: int, ids_itemsize: int):
    """(labels, nnz, ids, vals, fields, total_bytes) byte offsets."""
    off = _ALIGN
    labels = off
    off = _align(labels + 4 * n_rows)
    nnz = off
    off = _align(nnz + 4 * n_rows)
    ids = off
    off = _align(ids + ids_itemsize * n_rows * width)
    vals = off
    off = _align(vals + 4 * n_rows * width)
    fields = off
    off = _align(fields + 4 * n_rows * width)
    return labels, nnz, ids, vals, fields, off


@dataclasses.dataclass
class FmbFile:
    """An open (read-only, memmapped) FMB dataset."""

    path: str
    n_rows: int
    width: int
    vocabulary_size: int
    hashed: bool
    src_size: int
    src_mtime_ns: int
    max_row_nnz: int  # widest actual row; 0 = unknown (pre-field files)
    flags: int  # FLAG_* wire-compressibility bits (0 for v1 files)
    labels: np.ndarray  # f32 [n_rows]
    nnz: np.ndarray  # i32 [n_rows]
    ids: np.ndarray  # i32 [n_rows, width]
    vals: np.ndarray  # f32 [n_rows, width]
    fields: np.ndarray  # i32 [n_rows, width]


def is_fmb(path) -> bool:
    """True when ``path`` starts with the FMB magic (missing file → False)."""
    try:
        with open(path, "rb") as f:
            return f.read(4) == FMB_MAGIC
    except OSError:
        return False


def _read_header(path):
    with open(path, "rb") as f:
        raw = f.read(_HEADER.size)
    if len(raw) < _HEADER.size:
        raise ValueError(f"{path}: truncated FMB header")
    magic, version, n_rows, width, vocab, hashed, isz, flags, src_size, src_mtime, widest = (
        _HEADER.unpack(raw)
    )
    if magic != FMB_MAGIC:
        raise ValueError(f"{path}: not an FMB file")
    if version not in (1, 2):
        raise ValueError(f"{path}: unsupported FMB version {version}")
    if version == 1:
        # v1's pad bytes carried no meaning; never trust them as flags.
        flags = 0
    if isz != 4:
        # int32 ids only: Batch.from_parsed narrows ids to int32 (the TPU
        # gather index dtype) and config caps vocabulary_size to match, so
        # a wider id section could only ever truncate silently downstream.
        raise ValueError(f"{path}: unsupported ids itemsize {isz} (int32 only)")
    return (
        n_rows, width, vocab, bool(hashed), isz, src_size, src_mtime,
        widest, flags, version,
    )


def open_fmb(path) -> FmbFile:
    """Memmap an FMB file into array views (no data is read eagerly)."""
    path = os.fspath(path)
    n_rows, width, vocab, hashed, isz, src_size, src_mtime, widest, flags, _ver = (
        _read_header(path)
    )
    o_lab, o_nnz, o_ids, o_val, o_fld, total = _section_offsets(n_rows, width, isz)
    if os.path.getsize(path) < total:
        raise ValueError(f"{path}: truncated FMB file (partial write?)")
    mm = np.memmap(path, np.uint8, mode="r")

    def view(off, count, dtype, shape):
        return mm[off : off + count * np.dtype(dtype).itemsize].view(dtype).reshape(shape)

    return FmbFile(
        path=path,
        n_rows=n_rows,
        width=width,
        vocabulary_size=vocab,
        hashed=hashed,
        src_size=src_size,
        src_mtime_ns=src_mtime,
        max_row_nnz=widest,
        flags=flags,
        labels=view(o_lab, n_rows, np.float32, (n_rows,)),
        nnz=view(o_nnz, n_rows, np.int32, (n_rows,)),
        ids=view(o_ids, n_rows * width, np.int32, (n_rows, width)),
        vals=view(o_val, n_rows * width, np.float32, (n_rows, width)),
        fields=view(o_fld, n_rows * width, np.int32, (n_rows, width)),
    )


def write_fmb(
    src_path,
    out_path,
    *,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    parser=None,
    chunk: int = 8192,
) -> str:
    """Convert ONE libsvm/libffm text file to an FMB file (atomic write).

    One FMB per source file, so per-file example weights (cfg.weight_files)
    keep their alignment at stream time.  ``max_nnz`` fixes the stored
    width; default is the file's widest row.  The write goes to a
    process-unique temp name and lands via ``os.replace`` — concurrent
    converters (multi-host cache fill on a shared filesystem) are safe and
    idempotent.
    """
    from fast_tffm_tpu.data.native import best_parser, scan_files
    from fast_tffm_tpu.data.pipeline import batch_stream

    src_path, out_path = os.fspath(src_path), os.fspath(out_path)
    if vocabulary_size > np.iinfo(np.int32).max:
        # Mirrors Config.validate: device ids are int32 (the TPU gather
        # index dtype), so a wider id space could only truncate silently.
        raise ValueError(
            f"vocabulary_size {vocabulary_size} exceeds int32; hash ids "
            "into range instead (hash_feature_id)"
        )
    st = os.stat(src_path)
    # Temp name unique across hosts too: multi-host cache fills on a shared
    # filesystem can race, and containerized pod workers routinely share
    # PIDs — a colliding temp name would truncate a peer's half-written
    # file.  os.replace keeps the visible path atomic either way.
    tmp = f"{out_path}.{socket.gethostname()}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp"
    try:
        # Probe writability BEFORE the full source scan: on an unwritable
        # cache location the OSError must land cheaply (ensure_fmb_cache
        # falls back to text per stream, and a multi-GB pre-scan per epoch
        # would be pure waste).
        with open(tmp, "wb"):
            pass
        n_rows, widest = scan_files([src_path])
        width = int(max_nnz) if max_nnz else max(1, widest)
        ids_dtype = np.int32
        isz = 4
        o_lab, o_nnz, o_ids, o_val, o_fld, total = _section_offsets(n_rows, width, isz)
        with open(tmp, "r+b") as f:
            f.truncate(total)
        mm = np.memmap(tmp, np.uint8, mode="r+")

        def view(off, count, dtype, shape):
            return mm[off : off + count * np.dtype(dtype).itemsize].view(dtype).reshape(shape)

        labels = view(o_lab, n_rows, np.float32, (n_rows,))
        nnz = view(o_nnz, n_rows, np.int32, (n_rows,))
        ids = view(o_ids, n_rows * width, ids_dtype, (n_rows, width))
        vals = view(o_val, n_rows * width, np.float32, (n_rows, width))
        fields = view(o_fld, n_rows * width, np.int32, (n_rows, width))

        # Parse-time constant detection (wire format v2): track, chunk by
        # chunk, whether EVERY row's vals follow the all-ones pattern and
        # whether any field id appears — the header flags data/wire.py
        # later elides H2D tensors on.  The C parser scans in-kernel when
        # built (fm_vals_all_ones); numpy otherwise.
        from fast_tffm_tpu.data.wire import vals_all_ones as _np_all_ones

        use_parser = parser if parser is not None else best_parser()
        native_check = getattr(use_parser, "vals_all_ones", None)
        all_ones = True
        fields_zero = True
        row = 0
        for parsed, _w in batch_stream(
            [src_path],
            batch_size=min(chunk, max(1, n_rows)),
            vocabulary_size=vocabulary_size,
            hash_feature_id=hash_feature_id,
            max_nnz=width,
            parser=use_parser,
        ):
            take = min(parsed.batch_size, n_rows - row)  # strip tail padding
            labels[row : row + take] = parsed.labels[:take]
            nnz[row : row + take] = parsed.nnz[:take]
            ids[row : row + take] = parsed.ids[:take].astype(ids_dtype, copy=False)
            vals[row : row + take] = parsed.vals[:take]
            fields[row : row + take] = parsed.fields[:take]
            if all_ones:
                chunk_vals, chunk_nnz = parsed.vals[:take], parsed.nnz[:take]
                all_ones = bool(
                    native_check(chunk_vals, chunk_nnz)
                    if native_check is not None
                    else _np_all_ones(chunk_vals, chunk_nnz)
                )
            if fields_zero and parsed.fields[:take].any():
                fields_zero = False
            row += take
        if row != n_rows:
            raise RuntimeError(
                f"{src_path}: parsed {row} rows, scan said {n_rows} "
                "(file changed mid-convert?)"
            )
        flags = (FLAG_VALS_ALL_ONES if all_ones else 0) | (
            FLAG_FIELDS_ALL_ZERO if fields_zero else 0
        )
        # Header LAST: the flags are facts about the whole file, and a
        # crash mid-fill leaves a magic-less temp, never a lying header.
        mm[: _HEADER.size] = np.frombuffer(
            _HEADER.pack(
                FMB_MAGIC, FMB_VERSION, n_rows, width, vocabulary_size,
                1 if hash_feature_id else 0, isz, flags, st.st_size,
                st.st_mtime_ns, max(1, widest),
            ),
            np.uint8,
        )
        mm.flush()
        del mm
        os.replace(tmp, out_path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return out_path


def fmb_wire_flags(files) -> tuple[bool, bool]:
    """(vals_all_ones, fields_all_zero) for a STREAM over ``files`` — the
    AND of every file's v2 header flags.  Any non-FMB or v1 file makes
    both False: elision is only ever claimed when every row was verified
    at convert time (the packer re-verifies per batch regardless)."""
    ones = zero = True
    for path in files:
        try:
            if not is_fmb(path):
                return False, False
            flags = _read_header(os.fspath(path))[8]
        except (OSError, ValueError):
            return False, False
        ones = ones and bool(flags & FLAG_VALS_ALL_ONES)
        zero = zero and bool(flags & FLAG_FIELDS_ALL_ZERO)
    return ones, zero


def fmb_stats(path, chunk: int = 1 << 16) -> dict:
    """Wire-compressibility report for one FMB file (convert_dataset
    --stats): per-row all-ones/constant-fields fractions from a full
    chunked scan (ground truth, not the header flags — a v1 file reports
    honestly here), plus the projected packed-wire byte saving."""
    from fast_tffm_tpu.data.wire import arrays_nbytes, make_spec

    f = open_fmb(path)
    ones_rows = 0
    zero_field_rows = 0
    cols = np.arange(f.width)
    for lo in range(0, f.n_rows, chunk):
        sl = slice(lo, min(lo + chunk, f.n_rows))
        expect = (cols < f.nnz[sl][:, None]).astype(np.float32)
        ones_rows += int((f.vals[sl] == expect).all(axis=1).sum())
        zero_field_rows += int((~f.fields[sl].any(axis=1)).sum())
    n = max(1, f.n_rows)
    all_ones = ones_rows == f.n_rows
    fields_zero = zero_field_rows == f.n_rows
    spec = make_spec(
        f.vocabulary_size,
        f.width,
        with_vals=not all_ones,
        with_fields=not fields_zero,
    )
    arrays_row = arrays_nbytes(1, f.width, with_fields=not fields_zero)
    return {
        "path": f.path,
        "rows": f.n_rows,
        "width": f.width,
        "vocabulary_size": f.vocabulary_size,
        "header_flags": f.flags,
        "vals_all_ones_fraction": round(ones_rows / n, 6),
        "fields_zero_fraction": round(zero_field_rows / n, 6),
        "arrays_wire_bytes_per_row": arrays_row,
        "packed_wire_bytes_per_row": spec.row_bytes,
        "projected_wire_cut_x": round(arrays_row / spec.row_bytes, 3),
    }


def _io_retry(fn, *, what: str, attempts: int = 3, backoff_s: float = 0.05):
    """Run ``fn`` retrying transient OSErrors with exponential backoff.

    The FMB read path sits on memmapped (possibly network) files; a
    transient hiccup mid-epoch used to kill the whole run even though
    the read is idempotent (the copies overwrite the same destination
    slice, so a retry can never lose or duplicate rows).  Each absorbed
    retry is recorded through resilience.note_io_retry so the run's
    telemetry shows the near-miss; attempts exhausted re-raises the last
    error.  ``resilience.maybe_io_fault`` inside the try is the
    deterministic chaos injection point — an injected fault is absorbed
    exactly like a real one.
    """
    delay = max(0.0, float(backoff_s))
    attempts = max(0, int(attempts))
    for attempt in range(attempts + 1):
        try:
            resilience.maybe_io_fault(what)
            return fn()
        except OSError as e:
            if attempt >= attempts:
                raise
            resilience.note_io_retry(what, e, attempt=attempt + 1)
            if delay:
                time.sleep(delay)
                delay *= 2


def fold_epoch_seed(shuffle_seed: int, epoch: int) -> int:
    """THE per-epoch seed fold shared by every shuffling surface (the
    streamed driver creates one single-epoch stream per training epoch and
    folds the epoch in here; the device cache draws the same permutation).
    One definition keeps shuffled bit-parity structural, not coincidental."""
    return shuffle_seed * 1_000_003 + epoch


def draw_permutation(seed: int, epoch: int, n: int) -> np.ndarray:
    """THE permutation draw behind ``shuffle = true`` — all consumers
    (fmb_batch_stream's slot order, device_cache's resident gather) must
    call this, never default_rng directly."""
    return np.random.default_rng((seed, epoch)).permutation(n)


def _shard_runs(
    counter: int, n: int, shard_index: int, shard_count: int, block: int
) -> Iterator[tuple[int, int]]:
    """Contiguous LOCAL [start, stop) row runs of this shard's selection.

    Selection rule is pipeline.line_stream's: global row g is ours iff
    ``(g // block) % shard_count == shard_index``; ``counter`` is the global
    index of local row 0.  Owned rows form length-``block`` runs every
    ``shard_count * block`` — yielding runs keeps every copy a memcpy.
    """
    if shard_count == 1:
        if n > 0:
            yield 0, n
        return
    period = shard_count * block
    lo, hi = counter, counter + n
    m = (lo - shard_index * block) // period  # floor; first run touching lo
    while True:
        start = m * period + shard_index * block
        if start >= hi:
            return
        s, e = max(start, lo), min(start + block, hi)
        if s < e:
            yield s - counter, e - counter
        m += 1


def fmb_batch_stream(
    files: Sequence[str],
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    epochs: int = 1,
    shard_index: int = 0,
    shard_count: int = 1,
    shard_block: int = 1,
    weights: Sequence[float] | None = None,
    drop_remainder: bool = False,
    pad_to_batches: int | None = None,
    shuffle_seed: int | None = None,
    skip_rows: int = 0,
    io_retries: int = 3,
    io_retry_backoff_s: float = 0.05,
) -> Iterator[tuple[ParsedBatch, np.ndarray]]:
    """Stream (ParsedBatch, example_weights) from FMB files.

    Same contract as ``pipeline.batch_stream`` (epoch repeats, per-file
    example weights, block-cyclic sharding by global row index, zero-padded
    short final batch with weight-0 rows, ``pad_to_batches`` for fixed
    multi-host step counts) — but every copy is a memmap slice, no parsing.
    Batches freely span file and epoch boundaries, exactly like the text
    streams, and the emitted batches are bit-identical to the text path
    over the same source data.

    ``shuffle_seed`` enables per-epoch global shuffling — a capability the
    memmap format makes cheap (random access) and text streaming cannot
    offer.  Semantics: each epoch e draws one permutation of ALL rows from
    ``(shuffle_seed, e)``, defining an output SLOT order; sharding selects
    slots (not source rows) by the same block-cyclic rule, so multi-host
    processes assemble the same global batches from disjoint slot ranges
    without communicating.  Same seed ⇒ same order everywhere, epochs
    differ, and every epoch visits every row exactly once.  Memory:
    O(8 bytes × total rows) per process for the permutation — fine into
    the hundreds of millions of rows; beyond that, pre-shuffle at convert
    time instead.

    ``skip_rows`` is the exact-position-resume seek: skip that many rows
    of THIS SHARD'S selection (in slot order when shuffled) before
    emitting the first batch — a memmap-cheap mid-epoch reopen, no
    parsing or copying of the skipped rows.  Must be a whole number of
    batches (resume cursors count batches); ``pad_to_batches``
    accounting starts at the skipped count, so a resumed multi-host
    stream emits exactly the REMAINING steps of the epoch.

    Reads go through retry-with-backoff (``io_retries`` transient-OSError
    retries per read op, backoff doubling from ``io_retry_backoff_s``):
    the copies are idempotent (each retry overwrites the same destination
    slice), so an absorbed retry can never lose or duplicate a batch.
    """
    if weights is not None and len(weights) != len(files):
        raise ValueError(f"weights has {len(weights)} entries for {len(files)} files")
    if shard_block > 1 and epochs != 1:
        raise ValueError(
            "shard_block > 1 requires epochs == 1 (batch-aligned sharding "
            "does not survive epoch boundaries); create one stream per epoch"
        )
    if skip_rows < 0 or skip_rows % batch_size:
        raise ValueError(
            f"skip_rows must be a non-negative whole number of batches "
            f"(batch_size {batch_size}), got {skip_rows}"
        )

    def _retry(fn, what):
        return _io_retry(
            fn, what=what, attempts=io_retries, backoff_s=io_retry_backoff_s
        )

    fs = [_retry(lambda p=p: open_fmb(p), f"fmb-open:{p}") for p in files]
    # Per-file retry labels, formatted ONCE: the copy loops below run per
    # batch segment on the hot streaming path.
    read_what = [f"fmb-read:{f.path}" for f in fs]
    for f in fs:
        if f.hashed != bool(hash_feature_id):
            raise ValueError(
                f"{f.path}: written with hash_feature_id={f.hashed}, "
                f"requested {bool(hash_feature_id)} — re-convert the file"
            )
        if f.hashed and f.vocabulary_size != vocabulary_size:
            raise ValueError(
                f"{f.path}: hashed into vocabulary_size={f.vocabulary_size}, "
                f"requested {vocabulary_size} — re-convert the file"
            )
        if not f.hashed and f.vocabulary_size > vocabulary_size:
            raise ValueError(
                f"{f.path}: ids validated against vocabulary_size="
                f"{f.vocabulary_size} > requested {vocabulary_size} — "
                "re-convert the file"
            )
    width = int(max_nnz) if max_nnz else max([f.width for f in fs] or [1])
    for f in fs:
        if f.width > width:
            # The stored width is the converter's (possibly generous)
            # padding choice, not the data's — only an actual ROW wider
            # than the request is an error (the condition the text path
            # surfaces mid-stream, here at open time).  Columns beyond
            # ``width`` in such a file are guaranteed padding zeros and
            # the copy loops below clamp them off.
            widest = f.max_row_nnz or (int(f.nnz.max()) if f.n_rows else 0)
            if widest > width:
                raise ValueError(
                    f"{f.path}: rows up to {widest} features > max_nnz={width}"
                )
    def alloc():
        return (
            np.zeros((batch_size,), np.float32),
            np.zeros((batch_size, width), np.int32),
            np.zeros((batch_size, width), np.float32),
            np.zeros((batch_size, width), np.int32),
            np.zeros((batch_size,), np.int32),
            np.zeros((batch_size,), np.float32),
        )

    labels, ids, vals, flds, nnz, w = alloc()
    filled = 0
    # Skipped batches COUNT as emitted: the pad_to_batches contract is
    # "this epoch has exactly N steps", and a resumed stream owes only
    # the remaining N - skipped of them.
    emitted = skip_rows // batch_size
    skip_left = skip_rows

    def cycle_buffers():
        """Emit the full batch and start fresh buffers — the one place the
        buffer lifecycle lives, shared by the sequential and shuffled
        loops (fresh zeroed buffers per yield is what makes column/tail
        padding and prefetch-queue safety hold)."""
        nonlocal labels, ids, vals, flds, nnz, w, filled, emitted
        out = ParsedBatch(labels, ids, vals, flds, nnz), w
        labels, ids, vals, flds, nnz, w = alloc()
        filled = 0
        emitted += 1
        return out

    if shuffle_seed is not None:
        bounds = np.cumsum([0] + [f.n_rows for f in fs])
        total = int(bounds[-1])
        fweights = np.asarray(
            [1.0] * len(fs) if weights is None else [float(x) for x in weights],
            np.float32,
        )
        slot_base = 0  # global slot counter across epochs (cyclic-rule parity)
        block = max(1, shard_block)
        for e in range(max(0, epochs)):
            # One permutation of ALL rows per epoch; slots are the output
            # order, and this shard owns slots by the block-cyclic rule —
            # every process derives the identical permutation from the seed.
            perm = draw_permutation(shuffle_seed, e, total)
            slots = np.arange(total, dtype=np.int64)
            mine = ((slot_base + slots) // block) % shard_count == shard_index
            rows = perm[mine]  # source row per owned slot, in slot order
            slot_base += total
            if skip_left:
                # Mid-epoch reopen: drop the already-consumed slot prefix
                # (the permutation is redrawn identically from the seed,
                # so slot K of a resumed epoch IS slot K of the original).
                adv = min(skip_left, len(rows))
                rows = rows[adv:]
                skip_left -= adv
            pos = 0
            while pos < len(rows):
                take = min(len(rows) - pos, batch_size - filled)
                chunk = rows[pos : pos + take]
                fidx = np.searchsorted(bounds, chunk, side="right") - 1
                local = chunk - bounds[fidx]
                for fi in np.unique(fidx):
                    m = fidx == fi
                    f = fs[fi]
                    li = local[m]
                    dst = np.flatnonzero(m) + filled
                    cw = min(f.width, width)  # clamp generous padding off

                    def copy(f=f, li=li, dst=dst, cw=cw, fi=fi):
                        labels[dst] = f.labels[li]
                        nnz[dst] = f.nnz[li]
                        ids[dst, :cw] = f.ids[li, :cw]
                        vals[dst, :cw] = f.vals[li, :cw]
                        flds[dst, :cw] = f.fields[li, :cw]
                        w[dst] = fweights[fi]

                    _retry(copy, read_what[fi])
                filled += take
                pos += take
                if filled == batch_size:
                    yield cycle_buffers()
                    if pad_to_batches is not None and emitted >= pad_to_batches:
                        return
        from fast_tffm_tpu.data.pipeline import emit_assembled_tail

        yield from emit_assembled_tail(
            alloc, (labels, ids, vals, flds, nnz, w), filled, emitted,
            drop_remainder, pad_to_batches,
        )
        return

    counter = 0  # global row index, running across files AND epochs
    for _ in range(max(0, epochs)):
        for fi, f in enumerate(fs):
            fw = 1.0 if weights is None else float(weights[fi])
            cw = min(f.width, width)  # clamp generous padding off
            for lo, hi in _shard_runs(counter, f.n_rows, shard_index, shard_count, shard_block):
                while lo < hi:
                    if skip_left:
                        # Mid-epoch reopen: advance past already-consumed
                        # rows of this shard's selection without copying.
                        adv = min(skip_left, hi - lo)
                        lo += adv
                        skip_left -= adv
                        continue
                    take = min(hi - lo, batch_size - filled)
                    sl = slice(lo, lo + take)
                    out = slice(filled, filled + take)

                    def copy(f=f, sl=sl, out=out, cw=cw, fw=fw):
                        labels[out] = f.labels[sl]
                        nnz[out] = f.nnz[sl]
                        ids[out, :cw] = f.ids[sl, :cw]
                        vals[out, :cw] = f.vals[sl, :cw]
                        flds[out, :cw] = f.fields[sl, :cw]
                        w[out] = fw

                    _retry(copy, read_what[fi])
                    filled += take
                    lo += take
                    if filled == batch_size:
                        yield cycle_buffers()
                        if pad_to_batches is not None and emitted >= pad_to_batches:
                            return
            counter += f.n_rows
    from fast_tffm_tpu.data.pipeline import emit_assembled_tail

    yield from emit_assembled_tail(
        alloc, (labels, ids, vals, flds, nnz, w), filled, emitted,
        drop_remainder, pad_to_batches,
    )


# Cache paths whose build failed in THIS process (ENOSPC, quota, …): later
# ensure_fmb_cache calls skip the peer wait and the rebuild attempt for
# them, keeping the per-epoch text fallback cheap.  Freshness is still
# checked first, so a cache that eventually appears is adopted.
_BUILD_FAILED: set[str] = set()


def _cache_location_writable(cache_path: str) -> bool:
    """Can a cache file be created at ``cache_path``?  Probe with a unique
    sibling temp file (the cache itself must never be touched non-atomically)."""
    probe = f"{cache_path}.{socket.gethostname()}.{os.getpid()}.{uuid.uuid4().hex[:8]}.probe"
    try:
        with open(probe, "wb"):
            pass
    except OSError:
        return False
    try:
        os.remove(probe)
    except OSError:
        pass
    return True


def ensure_fmb_cache(
    files: Sequence[str],
    *,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    parser=None,
    log=None,
    wait_for_peer: float = 0.0,
) -> tuple[str, ...]:
    """Map text files to fresh ``<file>.fmb`` caches, converting as needed.

    Files that already ARE FMB pass through untouched.  A cache is reused
    only when its header matches the source file's (size, mtime_ns) and the
    requested (vocabulary_size, hash) configuration — anything else triggers
    a rebuild, so a stale or mismatched cache can never silently feed
    training.  Concurrent builders race benignly (atomic replace).

    An unwritable cache location (read-only data mount) is NOT fatal: the
    source text path is returned for that file with a warning, and the
    stream falls back to parsing — binary_cache is an accelerator, not a
    correctness knob.

    ``wait_for_peer`` > 0 polls up to that many seconds for ANOTHER
    process to finish building a stale cache before building locally —
    on a multi-host pod with a shared filesystem, the lead process builds
    once and the other N−1 skip the duplicate parse (hosts with separate
    local disks simply hit the timeout and build their own copy).
    """
    import time
    import warnings

    def check_fresh(cache, st):
        try:
            if not is_fmb(cache):
                return False
            (n, width, vocab, hashed, _isz, src_size, src_mtime, widest,
             _flags, version) = _read_header(cache)
        except (ValueError, OSError):
            # OSError: the wait loop polls exactly while a peer's
            # os.replace lands — transient ESTALE/ENOENT on network
            # filesystems means "not fresh yet", never "crash".
            return False
        return (
            src_size == st.st_size
            and src_mtime == st.st_mtime_ns
            # A pre-wire-flags cache (v1) is data-valid but its flags byte
            # is meaningless, so the packed wire could never elide from
            # it — rebuild ONCE on first use after the upgrade (the source
            # text still exists on this path, unlike direct .fmb inputs,
            # which pass through above regardless of version).
            and version >= 2
            and hashed == bool(hash_feature_id)
            and (vocab == vocabulary_size if hashed else vocab <= vocabulary_size)
            # A generously-padded cache still serves a narrower max_nnz as
            # long as its ACTUAL widest row fits (the stream clamps the
            # padding columns); widest == 0 means a pre-field file, where
            # only the stored width is trustworthy.
            and (
                max_nnz is None
                or width <= max_nnz
                or (widest > 0 and widest <= max_nnz)
            )
        )

    out: list[str] = []
    # ONE wait budget for the whole file list: when no peer exists
    # (host-local disks), the first file burns the timeout and the rest
    # skip straight to building — not wait_for_peer × n_files of sleep.
    deadline = time.monotonic() + wait_for_peer if wait_for_peer > 0 else 0.0
    for path in files:
        path = os.fspath(path)
        if is_fmb(path):
            out.append(path)
            continue
        cache = path + ".fmb"
        st = os.stat(path)
        fresh = check_fresh(cache, st)
        if (
            not fresh
            and wait_for_peer > 0
            and cache not in _BUILD_FAILED
            and _cache_location_writable(cache)
        ):
            # Only wait when a peer's build is actually possible: on an
            # unwritable (read-only) mount no peer can ever produce the
            # cache, and the wait would stall every epoch's stream for the
            # full timeout before the text fallback.  (Writability here is
            # a proxy for the lead's — same shared mount, same perms.)
            while not fresh and time.monotonic() < deadline:
                time.sleep(min(1.0, wait_for_peer))
                fresh = check_fresh(cache, st)
        if not fresh:
            # One un-cacheable file means the WHOLE list stays text: a
            # stream cannot mix FMB and text (batch_stream rejects the
            # ambiguity), and correctness never depended on the cache.
            # If the list ALREADY mixes in .fmb files, there is no text
            # form to fall back to for those — a hard, pointed error.
            def fall_back_to_text(err):
                passthrough = [os.fspath(p) for p in files if is_fmb(p)]
                if passthrough:
                    # Mixed list, conversion failed (DESIGN §8.3): say
                    # exactly WHICH entries block the text fallback and
                    # what fixes each side — the bare "hard error" left
                    # the operator grepping the file list by hand.
                    listed = "\n".join(f"    {p}" for p in passthrough)
                    raise OSError(
                        f"binary_cache: cannot build the FMB cache for "
                        f"{path!r} ({err}), and the whole stream cannot fall "
                        "back to text because these input entries are "
                        "pre-built FMB with no text form:\n"
                        f"{listed}\n"
                        f"  fix one side: make {os.path.dirname(cache) or '.'!r} "
                        f"writable (or pre-convert {path!r} with the `convert` "
                        "verb), or make the input list all-text / all-FMB"
                    )
                warnings.warn(
                    f"binary_cache: cannot write {cache} ({err}); streaming "
                    "text for all input files instead",
                    RuntimeWarning,
                    stacklevel=2,
                )
                return tuple(os.fspath(p) for p in files)

            if cache in _BUILD_FAILED:
                # A build already failed in this process: don't re-pay the
                # full parse (epochs recreate this stream) just to fail the
                # same way again.
                return fall_back_to_text("previous build failed")
            if log is not None:
                log(f"building binary cache {cache}")
            try:
                write_fmb(
                    path,
                    cache,
                    vocabulary_size=vocabulary_size,
                    hash_feature_id=hash_feature_id,
                    max_nnz=max_nnz,
                    parser=parser,
                )
            except OSError as e:
                _BUILD_FAILED.add(cache)
                return fall_back_to_text(e)
        out.append(cache)
    return tuple(out)
