"""Device-resident dataset mode: load the epoch ONCE, slice batches on-chip.

The reference streams every batch from the host every epoch — it had to,
being CPU-only (`renyi533/fast_tffm` :: py/ input queues feeding the
session loop).  On a TPU the jitted train step sustains hundreds of
millions of examples/sec while the host→device link delivers a few million
(and on this dev box the tunnel swings ~100×, README "Benchmarks") — so
for any dataset whose packed arrays fit HBM **beside the table**, per-step
H2D transfer is pure overhead the framework can eliminate entirely.

``device_cache = true`` ([Train]) does that: the FMB-backed input is
assembled into flat row-major device arrays ``[batches·B, ...]`` ONE time,
and every train step slices its batch out with ``lax.dynamic_slice``
inside the SAME jitted program as the model step — zero host↔device bytes
per step, zero extra dispatches.  Epochs re-visit the resident arrays; a
per-epoch ``shuffle`` uploads one [rows] permutation (the identical
permutation the streamed path draws — bit-parity holds shuffled too) and
the step gathers its batch through it.

Bit-identity with the streamed path is BY CONSTRUCTION: the resident
arrays are assembled by ``fmb_batch_stream`` itself (same padding, width
clamping, per-file weights, header validation), and the step applies
``trainer.train_step_body`` — the same function the streamed step jits —
to the same values (test-pinned in tests/test_device_cache.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fast_tffm_tpu.models.base import Batch
from fast_tffm_tpu.trainer import TrainState, train_step_body

__all__ = [
    "DeviceDataset",
    "load_device_dataset",
    "epoch_permutation",
    "full_epoch_perm",
    "make_cached_train_step",
    "make_cached_scan_train_step",
    "make_cached_touched_marker",
    "epoch_index_chunks",
]


class DeviceDataset(NamedTuple):
    """Flat row-major device-resident arrays: leading dim [batches·B]
    (ONE copy serves both the sequential slice and the shuffled gather —
    a second batch-major copy would halve the max cacheable dataset)."""

    labels: Any  # f32 [batches·B]
    ids: Any  # i32 [batches·B, N]
    vals: Any  # f32 [batches·B, N]
    fields: Any  # i32 [batches·B, N] (or [batches·B, 0] when unused)
    weights: Any  # f32 [batches·B]  (0.0 on tail-padding rows)
    batches: int
    batch_size: int
    n_rows: int  # real (unpadded) rows

    @property
    def nbytes(self) -> int:
        return sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in (self.labels, self.ids, self.vals, self.fields, self.weights)
        )


def _load_host_arrays(
    files,
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    weights=None,
    with_fields: bool = True,
    shard_index: int = 0,
    shard_count: int = 1,
):
    """Flat host staging arrays via fmb_batch_stream (shared by the local
    and mesh-sharded loaders — the sharded one uploads straight from
    host to its mesh placement, never bouncing through one device).

    ``shard_count`` > 1 loads only this PROCESS's block-cyclic shard of
    every global batch (rows [p·B/P, (p+1)·B/P), the multi-host input
    scheme dist_train's streamed path uses): ``batch_size`` stays the
    GLOBAL batch, the staged arrays hold batch_size/shard_count rows per
    batch, and the emitted ``batches`` count is the global one (every
    process stages the same number of per-batch slices).
    """
    from fast_tffm_tpu.data.binary import fmb_batch_stream, open_fmb

    files = [str(f) for f in files]
    n_rows = sum(open_fmb(f).n_rows for f in files)
    if n_rows == 0:
        raise ValueError(f"device_cache: no rows in {files}")
    if batch_size % shard_count:
        raise ValueError(
            f"device_cache: global batch_size {batch_size} not divisible "
            f"by {shard_count} processes"
        )
    batches = -(-n_rows // batch_size)  # ceil; tail pads with weight-0 rows
    local_bs = batch_size // shard_count
    flat = batches * local_bs
    # Preallocate the flat host staging arrays (shapes are known upfront)
    # and fill per-batch slices — a list-then-concatenate would hold the
    # whole dataset on the host TWICE, OOMing exactly the near-HBM-sized
    # datasets this mode exists for.
    host = None
    lo = 0
    for parsed, w in fmb_batch_stream(
        files,
        batch_size=local_bs,
        vocabulary_size=vocabulary_size,
        hash_feature_id=hash_feature_id,
        max_nnz=max_nnz,
        epochs=1,
        weights=weights,
        shard_index=shard_index,
        shard_count=shard_count,
        shard_block=local_bs if shard_count > 1 else 1,
        pad_to_batches=batches if shard_count > 1 else None,
    ):
        if host is None:
            width = parsed.ids.shape[1]
            host = dict(
                labels=np.zeros(flat, np.float32),
                ids=np.zeros((flat, width), np.int32),
                vals=np.zeros((flat, width), np.float32),
                fields=np.zeros((flat, width if with_fields else 0), np.int32),
                weights=np.zeros(flat, np.float32),
            )
        hi = lo + parsed.labels.shape[0]
        host["labels"][lo:hi] = parsed.labels
        host["ids"][lo:hi] = parsed.ids
        host["vals"][lo:hi] = parsed.vals
        if with_fields:
            host["fields"][lo:hi] = parsed.fields
        host["weights"][lo:hi] = w
        lo = hi
    return host, batches, n_rows


def load_device_dataset(
    files,
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    weights=None,
    with_fields: bool = True,
    device=None,
) -> DeviceDataset:
    """Assemble FMB files into one device-resident DeviceDataset.

    Every row goes through ``fmb_batch_stream`` — the exact batches the
    streamed trainer would see (same order, padding, weights, header
    validation) — then the concatenated arrays transfer to the device
    once, COMMITTED to ``device`` (default: the first device) so nothing
    moves them implicitly later.
    """
    host, batches, n_rows = _load_host_arrays(
        files,
        batch_size=batch_size,
        vocabulary_size=vocabulary_size,
        hash_feature_id=hash_feature_id,
        max_nnz=max_nnz,
        weights=weights,
        with_fields=with_fields,
    )
    put = partial(jax.device_put, device=device or jax.devices()[0])
    return DeviceDataset(
        labels=put(host["labels"]),
        ids=put(host["ids"]),
        vals=put(host["vals"]),
        fields=put(host["fields"]),
        weights=put(host["weights"]),
        batches=batches,
        batch_size=batch_size,
        n_rows=n_rows,
    )


def epoch_permutation(shuffle_seed: int, epoch: int, n_rows: int) -> np.ndarray:
    """THE permutation the streamed path draws for this epoch: the driver
    folds the epoch into the seed (fold_epoch_seed) and the per-epoch
    stream draws its epoch-0 permutation — both through binary.py's shared
    helpers, so device-cached shuffling is STRUCTURALLY bit-identical to
    streamed shuffling (one definition, not three synchronized copies)."""
    from fast_tffm_tpu.data.binary import draw_permutation, fold_epoch_seed

    return draw_permutation(fold_epoch_seed(shuffle_seed, epoch), 0, n_rows)


def full_epoch_perm(data: DeviceDataset, shuffle_seed: int, epoch: int) -> np.ndarray:
    """Flat-row index order for one shuffled epoch: the streamed-path
    permutation over the real rows, then the tail-padding rows in place
    (they sit at flat positions [n_rows, batches·B) and always land in the
    final batch, exactly like the streamed tail)."""
    flat_rows = data.batches * data.batch_size
    return np.concatenate(
        [
            epoch_permutation(shuffle_seed, epoch, data.n_rows),
            np.arange(data.n_rows, flat_rows, dtype=np.int64),
        ]
    ).astype(np.int32)


def make_cached_train_step(model, learning_rate: float, data: DeviceDataset, body=None):
    """Returns jitted ``step(state, i) -> (state, data_loss)`` over the
    resident arrays — and ``step_shuffled(state, perm, i)`` whose batch
    rows come through a device-resident [rows] permutation.

    ``i`` is a traced scalar (one executable serves every step; a Python
    int would retrace per step).  The resident arrays are EXPLICIT jit
    arguments, never closure captures: a closure-captured jax.Array
    becomes an embedded constant, and this backend re-materializes
    embedded constants per call — measured 217 ms/step vs 32 µs with the
    same arrays passed as arguments (an 8000× cliff; see DESIGN §6).
    One dispatch per step; XLA fuses the batch slice into the model
    program, so the slice costs O(B·N) HBM reads, not a transfer.
    """
    B = data.batch_size
    arrays = (data.labels, data.ids, data.vals, data.fields, data.weights)
    body = body or train_step_body  # packed layout passes its own body

    @partial(jax.jit, donate_argnums=(0,))
    def _step(state: TrainState, arrs, i):
        sl = lambda a: lax.dynamic_slice_in_dim(a, i * B, B, axis=0)
        b = Batch(*map(sl, arrs))
        return body(model, learning_rate, state, b)

    @partial(jax.jit, donate_argnums=(0,))
    def _step_shuffled(state: TrainState, arrs, perm, i):
        idx = lax.dynamic_slice_in_dim(perm, i * B, B)
        b = Batch(*(jnp.take(a, idx, axis=0) for a in arrs))
        return body(model, learning_rate, state, b)

    def step(state, i):
        return _step(state, arrays, i)

    def step_shuffled(state, perm, i):
        return _step_shuffled(state, arrays, perm, i)

    # Measured-cost hooks (profiling.CostLedger): the closures stay
    # profileable by delegating .lower to the inner jit with the resident
    # arrays bound — lowering only, never a second backend compile.
    # analysis: ok recompile-hazard delegated CostLedger .lower hook, not a second compile
    step.lower = lambda st, i: _step.lower(st, arrays, i)
    # analysis: ok recompile-hazard delegated CostLedger .lower hook, not a second compile
    step_shuffled.lower = lambda st, perm, i: _step_shuffled.lower(
        st, arrays, perm, i
    )

    return step, step_shuffled


def make_cached_touched_marker(data: DeviceDataset):
    """Touched-row bitmap markers for the delta-checkpoint subsystem on
    the device-cache path, where the driver's per-step "batch" is a
    resident batch index (scalar) or a [K] scan chunk — the ids live on
    device, so the mark slices them there (``(mark, mark_shuffled)``;
    the shuffled variant routes through the epoch permutation exactly as
    the shuffled step gathers its rows).  Resident arrays are EXPLICIT
    jit arguments, never closure captures (the embedded-constant cliff,
    DESIGN §6)."""
    B = data.batch_size

    def _rows(i):
        starts = i.reshape(-1).astype(jnp.int32)
        return (
            starts[:, None] * B + jnp.arange(B, dtype=jnp.int32)[None, :]
        ).reshape(-1)

    @partial(jax.jit, donate_argnums=(0,))
    def _mark(bitmap, ids_arr, i):
        return bitmap.at[ids_arr[_rows(i)].reshape(-1)].set(True, mode="drop")

    @partial(jax.jit, donate_argnums=(0,))
    def _mark_shuffled(bitmap, ids_arr, perm, i):
        return bitmap.at[ids_arr[perm[_rows(i)]].reshape(-1)].set(
            True, mode="drop"
        )

    def mark(bitmap, i):
        return _mark(bitmap, data.ids, i)

    def mark_shuffled(bitmap, perm, i):
        return _mark_shuffled(bitmap, data.ids, perm, i)

    return mark, mark_shuffled


def make_cached_ids_slicer(data: DeviceDataset):
    """``ids_fn(batch_index) -> ids`` for the datastats collector on the
    device-cache path, where the driver's per-step "batch" is a resident
    batch index (scalar) or a [K] scan chunk: the sampled window's ids
    are sliced ON DEVICE from the resident array — no host round-trip.
    Same explicit-argument jit discipline as the touched marker above."""
    B = data.batch_size

    @jax.jit
    def _slice(ids_arr, i):
        starts = i.reshape(-1).astype(jnp.int32)
        rows = (
            starts[:, None] * B + jnp.arange(B, dtype=jnp.int32)[None, :]
        ).reshape(-1)
        return ids_arr[rows]

    def ids_at(b):
        return _slice(data.ids, jnp.asarray(b))

    return ids_at


def epoch_index_chunks(batches: int, k: int, start: int = 0):
    """Pre-placed device index vectors for one scan-fused epoch: [K]-long
    chunks of the batch indices, plus one [batches % K] remainder — the
    per-call "input" of the scanned cached step.  Placed on device ONCE
    (the same vectors serve every epoch), so an epoch is ``ceil(batches/K)``
    dispatches with zero host involvement in between.  At most two distinct
    lengths exist (K and the remainder), so the scanned step compiles at
    most twice.

    ``start`` > 0 is the exact-position-resume seek: chunks stay aligned
    to the SAME K-grid an uninterrupted epoch uses (so every full chunk
    re-hits the already-compiled shapes) and the first chunk is clipped
    to begin at ``start`` — at most one extra compiled length when a
    resume lands mid-chunk (save boundaries are K-aligned, so normally
    none)."""
    lo0 = (max(0, start) // k) * k
    out = []
    for lo in range(lo0, batches, k):
        a, b = max(lo, start), min(lo + k, batches)
        if a < b:
            out.append(jax.device_put(np.arange(a, b, dtype=np.int32)))
    return out


def make_cached_scan_train_step(model, learning_rate: float, data: DeviceDataset, body=None):
    """Scan-fused twins of ``make_cached_train_step``'s steps: jitted
    ``step(state, idxs [K]) -> (state, losses [K])`` running K consecutive
    batch slices through ONE dispatch via ``lax.scan`` (and
    ``step_shuffled(state, perm, idxs)`` gathering through the epoch
    permutation).  The scan body applies the SAME ``body`` to the SAME
    slices the per-step functions would, so K>1 is bit-identical to K
    sequential calls (test-pinned).  K is read from ``idxs``' shape —
    epoch_index_chunks' remainder vector reuses this function and compiles
    its own (single) executable.  Resident arrays stay EXPLICIT jit
    arguments (the embedded-constant cliff, DESIGN §6); the donated state
    threads through the scan carry, so the table still updates in place.
    """
    B = data.batch_size
    arrays = (data.labels, data.ids, data.vals, data.fields, data.weights)
    body = body or train_step_body

    @partial(jax.jit, donate_argnums=(0,))
    def _scan_step(state: TrainState, arrs, idxs):
        def one(st, i):
            sl = lambda a: lax.dynamic_slice_in_dim(a, i * B, B, axis=0)
            return body(model, learning_rate, st, Batch(*map(sl, arrs)))

        return lax.scan(one, state, idxs)

    @partial(jax.jit, donate_argnums=(0,))
    def _scan_step_shuffled(state: TrainState, arrs, perm, idxs):
        def one(st, i):
            idx = lax.dynamic_slice_in_dim(perm, i * B, B)
            b = Batch(*(jnp.take(a, idx, axis=0) for a in arrs))
            return body(model, learning_rate, st, b)

        return lax.scan(one, state, idxs)

    def step(state, idxs):
        return _scan_step(state, arrays, idxs)

    def step_shuffled(state, perm, idxs):
        return _scan_step_shuffled(state, arrays, perm, idxs)

    # Same measured-cost .lower delegation as make_cached_train_step's.
    # analysis: ok recompile-hazard delegated CostLedger .lower hook, not a second compile
    step.lower = lambda st, idxs: _scan_step.lower(st, arrays, idxs)
    # analysis: ok recompile-hazard delegated CostLedger .lower hook, not a second compile
    step_shuffled.lower = lambda st, perm, idxs: _scan_step_shuffled.lower(
        st, arrays, perm, idxs
    )

    return step, step_shuffled


def load_sharded_device_dataset(
    files,
    *,
    mesh,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    weights=None,
    with_fields: bool = True,
) -> DeviceDataset:
    """Device-resident dataset SHARDED over a ('data','row') mesh.

    Layout is batch-major ``[batches, B, ...]`` with the BATCH dim sharded
    over both mesh axes (P(None, ('data','row'))): every step's
    ``dynamic_slice`` runs on the unsharded batches axis — trivially
    SPMD-partitionable — and each chip holds exactly its micro-batch slice
    of every batch, so per-chip HBM cost is total/n_devices.

    MULTI-HOST meshes work the same way the streamed input path does:
    each process stages only ITS rows of every global batch (block-cyclic
    shard, the make_global_batch scheme) and contributes exactly its
    addressable devices' slice via
    ``jax.make_array_from_process_local_data`` — no process ever holds
    (or transfers) another host's shard.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from fast_tffm_tpu.parallel.mesh import DATA_AXIS, ROW_AXIS

    nproc = jax.process_count()
    host, batches, n_rows = _load_host_arrays(
        files,
        batch_size=batch_size,
        vocabulary_size=vocabulary_size,
        hash_feature_id=hash_feature_id,
        max_nnz=max_nnz,
        weights=weights,
        with_fields=with_fields,
        shard_index=jax.process_index() if nproc > 1 else 0,
        shard_count=nproc,
    )

    def shard(a):
        # Upload straight from the host staging array to the mesh
        # placement: each chip receives only its shard, so a dataset
        # sized for AGGREGATE mesh HBM never has to fit one device (and
        # multi-host, never has to fit one HOST either).
        local_rows = a.shape[0] // batches
        bm = np.ascontiguousarray(
            a.reshape((batches, local_rows) + a.shape[1:])
        )
        spec = P(None, (DATA_AXIS, ROW_AXIS), *([None] * (bm.ndim - 2)))
        sharding = NamedSharding(mesh, spec)
        if nproc > 1:
            return jax.make_array_from_process_local_data(sharding, bm)
        return jax.device_put(bm, sharding)

    return DeviceDataset(
        labels=shard(host["labels"]),
        ids=shard(host["ids"]),
        vals=shard(host["vals"]),
        fields=shard(host["fields"]),
        weights=shard(host["weights"]),
        batches=batches,
        batch_size=batch_size,
        n_rows=n_rows,
    )


def make_cached_sharded_train_step(
    sharded_step, data: DeviceDataset, steps_per_call: int = 1,
    overflow_flagged: bool | None = None,
):
    """Wrap a ``make_sharded_train_step`` step so each call slices batch
    ``i`` out of the mesh-sharded resident arrays on-device (sequential
    order only — a shuffled gather across the sharded batch dim would be
    per-step cross-chip traffic, exactly what this mode exists to avoid).

    Same closure rule as the local cached step: resident arrays travel as
    explicit jit arguments (embedded-constant cliff, DESIGN §6).

    ``steps_per_call`` > 1 returns the scan-fused form instead:
    ``step(state, idxs [K]) -> (state, losses [K])`` runs K consecutive
    resident batches through ONE dispatch, the SPMD body scanning on
    device (epoch_index_chunks supplies the pre-placed index vectors,
    remainder included).  An overflow-flagged sharded step (the alltoall
    ``fallback`` 3-tuple) scans transparently: per-step losses stay [K]
    and the per-step overflow flags SUM into one replicated int32 (the
    driver only ever counts them, so K-granularity is not lost — the
    count is exact).  ``overflow_flagged`` tells the scan whether the
    wrapped step returns that 3-tuple; callers that built the step from
    config (dist_train) pass it explicitly, and the default reads the
    marker make_sharded_train_step sets on its return value.
    """
    from fast_tffm_tpu.models.base import Batch as _Batch

    arrays = (data.labels, data.ids, data.vals, data.fields, data.weights)

    if steps_per_call <= 1:

        @partial(jax.jit, donate_argnums=(0,))
        def _step(state, arrs, i):
            sl = lambda a: lax.dynamic_slice_in_dim(a, i, 1, axis=0)[0]
            return sharded_step(state, _Batch(*map(sl, arrs)))

        def step(state, i):
            return _step(state, arrays, i)

        return step

    # The scan must mirror the wrapped step's signature exactly.
    flagged = (
        bool(getattr(sharded_step, "overflow_flagged", False))
        if overflow_flagged is None
        else bool(overflow_flagged)
    )

    @partial(jax.jit, donate_argnums=(0,))
    def _scan_step(state, arrs, idxs):
        def one(st, i):
            sl = lambda a: lax.dynamic_slice_in_dim(a, i, 1, axis=0)[0]
            out = sharded_step(st, _Batch(*map(sl, arrs)))
            if flagged:
                st, loss, ovf = out
            else:
                st, loss = out
                ovf = jnp.zeros((), jnp.int32)
            return st, (loss, ovf)

        state, (losses, ovfs) = lax.scan(one, state, idxs)
        return state, losses, jnp.sum(ovfs)

    def step_k(state, idxs):
        state, losses, ovf_sum = _scan_step(state, arrays, idxs)
        if flagged:
            return state, losses, ovf_sum
        return state, losses

    return step_k
