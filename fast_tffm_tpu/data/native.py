"""ctypes bindings for the native C++ libsvm parser.

Loads ``_libsvm_parser.so`` (built by csrc/Makefile) and exposes the same
``parse_lines`` contract as the pure-Python reference implementation in
data/libsvm.py.  Mirrors the reference's py/fm_ops.py, which
``tf.load_op_library``'d the compiled fm_ops.so — here the binding is plain
ctypes because the op consumes host NumPy buffers, not graph tensors.

If the shared library is absent (not built), ``load_native_parser`` returns
None and callers fall back to the Python parser.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess

import numpy as np

from fast_tffm_tpu.data.libsvm import ParsedBatch

_SO_PATH = os.path.join(os.path.dirname(__file__), "_libsvm_parser.so")


def _find_csrc_dir() -> str | None:
    """csrc/ from a repo checkout / sdist build tree, or the copy setup.py
    places inside the package for wheel installs."""
    here = os.path.dirname(__file__)
    for cand in (
        os.path.join(here, os.pardir, os.pardir, "csrc"),
        os.path.join(here, os.pardir, "csrc"),
    ):
        if os.path.isfile(os.path.join(cand, "Makefile")):
            return cand
    return None


_CSRC_DIR = _find_csrc_dir()
_BUILD_ATTEMPTED = False


def _try_build() -> None:
    """Build the .so from csrc/ once per process if a toolchain is present.

    The reference shipped its kernels as a compile-it-yourself Makefile; here
    the build is a sub-second g++ invocation, so running it lazily on first
    use keeps the fast path on by default without a packaging step.  Any
    failure (no make/g++, read-only tree, concurrent writer) just leaves the
    pure-Python parser in place.
    """
    global _BUILD_ATTEMPTED
    if _BUILD_ATTEMPTED:
        return
    _BUILD_ATTEMPTED = True
    if _CSRC_DIR is None or not shutil.which("make"):
        return
    # Build to a process-unique name, then atomically rename into place:
    # concurrent processes (multi-host pods share the filesystem) must never
    # dlopen a half-written ELF.
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["make", "-C", _CSRC_DIR, f"OUT={tmp}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO_PATH)
    except (subprocess.SubprocessError, OSError):
        pass
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass

_ERRORS = {
    1: "empty line",
    2: "bad label",
    3: "bad token",
    4: "feature id out of range",
    5: "row wider than max_nnz",
    6: "read error (I/O failure mid-file, not clean EOF)",
}


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.fm_fnv1a64.restype = ctypes.c_uint64
    lib.fm_fnv1a64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.fm_parse_shape.restype = None
    lib.fm_parse_shape.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.fm_parse_mt.restype = ctypes.c_int32
    lib.fm_parse_mt.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,  # n
        ctypes.c_int64,  # width
        ctypes.c_int64,  # vocabulary_size
        ctypes.c_int32,  # hash_feature_id
        ctypes.c_int32,  # threads
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # labels
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # ids
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # vals
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # fields
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # nnz
        ctypes.POINTER(ctypes.c_int64),  # error_line
    ]
    lib.fm_reader_open.restype = ctypes.c_void_p
    lib.fm_reader_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,  # shard_index
        ctypes.c_int64,  # shard_count
        ctypes.c_int64,  # counter_start
    ]
    lib.fm_reader_open2.restype = ctypes.c_void_p
    lib.fm_reader_open2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,  # shard_index
        ctypes.c_int64,  # shard_count
        ctypes.c_int64,  # shard_block
        ctypes.c_int64,  # counter_start
    ]
    lib.fm_count_lines.restype = ctypes.c_int64
    lib.fm_count_lines.argtypes = [ctypes.c_char_p]
    lib.fm_scan_file.restype = ctypes.c_int32
    lib.fm_scan_file.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),  # n_lines
        ctypes.POINTER(ctypes.c_int64),  # widest
    ]
    lib.fm_reader_counter.restype = ctypes.c_int64
    lib.fm_reader_counter.argtypes = [ctypes.c_void_p]
    lib.fm_reader_close.restype = None
    lib.fm_reader_close.argtypes = [ctypes.c_void_p]
    lib.fm_reader_next.restype = ctypes.c_int64
    lib.fm_reader_next.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,  # want
        ctypes.c_int64,  # width
        ctypes.c_int64,  # vocabulary_size
        ctypes.c_int32,  # hash_feature_id
        ctypes.c_int32,  # threads
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # labels
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # ids
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # vals
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # fields
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # nnz
        ctypes.POINTER(ctypes.c_int32),  # error_code
        ctypes.POINTER(ctypes.c_int64),  # error_line
    ]
    lib.fm_reader_next32.restype = ctypes.c_int64
    lib.fm_reader_next32.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,  # want
        ctypes.c_int64,  # width
        ctypes.c_int64,  # vocabulary_size
        ctypes.c_int32,  # hash_feature_id
        ctypes.c_int32,  # threads
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # labels
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # ids (int32!)
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # vals
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # fields
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # nnz
        ctypes.POINTER(ctypes.c_int32),  # error_code
        ctypes.POINTER(ctypes.c_int64),  # error_line
    ]
    try:
        # Wire-v2 constant detection: absent from .so's built before the
        # symbol existed — optional, so a prebuilt library on a box with
        # no toolchain keeps parsing (callers fall back to numpy).
        lib.fm_vals_all_ones.restype = ctypes.c_int32
        lib.fm_vals_all_ones.argtypes = [
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # vals
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # nnz
            ctypes.c_int64,  # n
            ctypes.c_int64,  # width
        ]
    except AttributeError:
        pass
    return lib


def usable_cores() -> int:
    """Cores THIS process may run on — cgroup/affinity-aware where the OS
    exposes it (a containerized pod worker pinned to 8 of 64 cores must
    size its pool at 8, not 64)."""
    try:
        return len(os.sched_getaffinity(0)) or 1
    except AttributeError:  # non-Linux
        return os.cpu_count() or 1


class NativeParser:
    """Callable with the signature of ``libsvm.parse_lines``.

    ``threads`` spreads the parse over an in-kernel std::thread pool — the
    analog of the reference trainer's cfg-driven parse-thread count, but
    inside one GIL-released ctypes call instead of TF queue-runner threads.
    ``threads=0`` (the default) uses every USABLE core: a pod host feeding
    4-8 chips needs the full parse bandwidth, and the pool only spins up
    when a batch is large enough to pay for it (parse_spans_mt in
    csrc/libsvm_parser.cpp).
    """

    def __init__(self, lib: ctypes.CDLL, threads: int = 0):
        self._lib = lib
        if threads < 0:
            # Mirror config.validate: a negative count is a bug upstream,
            # not a request for every core.
            raise ValueError(f"threads must be >= 0 (0 = all cores), got {threads}")
        self.threads = int(threads) if threads > 0 else usable_cores()

    def fnv1a64(self, token: bytes) -> int:
        return int(self._lib.fm_fnv1a64(token, len(token)))

    def vals_all_ones(self, vals, nnz) -> bool:
        """In-kernel twin of data/wire.py's ``vals_all_ones`` (the wire-v2
        convert-time constant detection); numpy fallback when the loaded
        .so predates the symbol."""
        vals = np.ascontiguousarray(vals, np.float32)
        nnz = np.ascontiguousarray(nnz, np.int32)
        if not hasattr(self._lib, "fm_vals_all_ones"):
            from fast_tffm_tpu.data.wire import vals_all_ones

            return vals_all_ones(vals, nnz)
        n, width = vals.shape
        return bool(self._lib.fm_vals_all_ones(vals, nnz, n, width))

    def __call__(
        self,
        lines: list[str],
        *,
        vocabulary_size: int,
        hash_feature_id_flag: bool = False,
        max_nnz: int | None = None,
    ) -> ParsedBatch:
        buf = ("\n".join(lines)).encode("utf-8")
        n = len(lines)
        if max_nnz is not None:
            width = max_nnz
        else:
            n_lines = ctypes.c_int64()
            widest = ctypes.c_int64()
            self._lib.fm_parse_shape(buf, ctypes.byref(n_lines), ctypes.byref(widest))
            width = max(int(widest.value), 1)
        labels = np.zeros((n,), np.float32)
        ids = np.zeros((n, width), np.int64)
        vals = np.zeros((n, width), np.float32)
        fields = np.zeros((n, width), np.int32)
        nnz = np.zeros((n,), np.int32)
        err_line = ctypes.c_int64(-1)
        code = self._lib.fm_parse_mt(
            buf,
            n,
            width,
            vocabulary_size,
            1 if hash_feature_id_flag else 0,
            self.threads,
            labels,
            ids,
            vals,
            fields,
            nnz,
            ctypes.byref(err_line),
        )
        if code != 0:
            raise ValueError(
                f"{_ERRORS.get(code, f'error {code}')} at line {err_line.value}"
            )
        return ParsedBatch(labels=labels, ids=ids, vals=vals, fields=fields, nnz=nnz)


def native_batch_stream(
    parser: "NativeParser",
    files,
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int,
    epochs: int = 1,
    shard_index: int = 0,
    shard_count: int = 1,
    shard_block: int = 1,
    weights=None,
    drop_remainder: bool = False,
    pad_to_batches: int | None = None,
):
    """Stream (ParsedBatch, example_weights) batches entirely through C++.

    Same contract as ``pipeline.batch_stream`` (epoch repeats, per-file
    example weights, block-cyclic line sharding by global non-blank line
    index, zero-padded short final batch with weight-0 rows, optional
    pad_to_batches for fixed multi-host step counts), but the file reading,
    line splitting, sharding, and parsing all happen inside
    ``fm_reader_next`` — the Python side only schedules files and yields
    filled NumPy buffers.  Batches freely span file and epoch boundaries,
    exactly like the Python generator chain.
    """
    if weights is not None and len(weights) != len(files):
        raise ValueError(f"weights has {len(weights)} entries for {len(files)} files")
    if shard_block > 1 and epochs != 1:
        raise ValueError(
            "shard_block > 1 requires epochs == 1 (batch-aligned sharding "
            "does not survive epoch boundaries); create one stream per epoch"
        )
    lib = parser._lib
    width = int(max_nnz)
    # int32 ids whenever the vocabulary fits (always, for the device batch:
    # TPU gathers index with int32) — halves the largest buffer/transfer
    # and skips the astype copy in Batch.from_parsed.
    ids_dtype = np.int32 if vocabulary_size <= np.iinfo(np.int32).max else np.int64
    reader_next = lib.fm_reader_next32 if ids_dtype is np.int32 else lib.fm_reader_next

    def alloc():
        return (
            np.zeros((batch_size,), np.float32),
            np.zeros((batch_size, width), ids_dtype),
            np.zeros((batch_size, width), np.float32),
            np.zeros((batch_size, width), np.int32),
            np.zeros((batch_size,), np.int32),
            np.zeros((batch_size,), np.float32),
        )

    labels, ids, vals, fields, nnz, w = alloc()
    filled = 0
    emitted = 0
    counter = 0  # global non-blank line index, threaded through every file
    for _ in range(max(0, epochs)):
        for fi, path in enumerate(files):
            fw = 1.0 if weights is None else float(weights[fi])
            handle = lib.fm_reader_open2(
                os.fspath(path).encode(),
                shard_index,
                shard_count,
                max(1, shard_block),
                counter,
            )
            if not handle:
                raise FileNotFoundError(path)
            try:
                while True:
                    want = batch_size - filled
                    ec = ctypes.c_int32(0)
                    el = ctypes.c_int64(-1)
                    got = reader_next(
                        handle,
                        want,
                        width,
                        vocabulary_size,
                        1 if hash_feature_id else 0,
                        parser.threads,
                        labels[filled:],
                        ids[filled:],
                        vals[filled:],
                        fields[filled:],
                        nnz[filled:],
                        ctypes.byref(ec),
                        ctypes.byref(el),
                    )
                    if got < 0:
                        # el is relative to THIS fm_reader_next call, which
                        # writes at offset `filled`; report the batch row.
                        where = (
                            f" (batch row {filled + el.value})" if el.value >= 0 else ""
                        )
                        raise ValueError(
                            f"{_ERRORS.get(ec.value, f'error {ec.value}')} in {path}{where}"
                        )
                    w[filled : filled + got] = fw
                    filled += int(got)
                    if filled == batch_size:
                        yield ParsedBatch(labels, ids, vals, fields, nnz), w
                        emitted += 1
                        labels, ids, vals, fields, nnz, w = alloc()
                        filled = 0
                        if pad_to_batches is not None and emitted >= pad_to_batches:
                            return
                        continue
                    break  # got < want: file exhausted
            finally:
                counter = int(lib.fm_reader_counter(handle))
                lib.fm_reader_close(handle)
    from fast_tffm_tpu.data.pipeline import emit_assembled_tail

    yield from emit_assembled_tail(
        alloc, (labels, ids, vals, fields, nnz, w), filled, emitted,
        drop_remainder, pad_to_batches,
    )


# (path, mtime_ns, size) -> (n_lines, widest).  Startup calls scan_files /
# count_lines on overlapping file sets (static width scan, then multi-host
# steps-per-epoch on train and again on validation files); caching per file
# keeps that one streaming pass each.  Entries invalidate when the file
# changes; the table stays tiny (one tuple per data file).
_scan_cache: dict[tuple[str, int, int], tuple[int, int]] = {}


def _scan_one(path) -> tuple[int, int]:
    path = os.fspath(path)
    st = os.stat(path)
    key = (path, st.st_mtime_ns, st.st_size)
    hit = _scan_cache.get(key)
    if hit is not None:
        return hit
    from fast_tffm_tpu.data.binary import _read_header, is_fmb

    if is_fmb(path):
        # Header-only read (64 bytes) — no reason to memmap the data
        # sections here.  Prefer the recorded widest ACTUAL row over the
        # stored width (the converter's possibly-generous --max-nnz
        # padding choice), so an auto-derived training max_nnz doesn't
        # inherit padding; 0 means a pre-field file, where only the
        # stored width is trustworthy.
        n_rows, width, _v, _h, _i, _s, _m, widest, _f, _ver = _read_header(path)
        out = (n_rows, widest if widest > 0 else width)
        _scan_cache[key] = out
        return out
    native = load_native_parser()
    if native is not None:
        n = ctypes.c_int64()
        w = ctypes.c_int64()
        if native._lib.fm_scan_file(path.encode(), ctypes.byref(n), ctypes.byref(w)):
            raise OSError(f"cannot read {path}")
        out = (n.value, w.value)
    else:
        total, widest = 0, 0
        with open(path, "r") as f:
            for line in f:
                toks = len(line.split())
                if toks > 0:
                    total += 1
                    widest = max(widest, toks - 1)
        out = (total, widest)
    _scan_cache[key] = out
    return out


def scan_files(files) -> tuple[int, int]:
    """(total non-blank lines, widest row nnz) across ``files`` in ONE
    streaming pass per file (C++ when the native library is built, buffered
    Python otherwise; per-file results cached by (path, mtime, size)).
    Serves both the multi-host steps-per-epoch count and the static batch
    width (``max_nnz = 0`` config scan)."""
    total, widest = 0, 0
    for path in files:
        n, w = _scan_one(path)
        total += n
        widest = max(widest, w)
    return total, widest


def count_lines(files) -> int:
    """Total non-blank lines across ``files``.

    Uses cached scan_files results when present; a cold count-only call
    takes the cheaper fm_count_lines path (per-line is_blank check instead
    of tokenizing every byte)."""
    native = load_native_parser()
    total = 0
    for path in files:
        path = os.fspath(path)
        st = os.stat(path)
        hit = _scan_cache.get((path, st.st_mtime_ns, st.st_size))
        if hit is not None:
            total += hit[0]
            continue
        from fast_tffm_tpu.data.binary import is_fmb

        if is_fmb(path):
            total += _scan_one(path)[0]
        elif native is not None:
            n = int(native._lib.fm_count_lines(path.encode()))
            if n < 0:
                raise OSError(f"cannot read {path}")
            total += n
        else:
            with open(path, "r") as f:
                total += sum(1 for line in f if line.strip())
    return total


def _stale() -> bool:
    """True when the .so is missing or older than any csrc/ source file."""
    if not os.path.exists(_SO_PATH):
        return True
    if _CSRC_DIR is None:
        return False
    so_mtime = os.path.getmtime(_SO_PATH)
    try:
        entries = os.listdir(_CSRC_DIR)
    except OSError:
        return False
    return any(
        e.endswith((".cpp", ".h")) and os.path.getmtime(os.path.join(_CSRC_DIR, e)) > so_mtime
        for e in entries
    )


def load_native_parser(threads: int = 0) -> NativeParser | None:
    """Load the C++ parser, (re)building it on first use; None → Python fallback."""
    if _stale():
        _try_build()
    if not os.path.exists(_SO_PATH):
        return None
    try:
        lib = _bind(ctypes.CDLL(_SO_PATH))
    except (OSError, AttributeError):
        # AttributeError: a stale pre-fm_parse_mt .so — rebuild next process.
        return None
    return NativeParser(lib, threads)


def best_parser(threads: int = 0):
    """The fastest available parser honoring the parse_lines contract."""
    native = load_native_parser(threads)
    if native is not None:
        return native
    from fast_tffm_tpu.data.libsvm import parse_lines

    return parse_lines
