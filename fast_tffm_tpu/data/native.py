"""ctypes bindings for the native C++ libsvm parser.

Loads ``_libsvm_parser.so`` (built by csrc/Makefile) and exposes the same
``parse_lines`` contract as the pure-Python reference implementation in
data/libsvm.py.  Mirrors the reference's py/fm_ops.py, which
``tf.load_op_library``'d the compiled fm_ops.so — here the binding is plain
ctypes because the op consumes host NumPy buffers, not graph tensors.

If the shared library is absent (not built), ``load_native_parser`` returns
None and callers fall back to the Python parser.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess

import numpy as np

from fast_tffm_tpu.data.libsvm import ParsedBatch

_SO_PATH = os.path.join(os.path.dirname(__file__), "_libsvm_parser.so")
_CSRC_DIR = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir, "csrc")
_BUILD_ATTEMPTED = False


def _try_build() -> None:
    """Build the .so from csrc/ once per process if a toolchain is present.

    The reference shipped its kernels as a compile-it-yourself Makefile; here
    the build is a sub-second g++ invocation, so running it lazily on first
    use keeps the fast path on by default without a packaging step.  Any
    failure (no make/g++, read-only tree, concurrent writer) just leaves the
    pure-Python parser in place.
    """
    global _BUILD_ATTEMPTED
    if _BUILD_ATTEMPTED:
        return
    _BUILD_ATTEMPTED = True
    if not os.path.isdir(_CSRC_DIR) or not shutil.which("make"):
        return
    # Build to a process-unique name, then atomically rename into place:
    # concurrent processes (multi-host pods share the filesystem) must never
    # dlopen a half-written ELF.
    tmp = f"{_SO_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["make", "-C", _CSRC_DIR, f"OUT={tmp}"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _SO_PATH)
    except (subprocess.SubprocessError, OSError):
        pass
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass

_ERRORS = {
    1: "empty line",
    2: "bad label",
    3: "bad token",
    4: "feature id out of range",
    5: "row wider than max_nnz",
}


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.fm_fnv1a64.restype = ctypes.c_uint64
    lib.fm_fnv1a64.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.fm_parse_shape.restype = None
    lib.fm_parse_shape.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.fm_parse.restype = ctypes.c_int32
    lib.fm_parse.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,  # n
        ctypes.c_int64,  # width
        ctypes.c_int64,  # vocabulary_size
        ctypes.c_int32,  # hash_feature_id
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # labels
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),  # ids
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),  # vals
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # fields
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),  # nnz
        ctypes.POINTER(ctypes.c_int64),  # error_line
    ]
    return lib


class NativeParser:
    """Callable with the signature of ``libsvm.parse_lines``."""

    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib

    def fnv1a64(self, token: bytes) -> int:
        return int(self._lib.fm_fnv1a64(token, len(token)))

    def __call__(
        self,
        lines: list[str],
        *,
        vocabulary_size: int,
        hash_feature_id_flag: bool = False,
        max_nnz: int | None = None,
    ) -> ParsedBatch:
        buf = ("\n".join(lines)).encode("utf-8")
        n_lines = ctypes.c_int64()
        widest = ctypes.c_int64()
        self._lib.fm_parse_shape(buf, ctypes.byref(n_lines), ctypes.byref(widest))
        n = len(lines)
        width = max_nnz if max_nnz is not None else max(int(widest.value), 1)
        labels = np.zeros((n,), np.float32)
        ids = np.zeros((n, width), np.int64)
        vals = np.zeros((n, width), np.float32)
        fields = np.zeros((n, width), np.int32)
        nnz = np.zeros((n,), np.int32)
        err_line = ctypes.c_int64(-1)
        code = self._lib.fm_parse(
            buf,
            n,
            width,
            vocabulary_size,
            1 if hash_feature_id_flag else 0,
            labels,
            ids,
            vals,
            fields,
            nnz,
            ctypes.byref(err_line),
        )
        if code != 0:
            raise ValueError(
                f"{_ERRORS.get(code, f'error {code}')} at line {err_line.value}"
            )
        return ParsedBatch(labels=labels, ids=ids, vals=vals, fields=fields, nnz=nnz)


def load_native_parser() -> NativeParser | None:
    """Load the C++ parser, building it on first use; None → Python fallback."""
    if not os.path.exists(_SO_PATH):
        _try_build()
    if not os.path.exists(_SO_PATH):
        return None
    try:
        return NativeParser(_bind(ctypes.CDLL(_SO_PATH)))
    except OSError:
        return None


def best_parser():
    """The fastest available parser honoring the parse_lines contract."""
    native = load_native_parser()
    if native is not None:
        return native
    from fast_tffm_tpu.data.libsvm import parse_lines

    return parse_lines
