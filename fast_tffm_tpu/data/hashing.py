"""Stateless feature-id hashing into a fixed vocabulary.

Capability parity with the reference's `hash_feature_id=true` path
(`renyi533/fast_tffm` :: cc/ FmParser kernel hashes raw ids into
``[0, vocabulary_size)`` at parse time).  AUC parity does not require the
reference's exact hash (SURVEY.md §7 "Hash compatibility"); what matters is
cross-run stability and a good collision rate at huge vocabularies, so we
use 64-bit FNV-1a over the raw token bytes — trivially reimplementable in
the C++ parser (csrc/libsvm_parser.cpp) so both parsers agree bit-for-bit.
"""

from __future__ import annotations

import numpy as np

FNV_OFFSET = 0xCBF29CE484222325
FNV_PRIME = 0x100000001B3
_MASK = 0xFFFFFFFFFFFFFFFF


def fnv1a64(token: bytes) -> int:
    """64-bit FNV-1a of a byte string."""
    h = FNV_OFFSET
    for b in token:
        h = ((h ^ b) * FNV_PRIME) & _MASK
    return h


def hash_feature_id(token: str | bytes, vocabulary_size: int) -> int:
    """Map a raw feature token to a stable id in [0, vocabulary_size)."""
    if isinstance(token, str):
        token = token.encode("utf-8")
    return fnv1a64(token) % vocabulary_size


def hash_feature_ids_np(ids: np.ndarray, vocabulary_size: int) -> np.ndarray:
    """Vectorized FNV-1a over the decimal byte representation of integer ids.

    Matches ``hash_feature_id(str(i).encode(), vocab)`` element-wise — the
    contract shared with the C++ parser.

    PERFORMANCE WARNING: this is a per-element Python loop (~10³× slower
    than the native path) kept only as the parity fallback when the C++
    parser is unavailable — the C++ parser and the FMB writer hash
    natively, so production paths never come through here.  If a profile
    shows this function, build the native parser (``make -C csrc``).
    """
    return np.fromiter(
        (hash_feature_id(str(int(i)), vocabulary_size) for i in ids.ravel()),
        dtype=np.int64,
        count=ids.size,
    ).reshape(ids.shape)
