"""Host-side input pipeline: files → line batches → ParsedBatch stream.

Capability parity with the reference's queue-runner pipeline
(`renyi533/fast_tffm` :: trainer module: filename queue → line reader →
string batches → FmParser, with epoch_num / batch_size / per-file weights
from the cfg).  TF queue runners don't exist in JAX; the TPU-idiomatic
equivalent is a simple host-side generator (optionally double-buffered by
the caller) feeding static-shape padded batches to the jitted step — input
parsing is legitimately CPU work even on pods (SURVEY.md §3 item 1).

File sharding for distributed data-parallel training: worker ``i`` of ``n``
takes every ``n``-th *line block*, the analog of the reference's per-worker
input file assignment.
"""

from __future__ import annotations

import itertools
from collections.abc import Iterator, Sequence

import numpy as np

from fast_tffm_tpu.data.libsvm import ParsedBatch, pad_batch

__all__ = ["line_stream", "batch_stream"]


def line_stream(
    files: Sequence[str],
    *,
    epochs: int = 1,
    shard_index: int = 0,
    shard_count: int = 1,
    weights: Sequence[float] | None = None,
) -> Iterator[tuple[str, float]]:
    """Yield (line, example_weight) over ``files`` for ``epochs`` passes.

    ``weights`` gives a per-file example weight (reference: optional per-file
    weight list aligned with the train file list); default 1.0.  Sharding is
    round-robin by line index across the whole file list so workers get
    near-equal, disjoint slices without coordination.
    """
    if weights is not None and len(weights) != len(files):
        raise ValueError(
            f"weights has {len(weights)} entries for {len(files)} files"
        )
    counter = itertools.count()
    for _ in range(epochs):
        for fi, path in enumerate(files):
            w = 1.0 if weights is None else float(weights[fi])
            with open(path, "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if next(counter) % shard_count == shard_index:
                        yield line, w


def batch_stream(
    files: Sequence[str],
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    epochs: int = 1,
    shard_index: int = 0,
    shard_count: int = 1,
    weights: Sequence[float] | None = None,
    drop_remainder: bool = False,
    parser=None,
) -> Iterator[tuple[ParsedBatch, np.ndarray]]:
    """Yield (ParsedBatch, example_weights[batch]) with static shapes.

    A short final batch is zero-padded up to ``batch_size`` (padded rows get
    weight 0 so the loss ignores them) unless ``drop_remainder``.

    ``max_nnz`` fixes the feature-axis width across all batches — required
    for a single XLA compilation.  If None, each batch is as wide as its
    widest row (fine for eval, recompiles on width change under jit).

    ``parser`` overrides the line parser (signature of
    ``libsvm.parse_lines``); data/native.py passes the C++ implementation.
    """
    from fast_tffm_tpu.data.libsvm import parse_lines
    from fast_tffm_tpu.data.native import NativeParser, native_batch_stream

    if isinstance(parser, NativeParser) and max_nnz is not None:
        # Full-native path: file reads, sharding, and parsing all in C++
        # (the Python per-line loop below costs as much as the parse).
        yield from native_batch_stream(
            parser,
            files,
            batch_size=batch_size,
            vocabulary_size=vocabulary_size,
            hash_feature_id=hash_feature_id,
            max_nnz=max_nnz,
            epochs=epochs,
            shard_index=shard_index,
            shard_count=shard_count,
            weights=weights,
            drop_remainder=drop_remainder,
        )
        return

    parse = parser if parser is not None else parse_lines
    stream = line_stream(
        files,
        epochs=epochs,
        shard_index=shard_index,
        shard_count=shard_count,
        weights=weights,
    )
    while True:
        chunk = list(itertools.islice(stream, batch_size))
        if not chunk:
            return
        if len(chunk) < batch_size and drop_remainder:
            return
        lines = [c[0] for c in chunk]
        w = np.asarray([c[1] for c in chunk], np.float32)
        batch = parse(
            lines,
            vocabulary_size=vocabulary_size,
            hash_feature_id_flag=hash_feature_id,
            max_nnz=max_nnz,
        )
        if len(chunk) < batch_size:
            batch = pad_batch(batch, batch_size)
            w = np.concatenate([w, np.zeros((batch_size - len(chunk),), np.float32)])
        yield batch, w
