"""Host-side input pipeline: files → line batches → ParsedBatch stream.

Capability parity with the reference's queue-runner pipeline
(`renyi533/fast_tffm` :: trainer module: filename queue → line reader →
string batches → FmParser, with epoch_num / batch_size / per-file weights
from the cfg).  TF queue runners don't exist in JAX; the TPU-idiomatic
equivalent is a simple host-side generator (optionally double-buffered by
the caller) feeding static-shape padded batches to the jitted step — input
parsing is legitimately CPU work even on pods (SURVEY.md §3 item 1).

File sharding for distributed data-parallel training: worker ``i`` of ``n``
takes every ``n``-th *line block*, the analog of the reference's per-worker
input file assignment.

Stream contract downstream: every stream here yields ``(ParsedBatch,
weights)`` host pairs; HOW those cross the host→device link is the
converter's choice — ``wire_format = packed`` routes FMB-backed streams
through data/wire.py (one coalesced byte buffer per superbatch,
device-side reconstruction), text streams ship classic per-tensor
arrays.  The pairs themselves are wire-format-agnostic, which is what
keeps the packed/arrays bit-parity structural.
"""

from __future__ import annotations

import itertools
from collections.abc import Iterator, Sequence

import numpy as np

from fast_tffm_tpu.data.libsvm import ParsedBatch, pad_batch

__all__ = ["line_stream", "batch_stream", "emit_assembled_tail"]


def emit_assembled_tail(alloc, buffers, filled, emitted, drop_remainder, pad_to_batches):
    """Shared end-of-stream semantics for the buffer-assembling streams
    (native.native_batch_stream, binary.fmb_batch_stream).

    ``buffers`` is the (labels, ids, vals, fields, nnz, weights) tuple with
    ``filled`` real rows; rows beyond ``filled`` are zero with weight 0
    (fresh ``alloc()`` output), which is exactly ``pad_batch``'s padding.
    Emits the short remainder batch unless ``drop_remainder``, then all-
    empty weight-0 batches up to ``pad_to_batches`` (fixed multi-host step
    counts).  One definition so the three streams cannot drift — their
    bit-identical-batches contract is also pinned by the parity tests.
    """
    labels, ids, vals, fields, nnz, w = buffers
    if filled and not drop_remainder and (pad_to_batches is None or emitted < pad_to_batches):
        yield ParsedBatch(labels, ids, vals, fields, nnz), w
        emitted += 1
    if pad_to_batches is not None:
        while emitted < pad_to_batches:
            labels, ids, vals, fields, nnz, w = alloc()  # all-zero, weight-0
            yield ParsedBatch(labels, ids, vals, fields, nnz), w
            emitted += 1


def line_stream(
    files: Sequence[str],
    *,
    epochs: int = 1,
    shard_index: int = 0,
    shard_count: int = 1,
    shard_block: int = 1,
    weights: Sequence[float] | None = None,
) -> Iterator[tuple[str, float]]:
    """Yield (line, example_weight) over ``files`` for ``epochs`` passes.

    ``weights`` gives a per-file example weight (reference: optional per-file
    weight list aligned with the train file list); default 1.0.  Sharding is
    block-cyclic by global line index (line i → shard (i // shard_block) %
    shard_count): block 1 is classic round-robin; block = local batch size
    hands each multi-host process the contiguous rows of its slice of every
    global batch.  Workers get near-equal, disjoint slices either way.

    ``shard_block > 1`` requires ``epochs == 1``: the counter runs across
    epoch repeats, so a second pass would start mid-block and the shard →
    global-batch-row alignment the block size exists for would silently
    break.  Multi-host callers make one stream per epoch (see dist_train).
    """
    if weights is not None and len(weights) != len(files):
        raise ValueError(
            f"weights has {len(weights)} entries for {len(files)} files"
        )
    if shard_block > 1 and epochs != 1:
        raise ValueError(
            "shard_block > 1 requires epochs == 1 (batch-aligned sharding "
            "does not survive epoch boundaries); create one stream per epoch"
        )
    counter = itertools.count()
    block = max(1, shard_block)
    for _ in range(epochs):
        for fi, path in enumerate(files):
            w = 1.0 if weights is None else float(weights[fi])
            with open(path, "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    if (next(counter) // block) % shard_count == shard_index:
                        yield line, w


def batch_stream(
    files: Sequence[str],
    *,
    batch_size: int,
    vocabulary_size: int,
    hash_feature_id: bool = False,
    max_nnz: int | None = None,
    epochs: int = 1,
    shard_index: int = 0,
    shard_count: int = 1,
    shard_block: int = 1,
    weights: Sequence[float] | None = None,
    drop_remainder: bool = False,
    pad_to_batches: int | None = None,
    parser=None,
    binary_cache: bool = False,
    shuffle_seed: int | None = None,
    skip_rows: int = 0,
    io_retries: int = 3,
    io_retry_backoff_s: float = 0.05,
) -> Iterator[tuple[ParsedBatch, np.ndarray]]:
    """Yield (ParsedBatch, example_weights[batch]) with static shapes.

    ``skip_rows`` (a whole number of batches) reopens the stream
    mid-epoch — the exact-position resume seek.  FMB streams seek at
    memmap cost (no copying of skipped rows); text streams skip raw
    lines before parsing (read-speed) or discard whole already-parsed
    batches on the native path.  ``pad_to_batches`` accounting starts at
    the skipped count either way, so a resumed stream emits exactly the
    remaining steps.  ``io_retries``/``io_retry_backoff_s`` bound the
    FMB reader's transient-IO retry (data/binary.py).

    A short final batch is zero-padded up to ``batch_size`` (padded rows get
    weight 0 so the loss ignores them) unless ``drop_remainder``.

    ``max_nnz`` fixes the feature-axis width across all batches — required
    for a single XLA compilation.  If None, each batch is as wide as its
    widest row (fine for eval, recompiles on width change under jit).

    ``pad_to_batches`` forces EXACTLY that many batches, appending all-empty
    (weight-0) batches after the data runs out.  Multi-host input sharding
    needs it: every process must run the same number of collective steps
    per epoch even when its shard is a batch short.  Requires ``max_nnz``
    so the pad batches match the data batches' static width.

    ``parser`` overrides the line parser (signature of
    ``libsvm.parse_lines``); data/native.py passes the C++ implementation.

    FMB files (data/binary.py) route to the memmap stream — no parsing at
    all; a mix of text and FMB in one list is rejected (the two halves
    would disagree about what a "line" is under sharding).
    ``binary_cache=True`` converts text files to ``<file>.fmb`` caches
    first (reused while fresh) and streams those.
    """
    from fast_tffm_tpu.data.binary import ensure_fmb_cache, fmb_batch_stream, is_fmb
    from fast_tffm_tpu.data.libsvm import parse_lines
    from fast_tffm_tpu.data.native import NativeParser, native_batch_stream

    if pad_to_batches is not None and max_nnz is None:
        raise ValueError(
            "pad_to_batches requires max_nnz (pad batches must share the "
            "data batches' static feature width)"
        )
    if skip_rows < 0 or skip_rows % batch_size:
        raise ValueError(
            f"skip_rows must be a non-negative whole number of batches "
            f"(batch_size {batch_size}), got {skip_rows}"
        )
    skip_batches = skip_rows // batch_size

    if binary_cache:
        files = ensure_fmb_cache(
            files,
            vocabulary_size=vocabulary_size,
            hash_feature_id=hash_feature_id,
            max_nnz=max_nnz,
            parser=parser,
        )
    fmb = [is_fmb(p) for p in files]
    # ensure_fmb_cache's fallback is all-or-nothing (a failed build turns
    # the WHOLE list back to text, and a failed build alongside .fmb
    # passthroughs raises there), so a cache fallback can never produce a
    # mixed list — the mixed-list error below always describes the
    # caller's own input.
    cache_fell_back = binary_cache and not all(fmb)
    if any(fmb):
        if not all(fmb):
            raise ValueError(
                "cannot mix FMB and text files in one stream: "
                f"{[p for p, b in zip(files, fmb) if not b]} are not FMB"
            )
        yield from fmb_batch_stream(
            files,
            batch_size=batch_size,
            vocabulary_size=vocabulary_size,
            hash_feature_id=hash_feature_id,
            max_nnz=max_nnz,
            epochs=epochs,
            shard_index=shard_index,
            shard_count=shard_count,
            shard_block=shard_block,
            weights=weights,
            drop_remainder=drop_remainder,
            pad_to_batches=pad_to_batches,
            shuffle_seed=shuffle_seed,
            skip_rows=skip_rows,
            io_retries=io_retries,
            io_retry_backoff_s=io_retry_backoff_s,
        )
        return
    if shuffle_seed is not None:
        if cache_fell_back:
            # The caller ALREADY asked for the cache; repeating "set
            # binary_cache = true" would send them in a circle.
            raise ValueError(
                "shuffle requires memmap (FMB) input, and the binary cache "
                "could not be built (cache location unwritable?) — fix the "
                "cache-directory permissions or convert the files to a "
                "writable location (tools/convert_dataset.py / the convert "
                "CLI verb)"
            )
        raise ValueError(
            "shuffle requires memmap (FMB) input — sequential text streaming "
            "cannot reorder rows; set binary_cache = true or convert the "
            "files (tools/convert_dataset.py / the convert CLI verb)"
        )

    if isinstance(parser, NativeParser) and max_nnz is not None:
        # Full-native path: file reads, sharding, and parsing all in C++
        # (the Python per-line loop below costs as much as the parse).
        # A resume seek discards whole parsed batches here (parse-speed —
        # the native stream has no random access); the islice keeps the
        # pad_to_batches total honest (N emitted underneath, first
        # skip_batches dropped = N - skip yielded, the remaining steps).
        gen = native_batch_stream(
            parser,
            files,
            batch_size=batch_size,
            vocabulary_size=vocabulary_size,
            hash_feature_id=hash_feature_id,
            max_nnz=max_nnz,
            epochs=epochs,
            shard_index=shard_index,
            shard_count=shard_count,
            shard_block=shard_block,
            weights=weights,
            drop_remainder=drop_remainder,
            pad_to_batches=pad_to_batches,
        )
        yield from (
            itertools.islice(gen, skip_batches, None) if skip_batches else gen
        )
        return

    parse = parser if parser is not None else parse_lines
    stream = line_stream(
        files,
        epochs=epochs,
        shard_index=shard_index,
        shard_count=shard_count,
        shard_block=shard_block,
        weights=weights,
    )
    if skip_rows:
        # Resume seek on the text path: skip raw lines BEFORE parsing
        # (read-speed, not parse-speed); skipped batches count as emitted
        # so pad_to_batches still means "this epoch has exactly N steps".
        stream = itertools.islice(stream, skip_rows, None)
    emitted = skip_batches
    while True:
        chunk = list(itertools.islice(stream, batch_size))
        if not chunk:
            break
        if len(chunk) < batch_size and drop_remainder:
            break
        lines = [c[0] for c in chunk]
        w = np.asarray([c[1] for c in chunk], np.float32)
        batch = parse(
            lines,
            vocabulary_size=vocabulary_size,
            hash_feature_id_flag=hash_feature_id,
            max_nnz=max_nnz,
        )
        if len(chunk) < batch_size:
            batch = pad_batch(batch, batch_size)
            w = np.concatenate([w, np.zeros((batch_size - len(chunk),), np.float32)])
        yield batch, w
        emitted += 1
        if pad_to_batches is not None and emitted >= pad_to_batches:
            return
    if pad_to_batches is not None:
        width = max_nnz
        while emitted < pad_to_batches:
            empty = ParsedBatch(
                labels=np.zeros((batch_size,), np.float32),
                ids=np.zeros((batch_size, width), np.int64),
                vals=np.zeros((batch_size, width), np.float32),
                fields=np.zeros((batch_size, width), np.int32),
                nnz=np.zeros((batch_size,), np.int32),
            )
            yield empty, np.zeros((batch_size,), np.float32)
            emitted += 1
