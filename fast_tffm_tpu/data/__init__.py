from fast_tffm_tpu.data.hashing import hash_feature_id  # noqa: F401
from fast_tffm_tpu.data.libsvm import ParsedBatch, parse_lines, pad_batch  # noqa: F401
from fast_tffm_tpu.data.pipeline import batch_stream, line_stream  # noqa: F401
