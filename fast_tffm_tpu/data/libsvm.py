"""libsvm / libffm text parsing into padded, static-shape batches.

TPU-native replacement for the reference's FmParser C++ op
(`renyi533/fast_tffm` :: cc/ parser kernel: batch of libsvm lines →
labels, flat feature ids, flat values, per-row offsets).  Two deliberate
departures, both TPU-first:

* output is a *padded dense* ``[batch, max_nnz]`` batch rather than flat
  CSR — XLA wants static shapes, and zero-valued padding is exactly neutral
  in the FM kernels (see ops/fm.py);
* field ids are parsed too (``field:feature:value`` libffm syntax) so the
  same parser feeds FFM.

A C++ implementation of the same contract lives in csrc/libsvm_parser.cpp
(loaded via ctypes in data/native.py); this module is the reference
implementation and fallback.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from fast_tffm_tpu.data.hashing import hash_feature_id

__all__ = ["ParsedBatch", "parse_lines", "pad_batch"]


@dataclasses.dataclass
class ParsedBatch:
    """A padded, static-shape batch — the framework's narrow waist.

    Attributes:
      labels:  [batch] float32 in {0, 1} (reference accepts 0/1 and ±1;
               −1 is mapped to 0).
      ids:     [batch, max_nnz] feature ids, 0-padded.  int64 from the
               line parsers (Python-int parity); the native STREAM emits
               int32 when the vocabulary fits (the device batch dtype —
               consumers must accept either).
      vals:    [batch, max_nnz] float32 feature values (0-padded; padding is
               identified by vals == 0, never by ids).
      fields:  [batch, max_nnz] int32 field ids (0-padded; all-zero for plain
               libsvm input).
      nnz:     [batch] int32 true per-row nonzero counts (the CSR row-splits
               equivalent, kept for diagnostics/oracles).
    """

    labels: np.ndarray
    ids: np.ndarray
    vals: np.ndarray
    fields: np.ndarray
    nnz: np.ndarray

    @property
    def batch_size(self) -> int:
        return int(self.labels.shape[0])

    @property
    def max_nnz(self) -> int:
        return int(self.ids.shape[1])


def _parse_label(tok: str) -> float:
    y = float(tok)
    return 0.0 if y <= 0.0 else 1.0


def parse_lines(
    lines: list[str],
    *,
    vocabulary_size: int,
    hash_feature_id_flag: bool = False,
    max_nnz: int | None = None,
) -> ParsedBatch:
    """Parse libsvm/libffm text lines into a ParsedBatch.

    Line grammar:  ``label tok tok ...`` where tok is ``feat:val`` (libsvm)
    or ``field:feat:val`` (libffm).  Malformed tokens raise ValueError with
    the offending line — the reference's parser likewise rejects bad input
    rather than silently skipping.
    """
    n = len(lines)
    labels = np.zeros((n,), np.float32)
    per_row: list[tuple[list[int], list[float], list[int]]] = []
    widest = 0
    for li, line in enumerate(lines):
        toks = line.split()
        if not toks:
            raise ValueError(f"empty line at index {li}")
        try:
            labels[li] = _parse_label(toks[0])
        except ValueError as e:
            raise ValueError(f"bad label {toks[0]!r} at line {li}") from e
        ids_, vals_, flds_ = [], [], []
        for tok in toks[1:]:
            parts = tok.split(":")
            try:
                if len(parts) == 2:
                    fld, feat, val = 0, parts[0], float(parts[1])
                elif len(parts) == 3:
                    fld, feat, val = int(parts[0]), parts[1], float(parts[2])
                else:
                    raise ValueError(tok)
            except ValueError as e:
                raise ValueError(f"bad token {tok!r} at line {li}") from e
            if hash_feature_id_flag:
                fid = hash_feature_id(feat, vocabulary_size)
            else:
                fid = int(feat)
                if not 0 <= fid < vocabulary_size:
                    raise ValueError(
                        f"feature id {fid} out of range [0, {vocabulary_size}) "
                        f"at line {li} (set hash_feature_id = True for raw tokens)"
                    )
            ids_.append(fid)
            vals_.append(val)
            flds_.append(fld)
        per_row.append((ids_, vals_, flds_))
        widest = max(widest, len(ids_))

    width = max_nnz if max_nnz is not None else max(widest, 1)
    ids = np.zeros((n, width), np.int64)
    vals = np.zeros((n, width), np.float32)
    fields = np.zeros((n, width), np.int32)
    nnz = np.zeros((n,), np.int32)
    for li, (ids_, vals_, flds_) in enumerate(per_row):
        if len(ids_) > width:
            raise ValueError(
                f"line {li} has {len(ids_)} features > max_nnz={width}"
            )
        m = len(ids_)
        ids[li, :m] = ids_
        with np.errstate(over="ignore"):  # huge decimals -> inf, like the C++ cast
            vals[li, :m] = vals_
        fields[li, :m] = flds_
        nnz[li] = m
    return ParsedBatch(labels=labels, ids=ids, vals=vals, fields=fields, nnz=nnz)


def pad_batch(batch: ParsedBatch, batch_size: int) -> ParsedBatch:
    """Pad a short tail batch up to ``batch_size`` rows with empty examples.

    Padded rows have nnz == 0 and label 0; callers weight them out of the
    loss with an example mask (vals are all-zero → score = 0).
    """
    n = batch.batch_size
    if n == batch_size:
        return batch
    if n > batch_size:
        raise ValueError(f"batch of {n} rows exceeds target {batch_size}")
    pad = batch_size - n
    return ParsedBatch(
        labels=np.concatenate([batch.labels, np.zeros((pad,), np.float32)]),
        ids=np.concatenate([batch.ids, np.zeros((pad, batch.max_nnz), batch.ids.dtype)]),
        vals=np.concatenate([batch.vals, np.zeros((pad, batch.max_nnz), np.float32)]),
        fields=np.concatenate([batch.fields, np.zeros((pad, batch.max_nnz), np.int32)]),
        nnz=np.concatenate([batch.nnz, np.zeros((pad,), np.int32)]),
    )
