"""Multi-process SPMD runtime: bring-up, coordination, fault tolerance.

The reference ran a ps/worker TF cluster where any worker could die and
the Supervisor restarted it from the last Saver checkpoint; the SPMD
translation (SNIPPETS.md [3]: "on TPU pods, pjit can run computations
across all available devices across processes") replaces the cluster
with N identical processes driving one global mesh — which makes the
FAILURE story harder, not easier: one host dying must not corrupt the
shared checkpoint chain or desync the survivors.  This module is the
coordination layer that makes the pod survivable:

  * **bring-up** — ``initialize_runtime`` wires the process into the
    pod: ``jax.distributed.initialize`` from either the classic config
    keys (coordinator_address / num_processes / process_id, or TPU
    metadata) or a supervisor-owned *generation file* (see below).  CPU
    pods get gloo collectives switched on automatically — without them
    the CPU backend refuses multi-process computations outright.

  * **DistributedRuntime** — barriers and a tiny cross-process KV store
    (jax's coordination-service store when the distributed client is up,
    a shared-filesystem fallback otherwise, no-ops single-process).
    This is what the checkpoint layer uses for the single-writer publish
    protocol (process 0 writes, everyone barriers on the content
    signature — DESIGN.md invariant 6), what resume uses to verify every
    host restored the same chain head and cursor vector, and what
    finally legalizes ``on_nan = rollback`` under dist_train (the
    rollback barrier: all processes agree, restore the same head, resume
    at the same cursor).

  * **heartbeats + HostMonitor** — every host writes a heartbeat file
    under the shared runtime dir; a monitor thread classifies a stale
    peer as a host-level ``kind=stall`` (heartbeat-lost vs straggler)
    long before jax's own ~100 s coordination-service timeout notices.

  * **generation protocol** — crash recovery for the pod.  jax's
    coordination service cannot re-admit a relaunched process into a
    live cluster (and a dead process 0 takes the coordinator with it),
    so recovery is *generational*: the pod supervisor
    (resilience.Supervisor with ``processes = N``) owns a
    ``generation.json`` naming {generation, coordinator, num_processes}.
    When ONE host dies the supervisor relaunches ONLY that host and
    bumps the generation with a fresh coordinator port; every survivor's
    ``GenerationWatcher`` thread notices the bump and **re-execs the
    process in place** (``os.execv`` — same PID, fresh image, forced
    ``--resume``).  exec-from-a-thread is the one escape hatch that
    works even while the main thread is wedged inside a collective whose
    peer is gone — the standard failure posture of a survivor.  All N
    processes of the new generation then park at the
    ``jax.distributed.initialize`` rendezvous (the restore barrier),
    restore the same chain head, verify signatures + cursor vector
    agreement, and resume — bit-identically, which the pod chaos tests
    pin.

Like resilience.py, this module must import WITHOUT jax (the supervisor
process never touches a device); all jax use is lazy.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

from fast_tffm_tpu.telemetry import log_quietly

__all__ = [
    "PEER_LOST_EXIT",
    "PeerLostError",
    "DistributedRuntime",
    "FileKV",
    "initialize_runtime",
    "host_metrics_path",
    "free_port",
    "read_generation",
    "write_generation",
    "wait_for_generation",
    "GENERATION_FILE",
    "HeartbeatWriter",
    "HostMonitor",
    "GenerationWatcher",
    "reexec_argv",
    "process_identity",
]

# Exit code a trainer uses when it deliberately dies because a PEER was
# lost (coordination timeout, failed barrier): the supervisor treats it
# as collateral of the incident, not a fresh crash of this host.
PEER_LOST_EXIT = 17

GENERATION_FILE = "generation.json"

# Environment contract between the pod supervisor and its children
# (resilience.Supervisor sets these; initialize_runtime reads them).
ENV_RUNTIME_DIR = "FM_DIST_RUNTIME_DIR"
ENV_PROCESS_ID = "FM_DIST_PROCESS_ID"
ENV_PROCESSES = "FM_DIST_PROCESSES"
ENV_GENERATION = "FM_DIST_GENERATION"


class PeerLostError(RuntimeError):
    """A cross-process barrier / KV wait timed out: a peer host is gone
    (or wedged past the deadline).  The caller should exit with
    PEER_LOST_EXIT — under the pod supervisor the generation bump will
    already be on its way."""


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def process_identity() -> tuple[int, int]:
    """(process_index, process_count) without forcing a jax backend up:
    jax answers when it is already imported (trainers), the supervisor
    env contract answers for device-free processes, (0, 1) otherwise."""
    if "jax" in sys.modules:
        try:
            import jax
            from jax._src import distributed as _jax_dist

            if _jax_dist.global_state.client is not None:
                return jax.process_index(), jax.process_count()
        # analysis: ok exception-hygiene jax-internal probe: any failure here means "not in a distributed runtime" and the env-var fallback below answers
        except Exception:
            pass
    try:
        return (
            int(os.environ.get(ENV_PROCESS_ID, "0")),
            int(os.environ.get(ENV_PROCESSES, "1")),
        )
    except ValueError:
        return 0, 1


def host_metrics_path(path: str, process_index: int | None = None) -> str:
    """Per-host telemetry JSONL path: the lead keeps ``path`` unchanged
    (every existing reader keeps working), host p > 0 writes
    ``path`` with a ``.p<N>`` inserted before the extension —
    ``run.jsonl`` -> ``run.p1.jsonl``.  tools/report.py merges them."""
    if not path:
        return path
    p = process_identity()[0] if process_index is None else int(process_index)
    if p == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{p}{ext or ''}"


# ---------------------------------------------------------------------------
# generation file (supervisor <-> children)
# ---------------------------------------------------------------------------


def write_generation(runtime_dir: str, info: dict) -> str:
    """Atomically publish a generation record ({generation, coordinator,
    num_processes, cause}) — the supervisor's single source of truth for
    which pod incarnation is current."""
    os.makedirs(runtime_dir, exist_ok=True)
    path = os.path.join(runtime_dir, GENERATION_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info, f, sort_keys=True)
    os.replace(tmp, path)
    return path


def read_generation(runtime_dir: str) -> dict | None:
    try:
        with open(os.path.join(runtime_dir, GENERATION_FILE)) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def wait_for_generation(
    runtime_dir: str, at_least: int, timeout_s: float = 120.0, poll_s: float = 0.1
) -> dict:
    """Block until the generation file names generation >= ``at_least``
    (a relaunched/re-exec'd child parking until the supervisor has
    published the incarnation it belongs to)."""
    deadline = time.monotonic() + timeout_s
    while True:
        info = read_generation(runtime_dir)
        if info is not None and int(info.get("generation", -1)) >= at_least:
            return info
        if time.monotonic() > deadline:
            raise PeerLostError(
                f"no generation >= {at_least} appeared in {runtime_dir} "
                f"within {timeout_s:.0f}s (supervisor gone?)"
            )
        time.sleep(poll_s)


# ---------------------------------------------------------------------------
# KV backends
# ---------------------------------------------------------------------------


class FileKV:
    """Shared-filesystem KV + barrier: one file per key under ``root``.
    The fallback (and unit-test) backend — the pod's checkpoint chain
    already assumes a shared filesystem, so this adds no new
    requirement.  Barrier = every process publishes a marker and polls
    for all P of them."""

    def __init__(self, root: str, poll_s: float = 0.05):
        self._root = root
        self._poll = poll_s
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        # Keys are runtime-generated (no user input); keep them readable.
        return os.path.join(self._root, key.replace("/", "_"))

    def set(self, key: str, value: str) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def get(self, key: str, timeout_s: float) -> str:
        path = self._path(key)
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                with open(path) as f:
                    return f.read()
            except OSError:
                pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"key {key!r} did not appear in {timeout_s:.0f}s")
            time.sleep(self._poll)

    def barrier(
        self, name: str, timeout_s: float, process_count: int, process_index: int
    ) -> None:
        self.set(f"{name}.{process_index}", "1")
        for p in range(process_count):
            self.get(f"{name}.{p}", timeout_s)


class _JaxKV:
    """jax coordination-service KV + native barrier (multi-host pods —
    no shared-FS round-trips on the hot path)."""

    def __init__(self, client):
        self._client = client

    def set(self, key: str, value: str) -> None:
        self._client.key_value_set(key, value)

    def get(self, key: str, timeout_s: float) -> str:
        try:
            return self._client.blocking_key_value_get(
                key, int(timeout_s * 1000)
            )
        except Exception as e:  # xla raises its own rpc error types
            raise TimeoutError(str(e)) from e

    def barrier(
        self, name: str, timeout_s: float, process_count: int, process_index: int
    ) -> None:
        try:
            self._client.wait_at_barrier(name, int(timeout_s * 1000))
        except Exception as e:
            raise TimeoutError(str(e)) from e


# ---------------------------------------------------------------------------
# the runtime (barriers / signatures / cursor vectors)
# ---------------------------------------------------------------------------


_RUNTIME_ORDINAL = [0]  # process-global DistributedRuntime construction count


class DistributedRuntime:
    """Cross-process coordination for one trainer run.

    Inactive (every method a cheap no-op returning None) when
    single-process or no KV backend is reachable — drivers call it
    unconditionally.  All methods must be called in the SAME order on
    every process (they are: every call site is step/boundary
    deterministic); keys self-namespace with per-tag counters plus an
    epoch namespace (``advance_namespace`` — bumped between rollback
    attempts so a fresh AsyncCheckpointer's sequence numbers can never
    collide with the aborted attempt's).

    A timed-out wait raises :class:`PeerLostError` — under the pod
    supervisor the survivor is normally re-exec'd before ever seeing it.
    """

    # Bring-up attachments (initialize_runtime sets them when present).
    runtime_dir: str | None = None
    heartbeat = None
    watcher = None

    def __init__(
        self,
        process_index: int = 0,
        process_count: int = 1,
        kv=None,
        *,
        barrier_timeout_s: float = 120.0,
        log=print,
        instance: int | None = None,
    ):
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self._kv = kv
        self.barrier_timeout_s = float(barrier_timeout_s)
        self._log = log
        self._ns = 0
        self._counters: dict[str, int] = {}
        # KV keys are write-once; a process may construct several runtimes
        # against ONE coordination service (dist_train then dist_predict,
        # or a resume in the same process).  Runtime construction is a
        # lock-step SPMD event, so a process-global instance ordinal keeps
        # every instance's keyspace disjoint AND matched across hosts.
        # (Tests simulating several hosts in one process pass ``instance``
        # explicitly.)
        if instance is None:
            _RUNTIME_ORDINAL[0] += 1
            instance = _RUNTIME_ORDINAL[0]
        self._instance = int(instance)

    # -- construction ------------------------------------------------------

    @classmethod
    def create(
        cls, *, barrier_timeout_s: float = 120.0, runtime_dir: str | None = None, log=print
    ) -> "DistributedRuntime":
        """The driver-facing constructor: jax KV when the distributed
        client is up, FileKV under ``runtime_dir`` otherwise, inert for
        single-process runs."""
        import jax

        n = jax.process_count()
        if n <= 1:
            return cls(0, 1, None, barrier_timeout_s=barrier_timeout_s, log=log)
        try:
            from jax._src import distributed as _jax_dist

            client = _jax_dist.global_state.client
        # analysis: ok exception-hygiene jax-internal probe: no coordination client means the FileKV fallback below takes over
        except Exception:
            client = None
        if client is not None:
            kv = _JaxKV(client)
        elif runtime_dir:
            kv = FileKV(os.path.join(runtime_dir, "kv"))
        else:
            kv = None
        if kv is None:
            log(
                "warning: multi-process run with no coordination backend — "
                "save-signature barriers disabled (set [Distributed] "
                "runtime_dir for the shared-filesystem fallback)"
            )
        return cls(
            jax.process_index(), n, kv, barrier_timeout_s=barrier_timeout_s, log=log
        )

    @property
    def active(self) -> bool:
        return self.process_count > 1 and self._kv is not None

    @property
    def is_lead(self) -> bool:
        return self.process_index == 0

    def advance_namespace(self) -> None:
        self._ns += 1
        self._counters.clear()

    def _next(self, tag: str) -> int:
        n = self._counters.get(tag, 0)
        self._counters[tag] = n + 1
        return n

    def _key(self, *parts) -> str:
        return "/".join(
            (f"fmr{self._instance}", str(self._ns), *map(str, parts))
        )

    # -- primitives --------------------------------------------------------

    def barrier(self, tag: str) -> None:
        """Rendezvous: returns once every process has called the same
        (order-matched) barrier."""
        if not self.active:
            return
        name = self._key("b", tag, self._next(f"b:{tag}"))
        try:
            self._kv.barrier(
                name, self.barrier_timeout_s, self.process_count, self.process_index
            )
        except TimeoutError as e:
            raise PeerLostError(f"barrier {tag!r}: {e}") from e

    def publish_signature(self, seq: int, sig: str | None, meta: str = "") -> None:
        """Lead-writer side of a checkpoint publish: record that save
        boundary ``seq``'s content signature ``sig`` is DURABLE (called
        only after the atomic rename returned).  ``sig=None`` with
        ``meta="failed"`` records a failed write — peers mirror the
        lead's promote-to-full recovery instead of timing out."""
        if not self.active:
            return
        self._kv.set(self._key("sig", seq), json.dumps({"sig": sig, "meta": meta}))

    def await_signature(self, seq: int) -> dict | None:
        """Non-writer side: block until the lead published save boundary
        ``seq`` (the save barrier — no host proceeds past it before the
        signature it observed is durable).  Returns the publish payload
        ``{"sig": ..., "meta": "full" | "delta" | "failed"}``."""
        if not self.active:
            return None
        try:
            raw = self._kv.get(self._key("sig", seq), self.barrier_timeout_s)
        except TimeoutError as e:
            raise PeerLostError(f"awaiting save signature {seq}: {e}") from e
        return json.loads(raw)

    def share_cursor(self, seq: int, cursor: dict | None) -> list[dict | None] | None:
        """Every host posts its input cursor for save boundary ``seq``;
        the LEAD returns the gathered per-host cursor vector (index =
        process), everyone else returns None.  The vector travels inside
        the lead's atomic publish, so resume can hand each host back its
        exact position."""
        if not self.active:
            return None
        self._kv.set(
            self._key("cur", seq, self.process_index), json.dumps(cursor)
        )
        if not self.is_lead:
            return None
        out = []
        for p in range(self.process_count):
            try:
                out.append(
                    json.loads(self._kv.get(self._key("cur", seq, p), self.barrier_timeout_s))
                )
            except TimeoutError as e:
                raise PeerLostError(f"gathering cursor {seq} from host {p}: {e}") from e
        return out

    def broadcast(self, tag: str, value):
        """Lead's ``value`` to every host (non-leads pass anything; they
        receive the lead's).  Used for run identity: one auto-generated
        telemetry run_id must cover every host's records."""
        if not self.active:
            return value
        key = self._key("bc", tag, self._next(f"bc:{tag}"))
        if self.is_lead:
            self._kv.set(key, json.dumps(value))
        try:
            raw = self._kv.get(key, self.barrier_timeout_s)
        except TimeoutError as e:
            raise PeerLostError(f"broadcast {tag!r}: {e}") from e
        return json.loads(raw)

    def allgather(self, tag: str, value) -> list:
        """Every host posts ``value``; every host returns the full
        per-process list (index = process).  The values may legitimately
        differ — use :meth:`agree` when they must not."""
        if not self.active:
            return [value]
        n = self._next(f"ag:{tag}")
        self._kv.set(self._key("ag", tag, n, self.process_index), json.dumps(value))
        out = []
        for p in range(self.process_count):
            try:
                out.append(
                    json.loads(
                        self._kv.get(self._key("ag", tag, n, p), self.barrier_timeout_s)
                    )
                )
            except TimeoutError as e:
                raise PeerLostError(f"allgather {tag!r}: waiting on host {p}: {e}") from e
        return out

    def agree(self, tag: str, value) -> list:
        """Every host posts ``value``; every host reads all P values and
        raises (loudly, naming the hosts) unless they are identical.
        The restore-consistency check: same chain head, same cursor."""
        if not self.active:
            return [value]
        n = self._next(f"a:{tag}")
        self._kv.set(
            self._key("agree", tag, n, self.process_index), json.dumps(value)
        )
        vals = []
        for p in range(self.process_count):
            try:
                vals.append(
                    json.loads(
                        self._kv.get(self._key("agree", tag, n, p), self.barrier_timeout_s)
                    )
                )
            except TimeoutError as e:
                raise PeerLostError(f"agree {tag!r}: waiting on host {p}: {e}") from e
        if any(v != vals[0] for v in vals[1:]):
            detail = ", ".join(f"host {p}: {v!r}" for p, v in enumerate(vals))
            raise RuntimeError(
                f"hosts disagree on {tag} — {detail}.  Refusing to train on "
                "desynced state (is every host reading the same checkpoint "
                "chain / dataset?)"
            )
        return vals


# ---------------------------------------------------------------------------
# heartbeats + host monitor
# ---------------------------------------------------------------------------


def _hb_path(runtime_dir: str, process_index: int) -> str:
    return os.path.join(runtime_dir, f"hb-{process_index}.json")


class HeartbeatWriter:
    """Daemon thread: publish this host's liveness + training position
    (``{process, step, wall}``) every ``interval_s`` under the shared
    runtime dir.  Freshness is judged by file mtime (wall clocks across
    hosts need not agree); the step payload feeds straggler detection."""

    def __init__(self, runtime_dir: str, process_index: int, interval_s: float = 2.0):
        self._path = _hb_path(runtime_dir, process_index)
        self._process = int(process_index)
        self._interval = float(interval_s)
        self._step = 0
        self._stop = threading.Event()
        os.makedirs(runtime_dir, exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="dist-heartbeat", daemon=True
        )
        self._thread.start()

    def set_step(self, step: int) -> None:
        self._step = int(step)

    def _write(self) -> None:
        tmp = f"{self._path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(
                    {"process": self._process, "step": self._step, "wall": time.time()},
                    f,
                )
            os.replace(tmp, self._path)
        except OSError:
            pass  # a full/unwritable runtime dir must not kill training

    def _run(self) -> None:
        self._write()
        while not self._stop.wait(self._interval):
            self._write()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def read_heartbeat(runtime_dir: str, process_index: int) -> tuple[dict | None, float | None]:
    """(payload, seconds-since-last-write) for one host's heartbeat file
    (None, None when it does not exist / is unreadable)."""
    path = _hb_path(runtime_dir, process_index)
    try:
        age = time.time() - os.path.getmtime(path)
        with open(path) as f:
            payload = json.load(f)
        return (payload if isinstance(payload, dict) else None), age
    except (OSError, ValueError):
        return None, None


class HostMonitor:
    """Daemon thread watching PEER heartbeats: a peer whose file goes
    stale past ``timeout_s`` triggers ``on_event(peer, classification,
    detail)`` once per episode (re-armed when the peer freshens).  The
    classifications are host-level: ``host-heartbeat-lost`` (no write —
    dead or wedged before entering a collective) and ``host-straggler``
    (still writing, but ``straggler_steps`` behind us — the
    collective-entry timeout precursor).  Used by trainers (events land
    as kind=stall telemetry) and by the pod supervisor (straggler
    kills)."""

    def __init__(
        self,
        runtime_dir: str,
        process_index: int,
        process_count: int,
        timeout_s: float,
        on_event,
        *,
        my_step=None,
        straggler_steps: int = 0,
        poll_s: float = 1.0,
    ):
        self._dir = runtime_dir
        self._process = int(process_index)
        self._count = int(process_count)
        self._timeout = float(timeout_s)
        self._on_event = on_event
        self._my_step = my_step  # callable -> int, or None
        self._straggler_steps = int(straggler_steps)
        self._poll = float(poll_s)
        self._fired: dict[tuple[int, str], bool] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dist-hostmonitor", daemon=True
        )
        self._thread.start()

    def _emit_once(self, peer: int, classification: str, detail: dict) -> None:
        key = (peer, classification)
        if self._fired.get(key):
            return
        self._fired[key] = True
        try:
            self._on_event(peer, classification, detail)
        # analysis: ok exception-hygiene owner-injected event callback; the monitor thread must survive any callback bug (the host-stall classification already fired)
        except Exception:
            pass

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            for p in range(self._count):
                if p == self._process:
                    continue
                payload, age = read_heartbeat(self._dir, p)
                if age is None:
                    continue  # never seen: peer still in bring-up
                if age > self._timeout:
                    self._emit_once(
                        p,
                        "host-heartbeat-lost",
                        {"age_s": round(age, 3), "last_step": (payload or {}).get("step")},
                    )
                    continue
                self._fired.pop((p, "host-heartbeat-lost"), None)
                if self._straggler_steps > 0 and self._my_step is not None and payload:
                    try:
                        behind = int(self._my_step()) - int(payload.get("step", 0))
                    except (TypeError, ValueError):
                        continue  # malformed heartbeat payload: no straggler verdict this poll
                    if behind >= self._straggler_steps:
                        self._emit_once(
                            p,
                            "host-straggler",
                            {"steps_behind": behind, "age_s": round(age, 3)},
                        )
                    else:
                        self._fired.pop((p, "host-straggler"), None)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# generation watcher (survivor-side recovery)
# ---------------------------------------------------------------------------


def reexec_argv(argv: list[str]) -> list[str]:
    """The argv a survivor re-execs with: ``--resume`` forced (the whole
    point is restoring the shared chain head) and any armed fault plan
    stripped (chaos plans fire on the FIRST incarnation only — a kill
    fault that re-armed on every re-exec would crash-loop the pod)."""
    out: list[str] = []
    skip = 0
    for a in argv:
        if skip:
            skip -= 1
            continue
        if a in ("--fault-plan", "--fault-seed", "--fault-horizon", "--fault-process"):
            skip = 1
            continue
        if a.startswith("--fault-"):
            continue
        if a == "--resume":
            continue  # re-added once below
        out.append(a)
    out.append("--resume")
    return out


class GenerationWatcher:
    """Daemon thread: when the supervisor bumps ``generation.json`` past
    this process's incarnation, re-exec in place (same PID, fresh image,
    ``--resume``) so this host joins the new pod generation.  exec from
    a side thread is deliberate: the main thread is typically wedged in
    a collective whose peer just died, and no Python-level signal or
    exception can reach it there."""

    def __init__(
        self,
        runtime_dir: str,
        generation: int,
        *,
        argv: list[str] | None = None,
        poll_s: float = 0.25,
        log=print,
        exec_fn=None,
    ):
        self._dir = runtime_dir
        self._generation = int(generation)
        self._argv = list(argv if argv is not None else sys.argv)
        self._poll = float(poll_s)
        self._log = log
        self._exec = exec_fn if exec_fn is not None else self._do_exec
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="dist-genwatcher", daemon=True
        )
        self._thread.start()

    def _do_exec(self, new_generation: int, argv: list[str]) -> None:
        os.environ[ENV_GENERATION] = str(new_generation)
        sys.stdout.flush()
        sys.stderr.flush()
        os.execv(sys.executable, [sys.executable, *argv])

    def _run(self) -> None:
        while not self._stop.wait(self._poll):
            info = read_generation(self._dir)
            if info is None:
                continue
            gen = int(info.get("generation", -1))
            if gen > self._generation:
                log_quietly(
                    self._log,
                    f"distributed: generation {self._generation} -> {gen} "
                    f"(cause: {info.get('cause', '?')}) — re-exec'ing into "
                    "the new pod generation with --resume",
                )
                self._exec(gen, reexec_argv(self._argv))
                return  # only reachable with an injected exec_fn (tests)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


# ---------------------------------------------------------------------------
# bring-up
# ---------------------------------------------------------------------------


def enable_cpu_collectives() -> bool:
    """Switch the CPU backend's cross-process collectives on (gloo) —
    without this a multi-process CPU mesh fails every computation with
    "Multiprocess computations aren't implemented on the CPU backend".
    Must run before backend init; no-op (False) when this jax predates
    the knob or the backend is already up."""
    try:
        import jax

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    # analysis: ok exception-hygiene capability probe: False means "no gloo on this jax", the caller proceeds single-process
    except Exception:
        return False


def initialize_runtime(cfg, log=print, argv: list[str] | None = None):
    """Pod bring-up for dist_train / dist_predict.  Returns a
    :class:`DistributedRuntime` (inert for single-process runs).

    Two paths in:

      * **supervised pod** (``FM_DIST_GENERATION`` env set by
        resilience.Supervisor): park until the supervisor's generation
        file names OUR generation (the restore barrier for relaunched /
        re-exec'd hosts), then ``jax.distributed.initialize`` against
        the generation's coordinator, and arm the GenerationWatcher +
        this host's HeartbeatWriter.
      * **classic** (config keys / env / TPU metadata): exactly the old
        parallel.multihost behavior — including "already initialized by
        the caller" (the multi-process tests initialize directly).
    """
    import jax

    runtime_dir = os.environ.get(ENV_RUNTIME_DIR, "") or getattr(cfg, "runtime_dir", "")
    gen_env = os.environ.get(ENV_GENERATION)
    watcher = heartbeat = None
    if gen_env is not None and runtime_dir:
        my_gen = int(gen_env)
        pid = int(os.environ.get(ENV_PROCESS_ID, "0"))
        info = wait_for_generation(
            runtime_dir, my_gen, timeout_s=float(cfg.barrier_timeout_s)
        )
        my_gen = int(info["generation"])
        os.environ[ENV_GENERATION] = str(my_gen)
        # The watcher goes up BEFORE the (blocking) initialize: a peer
        # that dies during bring-up itself must still be recoverable.
        watcher = GenerationWatcher(runtime_dir, my_gen, argv=argv, log=log)
        enable_cpu_collectives()
        log(
            f"distributed: joining pod generation {my_gen} as process "
            f"{pid}/{info['num_processes']} (coordinator {info['coordinator']})"
        )
        jax.distributed.initialize(
            info["coordinator"],
            num_processes=int(info["num_processes"]),
            process_id=pid,
            initialization_timeout=max(10, int(cfg.barrier_timeout_s)),
        )
        heartbeat = HeartbeatWriter(runtime_dir, pid, interval_s=cfg.heartbeat_s)
    else:
        from fast_tffm_tpu.parallel.multihost import maybe_initialize_distributed

        if cfg.coordinator_address or int(cfg.num_processes or 0) > 1:
            # Explicitly-configured multi-process bring-up: CPU meshes
            # need gloo before the backend comes up (TPU ignores it).
            enable_cpu_collectives()
        maybe_initialize_distributed(
            cfg.coordinator_address, cfg.num_processes, cfg.process_id
        )
        if jax.process_count() > 1 and runtime_dir:
            heartbeat = HeartbeatWriter(
                runtime_dir, jax.process_index(), interval_s=cfg.heartbeat_s
            )
    runtime = DistributedRuntime.create(
        barrier_timeout_s=cfg.barrier_timeout_s,
        runtime_dir=runtime_dir or None,
        log=log,
    )
    runtime.runtime_dir = runtime_dir or None
    runtime.heartbeat = heartbeat
    runtime.watcher = watcher
    return runtime
