from fast_tffm_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    ROW_AXIS,
    batch_sharding,
    check_batch_divides,
    make_mesh,
    pad_vocab,
    replicated,
    table_sharding,
)
from fast_tffm_tpu.parallel.train_step import (  # noqa: F401
    WireGlobalConverter,
    init_sharded_state,
    local_mesh_devices,
    make_global_batch,
    make_global_superbatch,
    make_replicator,
    make_sharded_predict_step,
    make_sharded_train_step,
    pack_sharded_on_device,
    packed_shard_meta,
    unpack_sharded_to_logical,
    unpack_sharded_on_device,
)
