"""Row-sharded embedding lookup and scatter-update over the device mesh.

TPU-native replacement for the reference's sharded parameter lookup
(`renyi533/fast_tffm` :: model-graph builder: feature ids routed to
`vocabulary_block_num` block variables by modulo, gathered over worker→ps
RPC, with gradients scatter-added back asynchronously).  Here the table is
contiguously row-sharded over the mesh ROW_AXIS and the lookup/update are
deterministic XLA collectives inside `shard_map`:

  lookup:  every row shard gathers the rows it owns (others masked to 0)
           and a `psum` over ROW_AXIS assembles full rows on all shards —
           ids travel nowhere (they are replicated over ROW_AXIS already);
           only owned rows ride the ICI ring once.
  update:  per-occurrence row gradients are deduped locally, `all_gather`ed
           over DATA_AXIS (replacing Hogwild's racy async scatter with a
           deterministic synchronous combine), re-deduped, and each shard
           applies sparse Adagrad to the rows it owns — no second collective.

These functions run INSIDE a shard_map body (parallel/train_step.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from fast_tffm_tpu.optim import AdagradState, dedup_rows
from fast_tffm_tpu.parallel.mesh import DATA_AXIS, ROW_AXIS

__all__ = ["sharded_gather", "sharded_sparse_adagrad_update"]


def sharded_gather(table_shard: jax.Array, ids: jax.Array) -> jax.Array:
    """Assemble full parameter rows for ``ids`` from the row-sharded table.

    table_shard: [V/R, D] this shard's contiguous rows.
    ids:         [B_local, N] global row ids (replicated over ROW_AXIS).
    Returns:     [B_local, N, D] full rows, identical on every row shard.
    """
    shard_rows = table_shard.shape[0]
    base = lax.axis_index(ROW_AXIS) * shard_rows
    local = ids - base
    owned = (local >= 0) & (local < shard_rows)
    local = jnp.where(owned, local, 0)
    rows = table_shard[local] * owned[..., None].astype(table_shard.dtype)
    return lax.psum(rows, ROW_AXIS)


def sharded_sparse_adagrad_update(
    table_shard: jax.Array,
    accum_shard: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    num_rows_global: int,
):
    """Sparse Adagrad on the local row shard from global per-occurrence grads.

    Dedup happens twice: locally (cheap, shrinks the all_gather payload's
    effective content) and again after gathering all data shards'
    contributions, because the same row id can be touched by several
    data-parallel workers and Adagrad must see the fully summed gradient
    exactly once (the determinism the reference's Hogwild explicitly gave
    up — SURVEY.md §4.2).
    """
    D = table_shard.shape[-1]
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, D), num_rows_global)
    all_uids = lax.all_gather(uids, DATA_AXIS, tiled=True)  # [W*M]
    all_gsum = lax.all_gather(gsum, DATA_AXIS, tiled=True)  # [W*M, D]
    # Sentinel ids (num_rows_global) from short shards collapse into one
    # segment and are dropped again below.
    guids, ggsum = dedup_rows(all_uids, all_gsum, num_rows_global)

    shard_rows = table_shard.shape[0]
    base = lax.axis_index(ROW_AXIS) * shard_rows
    local = guids - base
    owned = (local >= 0) & (local < shard_rows)
    local = jnp.where(owned, local, shard_rows)  # out of range → mode='drop'

    acc_rows = accum_shard[jnp.minimum(local, shard_rows - 1)] + ggsum * ggsum
    upd_rows = table_shard[jnp.minimum(local, shard_rows - 1)] - lr * ggsum / jnp.sqrt(acc_rows)
    accum_shard = accum_shard.at[local].set(acc_rows, mode="drop")
    table_shard = table_shard.at[local].set(upd_rows, mode="drop")
    return table_shard, accum_shard
