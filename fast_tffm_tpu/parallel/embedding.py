"""Row-sharded embedding lookup and scatter-update over the device mesh.

TPU-native replacement for the reference's sharded parameter lookup
(`renyi533/fast_tffm` :: model-graph builder: feature ids routed to
`vocabulary_block_num` block variables by modulo, gathered over worker→ps
RPC, with gradients scatter-added back asynchronously).  Here the table is
contiguously row-sharded over the mesh ROW_AXIS, the batch is sharded over
BOTH mesh axes (every chip computes a distinct micro-batch — no redundant
compute anywhere), and the lookup/update are deterministic XLA collectives
inside `shard_map`:

  lookup:  each chip all_gathers the (tiny, int32) ids of its ROW_AXIS
           peers, gathers the rows it owns (others masked to 0), and a
           `psum_scatter` over ROW_AXIS returns each requesting chip
           exactly its own rows — every parameter row crosses ICI once,
           and the heavy [*, N, D] float traffic rides the same
           reduce-scatter that a dense sharded matmul would use.
  update:  per-occurrence row gradients are deduped locally (sort +
           segment-sum, static shapes), all_gathered over BOTH axes
           (replacing Hogwild's racy async scatter with a deterministic
           synchronous combine), re-deduped, and each shard applies sparse
           Adagrad to the rows it owns — no second collective.

These functions run INSIDE a shard_map body (parallel/train_step.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from fast_tffm_tpu.optim import AdagradState, dedup_rows
from fast_tffm_tpu.parallel.mesh import DATA_AXIS, ROW_AXIS, axis_size

__all__ = [
    "sharded_gather",
    "sharded_sparse_adagrad_update",
    "apply_shard_adagrad",
    "packed_sharded_gather",
    "packed_sharded_update",
    "packed_sharded_dense_update",
    "fused_sharded_gather",
    "fused_sharded_update",
]


def owned_local_ids(global_ids, shard_logical_rows: int, sentinel: int):
    """Map global row ids to this ROW shard's local ids.

    Returns local ids with every unowned id replaced by ``sentinel``
    (callers pick the convention: 0 for masked gathers, past-the-end for
    dropped scatters) — the ONE place the base/owned arithmetic lives so
    the gather/update paths cannot diverge."""
    base = lax.axis_index(ROW_AXIS) * shard_logical_rows
    local = global_ids - base
    owned = (local >= 0) & (local < shard_logical_rows)
    return jnp.where(owned, local, sentinel), owned


def apply_shard_adagrad(table_shard, accum_shard, guids, ggsum, lr, base, decay=1.0):
    """Adagrad on this shard's rows from globally-combined unique grads.

    The one place the sharded Adagrad math lives — the all-gather update
    below and the all-to-all routed update (parallel/alltoall.py) must
    stay numerically identical, and both end here.  ``guids`` out of this
    shard's range (other shards' rows, dedup sentinels) drop.

    ``decay`` γ < 1 is the lazy touched-row accumulator decay
    (``[Online] adagrad_decay`` — optim.sparse_adagrad_update's sharded
    twin); γ=1.0 is a trace-time branch to the exact classic program."""
    from fast_tffm_tpu.optim import accum_sq

    shard_rows = table_shard.shape[0]
    local = guids - base
    owned = (local >= 0) & (local < shard_rows)
    local = jnp.where(owned, local, shard_rows)  # out of range → mode='drop'
    acc_prev = accum_shard[jnp.minimum(local, shard_rows - 1)]
    if decay != 1.0:
        acc_prev = decay * acc_prev
    acc_rows = acc_prev + accum_sq(accum_shard, ggsum)
    upd_rows = table_shard[jnp.minimum(local, shard_rows - 1)] - lr * ggsum / jnp.sqrt(acc_rows)
    accum_shard = accum_shard.at[local].set(acc_rows, mode="drop")
    table_shard = table_shard.at[local].set(upd_rows, mode="drop")
    return table_shard, accum_shard


def sharded_gather(table_shard: jax.Array, ids: jax.Array) -> jax.Array:
    """Assemble this chip's parameter rows from the row-sharded table.

    table_shard: [V/R, D] this shard's contiguous rows.
    ids:         [B_local, N] global row ids for THIS chip's micro-batch
                 (batch is sharded over data AND row axes).
    Returns:     [B_local, N, D] rows for this chip's ids.
    """
    shard_rows = table_shard.shape[0]
    if axis_size(ROW_AXIS) == 1:
        # One row shard: every id is local and the gather/scatter
        # collectives are identities — skip them (axis_size is static, so
        # this is a trace-time branch; mesh>1 programs are unchanged).
        # The in-range masking is KEPT: an out-of-range id would CLAMP to
        # the last row under single-device gather semantics where the
        # mesh>1 path returns zeros for unowned ids — a silent mesh=1 vs
        # mesh>1 divergence.  Clamp-with-zero enforces the same id-range
        # invariant on both (ADVICE r5); the identity collectives, the
        # bulk of the measured mesh=1 overhead (VERDICT r4 weak #3), stay
        # skipped.
        in_range = (ids >= 0) & (ids < shard_rows)
        rows = table_shard[jnp.where(in_range, ids, 0)]
        return rows * in_range[..., None].astype(rows.dtype)
    base = lax.axis_index(ROW_AXIS) * shard_rows
    # Ids are int32 and tiny next to D-wide rows; gather all ROW peers' ids,
    # serve the rows we own, and reduce-scatter each peer its answers (each
    # row is owned by exactly one shard, so the sum IS the row).
    all_ids = lax.all_gather(ids, ROW_AXIS, tiled=True)  # [R*B_local, N]
    local = all_ids - base
    owned = (local >= 0) & (local < shard_rows)
    local = jnp.where(owned, local, 0)
    rows = table_shard[local] * owned[..., None].astype(table_shard.dtype)
    return lax.psum_scatter(rows, ROW_AXIS, scatter_dimension=0, tiled=True)


def sharded_sparse_adagrad_update(
    table_shard: jax.Array,
    accum_shard: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    num_rows_global: int,
    decay: float = 1.0,
):
    """Sparse Adagrad on the local row shard from global per-occurrence grads.

    Dedup happens twice: locally (cheap, shrinks the all_gather payload's
    effective content) and again after gathering every chip's
    contributions, because the same row id can be touched by several
    micro-batches and Adagrad must see the fully summed gradient exactly
    once (the determinism the reference's Hogwild explicitly gave up —
    SURVEY.md §4.2).
    """
    D = table_shard.shape[-1]
    if axis_size(ROW_AXIS) == 1 and axis_size(DATA_AXIS) == 1:
        # 1×1 mesh: no peers to combine with — one dedup, straight to the
        # shard apply (exactly the single-device step's structure).
        guids, ggsum = dedup_rows(
            ids.reshape(-1), row_grads.reshape(-1, D), num_rows_global
        )
        return apply_shard_adagrad(
            table_shard, accum_shard, guids, ggsum, lr, 0, decay=decay
        )
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, D), num_rows_global)
    all_uids = lax.all_gather(uids, (DATA_AXIS, ROW_AXIS), tiled=True)  # [P*M]
    all_gsum = lax.all_gather(gsum, (DATA_AXIS, ROW_AXIS), tiled=True)  # [P*M, D]
    # Sentinel ids (num_rows_global) from short shards collapse into one
    # segment and are dropped again below.
    guids, ggsum = dedup_rows(all_uids, all_gsum, num_rows_global)

    base = lax.axis_index(ROW_AXIS) * table_shard.shape[0]
    return apply_shard_adagrad(
        table_shard, accum_shard, guids, ggsum, lr, base, decay=decay
    )


# --- lane-packed shard variants (ops/packed_table.py; DESIGN §6) ---------
#
# Same collectives, tile-aligned physical movement: the shard serves its
# rows from a lane-packed [VPs, 128] shard (wide gather + static slice
# extraction) and applies the update with one wide RMW per array instead
# of narrow partial-lane scatters.  Requires the shard's LOGICAL row count
# to be a multiple of rows_per_tile(D) (the padded-vocab helper in
# train_step guarantees it), so per-shard packing equals a row-block of
# the globally packed table and checkpoints stay layout-independent.


def packed_sharded_gather(
    packed_shard: jax.Array, ids: jax.Array, d: int, shard_logical_rows: int
) -> jax.Array:
    """sharded_gather on a lane-packed shard: [B_local, N, D] rows."""
    from fast_tffm_tpu.ops.packed_table import packed_gather

    if axis_size(ROW_AXIS) == 1:
        # One row shard: skip the identity collectives, keep the in-range
        # clamp-with-zero (see sharded_gather — without it OOB ids clamp
        # here where the mesh>1 path zeroes them).
        in_range = (ids >= 0) & (ids < shard_logical_rows)
        rows = packed_gather(packed_shard, jnp.where(in_range, ids, 0), d)
        return rows * in_range[..., None].astype(rows.dtype)
    all_ids = lax.all_gather(ids, ROW_AXIS, tiled=True)  # [R*B_local, N]
    local, owned = owned_local_ids(all_ids, shard_logical_rows, 0)
    rows = packed_gather(packed_shard, local, d)
    rows = rows * owned[..., None].astype(rows.dtype)
    return lax.psum_scatter(rows, ROW_AXIS, scatter_dimension=0, tiled=True)


def packed_sharded_update(
    packed_shard: jax.Array,
    accum_shard: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    num_rows_global: int,
    shard_logical_rows: int,
):
    """sharded_sparse_adagrad_update on a lane-packed shard.

    Local dedup + the same two-axis all_gather combine; the second dedup
    is SUBSUMED by the packed update's lane-space segment-sum (duplicate
    logical ids land in the same lanes of the same physical segment and
    sum there before the single RMW — Adagrad still sees the fully
    summed gradient exactly once per element).  Unowned and sentinel ids
    map past the last physical row and drop on scatter.
    """
    from fast_tffm_tpu.ops.packed_table import packed_sparse_adagrad_update, rows_per_tile

    D = row_grads.shape[-1]
    p = rows_per_tile(D)
    if axis_size(ROW_AXIS) == 1 and axis_size(DATA_AXIS) == 1:
        # 1×1 mesh: the packed update's lane-space segment-sum already
        # handles duplicate raw ids, so the local dedup + identity
        # collectives + owned mapping all vanish — this IS the
        # single-device packed sorted step.
        return packed_sparse_adagrad_update(
            packed_shard, accum_shard, ids, row_grads, lr
        )
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, D), num_rows_global)
    all_uids = lax.all_gather(uids, (DATA_AXIS, ROW_AXIS), tiled=True)
    all_gsum = lax.all_gather(gsum, (DATA_AXIS, ROW_AXIS), tiled=True)

    # Past-the-end sentinel: phys = vp -> dropped by the packed scatter.
    local, _ = owned_local_ids(all_uids, shard_logical_rows, packed_shard.shape[0] * p)
    return packed_sparse_adagrad_update(
        packed_shard, accum_shard, local, all_gsum, lr
    )


def packed_sharded_dense_update(
    packed_shard: jax.Array,
    accum_shard: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    shard_logical_rows: int,
    mode: str = "dense",
):
    """packed_sharded_update via scatter-ADD dedup — no sorts.

    The sorted path dedups locally before the all-gather only to keep
    Adagrad's sum-once semantics through its segment pipeline; the
    scatter-ADD paths get those semantics from the scatter itself
    (duplicates sum in flat order), so this path ships the RAW
    per-occurrence grads — the all-gather payload is the same [M, D]
    bytes either way — and each shard applies the ids it owns (unowned
    ids map past the last physical row and drop).  ``mode`` picks the
    tail: ``dense`` scatter-adds into a [VPs, 128] buffer + dense sweep;
    ``compact`` compacts touched rows sort-free (giant shards — DESIGN
    §6 round 5).  Every ROW replica sees the identical gathered arrays
    in the identical order, so the summed G (and hence the shard) is
    bit-consistent across replicas, and the whole update is
    bit-identical to the single-device step of the same mode on the same
    global batch (flat-order sums; test-pinned on the CPU mesh).
    """
    from fast_tffm_tpu.ops.packed_table import PACKED_UPDATE_FNS, rows_per_tile

    D = row_grads.shape[-1]
    p = rows_per_tile(D)
    update_fn = PACKED_UPDATE_FNS[mode]
    flat_ids = ids.reshape(-1)
    flat_g = row_grads.reshape(-1, D)
    one_shard = axis_size(ROW_AXIS) == 1
    if one_shard and axis_size(DATA_AXIS) == 1:
        # 1×1 mesh: no combine, no owned mapping (batch ids are already
        # in-range logical ids) — this IS the single-device packed step.
        return update_fn(packed_shard, accum_shard, flat_ids, flat_g, lr)
    all_ids = lax.all_gather(flat_ids, (DATA_AXIS, ROW_AXIS), tiled=True)
    all_g = lax.all_gather(flat_g, (DATA_AXIS, ROW_AXIS), tiled=True)
    if one_shard:
        # One row shard, several data peers: the combine is needed but
        # every gathered id is owned — skip the identity owned mapping.
        return update_fn(packed_shard, accum_shard, all_ids, all_g, lr)
    local, _ = owned_local_ids(all_ids, shard_logical_rows, packed_shard.shape[0] * p)
    return update_fn(packed_shard, accum_shard, local, all_g, lr)


# --- fused tile-row shard variants (ops/packed_table.py round 5) ----------
#
# Same collectives as the packed variants; the shard stores params + row
# accumulator in ONE [VPf_s, 128] fused array (stride D+1 slots), so the
# update's per-shard apply is one gather + one scatter.  Requires the
# shard's LOGICAL row count to be a multiple of fused_rows_per_tile(D)
# (train_step's packed_shard_meta handles the padding), so per-shard
# fusing equals a row-block of the globally fused table and checkpoints
# stay layout-independent.


def fused_sharded_gather(
    fused_shard: jax.Array, ids: jax.Array, d: int, shard_logical_rows: int
) -> jax.Array:
    """sharded_gather on a fused shard: [B_local, N, D] rows."""
    from fast_tffm_tpu.ops.packed_table import fused_gather

    if axis_size(ROW_AXIS) == 1:
        # One row shard: skip identity collectives, keep the in-range
        # clamp-with-zero (sharded_gather's mesh=1/mesh>1 invariant).
        in_range = (ids >= 0) & (ids < shard_logical_rows)
        rows = fused_gather(fused_shard, jnp.where(in_range, ids, 0), d)
        return rows * in_range[..., None].astype(rows.dtype)
    all_ids = lax.all_gather(ids, ROW_AXIS, tiled=True)
    local, owned = owned_local_ids(all_ids, shard_logical_rows, 0)
    rows = fused_gather(fused_shard, local, d)
    rows = rows * owned[..., None].astype(rows.dtype)
    return lax.psum_scatter(rows, ROW_AXIS, scatter_dimension=0, tiled=True)


def fused_sharded_update(
    fused_shard: jax.Array,
    ids: jax.Array,
    row_grads: jax.Array,
    lr: float,
    shard_logical_rows: int,
    mode: str = "compact",
    k_cap: int = 0,
):
    """packed_sharded_dense_update's fused twin: ship RAW per-occurrence
    grads (scatter-ADD dedup — the same all_gather payload), each shard
    applies the ids it owns through the fused tail (``mode``: dense |
    compact; compact honors ``k_cap``).  Unowned ids map past the last
    physical row and drop."""
    from fast_tffm_tpu.ops.packed_table import (
        apply_fused_update,
        fused_rows_per_tile,
    )

    D = row_grads.shape[-1]
    p = fused_rows_per_tile(D)

    def apply(shard, local_ids, g):
        return apply_fused_update(shard, local_ids, g, lr, mode, k_cap)

    flat_ids = ids.reshape(-1)
    flat_g = row_grads.reshape(-1, D)
    one_shard = axis_size(ROW_AXIS) == 1
    if one_shard and axis_size(DATA_AXIS) == 1:
        return apply(fused_shard, flat_ids, flat_g)
    all_ids = lax.all_gather(flat_ids, (DATA_AXIS, ROW_AXIS), tiled=True)
    all_g = lax.all_gather(flat_g, (DATA_AXIS, ROW_AXIS), tiled=True)
    if one_shard:
        return apply(fused_shard, all_ids, all_g)
    local, _ = owned_local_ids(all_ids, shard_logical_rows, fused_shard.shape[0] * p)
    return apply(fused_shard, local, all_g)
