"""Mesh-sharded train/predict steps via shard_map.

The distributed trainer, TPU-first: one jitted SPMD program per step over a
('data', 'row') mesh replaces the reference's ps/worker cluster
(`renyi533/fast_tffm` :: dist trainer: between-graph replication,
Supervisor, asynchronous Hogwild scatter-adds over gRPC).  Per step:

  gather:   ids all_gathered + rows psum_scattered over ROW_AXIS
            (parallel/embedding) — each parameter row crosses ICI once
  compute:  fused FM scorer + loss; the batch is split over BOTH mesh
            axes, so every chip scores a distinct micro-batch (no
            redundant compute on the row axis)
  combine:  all_gather over both axes of deduped sparse row grads +
            psum of dense grads — deterministic sync replacing Hogwild
            races
  update:   each row shard applies sparse Adagrad to its own rows

Semantics match trainer.py's single-device step exactly (tested on the
virtual 8-device CPU mesh), which is the determinism the reference gave up.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # JAX >= 0.6: top-level export, replication check spelled check_vma
    from jax import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # older JAX: experimental module, kwarg spelled check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """shard_map across the JAX compat break: one callsite spelling
    (``check_vma``), routed to whichever kwarg the installed JAX uses."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_SHARD_MAP_CHECK_KW: check_vma},
    )

from fast_tffm_tpu.models.base import Batch
from fast_tffm_tpu.optim import AdagradState, dense_adagrad_update
from fast_tffm_tpu.parallel.embedding import sharded_gather, sharded_sparse_adagrad_update
from fast_tffm_tpu.parallel.mesh import (
    DATA_AXIS,
    ROW_AXIS,
    pad_vocab,
    replicated,
    table_sharding,
)
from fast_tffm_tpu.trainer import TrainState, init_state

__all__ = [
    "init_sharded_state",
    "packed_shard_meta",
    "pack_sharded_on_device",
    "unpack_sharded_to_logical",
    "unpack_sharded_on_device",
    "make_sharded_train_step",
    "make_sharded_predict_step",
    "make_global_batch",
    "make_global_superbatch",
    "make_replicator",
    "local_mesh_devices",
    "WireGlobalConverter",
]


def make_global_batch(mesh: Mesh, parsed, w, *, with_fields: bool = True) -> Batch:
    """Assemble a GLOBAL batch from this process's local input shard.

    Multi-host input sharding: each process parses only rows
    [p·B_local, (p+1)·B_local) of every global batch (pipeline
    ``shard_block`` = B_local), then this stitches the per-process chunks
    into one global jax.Array per field — each process contributes exactly
    its addressable devices' slice, no cross-host data movement.  Works
    because make_mesh lays devices process-contiguously in (data, row)
    row-major order, so a process's slice of the leading batch dim is
    contiguous.
    """
    import numpy as np

    vec = NamedSharding(mesh, P(_BOTH))
    mat = NamedSharding(mesh, P(_BOTH, None))
    mk = jax.make_array_from_process_local_data
    fields = (
        np.ascontiguousarray(parsed.fields)
        if with_fields
        else np.zeros((parsed.fields.shape[0], 0), np.int32)
    )
    return Batch(
        labels=mk(vec, np.ascontiguousarray(parsed.labels)),
        ids=mk(mat, np.ascontiguousarray(parsed.ids.astype(np.int32, copy=False))),
        vals=mk(mat, np.ascontiguousarray(parsed.vals)),
        fields=mk(mat, fields),
        weights=mk(vec, np.ascontiguousarray(w)),
    )


def make_global_superbatch(mesh: Mesh, parsed_seq, w_seq, *, with_fields: bool = True) -> Batch:
    """make_global_batch for K stacked micro-batches: each process stacks
    ITS local chunks of K consecutive global batches into [K, B_local, ...]
    host arrays, then contributes them as its slice of the [K, B, ...]
    global superbatch (batch dim 1 sharded over both mesh axes, micro-step
    dim 0 unsharded — the scanned SPMD step slices dim 0 on device).  One
    stitch per K steps is the multi-host analog of the local path's one
    H2D per K steps."""
    import numpy as np

    vec = NamedSharding(mesh, P(None, _BOTH))
    mat = NamedSharding(mesh, P(None, _BOTH, None))
    mk = jax.make_array_from_process_local_data
    b_local = parsed_seq[0].labels.shape[0]
    fields = (
        np.stack([np.asarray(p.fields) for p in parsed_seq])
        if with_fields
        else np.zeros((len(parsed_seq), b_local, 0), np.int32)
    )
    return Batch(
        labels=mk(vec, np.stack([np.asarray(p.labels) for p in parsed_seq])),
        ids=mk(
            mat,
            np.stack(
                [p.ids.astype(np.int32, copy=False) for p in parsed_seq]
            ),
        ),
        vals=mk(mat, np.stack([np.asarray(p.vals) for p in parsed_seq])),
        fields=mk(mat, fields),
        weights=mk(vec, np.stack([np.asarray(w) for w in w_seq])),
    )


def make_replicator(mesh: Mesh):
    """Jitted identity gathering a (sharded) pytree to a fully-replicated
    layout — every process ends up holding the complete arrays.  This is
    what makes the npz single-writer checkpoint protocol possible on a
    multi-host pod: the sharded state replicates (one collective), then
    process 0 alone streams it to disk.  The memory bill is the full
    logical table per host, so it is the MODEST-table path — orbax stays
    the answer where the table exceeds one host (DESIGN §8)."""
    rep = NamedSharding(mesh, P())
    # ONE jitted identity per tree structure: a fresh jit per call would
    # recompile at every save boundary (a steady-state recompile the
    # telemetry sentinel rightly flags).
    cache: dict = {}

    def _replicate(tree):
        leaves, treedef = jax.tree.flatten(tree)
        fn = cache.get(treedef)
        if fn is None:
            fn = jax.jit(lambda *ls: ls, out_shardings=rep)
            cache[treedef] = fn
        return jax.tree.unflatten(treedef, fn(*leaves))

    return _replicate


def local_mesh_devices(mesh: Mesh) -> list:
    """This process's devices in GLOBAL mesh order, verified contiguous.

    The batch dim shards over (data, row) in mesh-flat order, so process
    p's addressable slice of a global batch is rows
    [p·B/P, (p+1)·B/P) exactly when its devices form one contiguous run
    of ``mesh.devices.flat`` — the layout make_mesh produces from jax's
    process-major device order, and the same assumption make_global_batch
    documents.  Raises loudly on exotic layouts rather than silently
    scrambling rows."""
    flat = list(mesh.devices.flat)
    pid = jax.process_index()
    idxs = [i for i, d in enumerate(flat) if d.process_index == pid]
    if not idxs or idxs != list(range(idxs[0], idxs[0] + len(idxs))):
        raise ValueError(
            "this process's devices are not contiguous in the mesh — "
            "host-local wire staging needs the process-contiguous layout "
            "make_mesh produces (use wire_format = arrays here)"
        )
    return [flat[i] for i in idxs]


class WireGlobalConverter:
    """Host-local packed-wire staging for the multi-host streamed path.

    Each host packs ITS local rows of every global (super)batch into one
    coalesced wire buffer, unpacks it on its own devices (PR 3's packed
    wire — per-host by construction), then donates the per-device shards
    straight into a global jax.Array (``make_array_from_single_device_
    arrays``) — the multi-host analog of make_global_batch with ~2-3×
    fewer H2D bytes per host and zero cross-host data movement.

    ``to_batch``-compatible (wraps data/wire.WireConverter, so the wire
    byte accounting feeds kind=input records unchanged).
    """

    def __init__(self, mesh: Mesh, spec, verify_ids: bool = True):
        import numpy as np

        from fast_tffm_tpu.data.wire import WireConverter

        self._mesh = mesh
        self._wire = WireConverter(spec, verify_ids)
        self._local_devs = local_mesh_devices(mesh)
        self._lmesh = Mesh(
            np.asarray(self._local_devs).reshape(len(self._local_devs)), ("b",)
        )
        self._nproc = jax.process_count()

    # WireConverter duck-type (training's InputStats reads these).
    @property
    def last_nbytes(self):
        return self._wire.last_nbytes

    @property
    def wire_bytes(self):
        return self._wire.wire_bytes

    @property
    def calls(self):
        return self._wire.calls

    def _globalize_leaf(self, x, batch_axis: int):
        lspec = [None] * x.ndim
        lspec[batch_axis] = "b"
        gspec = [None] * x.ndim
        gspec[batch_axis] = (DATA_AXIS, ROW_AXIS)
        lx = jax.device_put(x, NamedSharding(self._lmesh, P(*lspec)))
        by_dev = {s.device: s.data for s in lx.addressable_shards}
        gshape = list(x.shape)
        gshape[batch_axis] *= self._nproc
        return jax.make_array_from_single_device_arrays(
            tuple(gshape),
            NamedSharding(self._mesh, P(*gspec)),
            [by_dev[d] for d in self._local_devs],
        )

    def __call__(self, parsed, w):
        local = self._wire(parsed, w)  # local-device Batch ([B] or [K, B])
        batch_axis = 1 if isinstance(parsed, list) else 0
        return jax.tree.map(
            lambda x: self._globalize_leaf(x, batch_axis), local
        )


def _state_specs():
    return TrainState(
        table=P(ROW_AXIS, None),
        table_opt=AdagradState(P(ROW_AXIS, None)),
        dense=None,  # filled per-model (replicated)
        dense_opt=None,
        step=P(),
    )


_BOTH = (DATA_AXIS, ROW_AXIS)


def _batch_specs() -> Batch:
    # The batch splits over every chip (both mesh axes): compute is fully
    # data-parallel; only the table is row-sharded.
    return Batch(
        labels=P(_BOTH),
        ids=P(_BOTH, None),
        vals=P(_BOTH, None),
        fields=P(_BOTH, None),
        weights=P(_BOTH),
    )


def _pad_model_vocab(model, mesh: Mesh, pack: int = 1):
    """Round the table up so ROW_AXIS shards are equal (padded rows inert).

    ``pack`` > 1 additionally rounds each shard to a multiple of the
    lane-packing factor rows_per_tile(D), so per-shard packing equals a
    row-block of the globally packed table (checkpoints stay layout-free
    and the packed shard's physical rows divide exactly)."""
    import dataclasses

    rows = mesh.shape[ROW_AXIS] * pack
    padded = pad_vocab(model.vocabulary_size, rows)
    if padded == model.vocabulary_size:
        return model
    return dataclasses.replace(model, vocabulary_size=padded)


def init_sharded_state(
    model, mesh: Mesh, key, init_accumulator_value: float = 0.1,
    accumulator: str = "element", table_layout: str = "rows",
):
    """init_state placed with row-sharded table and replicated dense params.

    ``table_layout='packed'`` stores the shards lane-packed
    ([VP_shard, 128] each — ops/packed_table.py); the shard-aligned vocab
    padding makes the global packed array exactly the concatenation of the
    per-shard packings.  ``accumulator='row'`` with the packed layout
    packs the [V, 1] accumulator as [VP_shard, P] scalar slots;
    ``accumulator='fused'`` stores the row accumulator inside the table's
    own tile rows ([VPf_shard, 128], stride D+1 — the 2-random-op RMW)."""
    if table_layout == "packed":
        from fast_tffm_tpu.trainer import pack_state

        fused = accumulator == "fused"
        model, _, _ = packed_shard_meta(model, mesh, fused=fused)
        state = pack_state(
            init_state(model, key, init_accumulator_value, accumulator),
            init_accumulator_value,
            fused=fused,
        )
    else:
        model = _pad_model_vocab(model, mesh)
        state = init_state(model, key, init_accumulator_value, accumulator)
    ts = table_sharding(mesh)
    rep = replicated(mesh)
    return TrainState(
        table=jax.device_put(state.table, ts),
        table_opt=AdagradState(jax.device_put(state.table_opt.accum, ts)),
        dense=jax.tree.map(lambda x: jax.device_put(x, rep), state.dense),
        dense_opt=jax.tree.map(lambda x: jax.device_put(x, rep), state.dense_opt),
        step=jax.device_put(state.step, rep),
    )


def packed_shard_meta(model, mesh: Mesh, fused: bool = False):
    """(padded_model, shard_logical_rows, rows_per_tile) for the packed
    sharded layout — the one place its padding arithmetic lives.
    ``fused`` switches to the fused tile-row pack factor (stride D+1)."""
    from fast_tffm_tpu.ops.packed_table import fused_rows_per_tile, rows_per_tile

    p = fused_rows_per_tile(model.row_dim) if fused else rows_per_tile(model.row_dim)
    padded = _pad_model_vocab(model, mesh, pack=p)
    return padded, padded.vocabulary_size // mesh.shape[ROW_AXIS], p


def unpack_sharded_to_logical(state: TrainState, model, mesh: Mesh) -> TrainState:
    """Lane-packed row-sharded state -> host LOGICAL [V, D] arrays
    (per-shard unpack; checkpoints always hold the logical layout).

    The unpack itself runs in PURE NUMPY on the fetched host copy — the
    whole point of this path (the single-process save route, ADVICE r4)
    is to avoid device-memory transients next to the live packed state,
    so nothing here may round-trip through jnp.  The FUSED layout is
    recognized by its empty-accumulator sentinel (pack_state) and
    unpacks to the logical ([V, D] table, [V, 1] accumulator) pair."""
    import numpy as np

    from fast_tffm_tpu.ops.packed_table import LANES, rows_per_tile

    R = mesh.shape[ROW_AXIS]
    d = model.row_dim
    fused = state.table_opt.accum.size == 0
    _, shard_logical, p = packed_shard_meta(model, mesh, fused=fused)

    def shards(arr):
        a = np.asarray(arr)
        per = a.shape[0] // R
        return [a[r * per : (r + 1) * per] for r in range(R)]

    if fused:
        d1 = d + 1
        tabs, accs = [], []
        for a in shards(state.table):  # numpy twin of unpack_fused
            flat = a[:, : p * d1].reshape(a.shape[0] * p, d1)[:shard_logical]
            tabs.append(flat[:, :d])
            accs.append(flat[:, d:])
        return state._replace(
            table=np.concatenate(tabs),
            table_opt=state.table_opt._replace(accum=np.concatenate(accs)),
        )

    def unp_table(a):  # numpy twin of ops.packed_table.unpack_table
        return a[:, : p * d].reshape(a.shape[0] * p, d)[:shard_logical]

    def unp_accum(a):  # numpy twin of unpack_accum_any (same trailing-dim sniff)
        if a.shape[-1] == LANES and rows_per_tile(d) != LANES:
            return unp_table(a)
        q = a.shape[-1]
        return a.reshape(a.shape[0] * q, 1)[:shard_logical]

    return state._replace(
        table=np.concatenate([unp_table(a) for a in shards(state.table)]),
        table_opt=state.table_opt._replace(
            accum=np.concatenate(
                [unp_accum(a) for a in shards(state.table_opt.accum)]
            )
        ),
    )


from functools import lru_cache


@lru_cache(maxsize=32)
def _packed_io_fns(
    mesh: Mesh, shard_logical: int, d: int, init_value: float,
    fused: bool = False,
):
    """Jitted per-shard pack/unpack transforms for one (mesh, layout)
    combination, built ONCE and cached: dist_saveable calls the unpack at
    every checkpoint save, and rebuilding shard_map around fresh lambdas
    each time would retrace and recompile per save.  Mesh is hashable;
    the cache key pins everything the traces close over."""
    from fast_tffm_tpu.ops.packed_table import (
        pack_accum_any,
        pack_fused,
        pack_table,
        unpack_accum_any,
        unpack_fused,
        unpack_table,
    )

    spec = P(ROW_AXIS, None)

    def mapped(fn, n_in=1, n_out=1):
        return jax.jit(
            shard_map(
                fn, mesh=mesh,
                in_specs=spec if n_in == 1 else (spec,) * n_in,
                out_specs=spec if n_out == 1 else (spec,) * n_out,
                check_vma=False,
            )
        )

    if fused:
        return {
            "unpack_fused": mapped(
                lambda s: unpack_fused(s, shard_logical, d), n_out=2
            ),
            "pack_fused": mapped(
                lambda t, a: pack_fused(t, a, init_value), n_in=2
            ),
        }
    return {
        "unpack_table": mapped(lambda s: unpack_table(s, shard_logical, d)),
        "unpack_accum": mapped(lambda s: unpack_accum_any(s, shard_logical, d)),
        "pack_table": mapped(pack_table),
        "pack_accum": mapped(lambda s: pack_accum_any(s, d, init_value)),
    }


def unpack_sharded_on_device(state: TrainState, model, mesh: Mesh) -> TrainState:
    """Lane-packed row-sharded state -> LOGICAL row-sharded state, each
    shard unpacked ON ITS OWN DEVICES under shard_map — no host gather,
    so it works on multi-host meshes where ``unpack_sharded_to_logical``
    cannot (its np.asarray would touch non-addressable shards).  The
    result's logical table is [Vpad, D] row-sharded with the same mesh
    placement, ready for the sharded (orbax) checkpoint writer: every
    host saves only its own unpacked shards, which is exactly the
    per-process logical<->packed checkpoint assembly multi-host packed
    runs need.  Shard-aligned padding (packed_shard_meta) makes the
    concatenation of per-shard unpacks equal the global unpack.  A FUSED
    state (empty-accumulator sentinel) unpacks through unpack_fused."""
    fused = state.table_opt.accum.size == 0
    _, shard_logical, _ = packed_shard_meta(model, mesh, fused=fused)
    fns = _packed_io_fns(mesh, shard_logical, model.row_dim, 0.0, fused=fused)
    if fused:
        t, a = fns["unpack_fused"](state.table)
        return state._replace(
            table=t, table_opt=state.table_opt._replace(accum=a)
        )
    return state._replace(
        table=fns["unpack_table"](state.table),
        table_opt=state.table_opt._replace(
            accum=fns["unpack_accum"](state.table_opt.accum)
        ),
    )


def pack_sharded_on_device(
    logical: TrainState, model, mesh: Mesh, init_accumulator_value: float = 0.1,
    fused: bool = False,
) -> TrainState:
    """Inverse of ``unpack_sharded_on_device``: a LOGICAL row-sharded
    state (e.g. a checkpoint restored in place onto the packed-aligned
    padding — see ``packed_shard_meta``) -> lane-packed row-sharded
    state, packed per shard on its own devices.  Multi-host safe for the
    same reason: no host materialization of the global table.  ``fused``
    packs into the fused tile-row layout (the caller knows the target
    layout from its config; the logical input looks identical either way)."""
    _, shard_logical, _ = packed_shard_meta(model, mesh, fused=fused)
    if logical.table.shape[0] != shard_logical * mesh.shape[ROW_AXIS]:
        raise ValueError(
            f"pack_sharded_on_device needs the packed-aligned padded vocab "
            f"({shard_logical * mesh.shape[ROW_AXIS]} rows), got "
            f"{logical.table.shape[0]} — restore onto a template built from "
            "packed_shard_meta's padded model"
        )
    fns = _packed_io_fns(
        mesh, shard_logical, model.row_dim, float(init_accumulator_value),
        fused=fused,
    )
    if fused:
        return logical._replace(
            table=fns["pack_fused"](logical.table, logical.table_opt.accum),
            table_opt=logical.table_opt._replace(
                accum=jnp.zeros((0, 1), logical.table.dtype)
            ),
        )
    return logical._replace(
        table=fns["pack_table"](logical.table),
        table_opt=logical.table_opt._replace(
            accum=fns["pack_accum"](logical.table_opt.accum)
        ),
    )


def _make_gather(
    mesh: Mesh, local_ids_shape, lookup: str, capacity_factor: float,
    packed_meta=None, fused: bool = False,
):
    """Pick the lookup collective: all-gather (default) or all-to-all routing.

    ``local_ids_shape`` is the PER-CHIP [B_local, N] shape (this is called
    from inside the shard_map body at trace time).  ``packed_meta`` is
    ``(d_row, shard_logical_rows)`` when the shards are lane-packed
    (``fused``: the fused tile-row layout) —
    routing is identical, only the local serve reads the packed layout.
    Returns ``(gather_fn, capacity, can_overflow)`` — capacity is None on
    the all-gather path and is THE single sizing both all-to-all
    directions share (the routed update must use the same value);
    ``can_overflow`` is False when the capacity caps at M = ids-per-chip
    (every id fits one bucket, so overflow is statically impossible and
    callers may skip the per-step routing_overflow check and its lax.cond
    dual-compile)."""
    if lookup == "allgather":
        if packed_meta is not None:
            from fast_tffm_tpu.parallel.embedding import (
                fused_sharded_gather,
                packed_sharded_gather,
            )

            d_row, slr = packed_meta
            g = fused_sharded_gather if fused else packed_sharded_gather
            return (lambda table, ids: g(table, ids, d_row, slr)), None, False
        return sharded_gather, None, False
    if lookup != "alltoall":
        raise ValueError(f"unknown lookup {lookup!r} (allgather | alltoall)")
    from fast_tffm_tpu.parallel.alltoall import capacity_for, routed_gather

    b_local, n = local_ids_shape
    m = b_local * n
    cap = capacity_for(m, mesh.shape[ROW_AXIS], capacity_factor)
    if packed_meta is not None:
        d_row, slr = packed_meta
        return (
            lambda table, ids: routed_gather(
                table, ids, cap, d=d_row, shard_logical_rows=slr, fused=fused
            )
        ), cap, cap < m
    return (lambda table, ids: routed_gather(table, ids, cap)), cap, cap < m


def make_sharded_train_step(
    model, learning_rate: float, mesh: Mesh, *, lookup: str = "allgather",
    capacity_factor: float = 2.0, overflow_mode: str = "abort",
    table_layout: str = "rows", packed_update: str = "auto",
    accumulator: str = "element", compact_cap: int = 0,
    steps_per_call: int = 1, adagrad_decay: float = 1.0,
):
    """Returns jitted SPMD ``step(state, batch) -> (state, global mean loss)``.

    ``steps_per_call`` > 1 returns the scan-fused form instead:
    ``step(state, superbatch) -> (state, losses [K])`` where every
    ``superbatch`` field carries a leading micro-step dim ([K, B], ...;
    make_global_superbatch builds it) and ``lax.scan`` wraps the SAME
    shard_map body — one dispatch launches K SPMD steps, so pod runs
    amortize per-step dispatch exactly like the local paths.  K is read
    from the input shape (the epoch-tail remainder superbatch compiles its
    own executable).  Under ``fallback`` the return is
    ``(state, losses [K], overflow_steps)`` with the per-step flags SUMMED
    into one replicated int32 (drivers only count them).  Per-step losses
    and the final state are bit-identical to K sequential K=1 steps
    (test-pinned).

    Batch arrays must have leading dim divisible by the total device count
    (the batch splits over both mesh axes).  ``lookup`` picks the embedding
    collective for BOTH directions: ``allgather`` (default; robust to any
    id skew) or ``alltoall`` (SparseCore-style routing for the lookup AND
    the gradient update — ~R× fewer ICI bytes each way; needs
    near-uniform ids, see parallel/alltoall.py).

    ``overflow_mode`` (alltoall only) decides what a capacity overflow
    does.  ``abort``: affected rows NaN-poison and the loss goes NaN (the
    caller stops before checkpointing).  ``fallback``: the whole step
    reruns through the allgather collectives under ``lax.cond`` — the
    overflow flag is psum'd, so every chip takes the same branch, the
    step's result is exactly the allgather step's, and training continues
    deterministically; the step then returns ``(state, loss, overflowed)``
    with a replicated int32 flag so the driver can count skew events.

    Note the defaults differ by layer on purpose: the CONFIG default
    (``lookup_overflow = fallback``, what the train/predict drivers pass)
    is the operationally-kind choice, while this bare function defaults to
    ``abort`` so direct library callers keep the uniform
    ``(state, loss)`` return signature unless they opt into the flagged
    3-tuple.
    """
    packed = table_layout == "packed"
    fused = accumulator == "fused"
    if fused and not packed:
        raise ValueError("accumulator='fused' requires table_layout='packed'")
    if packed:
        model, shard_logical_rows, _ = packed_shard_meta(model, mesh, fused=fused)
    else:
        model = _pad_model_vocab(model, mesh)
        shard_logical_rows = model.vocabulary_size // mesh.shape[ROW_AXIS]
    num_rows_global = model.vocabulary_size
    d_row = model.row_dim
    if overflow_mode not in ("abort", "fallback"):
        raise ValueError(f"unknown overflow_mode {overflow_mode!r} (abort | fallback)")
    fallback = lookup == "alltoall" and overflow_mode == "fallback"
    packed_meta = (d_row, shard_logical_rows) if packed else None
    # [Online] adagrad_decay: touched-row accumulator decay, rows layout
    # only (config.validate enforces the restriction — the packed tile-row
    # RMWs rely on the zero-grad identity a lane-blind decay would break).
    # γ=1.0 is a trace-time no-op, so the default program is unchanged.
    decay = float(adagrad_decay)
    if decay != 1.0 and (packed or fused):
        raise ValueError(
            "adagrad_decay != 1.0 requires table_layout = rows (the packed "
            "tile-row updates rely on the zero-grad accumulator identity)"
        )

    def shard_body(table, accum, dense, dense_acc, batch: Batch):
        # Built per trace: the capacity is sized from THIS trace's batch
        # shape (a cached closure would pin a stale capacity across jit
        # retraces with bigger batches and spuriously overflow).
        gather, cap, can_overflow = _make_gather(
            mesh, batch.ids.shape, lookup, capacity_factor, packed_meta,
            fused=fused,
        )

        def loss_fn(rows, dense):
            scores = model.score(rows, dense, batch)
            per = (
                jnp.maximum(scores, 0.0)
                - scores * batch.labels
                + jnp.log1p(jnp.exp(-jnp.abs(scores)))
            )
            denom = jnp.maximum(lax.psum(jnp.sum(batch.weights), _BOTH), 1.0)
            data_loss = jnp.sum(per * batch.weights) / denom
            reg = model.regularization(rows, dense, batch)
            return data_loss + reg, data_loss

        grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)

        def allgather_branch():
            if fused:
                from fast_tffm_tpu.ops.packed_table import resolve_fused_update
                from fast_tffm_tpu.parallel.embedding import (
                    fused_sharded_gather,
                    fused_sharded_update,
                )

                rows = fused_sharded_gather(
                    table, batch.ids, d_row, shard_logical_rows
                )
                (_, dl), (g_rows, g_dense) = grad_fn(rows, dense)
                fmode = resolve_fused_update(packed_update, table.shape[0])
                t2 = fused_sharded_update(
                    table, batch.ids, g_rows, learning_rate,
                    shard_logical_rows, mode=fmode, k_cap=compact_cap,
                )
                return t2, accum, g_dense, dl
            if packed:
                from fast_tffm_tpu.ops.packed_table import resolve_packed_update
                from fast_tffm_tpu.parallel.embedding import (
                    packed_sharded_dense_update,
                    packed_sharded_gather,
                    packed_sharded_update,
                )

                rows = packed_sharded_gather(
                    table, batch.ids, d_row, shard_logical_rows
                )
                (_, dl), (g_rows, g_dense) = grad_fn(rows, dense)
                mode = resolve_packed_update(
                    packed_update, table.shape[0], accum.shape[-1]
                )
                if mode in ("dense", "compact"):
                    t2, a2 = packed_sharded_dense_update(
                        table, accum, batch.ids, g_rows, learning_rate,
                        shard_logical_rows, mode=mode,
                    )
                else:
                    t2, a2 = packed_sharded_update(
                        table, accum, batch.ids, g_rows, learning_rate,
                        num_rows_global, shard_logical_rows,
                    )
                return t2, a2, g_dense, dl
            rows = sharded_gather(table, batch.ids)
            (_, dl), (g_rows, g_dense) = grad_fn(rows, dense)
            t2, a2 = sharded_sparse_adagrad_update(
                table, accum, batch.ids, g_rows, learning_rate,
                num_rows_global, decay=decay,
            )
            return t2, a2, g_dense, dl

        if lookup == "alltoall":
            from fast_tffm_tpu.parallel.alltoall import routed_update, routing_overflow

            def routed_branch():
                rows = gather(table, batch.ids)
                (_, dl), (g_rows, g_dense) = grad_fn(rows, dense)
                if fused:
                    from fast_tffm_tpu.ops.packed_table import resolve_fused_update

                    fmode = resolve_fused_update(packed_update, table.shape[0])
                    t2, a2, overflow = routed_update(
                        table, accum, batch.ids, g_rows, learning_rate,
                        num_rows_global, cap,
                        shard_logical_rows=shard_logical_rows, packed_mode=fmode,
                        fused=True, compact_cap=compact_cap,
                    )
                elif packed:
                    from fast_tffm_tpu.ops.packed_table import resolve_packed_update

                    pmode = resolve_packed_update(
                        packed_update, table.shape[0], accum.shape[-1]
                    )
                    t2, a2, overflow = routed_update(
                        table, accum, batch.ids, g_rows, learning_rate,
                        num_rows_global, cap,
                        shard_logical_rows=shard_logical_rows, packed_mode=pmode,
                    )
                else:
                    t2, a2, overflow = routed_update(
                        table, accum, batch.ids, g_rows, learning_rate,
                        num_rows_global, cap, decay=decay,
                    )
                if not fallback:
                    # A dropped contribution must never persist silently:
                    # NaN the loss so the training loop aborts before
                    # checkpointing.
                    dl = jnp.where(overflow, jnp.nan, dl)
                return t2, a2, g_dense, dl

            # When overflow is statically impossible, emit the routed branch
            # alone — no bincount, no dual compile (HLO-pinned by
            # test_impossible_overflow_skips_cond).
            if fallback and can_overflow:
                # shard_logical_rows == table.shape[0] for the rows layout;
                # for packed shards the table's leading dim is PHYSICAL, so
                # the closure's logical count is the correct one either way.
                overflowed = routing_overflow(batch.ids, shard_logical_rows, cap)
                table, accum, g_dense, data_loss_local = lax.cond(
                    overflowed, allgather_branch, routed_branch
                )
            else:
                table, accum, g_dense, data_loss_local = routed_branch()
                overflowed = jnp.asarray(False)
        else:
            table, accum, g_dense, data_loss_local = allgather_branch()
            overflowed = jnp.asarray(False)
        if jax.tree.leaves(dense):
            g_dense = lax.psum(g_dense, _BOTH)
            dense, dense_acc = dense_adagrad_update(
                dense, AdagradState(dense_acc), g_dense, learning_rate,
                decay=decay,
            )
            dense_acc = dense_acc.accum
        data_loss = lax.psum(data_loss_local, _BOTH)
        return table, accum, dense, dense_acc, data_loss, overflowed.astype(jnp.int32)

    dense_spec = jax.tree.map(lambda _: P(), model.init_dense(jax.random.key(0)))
    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(ROW_AXIS, None),
            P(ROW_AXIS, None),
            dense_spec,
            dense_spec,
            _batch_specs(),
        ),
        out_specs=(
            P(ROW_AXIS, None), P(ROW_AXIS, None), dense_spec, dense_spec, P(), P(),
        ),
        check_vma=False,
    )

    def _apply(state: TrainState, batch: Batch):
        table, accum, dense, dense_acc, loss, overflowed = mapped(
            state.table, state.table_opt.accum, state.dense, state.dense_opt.accum, batch
        )
        new = TrainState(
            table, AdagradState(accum), dense, AdagradState(dense_acc), state.step + 1
        )
        return new, loss, overflowed

    if steps_per_call <= 1:

        @partial(jax.jit, donate_argnums=(0,))
        def step(state: TrainState, batch: Batch):
            new, loss, overflowed = _apply(state, batch)
            if fallback:
                return new, loss, overflowed
            return new, loss

    else:

        @partial(jax.jit, donate_argnums=(0,))
        def step(state: TrainState, superbatch: Batch):
            def one(st, b):
                new, loss, overflowed = _apply(st, b)
                return new, (loss, overflowed)

            state, (losses, ovfs) = lax.scan(one, state, superbatch)
            if fallback:
                return state, losses, jnp.sum(ovfs)
            return state, losses

    # The cached-dataset wrapper (make_cached_sharded_train_step) must
    # mirror the flagged signature without re-deriving the config.
    try:
        step.overflow_flagged = fallback
    except AttributeError:  # jit wrapper without settable attributes
        pass
    return step


def make_sharded_predict_step(
    model, mesh: Mesh, *, lookup: str = "allgather", capacity_factor: float = 2.0,
    overflow_mode: str = "abort", table_layout: str = "rows",
    accumulator: str = "element",
):
    """Returns jitted SPMD ``predict(state, batch) -> sigmoid scores [B]``.

    ``overflow_mode='fallback'`` (alltoall only) reruns an overflowing
    batch's lookup through the allgather collective instead of NaN-ing the
    scores — same ``lax.cond`` scheme as the train step.
    ``accumulator='fused'`` reads the fused tile-row table (the state a
    fused dist_train holds mid-run); _make_gather routes both lookups."""
    packed = table_layout == "packed"
    fused = accumulator == "fused"
    if packed:
        model, shard_logical_rows, _ = packed_shard_meta(model, mesh, fused=fused)
    else:
        model = _pad_model_vocab(model, mesh)
        shard_logical_rows = model.vocabulary_size // mesh.shape[ROW_AXIS]
    d_row = model.row_dim
    fallback = lookup == "alltoall" and overflow_mode == "fallback"
    packed_meta = (d_row, shard_logical_rows) if packed else None

    def shard_body(table, dense, batch: Batch):
        gather, cap, can_overflow = _make_gather(
            mesh, batch.ids.shape, lookup, capacity_factor, packed_meta,
            fused=fused,
        )
        if fallback and can_overflow:
            from fast_tffm_tpu.parallel.alltoall import routing_overflow

            # The allgather fallback is exactly _make_gather's allgather
            # selection (packed-aware) — build it there, not by hand.
            ag_gather, _, _ = _make_gather(
                mesh, batch.ids.shape, "allgather", capacity_factor, packed_meta,
                fused=fused,
            )
            rows = lax.cond(
                routing_overflow(batch.ids, shard_logical_rows, cap),
                lambda: ag_gather(table, batch.ids),
                lambda: gather(table, batch.ids),
            )
        else:
            rows = gather(table, batch.ids)
        scores = jax.nn.sigmoid(model.score(rows, dense, batch))
        # Replicate the (tiny, [B]) score vector so the result is fetchable
        # on every process of a multi-host mesh — a P(('data','row'))-sharded
        # output would span non-addressable devices there.
        return lax.all_gather(scores, _BOTH, tiled=True)

    dense_spec = jax.tree.map(lambda _: P(), model.init_dense(jax.random.key(0)))
    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(ROW_AXIS, None), dense_spec, _batch_specs()),
        out_specs=P(),
        check_vma=False,
    )

    @jax.jit
    def predict(state: TrainState, batch: Batch):
        return mapped(state.table, state.dense, batch)

    return predict
