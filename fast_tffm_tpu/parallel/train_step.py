"""Mesh-sharded train/predict steps via shard_map.

The distributed trainer, TPU-first: one jitted SPMD program per step over a
('data', 'row') mesh replaces the reference's ps/worker cluster
(`renyi533/fast_tffm` :: dist trainer: between-graph replication,
Supervisor, asynchronous Hogwild scatter-adds over gRPC).  Per step:

  gather:   psum over ROW_AXIS assembles touched rows (parallel/embedding)
  compute:  fused FM scorer + loss, batch split over DATA_AXIS
  combine:  all_gather(DATA_AXIS) of deduped sparse row grads +
            psum(DATA_AXIS) of dense grads — deterministic sync replacing
            Hogwild races
  update:   each row shard applies sparse Adagrad to its own rows

Semantics match trainer.py's single-device step exactly (tested on the
virtual 8-device CPU mesh), which is the determinism the reference gave up.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from fast_tffm_tpu.models.base import Batch
from fast_tffm_tpu.optim import AdagradState, dense_adagrad_update
from fast_tffm_tpu.parallel.embedding import sharded_gather, sharded_sparse_adagrad_update
from fast_tffm_tpu.parallel.mesh import (
    DATA_AXIS,
    ROW_AXIS,
    batch_sharding,
    pad_vocab,
    replicated,
    table_sharding,
)
from fast_tffm_tpu.trainer import TrainState, init_state

__all__ = ["init_sharded_state", "make_sharded_train_step", "make_sharded_predict_step"]


def _state_specs():
    return TrainState(
        table=P(ROW_AXIS, None),
        table_opt=AdagradState(P(ROW_AXIS, None)),
        dense=None,  # filled per-model (replicated)
        dense_opt=None,
        step=P(),
    )


def _batch_specs() -> Batch:
    return Batch(
        labels=P(DATA_AXIS),
        ids=P(DATA_AXIS, None),
        vals=P(DATA_AXIS, None),
        fields=P(DATA_AXIS, None),
        weights=P(DATA_AXIS),
    )


def _pad_model_vocab(model, mesh: Mesh):
    """Round the table up so ROW_AXIS shards are equal (padded rows inert)."""
    import dataclasses

    rows = mesh.shape[ROW_AXIS]
    padded = pad_vocab(model.vocabulary_size, rows)
    if padded == model.vocabulary_size:
        return model
    return dataclasses.replace(model, vocabulary_size=padded)


def init_sharded_state(model, mesh: Mesh, key, init_accumulator_value: float = 0.1):
    """init_state placed with row-sharded table and replicated dense params."""
    model = _pad_model_vocab(model, mesh)
    state = init_state(model, key, init_accumulator_value)
    ts = table_sharding(mesh)
    rep = replicated(mesh)
    return TrainState(
        table=jax.device_put(state.table, ts),
        table_opt=AdagradState(jax.device_put(state.table_opt.accum, ts)),
        dense=jax.tree.map(lambda x: jax.device_put(x, rep), state.dense),
        dense_opt=jax.tree.map(lambda x: jax.device_put(x, rep), state.dense_opt),
        step=jax.device_put(state.step, rep),
    )


def make_sharded_train_step(model, learning_rate: float, mesh: Mesh):
    """Returns jitted SPMD ``step(state, batch) -> (state, global mean loss)``.

    Batch arrays must have leading dim divisible by mesh.shape['data'].
    """
    model = _pad_model_vocab(model, mesh)
    num_rows_global = model.vocabulary_size
    from fast_tffm_tpu.trainer import batch_loss

    def shard_body(table, accum, dense, dense_acc, batch: Batch):
        rows = sharded_gather(table, batch.ids)

        def loss_fn(rows, dense):
            scores = model.score(rows, dense, batch)
            per = (
                jnp.maximum(scores, 0.0)
                - scores * batch.labels
                + jnp.log1p(jnp.exp(-jnp.abs(scores)))
            )
            denom = jnp.maximum(lax.psum(jnp.sum(batch.weights), DATA_AXIS), 1.0)
            data_loss = jnp.sum(per * batch.weights) / denom
            reg = model.regularization(rows, dense, batch)
            return data_loss + reg, data_loss

        grad_fn = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)
        (_, data_loss_local), (g_rows, g_dense) = grad_fn(rows, dense)

        table, accum = sharded_sparse_adagrad_update(
            table, accum, batch.ids, g_rows, learning_rate, num_rows_global
        )
        if jax.tree.leaves(dense):
            g_dense = lax.psum(g_dense, DATA_AXIS)
            dense, dense_acc = dense_adagrad_update(
                dense, AdagradState(dense_acc), g_dense, learning_rate
            )
            dense_acc = dense_acc.accum
        data_loss = lax.psum(data_loss_local, DATA_AXIS)
        return table, accum, dense, dense_acc, data_loss

    dense_spec = jax.tree.map(lambda _: P(), model.init_dense(jax.random.key(0)))
    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(ROW_AXIS, None),
            P(ROW_AXIS, None),
            dense_spec,
            dense_spec,
            _batch_specs(),
        ),
        out_specs=(P(ROW_AXIS, None), P(ROW_AXIS, None), dense_spec, dense_spec, P()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, batch: Batch):
        table, accum, dense, dense_acc, loss = mapped(
            state.table, state.table_opt.accum, state.dense, state.dense_opt.accum, batch
        )
        return (
            TrainState(table, AdagradState(accum), dense, AdagradState(dense_acc), state.step + 1),
            loss,
        )

    return step


def make_sharded_predict_step(model, mesh: Mesh):
    """Returns jitted SPMD ``predict(state, batch) -> sigmoid scores [B]``."""
    model = _pad_model_vocab(model, mesh)

    def shard_body(table, dense, batch: Batch):
        rows = sharded_gather(table, batch.ids)
        return jax.nn.sigmoid(model.score(rows, dense, batch))

    dense_spec = jax.tree.map(lambda _: P(), model.init_dense(jax.random.key(0)))
    mapped = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(ROW_AXIS, None), dense_spec, _batch_specs()),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )

    @jax.jit
    def predict(state: TrainState, batch: Batch):
        return mapped(state.table, state.dense, batch)

    return predict
