"""All-to-all routed embedding lookup (SparseCore-style id routing).

Optional alternative to ``embedding.sharded_gather`` (config key
``lookup = alltoall``).  The default all-gather scheme ships every chip's
full masked ``[R·B_local, N, D]`` contribution through ``psum_scatter`` —
R× the minimal bytes, because each row has exactly one owner.  Here each
chip instead routes its ids to their home shards and gets back only its
own rows:

  1. owner = id // shard_rows (contiguous row shards, same layout as the
     all-gather path — checkpoints are interchangeable);
  2. ids sort by owner into a ``[R, C]`` send buffer (C = capacity per
     destination), `lax.all_to_all` delivers each shard its requests;
  3. each shard serves its rows locally and a second all_to_all returns
     them; an inverse permutation restores batch order.

ICI bytes per chip: ~2·R·C·D ≈ 2·slack·M·D instead of R·M·D — an
~(R/2·slack)× reduction that grows with the mesh (R=64 on a v5e-64).

**Capacity and skew.**  Static shapes force a fixed per-destination
capacity C = ceil(capacity_factor · M / R).  With ``hash_feature_id``
(the 10B-row regime this path exists for) ids are uniform and
capacity_factor=2 overflows with negligible probability.  Zipf-skewed
RAW ids on contiguous shards can overflow; overflow is NEVER silent —
every affected row poisons to NaN, so the loss goes NaN on the first
overflowing step (test-pinned).  Raise capacity_factor or use the
default all-gather lookup for skewed id spaces.

These functions run INSIDE a shard_map body (parallel/train_step.py).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from fast_tffm_tpu.parallel.mesh import ROW_AXIS

__all__ = ["routed_gather", "capacity_for"]


def capacity_for(ids_per_chip: int, row_parallel: int, capacity_factor: float) -> int:
    """Per-destination slot count for M ids over R destinations.

    factor·M/R covers systematic imbalance; the additive 4·√(M/R) + 8 term
    covers the binomial tail, which dominates when M/R is small (without
    it, even uniform ids overflow a thin bucket with noticeable
    probability at toy sizes).  Rounded to a multiple of 8, capped at M
    (C = M can never overflow)."""
    mean = ids_per_chip / row_parallel
    c = int(capacity_factor * mean + 4.0 * mean**0.5 + 8.0)
    c = ((c + 7) // 8) * 8
    return max(8, min(c, ids_per_chip))


def routed_gather(table_shard: jnp.ndarray, ids: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """Assemble this chip's rows via all-to-all id routing.

    table_shard: [V/R, D] contiguous row shard.
    ids:         [B_local, N] global row ids for THIS chip's micro-batch.
    capacity:    static per-destination slot count (see capacity_for).
    Returns:     [B_local, N, D] rows (NaN-poisoned if any destination
                 overflowed its capacity — never silently wrong).
    """
    shard_rows = table_shard.shape[0]
    base = lax.axis_index(ROW_AXIS) * shard_rows
    R = lax.axis_size(ROW_AXIS)
    B, N = ids.shape
    M = B * N
    flat = ids.reshape(M)
    owner = flat // shard_rows  # [M] in [0, R)

    # Stable sort by owner; position of each element within its bucket.
    order = jnp.argsort(owner, stable=True)
    sorted_ids = flat[order]
    sorted_owner = owner[order]
    counts = jnp.bincount(owner, length=R)  # [R]
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(M) - starts[sorted_owner]  # [M] slot within bucket
    overflow = jnp.any(counts > capacity)

    # Scatter into the [R, C] send buffer; slots beyond capacity drop (their
    # rows are poisoned below), unused slots carry an out-of-range sentinel.
    sentinel = jnp.int32(shard_rows * R)
    send_ids = jnp.full((R, capacity), sentinel, dtype=flat.dtype)
    send_pos = jnp.where(pos < capacity, pos, capacity)  # capacity → dropped
    send_ids = send_ids.at[sorted_owner, send_pos].set(sorted_ids, mode="drop")

    # Exchange requests; serve locally; exchange answers.
    recv_ids = lax.all_to_all(send_ids, ROW_AXIS, 0, 0, tiled=True)  # [R, C]
    local = recv_ids - base
    ok = (local >= 0) & (local < shard_rows)  # sentinels fail
    served = table_shard[jnp.where(ok, local, 0)] * ok[..., None].astype(table_shard.dtype)
    recv_rows = lax.all_to_all(served, ROW_AXIS, 0, 0, tiled=True)  # [R, C, D]

    # recv_rows[s, c] answers MY request in send slot [s, c]; invert the
    # bucket placement, then the sort.
    in_cap = pos < capacity
    mine_sorted = recv_rows[sorted_owner, jnp.minimum(pos, capacity - 1)]
    mine_sorted = mine_sorted * in_cap[:, None].astype(mine_sorted.dtype)
    out = jnp.zeros((M, table_shard.shape[-1]), table_shard.dtype).at[order].set(mine_sorted)
    out = jnp.where(overflow, jnp.nan, out)
    return out.reshape(B, N, -1)
