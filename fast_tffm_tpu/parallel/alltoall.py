"""All-to-all routed embedding lookup (SparseCore-style id routing).

Optional alternative to ``embedding.sharded_gather`` (config key
``lookup = alltoall``).  The default all-gather scheme ships every chip's
full masked ``[R·B_local, N, D]`` contribution through ``psum_scatter`` —
R× the minimal bytes, because each row has exactly one owner.  Here each
chip instead routes its ids to their home shards and gets back only its
own rows:

  1. owner = id // shard_rows (contiguous row shards, same layout as the
     all-gather path — checkpoints are interchangeable);
  2. ids sort by owner into a ``[R, C]`` send buffer (C = capacity per
     destination), `lax.all_to_all` delivers each shard its requests;
  3. each shard serves its rows locally and a second all_to_all returns
     them; an inverse permutation restores batch order.

ICI bytes per chip: ~2·R·C·D ≈ 2·slack·M·D instead of R·M·D — an
~(R/2·slack)× reduction that grows with the mesh (R=64 on a v5e-64).

**Capacity and skew.**  Static shapes force a fixed per-destination
capacity C = ceil(capacity_factor · M / R).  With ``hash_feature_id``
(the 10B-row regime this path exists for) ids are uniform and
capacity_factor=2 overflows with negligible probability.  Zipf-skewed
RAW ids on contiguous shards can overflow; overflow is NEVER silent.
What happens next is the caller's ``lookup_overflow`` choice
(train_step.py): ``fallback`` (default) reruns the whole step through
the allgather collectives under ``lax.cond`` — deterministic, exactly
the allgather result, counted in the metrics — while ``abort`` poisons
every affected row to NaN so the loss goes NaN on the first overflowing
step and the run stops before checkpointing (both test-pinned).
``routing_overflow`` below is the globally-agreed predicate the
fallback branches on.

These functions run INSIDE a shard_map body (parallel/train_step.py).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from fast_tffm_tpu.parallel.mesh import DATA_AXIS, ROW_AXIS, axis_size

__all__ = ["routed_gather", "routed_update", "routing_overflow", "capacity_for"]


def routing_overflow(ids: jnp.ndarray, shard_rows: int, capacity: int):
    """GLOBAL flag: would routing this batch overflow any destination?

    Computed from the gather-direction bucket counts alone: the update
    direction buckets the DEDUPED ids, and per-owner unique counts can
    never exceed per-owner occurrence counts, so (with the shared
    capacity) "gather fits" implies "update fits".  The psum makes every
    chip agree — the caller can branch on it (lax.cond) without risking
    divergent collectives.
    """
    R = axis_size(ROW_AXIS)
    counts = jnp.bincount(ids.reshape(-1) // shard_rows, length=R)
    local = jnp.any(counts > capacity)
    return lax.psum(local.astype(jnp.int32), (DATA_AXIS, ROW_AXIS)) > 0


def capacity_for(ids_per_chip: int, row_parallel: int, capacity_factor: float) -> int:
    """Per-destination slot count for M ids over R destinations.

    factor·M/R covers systematic imbalance; the additive 4·√(M/R) + 8 term
    covers the binomial tail, which dominates when M/R is small (without
    it, even uniform ids overflow a thin bucket with noticeable
    probability at toy sizes).  Rounded to a multiple of 8, capped at M
    (C = M can never overflow)."""
    mean = ids_per_chip / row_parallel
    c = int(capacity_factor * mean + 4.0 * mean**0.5 + 8.0)
    c = ((c + 7) // 8) * 8
    return max(8, min(c, ids_per_chip))


def _bucketize(owner: jnp.ndarray, n_buckets: int, capacity: int):
    """Stable-sort elements by ``owner`` and assign each a send-buffer slot.

    Shared by the lookup and update routes (they must agree exactly —
    both directions use one capacity).  Owners >= n_buckets (sentinels)
    are excluded from counts and land on out-of-range scatter indices.

    Returns (order, sorted_owner, send_pos, in_cap_sorted, overflow):
    ``order`` is the sort permutation; element ``order[j]`` goes to slot
    ``[sorted_owner[j], send_pos[j]]`` (send_pos == capacity → caller
    scatters with mode='drop'); ``overflow`` is True when any bucket
    exceeded capacity."""
    m = owner.shape[0]
    order = jnp.argsort(owner, stable=True)
    sorted_owner = owner[order]
    counts = jnp.bincount(owner, length=n_buckets)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(m) - starts[jnp.minimum(sorted_owner, n_buckets - 1)]
    in_cap = pos < capacity
    send_pos = jnp.where(in_cap, pos, capacity)
    overflow = jnp.any(counts > capacity)
    return order, sorted_owner, send_pos, in_cap, overflow


def routed_gather(
    table_shard: jnp.ndarray,
    ids: jnp.ndarray,
    capacity: int,
    *,
    d: int | None = None,
    shard_logical_rows: int | None = None,
    fused: bool = False,
) -> jnp.ndarray:
    """Assemble this chip's rows via all-to-all id routing.

    table_shard: [V/R, D] contiguous row shard — or, when ``d`` is given,
                 a lane-packed [VPs, 128] shard (ops/packed_table.py) of
                 ``shard_logical_rows`` logical rows (``fused=True``: the
                 fused tile-row layout, accumulator lanes in-slot).  The
                 routing math is identical either way (ids are LOGICAL
                 everywhere); only the local serve step reads the layout,
                 via a wide full-tile-row gather instead of a narrow one.
    ids:         [B_local, N] global row ids for THIS chip's micro-batch.
    capacity:    static per-destination slot count (see capacity_for).
    Returns:     [B_local, N, D] rows (NaN-poisoned if any destination
                 overflowed its capacity — never silently wrong).
    """
    packed = d is not None
    shard_rows = shard_logical_rows if packed else table_shard.shape[0]
    base = lax.axis_index(ROW_AXIS) * shard_rows
    R = axis_size(ROW_AXIS)
    B, N = ids.shape
    M = B * N
    flat = ids.reshape(M)
    owner = flat // shard_rows  # [M] in [0, R)
    order, sorted_owner, send_pos, in_cap, overflow = _bucketize(owner, R, capacity)
    sorted_ids = flat[order]

    # Scatter into the [R, C] send buffer; slots beyond capacity drop (their
    # rows are poisoned below), unused slots carry an out-of-range sentinel.
    sentinel = jnp.int32(shard_rows * R)
    send_ids = jnp.full((R, capacity), sentinel, dtype=flat.dtype)
    send_ids = send_ids.at[sorted_owner, send_pos].set(sorted_ids, mode="drop")

    # Exchange requests; serve locally; exchange answers.
    recv_ids = lax.all_to_all(send_ids, ROW_AXIS, 0, 0, tiled=True)  # [R, C]
    local = recv_ids - base
    ok = (local >= 0) & (local < shard_rows)  # sentinels fail
    safe = jnp.where(ok, local, 0)
    if packed:
        from fast_tffm_tpu.ops.packed_table import fused_gather, packed_gather

        served = (fused_gather if fused else packed_gather)(table_shard, safe, d)
    else:
        served = table_shard[safe]
    served = served * ok[..., None].astype(served.dtype)
    recv_rows = lax.all_to_all(served, ROW_AXIS, 0, 0, tiled=True)  # [R, C, D]

    # recv_rows[s, c] answers MY request in send slot [s, c]; invert the
    # bucket placement, then the sort.
    mine_sorted = recv_rows[sorted_owner, jnp.minimum(send_pos, capacity - 1)]
    mine_sorted = mine_sorted * in_cap[:, None].astype(mine_sorted.dtype)
    out = jnp.zeros((M, served.shape[-1]), served.dtype).at[order].set(mine_sorted)
    out = jnp.where(overflow, jnp.nan, out)
    return out.reshape(B, N, -1)


def routed_update(
    table_shard: jnp.ndarray,
    accum_shard: jnp.ndarray,
    ids: jnp.ndarray,
    row_grads: jnp.ndarray,
    lr: float,
    num_rows_global: int,
    capacity: int,
    *,
    shard_logical_rows: int | None = None,
    packed_mode: str | None = None,
    fused: bool = False,
    compact_cap: int = 0,
    decay: float = 1.0,
):
    """Sparse Adagrad update via routed gradients (the all-to-all analog of
    ``embedding.sharded_sparse_adagrad_update``).

    When ``shard_logical_rows`` is given the shards are LANE-PACKED
    ([VPs, 128] — ops/packed_table.py; ``fused=True``: the fused tile-row
    layout, whose apply is table-only and returns ``accum_shard``
    untouched) and ``packed_mode`` picks the
    packed tail ('dense' | 'compact' | 'sorted'); the routing is unchanged
    (deduped logical ids + summed grads ride the same all_to_all), only
    the final per-shard apply reads/writes the packed layout.

    Per chip: dedup local occurrences, route each (id, summed grad) to its
    home shard over ROW (all_to_all, capacity C per destination), then
    all_gather the received buffers over DATA only — every replica of a
    row shard sees the identical union of contributions, dedups it once
    more, and applies Adagrad exactly once per row.  ICI bytes
    ~ data·(R·C)·D ≈ data·slack·M·D instead of data·row·M·D.

    Returns (table, accum, overflow) — ``overflow`` is a GLOBAL flag
    (psum over both axes): any chip that had to drop contributions raises
    it, and the caller must poison its loss with it so the run aborts
    before a silently-partial update is ever checkpointed.  (Dropped
    entries leave the tables CONSISTENT across replicas — every replica
    sees the same post-drop union — just not the full-batch update.)
    """
    from fast_tffm_tpu.optim import dedup_rows

    packed = shard_logical_rows is not None
    if packed and not fused and packed_mode not in ("dense", "compact", "sorted"):
        raise ValueError(
            f"packed routed_update needs packed_mode 'dense', 'compact' or "
            f"'sorted', got {packed_mode!r} (pass resolve_packed_update's result)"
        )
    if fused and packed_mode not in ("dense", "compact"):
        raise ValueError(
            f"fused routed_update needs packed_mode 'dense' or 'compact', "
            f"got {packed_mode!r} (pass resolve_fused_update's result)"
        )
    if fused and shard_logical_rows is None:
        # Without the logical shard size the routing would divide by the
        # PHYSICAL fused row count and send ids to the wrong shards —
        # wrong-but-finite results, so refuse loudly instead.
        raise ValueError("fused routed_update requires shard_logical_rows")
    D = row_grads.shape[-1]
    shard_rows = shard_logical_rows if packed else table_shard.shape[0]
    base = lax.axis_index(ROW_AXIS) * shard_rows
    R = axis_size(ROW_AXIS)
    uids, gsum = dedup_rows(ids.reshape(-1), row_grads.reshape(-1, D), num_rows_global)
    # Sentinel uids (== num_rows_global) route to owner R: excluded from
    # counts (bincount length R) and dropped by the out-of-range scatter.
    owner = jnp.where(uids >= num_rows_global, R, uids // shard_rows)
    order, sorted_owner, send_pos, _in_cap, overflow = _bucketize(owner, R, capacity)
    sorted_ids = uids[order]
    sorted_g = gsum[order]

    sentinel = jnp.asarray(num_rows_global, uids.dtype)
    send_ids = jnp.full((R, capacity), sentinel, dtype=uids.dtype)
    send_g = jnp.zeros((R, capacity, D), gsum.dtype)
    send_ids = send_ids.at[sorted_owner, send_pos].set(sorted_ids, mode="drop")
    send_g = send_g.at[sorted_owner, send_pos].set(sorted_g, mode="drop")

    recv_ids = lax.all_to_all(send_ids, ROW_AXIS, 0, 0, tiled=True)  # [R, C]
    recv_g = lax.all_to_all(send_g, ROW_AXIS, 0, 0, tiled=True)  # [R, C, D]
    # Data-axis union: every replica of this row shard must apply the SAME
    # update, so gather all data-peers' received contributions.
    all_ids = lax.all_gather(recv_ids.reshape(-1), DATA_AXIS, tiled=True)
    all_g = lax.all_gather(recv_g.reshape(-1, D), DATA_AXIS, tiled=True)
    guids, ggsum = dedup_rows(all_ids, all_g, num_rows_global)

    if fused:
        from fast_tffm_tpu.ops.packed_table import (
            apply_fused_update,
            fused_rows_per_tile,
        )
        from fast_tffm_tpu.parallel.embedding import owned_local_ids

        p = fused_rows_per_tile(D)
        local, _ = owned_local_ids(guids, shard_rows, table_shard.shape[0] * p)
        table_shard = apply_fused_update(
            table_shard, local, ggsum, lr, packed_mode, compact_cap
        )
    elif packed:
        from fast_tffm_tpu.ops.packed_table import PACKED_UPDATE_FNS, rows_per_tile
        from fast_tffm_tpu.parallel.embedding import owned_local_ids

        p = rows_per_tile(D)
        # Unowned and sentinel ids map past the last physical row → drop.
        local, _ = owned_local_ids(guids, shard_rows, table_shard.shape[0] * p)
        update_fn = PACKED_UPDATE_FNS[packed_mode]
        table_shard, accum_shard = update_fn(
            table_shard, accum_shard, local, ggsum, lr
        )
    else:
        from fast_tffm_tpu.parallel.embedding import apply_shard_adagrad

        table_shard, accum_shard = apply_shard_adagrad(
            table_shard, accum_shard, guids, ggsum, lr, base, decay=decay
        )
    overflow = lax.psum(overflow.astype(jnp.int32), (DATA_AXIS, ROW_AXIS)) > 0
    return table_shard, accum_shard, overflow
