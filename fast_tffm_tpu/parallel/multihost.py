"""Multi-host (pod / multi-slice) process initialization.

The reference ran one TF server per ps/worker task over gRPC
(`renyi533/fast_tffm` :: dist trainer: ClusterSpec + tf.train.Server).
The TPU-native equivalent is JAX multi-controller SPMD: every host runs
the SAME program, `jax.distributed.initialize` wires the processes into
one runtime, and the ('data','row') mesh then spans every chip of every
host — collectives ride ICI within a slice and DCN across slices with no
further code changes (the mesh IS the cluster).

On TPU pods the coordinator/process topology is discovered from the TPU
metadata automatically, so `initialize()` needs no arguments; explicit
coordinator_address/num_processes/process_id (cfg or env) cover GPU/CPU
clusters and manual setups.  Single-process runs skip initialization
entirely — the local trainer works unchanged.
"""

from __future__ import annotations

import os

import jax

__all__ = ["maybe_initialize_distributed", "is_multihost", "process_index"]

_INITIALIZED = False


def maybe_initialize_distributed(
    coordinator_address: str = "",
    num_processes: int = 0,
    process_id: int = -1,
) -> bool:
    """Call jax.distributed.initialize when multi-host context is present.

    Returns True if the distributed runtime was (already) initialized.
    Priority: explicit args > JAX_COORDINATOR_ADDRESS env > TPU metadata
    auto-detection (initialize() with no args when JAX_NUM_PROCESSES is
    set).  A plain single-host launch returns False and touches nothing.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS", "")
    env_np = int(os.environ.get("JAX_NUM_PROCESSES", "0"))
    num_processes = num_processes or env_np
    if process_id < 0:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "-1"))

    if coordinator_address:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes or None,
            process_id=None if process_id < 0 else process_id,
        )
        _INITIALIZED = True
    elif num_processes > 1:
        jax.distributed.initialize()  # TPU metadata auto-detection
        _INITIALIZED = True
    return _INITIALIZED


def is_multihost() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()
