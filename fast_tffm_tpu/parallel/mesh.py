"""Device mesh construction for data×row parallelism.

The reference scales two ways (SURVEY.md §3): data-parallel asynchronous
workers (Hogwild on a parameter server) and a `vocabulary_block_num`-way
row partition of the parameter table across ps tasks.  The TPU-native
equivalents are the two axes of one `jax.sharding.Mesh`:

  * ``data``  — batch sharding, synchronous gradient combination over ICI
                (replacing Hogwild with deterministic sync updates);
  * ``row``   — contiguous row sharding of the embedding/parameter table
                (replacing the modulo block partition over ps hosts).

On a multi-host pod the same mesh spans all chips: JAX lays ICI within a
slice and DCN across slices automatically from the device order.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "table_sharding",
    "batch_sharding",
    "replicated",
    "pad_vocab",
    "axis_size",
]

DATA_AXIS = "data"
ROW_AXIS = "row"

try:  # JAX >= 0.4.31 exports lax.axis_size
    from jax.lax import axis_size
except ImportError:  # older JAX: psum of the literal 1 constant-folds to
    # the same STATIC int at trace time, so `axis_size(ax) == 1` branches
    # still resolve while tracing (the mesh=1 fast paths depend on that).
    def axis_size(name):
        """Static size of mesh axis ``name`` inside a shard_map body."""
        return jax.lax.psum(1, name)


def make_mesh(
    data_parallel: int | None = None,
    row_parallel: int = 1,
    devices=None,
) -> Mesh:
    """Mesh of shape [data_parallel, row_parallel] over ``devices``.

    ``data_parallel=None`` uses all remaining devices after row_parallel.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if data_parallel is None:
        if n % row_parallel:
            raise ValueError(f"{n} devices not divisible by row_parallel={row_parallel}")
        data_parallel = n // row_parallel
    need = data_parallel * row_parallel
    if need > n:
        raise ValueError(f"need {need} devices, have {n}")
    grid = np.asarray(devices[:need]).reshape(data_parallel, row_parallel)
    return Mesh(grid, (DATA_AXIS, ROW_AXIS))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """[V, D] tables: rows split over ROW_AXIS, replicated over DATA_AXIS."""
    return NamedSharding(mesh, P(ROW_AXIS, None))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-major arrays: leading dim split over EVERY chip (both axes) —
    matches the train/predict steps' batch specs (compute is fully
    data-parallel; only the table is row-sharded)."""
    return NamedSharding(mesh, P((DATA_AXIS, ROW_AXIS)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_vocab(vocabulary_size: int, row_parallel: int) -> int:
    """Round the table row count up so every row shard is equal-sized."""
    r = row_parallel
    return ((vocabulary_size + r - 1) // r) * r


def check_batch_divides(batch_size: int, mesh: Mesh) -> None:
    """Fail fast when the global batch cannot split over every chip.

    The train/predict steps shard the batch over BOTH mesh axes; catching
    the mismatch here gives a config-level message instead of a shard_map
    axis-divisibility error from inside the first step."""
    if batch_size % mesh.devices.size:
        raise ValueError(
            f"batch_size {batch_size} not divisible by the "
            f"{mesh.devices.size}-device mesh"
        )
