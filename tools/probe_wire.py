#!/usr/bin/env python
"""Packed-wire acceptance probe (ISSUE 3): bytes cut + bitwise parity.

Two halves, one JSON:

  wire_bytes   the headline-shape (B=65536, nnz=39, vocab 2^24) all-ones
               FM workload streamed through BOTH wire formats, counting
               the ACTUAL bytes each format ships per step (packed: the
               coalesced buffer's nbytes; arrays: the sum of the five
               staged host arrays) and timing the per-batch staging call.
               The ≥2.5x cut criterion reads off `wire_cut_x`.
  parity       driver-level train runs, wire_format packed vs arrays, on
               an all-ones FMB set: streamed (K=1 and K=8 superbatch),
               device-cached, and sharded/SPMD (8-device virtual mesh) —
               final states compared BITWISE, logged losses record for
               record.  Runs in a CPU subprocess (the mesh paths need 8
               devices; parity is platform-independent logic).

The staging half prefers the default backend (the tunneled TPU on this
box) in a subprocess with a timeout; a dead tunnel degrades to CPU
staging numbers with the platform recorded, never to a hung probe.

Writes PROBE_WIRE_r06.json.  Usage:
  python tools/probe_wire.py [--rows 262144] [--cpu-only]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCH = 65536
NNZ = 39
VOCAB = 1 << 24

_STAGE_WORKER = textwrap.dedent(
    """
    import json, sys, time
    sys.path.insert(0, {repo!r})
    rows = int(sys.argv[1])
    import jax
    import numpy as np
    import bench
    from fast_tffm_tpu.data.binary import fmb_batch_stream, fmb_wire_flags
    from fast_tffm_tpu.data.wire import WireConverter, make_spec
    from fast_tffm_tpu.models import Batch

    B, N, V = {batch}, {nnz}, {vocab}
    path = bench.ensure_scale_fmb(V, rows=rows, all_ones=True)
    all_ones, _ = fmb_wire_flags([path])
    assert all_ones, "synthetic all-ones file must carry the v2 flag"

    def batches():
        return fmb_batch_stream(
            [path], batch_size=B, vocabulary_size=V, hash_feature_id=True,
            max_nnz=N, epochs=1, drop_remainder=True,
        )

    conv = WireConverter(make_spec(V, N, with_vals=False, with_fields=False))
    out = {{"platform": jax.default_backend(),
            "device_kind": getattr(jax.devices()[0], "device_kind", "cpu")}}

    def force(b):
        np.asarray(b.labels[:1])  # value dependency: staging really landed

    times = {{"packed": [], "arrays": []}}
    packed_bytes = arrays_bytes = steps = 0
    warm = True
    for _ in range(2):  # pass 1 warms page cache + compiles, pass 2 times
        for p, w in batches():
            t0 = time.perf_counter()
            bp = conv(p, w)
            force(bp)
            t1 = time.perf_counter()
            ba = Batch.from_parsed(p, w, with_fields=False)
            force(ba)
            t2 = time.perf_counter()
            if not warm:
                times["packed"].append(1e3 * (t1 - t0))
                times["arrays"].append(1e3 * (t2 - t1))
                packed_bytes += conv.last_nbytes
                arrays_bytes += (
                    ba.labels.nbytes + ba.ids.nbytes + ba.vals.nbytes
                    + ba.fields.nbytes + ba.weights.nbytes
                )
                steps += 1
        warm = False
    med = lambda xs: sorted(xs)[len(xs) // 2]
    out.update(
        steps=steps,
        packed_wire_bytes_per_step=packed_bytes // steps,
        arrays_wire_bytes_per_step=arrays_bytes // steps,
        wire_cut_x=round(arrays_bytes / packed_bytes, 3),
        packed_h2d_stage_ms_median=round(med(times["packed"]), 3),
        arrays_h2d_stage_ms_median=round(med(times["arrays"]), 3),
    )
    if out["platform"] == "cpu":
        out["staging_ms_note"] = (
            "on the cpu backend device_put is ~free (often zero-copy), so "
            "arrays 'staging' measures nothing while packed pays real host "
            "pack+verify cpu time; the stage-ms comparison only means "
            "something where an actual wire exists (PCIe/tunnel) — the "
            "BYTE counts are the platform-independent acceptance metric, "
            "and the pack cost runs inside the prefetch thread, overlapped"
        )
    print("PROBE_JSON " + json.dumps(out), flush=True)
    """
).format(repo=REPO, batch=BATCH, nnz=NNZ, vocab=VOCAB)


_PARITY_WORKER = textwrap.dedent(
    """
    import json, os, sys, tempfile
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np
    import jax
    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.data.binary import write_fmb
    from fast_tffm_tpu.training import dist_train, train
    from fast_tffm_tpu.parallel import make_mesh

    tmp = tempfile.mkdtemp()
    rng = np.random.default_rng(42)
    files = []
    for name, rows in (("a", 83), ("b", 41)):
        src = os.path.join(tmp, name + ".libsvm")
        with open(src, "w") as f:
            for _ in range(rows):
                nnz = rng.integers(1, 8)
                toks = [f"{{rng.integers(0, 1000)}}:1" for _ in range(nnz)]
                f.write(f"{{rng.integers(0, 2)}} {{' '.join(toks)}}\\n")
        files.append(write_fmb(src, src + ".fmb", vocabulary_size=1000))

    def cfg(tag, **kw):
        base = dict(
            model="fm", factor_num=4, vocabulary_size=1000,
            model_file=os.path.join(tmp, f"m_{{tag}}.ckpt"),
            train_files=tuple(files), epoch_num=2, batch_size=32,
            learning_rate=0.05, log_every=2,
            metrics_path=os.path.join(tmp, f"m_{{tag}}.jsonl"),
        )
        base.update(kw)
        return Config(**base).validate()

    def losses(tag):
        path = os.path.join(tmp, f"m_{{tag}}.jsonl")
        return [json.loads(l)["loss"] for l in open(path) if "loss" in json.loads(l)]

    def state_bits(st):
        return (np.asarray(st.table).tobytes(),
                np.asarray(st.table_opt.accum).tobytes(), int(st.step))

    silent = lambda *a: None
    out = {{}}
    runs = {{}}
    runs["streamed_arrays"] = train(cfg("sa", wire_format="arrays"), log=silent)
    runs["streamed_packed"] = train(cfg("sp", wire_format="packed"), log=silent)
    runs["streamed_packed_k8"] = train(
        cfg("sp8", wire_format="packed", steps_per_call=8), log=silent)
    runs["streamed_arrays_k8"] = train(
        cfg("sa8", wire_format="arrays", steps_per_call=8), log=silent)
    runs["device_cached"] = train(cfg("dc", device_cache=True), log=silent)
    runs["sharded_arrays"] = dist_train(
        cfg("da", wire_format="arrays"), log=silent, mesh=make_mesh(2, 4))
    runs["sharded_packed"] = dist_train(
        cfg("dp", wire_format="packed"), log=silent, mesh=make_mesh(2, 4))

    ref = state_bits(runs["streamed_arrays"])
    for name, st in runs.items():
        if name.startswith("sharded"):
            continue  # sharded compares packed-vs-arrays against itself below
        out[f"{{name}}_bitwise_vs_streamed_arrays"] = state_bits(st) == ref
    out["sharded_packed_bitwise_vs_sharded_arrays"] = (
        state_bits(runs["sharded_packed"]) == state_bits(runs["sharded_arrays"]))
    out["streamed_losses_match"] = losses("sa") == losses("sp")
    out["streamed_k8_losses_match"] = losses("sa8") == losses("sp8")
    out["sharded_losses_match"] = losses("da") == losses("dp")
    inrec = [json.loads(l) for l in open(os.path.join(tmp, "m_sp.jsonl"))]
    inrec = [r for r in inrec if r.get("kind") == "input"]
    if inrec:
        out["small_run_packed_wire_bytes_per_step"] = inrec[0]["wire_bytes_per_step"]
    print("PROBE_JSON " + json.dumps(out), flush=True)
    """
).format(repo=REPO)


def _run_worker(code, args=(), env=None, timeout=1500):
    r = subprocess.run(
        [sys.executable, "-c", code, *map(str, args)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env={**os.environ, **(env or {})},
    )
    for line in reversed((r.stdout or "").strip().splitlines()):
        if line.startswith("PROBE_JSON "):
            return json.loads(line[len("PROBE_JSON "):])
    tail = (r.stderr or r.stdout or "no output").strip().splitlines()
    raise RuntimeError("; ".join(tail[-3:])[-300:])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--cpu-only", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "PROBE_WIRE_r06.json"))
    args = ap.parse_args(argv)

    res = {"batch": BATCH, "nnz": NNZ, "vocab": VOCAB, "fmb_rows": args.rows}

    # Staging A/B: default backend first (the tunneled TPU), CPU fallback.
    envs = [("default", {})] if not args.cpu_only else []
    envs.append(("cpu", {"JAX_PLATFORMS": "cpu"}))
    for name, env in envs:
        try:
            res["wire_bytes"] = _run_worker(
                _STAGE_WORKER, [args.rows], env=env, timeout=1500
            )
            break
        except (RuntimeError, subprocess.TimeoutExpired) as e:
            res[f"stage_{name}_error"] = str(e)[:300]
    print("wire_bytes ->", res.get("wire_bytes"), flush=True)

    try:
        res["parity"] = _run_worker(_PARITY_WORKER, timeout=1500)
    except (RuntimeError, subprocess.TimeoutExpired) as e:
        res["parity_error"] = str(e)[:300]
    print("parity ->", res.get("parity"), flush=True)

    wb = res.get("wire_bytes", {})
    par = res.get("parity", {})
    res["acceptance"] = {
        "wire_cut_x_ge_2p5": bool(wb.get("wire_cut_x", 0) >= 2.5),
        "all_parity_bitwise": bool(par) and all(
            v for k, v in par.items() if isinstance(v, bool)
        ),
    }
    from fast_tffm_tpu.telemetry import write_json_artifact

    write_json_artifact(args.out, res, sort_keys=False)
    print("wrote", args.out)
    return 0


if __name__ == "__main__":
    from fast_tffm_tpu.telemetry import arm_hang_exit

    arm_hang_exit(seconds=3300, what="probe_wire.py")
    raise SystemExit(main())
