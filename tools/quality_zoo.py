#!/usr/bin/env python
"""Model-zoo convergence artifacts: held-out AUC vs a planted-oracle ceiling.

VERDICT r4 #7: the committed quality rows covered order-2 FM only.  This
tool trains each remaining BASELINE family through the REAL drivers on
planted-model synthetic data whose generating process matches the family:

  ffm     planted field-aware factors (v[id, partner_field, k]); libffm
          input; config #3's model class
  fm3     planted order-3 FM (linear + ANOVA_2 + ANOVA_3, the exact
          semantics of ops/fm.py's DP); config #5's model class
  deepfm  planted FM signal PLUS a tanh-pooled nonlinearity no plain FM
          can represent; trains BOTH deepfm and fm on the same rows so the
          row shows DeepFM's lift where the MLP has signal to find
          (config #4's model class)

Each row reports the best validation AUC from the driver's JSONL metrics
next to the ORACLE ceiling (the planted model scoring the same held-out
rows — the best ANY learner can do on Bernoulli(sigmoid(score)) labels).
Writes QUALITY_ZOO_r05.json; bench_all.py folds the rows into BENCH_ALL.

Usage: python tools/quality_zoo.py [--rows 1200000] [--epochs 6] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

from gen_synthetic import _id_normal, _zipf_ids  # noqa: E402

VOCAB = 1 << 12
# Vocab 4096, not the FM study's 2^14: FFM learns F·k = 32 factor params
# per id (8× plain FM), so matching the study's observations-per-PARAMETER
# at a budget-sized row count needs proportionally more observations per
# id — the first run at 2^14 plateaued at 0.60 of a 0.86 oracle for
# exactly this reason (sample-starved, not trainer-broken).
K = 4
SPREAD = 2.2  # label noise calibration (gen_synthetic rationale)


def _draw_rows(rng, rows: int, fields: int):
    bounds = np.linspace(0, VOCAB, fields + 1).astype(np.int64)
    ids = np.stack(
        [_zipf_ids(rng, rows, bounds[f], bounds[f + 1]) for f in range(fields)],
        axis=1,
    )
    vals = np.round(
        np.abs(rng.normal(0.5, 0.35, size=(rows, fields))) + 0.05, 4
    ).astype(np.float32)
    return ids, vals


def planted_ffm_score(ids, vals, fields: int, seed: int = 777):
    """bias + Σ_{a<b} <v(id_a, b), v(id_b, a)> x_a x_b, v planted per
    (id, partner_field, k) via the stateless hash-normal.  Chunked over
    rows: the [rows, F, F, K] factor tensor at 2.4M rows would be
    ~2.4 GB×2 of transient host RAM."""
    rows = ids.shape[0]
    bias = 0.5 * _id_normal(ids, seed)
    score = (bias * vals).sum(axis=1)
    chunk = 200_000
    for lo in range(0, rows, chunk):
        hi = min(lo + chunk, rows)
        cid, cv = ids[lo:hi], vals[lo:hi]
        fac = np.zeros((hi - lo, fields, fields, K), np.float32)
        for g in range(fields):
            for j in range(K):
                fac[:, :, g, j] = 0.55 * _id_normal(cid, seed + 13 + g * K + j)
        zx = fac * cv[..., None, None]  # [chunk, i, g, k]
        for a in range(fields):
            for b in range(a + 1, fields):
                score[lo:hi] += np.einsum("rk,rk->r", zx[:, a, b], zx[:, b, a])
    return score


def planted_fm3_score(ids, vals, seed: int = 888):
    """linear + ANOVA_2 + ANOVA_3 over planted v[id, k] — the exact order-3
    semantics of ops/fm.py (elementary symmetric polynomials per factor dim)."""
    bias = 0.5 * _id_normal(ids, seed)
    v = np.stack(
        [0.5 * _id_normal(ids, seed + 7 + j) for j in range(K)], axis=-1
    )
    z = v * vals[..., None]  # [rows, n, k]
    s1 = z.sum(axis=1)
    s2 = (z * z).sum(axis=1)
    s3 = (z * z * z).sum(axis=1)
    e2 = 0.5 * (s1 * s1 - s2)
    e3 = (s1**3 - 3 * s1 * s2 + 2 * s3) / 6.0
    return (bias * vals).sum(axis=1) + (e2 + e3).sum(axis=-1)


def planted_deep_score(ids, vals, seed: int = 999):
    """Planted FM score + a tanh-pooled term: s += Σ_j w_j tanh(3 p_j),
    p = Σ_i u(id_i) x_i — smooth but outside the FM function class, so the
    MLP head has genuine signal to capture."""
    import gen_synthetic

    base = gen_synthetic.planted_score(ids, vals, factor_num=K, model_seed=seed)
    u = np.stack(
        [0.6 * _id_normal(ids, seed + 101 + j) for j in range(K)], axis=-1
    )
    p = (u * vals[..., None]).sum(axis=1)  # [rows, k]
    w = np.array([1.7, -1.3, 1.1, -0.9], np.float32)[:K]
    return base + 1.6 * np.tanh(1.5 * p) @ w


def _write(path, labels, ids, vals, fmt):
    with open(path, "w") as f:
        for r in range(ids.shape[0]):
            if fmt == "libffm":
                toks = " ".join(
                    f"{fi}:{ids[r, fi]}:{vals[r, fi]:.4f}"
                    for fi in range(ids.shape[1])
                )
            else:
                toks = " ".join(
                    f"{ids[r, fi]}:{vals[r, fi]:.4f}" for fi in range(ids.shape[1])
                )
            f.write(f"{labels[r]} {toks}\n")


def _labels(rng, score):
    s = (score - score.mean()) / (score.std() + 1e-6) * SPREAD
    return (rng.random(s.shape[0]) < 1.0 / (1.0 + np.exp(-s))).astype(np.int64), s


def _gen_split(tmp, tag, scorer, fields, rows, seed, fmt):
    rng = np.random.default_rng(seed)
    ids, vals = _draw_rows(rng, rows, fields)
    labels, s = _labels(rng, scorer(ids, vals))
    path = os.path.join(tmp, f"{tag}.{fmt}")
    _write(path, labels, ids, vals, fmt)
    return path, labels, s


def _train(tmp, tag, train_file, test_file, *, model, fields, epochs, order=2,
           hidden=(), lr=0.1):
    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.training import train

    cfg = Config(
        model=model, factor_num=K, vocabulary_size=VOCAB, order=order,
        num_fields=fields if model in ("ffm", "deepfm") else 0,
        hidden_dims=tuple(hidden),
        model_file=os.path.join(tmp, f"m_{tag}.npz"),
        train_files=(train_file,), validation_files=(test_file,),
        epoch_num=epochs, batch_size=8192, learning_rate=lr,
        init_accumulator_value=0.1, log_every=200, binary_cache=True,
        metrics_path=os.path.join(tmp, f"jl_{tag}.jsonl"),
    ).validate()
    train(cfg, log=lambda *_: None)
    aucs = [
        r["validation_auc"]
        for r in map(json.loads, open(cfg.metrics_path).read().splitlines())
        if "validation_auc" in r
    ]
    return max(aucs)


def main(argv=None) -> int:
    from fast_tffm_tpu.metrics import auc

    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_400_000)
    ap.add_argument("--test-rows", type=int, default=50_000)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes for a smoke run")
    ap.add_argument("--families", default="ffm,fm3,deepfm",
                    help="comma list: ffm,fm3,deepfm (skip the rest)")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "QUALITY_ZOO_r05.json"))
    args = ap.parse_args(argv)
    if args.quick:
        args.rows, args.test_rows, args.epochs = 60_000, 8_000, 2

    # Per-family training budgets.  The interaction-only families (ffm,
    # fm3) get more passes + a hotter lr than the base budget — products
    # of two ~0.01-init factors barely move early Adagrad steps — and
    # DeepFM's MLP head gets a few extra.  Under --quick everything keeps
    # the tiny smoke budget.  Each family's artifact row records ITS OWN
    # (epochs, lr), so the reported AUCs are reproducible from the record.
    budget = {
        "ffm": (args.epochs if args.quick else args.epochs + 10, 0.25),
        "fm3": (args.epochs if args.quick else args.epochs + 10, 0.25),
        "deepfm": (args.epochs if args.quick else args.epochs + 4, 0.05),
        "fmbase": (args.epochs, 0.1),
    }

    wanted = set(args.families.split(","))
    res = {"rows": args.rows, "test_rows": args.test_rows,
           "base_epochs": args.epochs,
           "vocab": VOCAB, "k": K, "families": {}}
    with tempfile.TemporaryDirectory() as tmp:
        if "ffm" in wanted:
            # --- FFM (config #3): 8 fields keeps the planted pair tensor sane.
            F = 8
            tr, _, _ = _gen_split(tmp, "ffm_tr",
                                  lambda i, v: planted_ffm_score(i, v, F),
                                  F, args.rows, 10, "libffm")
            te, te_labels, te_score = _gen_split(
                tmp, "ffm_te", lambda i, v: planted_ffm_score(i, v, F),
                F, args.test_rows, 11, "libffm")
            # Interaction-only signal trains slowly from the small factor init
            # (products of two ~0.01 factors barely move early Adagrad steps);
            # a hotter lr + more passes close most of the optimization gap,
            # and the per-epoch max of validation AUC keeps the best point.
            ep, lr = budget["ffm"]
            learned = _train(tmp, "ffm", tr, te, model="ffm", fields=F,
                             epochs=ep, lr=lr)
            res["families"]["ffm"] = {
                "heldout_auc": round(float(learned), 5),
                "oracle_auc": round(float(auc(te_labels, te_score)), 5),
                "epochs": ep, "lr": lr,
            }
            print("ffm ->", res["families"]["ffm"], flush=True)

        if "fm3" in wanted:
            # --- order-3 FM (config #5).
            F = 12
            tr, _, _ = _gen_split(tmp, "fm3_tr", planted_fm3_score, F, args.rows,
                                  20, "libsvm")
            te, te_labels, te_score = _gen_split(
                tmp, "fm3_te", planted_fm3_score, F, args.test_rows, 21, "libsvm")
            ep, lr = budget["fm3"]
            learned = _train(tmp, "fm3", tr, te, model="fm", fields=0,
                             epochs=ep, order=3, lr=lr)
            res["families"]["fm3"] = {
                "heldout_auc": round(float(learned), 5),
                "oracle_auc": round(float(auc(te_labels, te_score)), 5),
                "epochs": ep, "lr": lr,
            }
            print("fm3 ->", res["families"]["fm3"], flush=True)

        if "deepfm" in wanted:
            # --- DeepFM (config #4) vs plain FM on nonlinear planted signal.
            F = 12
            tr, _, _ = _gen_split(tmp, "deep_tr", planted_deep_score, F, args.rows,
                                  30, "libsvm")
            te, te_labels, te_score = _gen_split(
                tmp, "deep_te", planted_deep_score, F, args.test_rows, 31, "libsvm")
            # The MLP head needs more passes than the embeddings to fit the
            # planted nonlinearity (the quick smoke shows it under-trained at
            # equal epochs), so DeepFM gets extra epochs; the FM baseline
            # keeps the common budget (more epochs do not help a model class
            # that cannot represent the signal).
            ep, lr = budget["deepfm"]
            bep, blr = budget["fmbase"]
            deep = _train(tmp, "deepfm", tr, te, model="deepfm", fields=F,
                          epochs=ep, hidden=(64, 32), lr=lr)
            plain = _train(tmp, "fmbase", tr, te, model="fm", fields=0,
                           epochs=bep, lr=blr)
            res["families"]["deepfm"] = {
                "heldout_auc": round(float(deep), 5),
                "fm_baseline_auc": round(float(plain), 5),
                "oracle_auc": round(float(auc(te_labels, te_score)), 5),
                "lift_over_fm": round(float(deep - plain), 5),
                "epochs": ep, "lr": lr,
                "fm_baseline_epochs": bep, "fm_baseline_lr": blr,
            }
            print("deepfm ->", res["families"]["deepfm"], flush=True)

    from fast_tffm_tpu.telemetry import write_json_artifact

    write_json_artifact(args.out, res, sort_keys=False)
    print("wrote", args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
