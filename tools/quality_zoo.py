#!/usr/bin/env python
"""Model-zoo convergence artifacts: held-out AUC vs a planted-oracle ceiling.

VERDICT r4 #7: the committed quality rows covered order-2 FM only.  This
tool trains each remaining BASELINE family through the REAL drivers on
planted-model synthetic data whose generating process matches the family:

  ffm     planted field-aware factors (v[id, partner_field, k]); libffm
          input; config #3's model class
  fm3     planted order-3 FM (linear + ANOVA_2 + ANOVA_3, the exact
          semantics of ops/fm.py's DP); config #5's model class
  deepfm  planted FM signal PLUS a tanh-pooled nonlinearity no plain FM
          can represent; trains BOTH deepfm and fm on the same rows so the
          row shows DeepFM's lift where the MLP has signal to find
          (config #4's model class)

Each row reports the best validation AUC from the driver's JSONL metrics
next to the ORACLE ceiling (the planted model scoring the same held-out
rows — the best ANY learner can do on Bernoulli(sigmoid(score)) labels).
Writes QUALITY_ZOO_r05.json; bench_all.py folds the rows into BENCH_ALL.

Usage: python tools/quality_zoo.py [--rows 1200000] [--epochs 6] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

from gen_synthetic import _id_normal, _zipf_ids  # noqa: E402

VOCAB = 1 << 14
K = 4
SPREAD = 2.2  # label noise calibration (gen_synthetic rationale)


def _draw_rows(rng, rows: int, fields: int):
    bounds = np.linspace(0, VOCAB, fields + 1).astype(np.int64)
    ids = np.stack(
        [_zipf_ids(rng, rows, bounds[f], bounds[f + 1]) for f in range(fields)],
        axis=1,
    )
    vals = np.round(
        np.abs(rng.normal(0.5, 0.35, size=(rows, fields))) + 0.05, 4
    ).astype(np.float32)
    return ids, vals


def planted_ffm_score(ids, vals, fields: int, seed: int = 777):
    """bias + Σ_{a<b} <v(id_a, b), v(id_b, a)> x_a x_b, v planted per
    (id, partner_field, k) via the stateless hash-normal."""
    rows = ids.shape[0]
    bias = 0.5 * _id_normal(ids, seed)
    score = (bias * vals).sum(axis=1)
    # fac[r, i, g, j] = v(ids[r, i])[partner g, dim j], built lazily per
    # (g, j) salt to bound memory.
    fac = np.zeros((rows, fields, fields, K), np.float32)
    for g in range(fields):
        for j in range(K):
            fac[:, :, g, j] = 0.55 * _id_normal(ids, seed + 13 + g * K + j)
    zx = fac * vals[..., None, None]  # [rows, i, g, k]
    for a in range(fields):
        for b in range(a + 1, fields):
            score += np.einsum("rk,rk->r", zx[:, a, b], zx[:, b, a])
    return score


def planted_fm3_score(ids, vals, seed: int = 888):
    """linear + ANOVA_2 + ANOVA_3 over planted v[id, k] — the exact order-3
    semantics of ops/fm.py (elementary symmetric polynomials per factor dim)."""
    bias = 0.5 * _id_normal(ids, seed)
    v = np.stack(
        [0.5 * _id_normal(ids, seed + 7 + j) for j in range(K)], axis=-1
    )
    z = v * vals[..., None]  # [rows, n, k]
    s1 = z.sum(axis=1)
    s2 = (z * z).sum(axis=1)
    s3 = (z * z * z).sum(axis=1)
    e2 = 0.5 * (s1 * s1 - s2)
    e3 = (s1**3 - 3 * s1 * s2 + 2 * s3) / 6.0
    return (bias * vals).sum(axis=1) + (e2 + e3).sum(axis=-1)


def planted_deep_score(ids, vals, seed: int = 999):
    """Planted FM score + a tanh-pooled term: s += Σ_j w_j tanh(3 p_j),
    p = Σ_i u(id_i) x_i — smooth but outside the FM function class, so the
    MLP head has genuine signal to capture."""
    import gen_synthetic

    base = gen_synthetic.planted_score(ids, vals, factor_num=K, model_seed=seed)
    u = np.stack(
        [0.6 * _id_normal(ids, seed + 101 + j) for j in range(K)], axis=-1
    )
    p = (u * vals[..., None]).sum(axis=1)  # [rows, k]
    w = np.array([1.7, -1.3, 1.1, -0.9], np.float32)[:K]
    return base + 1.6 * np.tanh(1.5 * p) @ w


def _write(path, labels, ids, vals, fmt):
    with open(path, "w") as f:
        for r in range(ids.shape[0]):
            if fmt == "libffm":
                toks = " ".join(
                    f"{fi}:{ids[r, fi]}:{vals[r, fi]:.4f}"
                    for fi in range(ids.shape[1])
                )
            else:
                toks = " ".join(
                    f"{ids[r, fi]}:{vals[r, fi]:.4f}" for fi in range(ids.shape[1])
                )
            f.write(f"{labels[r]} {toks}\n")


def _labels(rng, score):
    s = (score - score.mean()) / (score.std() + 1e-6) * SPREAD
    return (rng.random(s.shape[0]) < 1.0 / (1.0 + np.exp(-s))).astype(np.int64), s


def _gen_split(tmp, tag, scorer, fields, rows, seed, fmt):
    rng = np.random.default_rng(seed)
    ids, vals = _draw_rows(rng, rows, fields)
    labels, s = _labels(rng, scorer(ids, vals))
    path = os.path.join(tmp, f"{tag}.{fmt}")
    _write(path, labels, ids, vals, fmt)
    return path, labels, s


def _train(tmp, tag, train_file, test_file, *, model, fields, epochs, order=2,
           hidden=(), lr=0.1):
    from fast_tffm_tpu.config import Config
    from fast_tffm_tpu.training import train

    cfg = Config(
        model=model, factor_num=K, vocabulary_size=VOCAB, order=order,
        num_fields=fields if model in ("ffm", "deepfm") else 0,
        hidden_dims=tuple(hidden),
        model_file=os.path.join(tmp, f"m_{tag}.npz"),
        train_files=(train_file,), validation_files=(test_file,),
        epoch_num=epochs, batch_size=8192, learning_rate=lr,
        init_accumulator_value=0.1, log_every=200, binary_cache=True,
        metrics_path=os.path.join(tmp, f"jl_{tag}.jsonl"),
    ).validate()
    train(cfg, log=lambda *_: None)
    aucs = [
        r["validation_auc"]
        for r in map(json.loads, open(cfg.metrics_path).read().splitlines())
        if "validation_auc" in r
    ]
    return max(aucs)


def main(argv=None) -> int:
    from fast_tffm_tpu.metrics import auc

    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_200_000)
    ap.add_argument("--test-rows", type=int, default=50_000)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--quick", action="store_true",
                    help="tiny shapes for a smoke run")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "QUALITY_ZOO_r05.json"))
    args = ap.parse_args(argv)
    if args.quick:
        args.rows, args.test_rows, args.epochs = 60_000, 8_000, 2

    res = {"rows": args.rows, "test_rows": args.test_rows, "epochs": args.epochs,
           "vocab": VOCAB, "k": K, "families": {}}
    with tempfile.TemporaryDirectory() as tmp:
        # --- FFM (config #3): 8 fields keeps the planted pair tensor sane.
        F = 8
        tr, _, _ = _gen_split(tmp, "ffm_tr",
                              lambda i, v: planted_ffm_score(i, v, F),
                              F, args.rows, 10, "libffm")
        te, te_labels, te_score = _gen_split(
            tmp, "ffm_te", lambda i, v: planted_ffm_score(i, v, F),
            F, args.test_rows, 11, "libffm")
        learned = _train(tmp, "ffm", tr, te, model="ffm", fields=F,
                         epochs=args.epochs)
        res["families"]["ffm"] = {
            "heldout_auc": round(float(learned), 5),
            "oracle_auc": round(float(auc(te_labels, te_score)), 5),
        }
        print("ffm ->", res["families"]["ffm"], flush=True)

        # --- order-3 FM (config #5).
        F = 12
        tr, _, _ = _gen_split(tmp, "fm3_tr", planted_fm3_score, F, args.rows,
                              20, "libsvm")
        te, te_labels, te_score = _gen_split(
            tmp, "fm3_te", planted_fm3_score, F, args.test_rows, 21, "libsvm")
        learned = _train(tmp, "fm3", tr, te, model="fm", fields=0,
                         epochs=args.epochs, order=3)
        res["families"]["fm3"] = {
            "heldout_auc": round(float(learned), 5),
            "oracle_auc": round(float(auc(te_labels, te_score)), 5),
        }
        print("fm3 ->", res["families"]["fm3"], flush=True)

        # --- DeepFM (config #4) vs plain FM on nonlinear planted signal.
        F = 12
        tr, _, _ = _gen_split(tmp, "deep_tr", planted_deep_score, F, args.rows,
                              30, "libsvm")
        te, te_labels, te_score = _gen_split(
            tmp, "deep_te", planted_deep_score, F, args.test_rows, 31, "libsvm")
        deep = _train(tmp, "deepfm", tr, te, model="deepfm", fields=F,
                      epochs=args.epochs, hidden=(64, 32), lr=0.05)
        plain = _train(tmp, "fmbase", tr, te, model="fm", fields=0,
                       epochs=args.epochs)
        res["families"]["deepfm"] = {
            "heldout_auc": round(float(deep), 5),
            "fm_baseline_auc": round(float(plain), 5),
            "oracle_auc": round(float(auc(te_labels, te_score)), 5),
            "lift_over_fm": round(float(deep - plain), 5),
        }
        print("deepfm ->", res["families"]["deepfm"], flush=True)

    with open(args.out, "w") as f:
        json.dump(res, f, indent=1)
    print("wrote", args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
