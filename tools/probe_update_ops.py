#!/usr/bin/env python
"""Sub-op decomposition of the packed compact update at vocab 2^24.

Round-5 follow-up to PROBE_SCALE_OPS: the compact update measured 98 ms
against a 34 ms whole dense step, and the step's HLO shows XLA wrapping
scatter in a SORT-based dedup.  This probe times, marginal-slope style:

  g_build        scatter-ADD [M,128] -> [K,128] (duplicate indices; the
                 hidden sort lives here)
  rmw_flagged    2 wide gathers + Adagrad + 2 scatters DECLARED unique +
                 sorted (the new production RMW)
  rmw_plain      same with default scatter flags (the old RMW)
  gather_k128 / gather_k256
                 wide gather [K,128] vs [K,256]: if ~equal, the ops are
                 DESCRIPTOR-bound (per-row latency), not byte-bound —
                 motivates merging table+accum RMW traffic
  upd_compact / upd_sorted / upd_dense
                 the three full tails after the unique+sorted flags

Writes PROBE_UPDATE_OPS_r05.json.
"""

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fast_tffm_tpu.telemetry import arm_hang_exit

_watchdog = arm_hang_exit(seconds=2700, what="probe_update_ops.py")

import jax
import jax.numpy as jnp
import numpy as np

from bench import make_batch, zipf_ids
from fast_tffm_tpu.ops.packed_table import (
    LANES,
    lane_spread,
    packed_compact_adagrad_update,
    packed_dense_adagrad_update,
    packed_rows,
    packed_sparse_adagrad_update,
    rows_per_tile,
)

BATCH = 16384
NNZ = 39
K_FACTORS = 8
D = 1 + K_FACTORS
P = rows_per_tile(D)
VOCAB = 1 << 24


def slope_ms(jfn, args, k_lo=2, k_hi=8, reps=3):
    float(jfn(k_lo, *args))
    float(jfn(k_hi, *args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        float(jfn(k_lo, *args))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(jfn(k_hi, *args))
        t_hi = time.perf_counter() - t0
        best = min(best, (t_hi - t_lo) / (k_hi - k_lo))
    return round(best * 1e3, 3)


def main():
    rng = np.random.default_rng(0)
    vp = packed_rows(VOCAB, D)
    m = BATCH * NNZ
    k_cap = min(vp, m)

    table = jax.jit(
        lambda key: jax.random.uniform(key, (vp, LANES), jnp.float32, -0.01, 0.01)
    )(jax.random.key(0))
    accum = jnp.full((vp, LANES), 0.1, jnp.float32)
    ids = jnp.asarray(zipf_ids(rng, (BATCH, NNZ), VOCAB))
    flat = ids.reshape(-1)
    g128 = jnp.asarray(rng.normal(size=(m, LANES)).astype(np.float32) * 1e-3)
    g_rows = jnp.asarray(rng.normal(size=(BATCH, NNZ, D)).astype(np.float32) * 1e-3)
    # Compacted unique ascending uphys + per-slot sums, prebuilt on host.
    uniq = np.unique((np.asarray(flat) // P).astype(np.int32))
    un = uniq.shape[0]
    uphys_np = (vp + np.arange(k_cap, dtype=np.int32))
    uphys_np[:un] = uniq
    uphys = jnp.asarray(uphys_np)
    Gsum = jnp.asarray(rng.normal(size=(k_cap, LANES)).astype(np.float32) * 1e-3)

    out = {"vocab": VOCAB, "vp": vp, "m": m, "k_cap": k_cap, "unique_phys": int(un)}

    phys = (flat // P).astype(jnp.int32)
    slot_lane = (flat % P).astype(jnp.int32)

    @partial(jax.jit, static_argnums=(0,))
    def chain_gbuild(k, flat, g128):
        def body(i, s):
            ph = ((jnp.bitwise_xor(flat, i) // P)).astype(jnp.int32)
            G = jnp.zeros((k_cap, LANES), jnp.float32).at[
                jnp.minimum(ph, k_cap - 1)
            ].add(g128, mode="drop")
            return s + G[0, 0]
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["g_build_ms"] = slope_ms(chain_gbuild, (flat, g128))
    print("g_build_ms", out["g_build_ms"], flush=True)

    def make_rmw(flagged):
        kw = dict(mode="drop")
        if flagged:
            kw.update(unique_indices=True, indices_are_sorted=True)

        @partial(jax.jit, static_argnums=(0,))
        def chain_rmw(k, table, accum, uphys, Gsum):
            def body(i, carry):
                t, a, s = carry
                safe = jnp.minimum(uphys, vp - 1)
                cur = t[safe]
                acc = a[safe]
                acc2 = acc + Gsum * Gsum
                new = cur - 0.01 * Gsum / jnp.sqrt(acc2)
                t = t.at[uphys].set(new, **kw)
                a = a.at[uphys].set(acc2, **kw)
                return t, a, s + new[0, 0]
            t, a, s = jax.lax.fori_loop(0, k, body, (table, accum, jnp.float32(0)))
            return s + t[0, 0] + a[0, 0]

        return chain_rmw

    out["rmw_flagged_ms"] = slope_ms(
        make_rmw(True), (table, accum, uphys, Gsum)
    )
    print("rmw_flagged_ms", out["rmw_flagged_ms"], flush=True)
    out["rmw_plain_ms"] = slope_ms(
        make_rmw(False), (table, accum, uphys, Gsum)
    )
    print("rmw_plain_ms", out["rmw_plain_ms"], flush=True)

    # Descriptor-vs-byte bound: [K,128] vs [K,256] wide gathers.
    table256 = jnp.concatenate([table, table], axis=1)

    @partial(jax.jit, static_argnums=(0,))
    def chain_gather128(k, table, uphys):
        def body(i, s):
            # XOR with the loop index so the gather cannot hoist out.
            rows = table[jnp.minimum(jnp.bitwise_xor(uphys, i), vp - 1)]
            return s + rows[0, 0]
        return jax.lax.fori_loop(0, k, body, jnp.float32(0))

    out["gather_k128_ms"] = slope_ms(chain_gather128, (table, uphys))
    print("gather_k128_ms", out["gather_k128_ms"], flush=True)
    out["gather_k256_ms"] = slope_ms(chain_gather128, (table256, uphys))
    print("gather_k256_ms", out["gather_k256_ms"], flush=True)
    del table256

    # Full tails with the round-5 flags (2^24 fits the chain's double buffer).
    for tag, fn in (
        ("upd_compact", packed_compact_adagrad_update),
        ("upd_sorted", packed_sparse_adagrad_update),
        ("upd_dense", packed_dense_adagrad_update),
    ):
        @partial(jax.jit, static_argnums=(0,))
        def chain_upd(k, table, accum, ids, g_rows, fn=fn):
            def body(i, carry):
                t, a, s = carry
                t, a = fn(t, a, jnp.bitwise_xor(ids, i), g_rows, 0.01)
                return t, a, s + t[0, 0]
            t, a, s = jax.lax.fori_loop(0, k, body, (table, accum, jnp.float32(0)))
            return s + a[0, 0]

        out[f"{tag}_ms"] = slope_ms(chain_upd, (table, accum, ids, g_rows))
        print(tag, out[f"{tag}_ms"], flush=True)

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "PROBE_UPDATE_OPS_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    print("wrote", path)


if __name__ == "__main__":
    main()
